# Runtime image for the beholder-tpu service.
# The reference builds FROM tritonmedia/base (external, CMD defined there);
# this image is self-contained instead.

FROM python:3.12-slim

WORKDIR /app

# protoc is NOT needed: generated api_pb2.py is committed
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

COPY pyproject.toml README.md constraints.txt ./
COPY beholder_tpu ./beholder_tpu
COPY native ./native
COPY Makefile ./

# -c constraints.txt pins the full dependency closure (the reference's
# yarn.lock role) so image builds are reproducible
RUN pip install --no-cache-dir -c constraints.txt . && make native

# the package is imported from site-packages, so point it at the built
# scanner explicitly (its relative search paths don't cover /app)
ENV BEHOLDER_FRAMECODEC_LIB=/app/native/build/libframecodec.so

CMD ["beholder"]
