"""Protobuf registry — the triton-core/proto contract.

Reproduces the four operations the reference uses
(/root/reference/index.js:46-48,63,74,94,129,134,142):

- ``load('api.TelemetryStatus')``  -> message class
- ``decode(cls, bytes)``           -> message instance
- ``enum_to_string(cls_or_name, 'TelemetryStatusEntry', value)`` -> name
- ``string_to_enum(cls_or_name, 'TelemetryStatusEntry', name)``  -> value

``enum_to_string``/``string_to_enum`` accept (and ignore) the message-class
first argument the reference passes, because the enums here are package-level.
"""

from __future__ import annotations

from typing import Any, Type

from google.protobuf.message import Message

from . import api_pb2

#: Full-name registry, mirroring proto.load('api.<Name>') (index.js:46-48).
_MESSAGES: dict[str, Type[Message]] = {
    "api.TelemetryStatus": api_pb2.TelemetryStatus,
    "api.TelemetryProgress": api_pb2.TelemetryProgress,
    "api.Media": api_pb2.Media,
}

_ENUMS = {
    "TelemetryStatusEntry": api_pb2.TelemetryStatusEntry,
    "CreatorType": api_pb2.CreatorType,
}

# Re-export the generated classes for direct use.
TelemetryStatus = api_pb2.TelemetryStatus
TelemetryProgress = api_pb2.TelemetryProgress
Media = api_pb2.Media
TelemetryStatusEntry = api_pb2.TelemetryStatusEntry
CreatorType = api_pb2.CreatorType


def load(full_name: str) -> Type[Message]:
    """Look up a message class by full name, e.g. ``api.TelemetryStatus``."""
    try:
        return _MESSAGES[full_name]
    except KeyError:
        raise KeyError(
            f"unknown message type {full_name!r}; known: {sorted(_MESSAGES)}"
        ) from None


def decode(message_cls: Type[Message], data: bytes) -> Message:
    """Parse wire bytes into a message instance (index.js:63,129)."""
    msg = message_cls()
    msg.ParseFromString(data)
    return msg


def encode(msg: Message) -> bytes:
    """Serialize a message (the producer side, for tests and tools)."""
    return msg.SerializeToString()


def enum_to_string(_scope: Any, enum_name: str, value: int) -> str:
    """Enum value -> name, e.g. ``4 -> 'DEPLOYED'`` (index.js:74,134)."""
    return _ENUMS[enum_name].Name(value)


def string_to_enum(_scope: Any, enum_name: str, name: str) -> int:
    """Enum name -> value, e.g. ``'TRELLO' -> 1`` (index.js:94,142)."""
    return _ENUMS[enum_name].Value(name)
