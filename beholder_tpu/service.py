"""The beholder service: bootstrap + the two telemetry consumers.

Faithful rebuild of /root/reference/index.js:23-160. Observable semantics
preserved exactly:

- status consumer (index.js:62-125):
  decode -> update DB -> early-ack if NO_TRELLO -> fetch row -> move Trello
  card when creator is TRELLO and a flow list is mapped (pos=2) -> on
  DEPLOYED, fire Telegram + Emby hooks with errors swallowed (warn only) ->
  ack. Failures *before* the hook block (DB, Trello move) propagate and the
  message is left unacked, exactly as an unhandled rejection leaves it in
  the reference.
- progress consumer (index.js:127-155):
  entire body wrapped; any error warns and acks anyway — at-most-once.
- comment helper increments beholder_trello_comments (index.js:50-58).

Reliability extension (``instance.reliability.enabled``; OFF by default
so every reference semantic above is preserved byte-for-byte):

- consumers upgrade from ack-on-error/leave-unacked to AT-LEAST-ONCE
  with a dead-letter parking lot: a failing handler nacks for
  redelivery up to ``consumer.max_attempts`` total deliveries, then the
  message is parked on ``<topic>.dlq`` with death-provenance headers —
  never silently lost, never an infinite poison loop. An idempotency
  window acks redeliveries of already-handled messages without re-running
  side effects (effectively-once under ack loss).
- outbound HTTP (Trello/Telegram/Emby share one transport) rides a
  :class:`~beholder_tpu.reliability.ResilientTransport`: circuit
  breaker (closed/open/half-open), bounded-jittered retries under a
  shared retry budget, per-attempt timeouts capped by the configured
  deadline. An open breaker degrades the health probe (health.py).
"""

from __future__ import annotations

import time

from beholder_tpu import proto
from beholder_tpu.clients import (
    EmbyClient,
    HttpTransport,
    TelegramClient,
    TrelloClient,
)
from beholder_tpu.config import Config, ConfigNode, dyn, no_trello
from beholder_tpu.log import get_logger
from beholder_tpu.metrics import Metrics
from beholder_tpu.mq import Broker, Delivery
from beholder_tpu.mq.ingest import ingest_from_config
from beholder_tpu.storage import MediaNotFound, SqliteStorage, Storage

STATUS_TOPIC = "v1.telemetry.status"
PROGRESS_TOPIC = "v1.telemetry.progress"
PREFETCH = 100  # index.js:43


class BeholderService:
    def __init__(
        self,
        config: ConfigNode,
        broker: Broker,
        db: Storage,
        metrics: Metrics | None = None,
        transport: HttpTransport | None = None,
        logger=None,
    ):
        self.config = config
        self.broker = broker
        self.db = db
        self.metrics = metrics or Metrics()
        self.logger = logger or get_logger("beholder")

        #: optional deep observability (extension; off by default so the
        #: reference exposition stays byte-identical): per-message handle
        #: histograms on the consumers and outbound HTTP latency via
        #: TimedTransport, all riding the same /metrics endpoint
        self._observability = bool(config.get("instance.observability.enabled"))
        self.handle_seconds = None
        if self._observability:
            from beholder_tpu.clients.http import (
                RequestsTransport,
                TimedTransport,
            )
            from beholder_tpu.metrics import get_or_create

            self.handle_seconds = get_or_create(
                self.metrics.registry,
                "histogram",
                "beholder_message_handle_seconds",
                "Telemetry message handle wall time by topic and outcome",
                labelnames=["topic", "outcome"],
            )
            transport = TimedTransport(
                transport or RequestsTransport(), self.metrics.registry
            )

        #: optional reliability subsystem (extension; off by default so
        #: the reference's at-most-once/ack-on-error semantics and the
        #: default exposition stay byte-identical): at-least-once
        #: consumers with DLQ parking + dedup, and breaker/retry/deadline
        #: armor on the shared outbound transport
        self._at_least_once = bool(config.get("instance.reliability.enabled"))
        self.breaker = None
        self.reliability = None
        self.reliable_consumers: dict[str, object] = {}
        if self._at_least_once:
            from beholder_tpu.reliability import (
                CircuitBreaker,
                ReliabilityMetrics,
                ResilientTransport,
                RetryBudget,
                RetryPolicy,
            )

            if transport is None:
                from beholder_tpu.clients.http import RequestsTransport

                transport = RequestsTransport()

            rel = config.get("instance.reliability") or ConfigNode({})
            self.reliability = ReliabilityMetrics(self.metrics.registry)
            self.breaker = CircuitBreaker(
                name="http",
                window=int(rel.get("breaker.window", 20)),
                min_calls=int(rel.get("breaker.min_calls", 5)),
                failure_threshold=float(
                    rel.get("breaker.failure_threshold", 0.5)
                ),
                reset_timeout_s=float(rel.get("breaker.reset_timeout_s", 30.0)),
                half_open_probes=int(rel.get("breaker.half_open_probes", 1)),
                half_open_successes=int(
                    rel.get("breaker.half_open_successes", 2)
                ),
                metrics=self.reliability,
                logger=self.logger,
            )
            retry = RetryPolicy(
                max_attempts=int(rel.get("retry.max_attempts", 3)),
                base_delay_s=float(rel.get("retry.base_delay_s", 0.05)),
                max_delay_s=float(rel.get("retry.max_delay_s", 2.0)),
                budget=RetryBudget(
                    capacity=float(rel.get("retry.budget_capacity", 10.0)),
                    deposit_per_call=float(
                        rel.get("retry.budget_per_call", 0.1)
                    ),
                ),
                # the transport decides retryability per error (4xx never
                # raises; BreakerOpenError is excluded by should_retry)
                retry_on=(Exception,),
                metrics=self.reliability,
                logger=self.logger,
            )
            # Resilient OUTSIDE Timed: each attempt is individually timed
            # (and timeouts get their own outcome label), while the
            # breaker sees the attempt stream
            transport = ResilientTransport(
                transport,
                breaker=self.breaker,
                retry=retry,
                default_deadline_s=float(
                    config.get("instance.http.deadline_s", 10.0)
                ),
                logger=self.logger,
            )
            self._consumer_max_attempts = int(
                rel.get("consumer.max_attempts", 3)
            )
            self._consumer_dedup_window = int(
                rel.get("consumer.dedup_window", 4096)
            )

        #: optional caching subsystem (``instance.cache.enabled``; OFF by
        #: default so the reference's read-every-message semantics and
        #: the default exposition stay byte-identical): storage reads
        #: memoized with writer-side invalidation (a progress message's
        #: ``get_by_id`` stops re-querying Postgres for rows that only
        #: change on status transitions), and read-only outbound lookups
        #: TTL-cached OUTSIDE the resilience stack (a hit costs the
        #: dependency — and the breaker's failure window — nothing).
        #: Side-effectful GETs (Telegram sendMessage, Emby refresh) are
        #: never cached (clients.http.read_only_get is an allowlist).
        self._cache_enabled = bool(config.get("instance.cache.enabled"))
        if self._cache_enabled:
            cache_cfg = config.get("instance.cache") or ConfigNode({})
            if bool(cache_cfg.get("storage.enabled", True)):
                from beholder_tpu.storage.cached import CachingStorage

                db = CachingStorage(
                    db,
                    ttl_s=float(cache_cfg.get("storage.ttl_s", 30.0)),
                    max_entries=int(
                        cache_cfg.get("storage.max_entries", 1024)
                    ),
                    metrics=self.metrics.registry,
                )
                self.db = db
            if bool(cache_cfg.get("http.enabled", True)):
                from beholder_tpu.clients.http import (
                    CachingTransport,
                    RequestsTransport,
                )

                transport = CachingTransport(
                    transport or RequestsTransport(),
                    ttl_s=float(cache_cfg.get("http.ttl_s", 5.0)),
                    max_entries=int(cache_cfg.get("http.max_entries", 256)),
                    metrics=self.metrics.registry,
                )

        #: optional speculative-decoding config (``instance.spec.*``;
        #: OFF by default). Like the serving prefix cache, the spec
        #: subsystem is a LIBRARY feature — the service itself runs no
        #: batcher — so the service's role is to parse the knob once and
        #: hand the resulting :class:`beholder_tpu.spec.SpecConfig` to
        #: whatever embeds a ContinuousBatcher next to the consumers
        #: (``ContinuousBatcher(spec=service.spec)``). Parsing is
        #: import-light (no jax) and, disabled, yields None — behavior
        #: and the default exposition stay byte-identical.
        from beholder_tpu.spec import spec_from_config

        self.spec = spec_from_config(config)

        #: optional serving flight recorder (``instance.observability.
        #: flight_recorder.*``; OFF by default). A library knob like
        #: ``spec``: the service parses it once into a
        #: :class:`beholder_tpu.obs.FlightRecorder` for whatever embeds
        #: a ContinuousBatcher (``flight_recorder=service.
        #: flight_recorder``); on shutdown the service dumps the ring to
        #: the configured ``export_path`` so short-lived runs keep their
        #: timeline. Disabled it is None — serving behavior and the
        #: default exposition stay byte-identical.
        from beholder_tpu.obs import (
            flight_plane_from_config,
            flight_recorder_from_config,
        )

        self.flight_recorder = flight_recorder_from_config(config)
        if self.flight_recorder is not None:
            # drop-pressure series (dropped counter + ring high-water
            # gauge) and the beholder_build_info gauge register ONLY
            # when the recorder knob is armed — off, the exposition is
            # byte-identical
            self.flight_recorder.bind_metrics(self.metrics.registry)
            from beholder_tpu.obs import register_build_info

            register_build_info(self.metrics.registry)

        #: optional cluster-wide flight plane (``instance.observability.
        #: flight_plane.*``; OFF by default ⇒ wire bytes, serving
        #: output, and the default exposition stay byte-identical).
        #: Armed, it stamps this process's ring with worker identity +
        #: a clock anchor, arms cross-worker edge ids, propagates W3C
        #: ``traceparent`` onto outbound HTTP (TracingTransport below)
        #: and AMQP headers, serves the merged cluster timeline at
        #: ``GET /debug/cluster-flight``, and dumps it at SIGTERM.
        self.flight_plane = flight_plane_from_config(config)
        if self.flight_plane is not None and self.flight_recorder is not None:
            self.flight_plane.bind(self.flight_recorder)

        #: fused paged verify/prefix attention
        #: (``instance.serving.fused_verify``; OFF by default) plus the
        #: kernel autotune table location
        #: (``instance.serving.autotune.table``; None = the committed
        #: artifacts/autotune_paged.json). Library knobs like ``spec``:
        #: the service parses them once for whatever embeds a
        #: ContinuousBatcher
        #: (``ContinuousBatcher(fused_verify=service.fused_verify,
        #: autotune_table=service.autotune_table)``). Parsing is
        #: import-light (no jax); off, serving output and the default
        #: exposition stay byte-identical — the fused kernel is pinned
        #: bitwise against the dense-gather oracle either way.
        self.fused_verify = bool(
            config.get("instance.serving.fused_verify", False)
        )
        self.autotune_table = config.get(
            "instance.serving.autotune.table", None
        )
        #: KV page encoding (``instance.serving.cache_dtype``; "bf16"
        #: by default): "int8" halves KV value bytes, "fp8" shrinks the
        #: scale side-channel further (float8_e4m3fn values + uint8
        #: E8M0 scales) — parsed here import-light as a STRING; the
        #: embedder passes it to ``ContinuousBatcher(cache_dtype=
        #: service.cache_dtype)``, where init_paged maps the spelling
        #: to the pool encoding. "bf16" serves byte-identically to the
        #: pre-knob batcher.
        cache_dtype = str(
            config.get("instance.serving.cache_dtype", "bf16")
        )
        if cache_dtype not in ("bf16", "int8", "fp8"):
            raise ValueError(
                f"instance.serving.cache_dtype must be one of "
                f"bf16/int8/fp8, got {cache_dtype!r}"
            )
        self.cache_dtype = cache_dtype
        #: fused wave prefill (``instance.serving.fused_wave``; OFF by
        #: default): run_waves admits each wave through the fused chunk
        #: kernel instead of dense per-wave context buffers — same
        #: import-light contract as ``fused_verify`` (the embedder
        #: passes ``ContinuousBatcher(fused_wave=service.fused_wave)``;
        #: bitwise-identical deltas either way, pinned by
        #: tests/test_serving.py).
        self.fused_wave = bool(
            config.get("instance.serving.fused_wave", False)
        )

        #: optional request-level SLO engine (``instance.slo.*``; OFF
        #: by default ⇒ serving output and the default exposition stay
        #: byte-identical, same contract as cache/spec/cluster). The
        #: tracker folds the flight recorder's per-request lifecycle
        #: events into streaming TTFT/TPOT digests and multi-window
        #: error-budget burn rates: /healthz gains the ``slo`` check
        #: (degraded past the fast-window burn threshold), the metrics
        #: server gains ``GET /slo``, and the beholder_slo_* catalog
        #: registers. Import-light (no jax) like the other knobs.
        from beholder_tpu.obs.slo import slo_from_config

        self.slo = slo_from_config(config, registry=self.metrics.registry)
        if self.slo is not None and self.flight_recorder is not None:
            # the daemon feed: req.claim/req.retire/req.recovered
            # instants stream into the tracker as they are recorded
            self.flight_recorder.add_listener(self.slo.on_event)

        #: optional tail-based trace retention + online regression
        #: sentinel (``instance.observability.{retention,sentinel}.*``;
        #: OFF by default ⇒ serving output and the default exposition
        #: stay byte-identical, and /debug/traces + /debug/sentinel
        #: 404). Both are flight-recorder listeners: the vault decides
        #: keep/drop as requests retire (after the outcome is known),
        #: the sentinel diffs fast-vs-baseline phase attribution and
        #: opens incidents on the vault. Listener ORDER matters: the
        #: SLO tracker folds first (the vault probes its live digests
        #: for the p99-tail predicate), then the vault, then the
        #: sentinel. Import-light like the other knobs.
        from beholder_tpu.obs import (
            retention_from_config,
            sentinel_from_config,
        )

        self.trace_vault = retention_from_config(
            config, slo=self.slo, registry=self.metrics.registry
        )
        if self.trace_vault is not None:
            if self.flight_recorder is not None:
                self.flight_recorder.add_listener(self.trace_vault.on_event)
            if self.slo is not None:
                # worst_request blocks gain trace_ref joins
                self.slo.link_vault(self.trace_vault)
            # histogram exemplars gain trace_ref joins (module-global:
            # histograms predate the vault; resolution is render-time)
            from beholder_tpu.metrics import set_exemplar_resolver

            set_exemplar_resolver(self.trace_vault.trace_ref)
            if self.flight_plane is not None:
                # incident-kept traces federate: assembled from the
                # MERGED cluster flight plane (every worker's ring,
                # skew-aligned) and stamped ``federated: true``
                self.trace_vault.link_flight_plane(self.flight_plane)
        self.sentinel = sentinel_from_config(
            config,
            slo=self.slo,
            vault=self.trace_vault,
            registry=self.metrics.registry,
        )
        if self.sentinel is not None and self.flight_recorder is not None:
            self.flight_recorder.add_listener(self.sentinel.on_event)

        #: optional batched native ingest (``instance.ingest.*``; OFF
        #: by default ⇒ the per-message wire path, handler outcomes and
        #: the default exposition stay byte-identical). Enabled, a
        #: supporting broker (AmqpBroker) scans each socket poll in ONE
        #: native pass with zero-copy payload views, dispatches whole
        #: drained batches, and the consumers register batch PREPARE
        #: stages that fold per-message work: one protobuf decode pass
        #: and ONE storage transaction per drained batch
        #: (``update_status_batch``), while the per-message handler
        #: chain — tracing, timing, at-least-once settlement — runs
        #: unchanged. Parsing is import-light like the other knobs.
        self.ingest = ingest_from_config(config)

        #: optional cluster serving (``instance.cluster.*``; OFF by
        #: default). A library knob like ``spec``: the service parses
        #: it once into a :class:`beholder_tpu.cluster.ClusterConfig`
        #: (service.cluster) for whatever embeds the serving layer
        #: (``ClusterScheduler(model, params, service.cluster, ...)``).
        #: Parsing is import-light (no jax) and, disabled, yields
        #: None — behavior and the default exposition stay
        #: byte-identical.
        from beholder_tpu.cluster import cluster_from_config

        self.cluster = cluster_from_config(config)
        #: group-parallel decode (``instance.cluster.group.*``; OFF by
        #: default ⇒ every decode shard stays single-device and
        #: serving output, handoff wire bytes, and the /metrics
        #: exposition are byte-identical). The block parses import-
        #: light into ClusterConfig.group (GroupConfig rejects size<2,
        #: non-identifier axes and unknown head-partition policies at
        #: parse time; the KV-head and device-count divide checks live
        #: where the geometry is known — GroupBatcher and
        #: serving_shard_devices raise loudly at build). The one
        #: cross-knob conflict the service CAN see import-light is
        #: rejected here rather than deep in shard construction:
        if (
            self.cluster is not None
            and self.cluster.group is not None
            and self.spec is not None
        ):
            raise ValueError(
                "instance.cluster.group and instance.spec are mutually "
                "exclusive: speculative decoding is a single-device "
                "lane (GroupBatcher rejects spec) — disable one"
            )
        #: set by whatever embeds a live ClusterScheduler next to the
        #: consumers. The service only holds the reference: /healthz
        #: gains the ``cluster`` check (degraded while any worker is
        #: down — health.py), and close() drains it when
        #: ``instance.cluster.failover.drain_on_sigterm`` (queued work
        #: serves to completion before the process exits).
        self.cluster_scheduler = None

        #: optional SLO-acting control plane (``instance.control.*``;
        #: OFF by default ⇒ serving output and the default exposition
        #: stay byte-identical — the same contract as every subsystem
        #: knob, pinned by tests/test_control.py). The service parses
        #: the declared policy (service.control) and builds the
        #: host-side policy engine (service.control_plane — it reads
        #: the SLO tracker, holds no device state) for whatever embeds
        #: the serving layer: ``ClusterScheduler(...,
        #: control_plane=service.control_plane)`` arms tenant-fair
        #: shard intakes, burn/deadline-aware routing and the
        #: autoscaler; ``control_plane.attach_spec(batcher)`` arms
        #: burn-driven k-shedding; ``control_plane.intake(...)`` builds
        #: a tenant-fair intake for a bare batcher. The metrics server
        #: gains ``GET /control`` (policy + live per-tenant state).
        from beholder_tpu.control import control_from_config

        self.control = control_from_config(config)
        self.control_plane = None
        if self.control is not None:
            from beholder_tpu.control.policy import ControlPlane

            self.control_plane = ControlPlane(
                self.control,
                tracker=self.slo,
                registry=self.metrics.registry,
                flight_recorder=self.flight_recorder,
            )
        #: daemon-owned periodic autoscaler clock (``instance.control.
        #: autoscale.evaluator_interval_s``; OFF by default — None here
        #: means evaluation stays purely boundary-driven). Built and
        #: started by :meth:`start_scaling_evaluator` once the embedder
        #: has attached ``cluster_scheduler``; stopped in :meth:`close`.
        self.scaling_evaluator = None

        if self.flight_plane is not None:
            # trace-context propagation, OUTERMOST on the transport
            # chain (above caching: a cache hit has no wire request to
            # stamp): every egress call carries the active span's W3C
            # traceparent. Only built when the plane is armed — off,
            # no wrapper exists and outbound bytes are unchanged.
            from beholder_tpu.clients.http import (
                RequestsTransport,
                TracingTransport,
            )

            transport = TracingTransport(transport or RequestsTransport())

        deadline_s = float(config.get("instance.http.deadline_s", 10.0))
        self.trello = TrelloClient(
            config.get("keys.trello.key", ""),
            config.get("keys.trello.token", ""),
            transport=transport,
            deadline_s=deadline_s,
        )
        self.telegram = TelegramClient(
            config.get("keys.telegram.token", ""),
            transport=transport,
            deadline_s=deadline_s,
        )
        emby_host = config.get("instance.emby.host", "")
        self.emby = EmbyClient(
            emby_host,
            config.get("keys.emby.token", ""),
            transport=transport,
            deadline_s=deadline_s,
        )

        #: status-name (lowercase) -> Trello list id (index.js:60).
        #: Config is load-once in the reference too (triton-core Config),
        #: so resolving it to plain values here is parity-safe and keeps
        #: dotted lookups out of the per-message hot path.
        flow = config.get("instance.flow_ids") or ConfigNode({})
        self.flow_ids = flow.to_dict() if isinstance(flow, ConfigNode) else dict(flow)
        self._telegram_enabled = bool(config.get("instance.telegram.enabled"))
        self._telegram_channel = config.get("instance.telegram.channel")
        self._emby_enabled = bool(
            config.get("keys.emby.token") and config.get("instance.emby.enabled")
        )
        self._emby_host = config.get("instance.emby.host")
        self._progress_counters = {}  # status text -> bound counter child
        self._status_names = {}  # status int -> enum name (load-once enums)

        #: optional distributed tracing (the reference's triton-core layer
        #: carries jaeger-client — SURVEY.md §5; spans live at this layer)
        from beholder_tpu.tracing import tracer_from_config

        self.tracer = tracer_from_config(config, logger=self.logger)

        #: optional batch-analytics extension (not part of reference parity)
        self.analytics = None
        if config.get("instance.analytics.enabled"):
            from beholder_tpu.analytics import AnalyticsSink

            self.analytics = AnalyticsSink(
                flush_every=int(config.get("instance.analytics.flush_every", 4096)),
                logger=self.logger,
                async_flush=True,  # XLA work must not stall the consumer
            )

        #: set by init() when instance.health.enabled (see health.py)
        self.health = None

        self._status_proto = proto.load("api.TelemetryStatus")
        self._progress_proto = proto.load("api.TelemetryProgress")
        proto.load("api.Media")  # parity with index.js:48

        # enum constants resolved once; the names are compile-time literals
        # in the reference too (index.js:94,142)
        self._deployed_status = proto.string_to_enum(
            self._status_proto, "TelemetryStatusEntry", "DEPLOYED"
        )
        self._creator_trello = proto.string_to_enum(
            proto.Media, "CreatorType", "TRELLO"
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        """Register both consumers (index.js:62,127) and log 'initialized'."""
        if self.ingest is not None:
            # arm the broker's batched ingest path BEFORE connect (the
            # per-connection batch feed is built at handshake time);
            # brokers without the surface (InMemoryBroker) stay on the
            # per-message path with identical semantics
            configure = getattr(self.broker, "configure_ingest", None)
            if configure is not None:
                configure(
                    self.ingest,
                    registry=self.metrics.registry,
                    flight_recorder=self.flight_recorder,
                )
        self.broker.connect()
        status, progress = self.handle_status, self.handle_progress
        if self.handle_seconds is not None:
            # timing INSIDE tracing: observations then carry the active
            # consumer span's trace id in the metrics observation log
            status = self._timed(STATUS_TOPIC, status)
            progress = self._timed(PROGRESS_TOPIC, progress)
        if self.tracer is not None:
            # wrap at registration time so the disabled path (the default,
            # and the reference's behavior) pays zero per-message cost
            status = self._traced("telemetry.status", status)
            progress = self._traced("telemetry.progress", progress)
        if self._at_least_once:
            # OUTERMOST wrapper: it owns settlement on failure (nack for
            # redelivery, park to the DLQ at the attempt cap, dedup acks
            # on redelivered already-done messages)
            from beholder_tpu.reliability import ReliableConsumer

            status, progress = (
                ReliableConsumer(
                    self.broker,
                    topic,
                    handler,
                    max_attempts=self._consumer_max_attempts,
                    dedup_window=self._consumer_dedup_window,
                    metrics=self.reliability,
                    logger=self.logger,
                )
                for topic, handler in (
                    (STATUS_TOPIC, status),
                    (PROGRESS_TOPIC, progress),
                )
            )
            self.reliable_consumers = {
                STATUS_TOPIC: status,
                PROGRESS_TOPIC: progress,
            }
        if self.ingest is not None:
            self.broker.listen_batch(
                STATUS_TOPIC, status, self.prepare_status_batch
            )
            self.broker.listen_batch(
                PROGRESS_TOPIC, progress, self.prepare_progress_batch
            )
        else:
            self.broker.listen(STATUS_TOPIC, status)
            self.broker.listen(PROGRESS_TOPIC, progress)
        self.logger.info("initialized")

    def _timed(self, topic: str, handler):
        """Observe per-message handle wall time into
        ``beholder_message_handle_seconds{topic, outcome}``; an escaping
        exception (the status consumer's unacked-failure path) counts as
        ``outcome="error"`` and still propagates."""
        hist = self.handle_seconds

        def timed_handler(delivery: Delivery) -> None:
            t0 = time.perf_counter()
            try:
                handler(delivery)
            except Exception:
                hist.observe(
                    time.perf_counter() - t0, topic=topic, outcome="error"
                )
                raise
            hist.observe(time.perf_counter() - t0, topic=topic, outcome="ok")

        return timed_handler

    def _traced(self, operation: str, handler):
        """Run ``handler`` inside a consumer span; joins the producer's
        trace when the delivery carries an uber-trace-id header."""
        from beholder_tpu.tracing import extract

        tracer = self.tracer

        def traced_handler(delivery: Delivery) -> None:
            parent = extract(delivery.headers)
            with tracer.start_span(
                operation,
                child_of=parent,
                tags={"topic": delivery.topic, "redelivered": delivery.redelivered},
            ):
                handler(delivery)

        return traced_handler

    def start_scaling_evaluator(self):
        """Start the periodic autoscaler evaluator thread, if armed.

        Call AFTER attaching ``cluster_scheduler`` (the evaluator
        drives ``control_plane.evaluate_scaling(scheduler)``). Returns
        the running :class:`~beholder_tpu.control.evaluator.
        ScalingEvaluator`, or None when any prerequisite is off — the
        control plane, the autoscale actuator, the
        ``evaluator_interval_s`` knob, or the scheduler itself (all
        default-off: no knob, no thread, byte-identical daemon)."""
        if self.scaling_evaluator is not None:
            return self.scaling_evaluator
        cfg = getattr(self.control, "autoscale", None)
        if (
            self.control_plane is None
            or cfg is None
            or cfg.evaluator_interval_s is None
            or self.cluster_scheduler is None
        ):
            return None
        from beholder_tpu.control.evaluator import ScalingEvaluator

        self.scaling_evaluator = ScalingEvaluator(
            self.control_plane,
            self.cluster_scheduler,
            cfg.evaluator_interval_s,
            logger=self.logger,
        ).start()
        return self.scaling_evaluator

    def close(self) -> None:
        """Graceful teardown: stop consuming, drain analytics, flush the
        observability tail (open spans, raw observations, the flight-
        recorder ring), close."""
        self.logger.info("shutting down")
        if self.scaling_evaluator is not None:
            # the autoscaler clock stops before the drain below: a
            # scale decision racing teardown helps nobody
            try:
                self.scaling_evaluator.stop()
            except Exception:  # noqa: BLE001 - best effort on the way out
                pass
        self.broker.close()
        # graceful cluster drain (SIGTERM routes here): stop admitting
        # and serve what's queued, so a decommission loses nothing
        if (
            self.cluster_scheduler is not None
            and self.cluster is not None
            and self.cluster.failover is not None
            and self.cluster.failover.drain_on_sigterm
        ):
            try:
                self.cluster_scheduler.shutdown(drain=True)
            except Exception as err:  # noqa: BLE001 - best effort on the way out
                self.logger.warning(
                    f"cluster drain at shutdown failed: {err!r}"
                )
        if self.analytics is not None:
            try:
                self.analytics.flush()
                self.analytics.drain()
            except Exception:  # noqa: BLE001 - best effort on the way out
                pass
        if self.health is not None:
            self.health.close()
        # observability tail: a SIGTERM'd short-lived run must not drop
        # its last spans/observations/timeline (main() routes SIGTERM
        # here). Every step is best-effort — teardown always completes.
        if self.tracer is not None:
            try:
                flushed = self.tracer.flush()
                if flushed:
                    self.logger.info(
                        "flushed %d open trace span(s) at shutdown", flushed
                    )
            except Exception:  # noqa: BLE001
                pass
        from beholder_tpu.metrics import flush_observation_log

        flush_observation_log()
        if (
            self.flight_recorder is not None
            and self.flight_recorder.export_path
        ):
            try:
                self.flight_recorder.dump()
            except Exception:  # noqa: BLE001
                pass
        if (
            self.flight_plane is not None
            and self.flight_plane.export_path
        ):
            # the MERGED cluster timeline (skew-aligned, flow-edged)
            # dumps alongside the raw ring
            try:
                self.flight_plane.dump()
            except Exception:  # noqa: BLE001
                pass
        if self.trace_vault is not None:
            if self.trace_vault.config.export_path:
                # the kept-trace vault lands next to the flight ring,
                # shift-rotating any previous generation
                try:
                    self.trace_vault.dump()
                except Exception:  # noqa: BLE001
                    pass
            # the exemplar join is module-global; un-install it so a
            # later vault-less service renders the pinned off-shape
            from beholder_tpu.metrics import set_exemplar_resolver

            set_exemplar_resolver(None)
        self.metrics.close()
        self.db.close()

    # -- helpers -----------------------------------------------------------
    def comment(self, card_id: str, text: str) -> None:
        """Comment on a Trello card + count it (index.js:50-58)."""
        self.logger.info("creating comment on %s with text: %s", card_id, text)
        self.trello.comment_card(card_id, text)
        self.metrics.trello_comments_total.inc()

    # -- batched ingest prepare stages -------------------------------------
    def prepare_status_batch(self, deliveries: list[Delivery]) -> None:
        """Batched-ingest prepare for ``v1.telemetry.status``: one
        protobuf decode pass and ONE storage transaction for the whole
        drained run (``update_status_batch``), stashing per-delivery
        results on ``delivery.prepared`` for :meth:`handle_status` —
        which still runs per message under its usual wrappers, so acks,
        redelivery, tracing and error outcomes are unchanged.

        In at-least-once mode the fold STOPS at the first redelivered
        message: the ReliableConsumer's dedup window may skip its
        handler entirely (the prepare must not run side effects the
        handler won't), and folding LATER same-media writes into a
        transaction that commits BEFORE the redelivered message's own
        inline write would invert the per-message loop's arrival-order
        outcome — so everything from the redelivered message on falls
        back to the per-message path, in order.
        A message whose decode fails is left without a ``msg`` (the
        handler re-decodes and raises in its OWN scope, exactly like
        the per-message loop); a wholesale write failure leaves the
        ``found`` flags off and every handler re-runs its update inline."""
        rows: dict[str, proto.Media] = {}
        pending: list[tuple[dict, str, int]] = []
        for delivery in deliveries:
            if self._at_least_once and delivery.redelivered:
                break
            prepared: dict = {"rows": rows}
            delivery.prepared = prepared
            try:
                msg = proto.decode(self._status_proto, delivery.body)
            except Exception:  # noqa: BLE001 - re-raised by the handler
                continue
            prepared["msg"] = msg
            pending.append((prepared, msg.mediaId, msg.status))
        if not pending or not self.ingest.batch_storage:
            return
        try:
            found = self.db.update_status_batch(
                [(media_id, status) for _, media_id, status in pending]
            )
        except Exception as err:  # noqa: BLE001 - degrade to inline writes
            self.logger.warning(
                f"batched status write failed ({err!r}); "
                "falling back to per-message updates"
            )
            return
        for (prepared, _, _), ok in zip(pending, found):
            prepared["found"] = ok
        # prefetch the post-write rows in ONE query (the handlers'
        # read-after-own-write; _read_media overrides status per
        # message). Best-effort: a miss here just re-reads inline.
        # NO_TRELLO handlers ack right after the write and never read —
        # match the per-message loop's zero reads in that mode.
        if no_trello():
            return
        try:
            rows.update(
                self.db.get_by_ids(
                    [p[1] for p, ok in zip(pending, found) if ok]
                )
            )
        except Exception:  # noqa: BLE001
            pass

    def prepare_progress_batch(self, deliveries: list[Delivery]) -> None:
        """Batched-ingest prepare for ``v1.telemetry.progress``: one
        decode pass plus a shared per-run row-read memo (the progress
        handler only reads media rows — one ``get_by_id`` per distinct
        id per run instead of per message)."""
        rows: dict[str, proto.Media] = {}
        media_ids: list[str] = []
        for delivery in deliveries:
            if self._at_least_once and delivery.redelivered:
                continue
            prepared: dict = {"rows": rows}
            delivery.prepared = prepared
            try:
                msg = proto.decode(self._progress_proto, delivery.body)
            except Exception:  # noqa: BLE001 - re-raised by the handler
                continue
            prepared["msg"] = msg
            media_ids.append(msg.mediaId)
        # one read round trip for the whole run; a missing id keeps its
        # MediaNotFound outcome (the handler's fallback read raises)
        if media_ids:
            try:
                rows.update(self.db.get_by_ids(media_ids))
            except Exception:  # noqa: BLE001 - handlers re-read inline
                pass

    def _read_media(
        self, prepared: dict | None, media_id: str, status: int | None = None
    ) -> proto.Media:
        """Row read, batch-aware: on the per-message path it is exactly
        ``db.get_by_id``; on the batched path the run's shared memo
        serves one read per distinct id (the per-message loop re-reads
        the same row identically on this same thread). ``status``
        overrides the returned row's status with THIS message's own
        just-written value — which is precisely what the per-message
        read-after-own-write observes, including when a later message
        in the batch already moved the row on."""
        if prepared is None:
            return self.db.get_by_id(media_id)
        rows = prepared["rows"]
        media = rows.get(media_id)
        if media is None:
            media = rows[media_id] = self.db.get_by_id(media_id)
        clone = proto.Media()
        clone.CopyFrom(media)
        if status is not None:
            clone.status = status
        return clone

    # -- consumers ---------------------------------------------------------
    def handle_status(self, delivery: Delivery) -> None:
        """v1.telemetry.status (index.js:62-125)."""
        prepared = delivery.prepared
        if prepared is not None and "msg" in prepared:
            msg = prepared["msg"]
        else:
            msg = proto.decode(self._status_proto, delivery.body)
        media_id, status = msg.mediaId, msg.status

        self.logger.info(
            "processing status update for media %s, status: %s", media_id, status
        )

        found = prepared.get("found") if prepared is not None else None
        if found is None:
            self.db.update_status(media_id, status)
        elif not found:
            raise MediaNotFound(media_id)

        if no_trello():
            return delivery.ack()  # index.js:70-72

        status_text = self._status_names.get(status)
        if status_text is None:
            status_text = self._status_names[status] = proto.enum_to_string(
                self._status_proto, "TelemetryStatusEntry", status
            )
        media = self._read_media(prepared, media_id, status)

        # Trello card movement (index.js:79-90)
        if media.creator == 1:
            list_pointer = self.flow_ids.get(status_text.lower())
            if list_pointer:
                self.logger.info(
                    "moving media card %s (card id %s)", media_id, media.creatorId
                )
                self.trello.move_card(media.creatorId, list_pointer, pos=2)
            else:
                self.logger.warning(
                    f"unable to find list for status {status} ({status_text}) "
                    f"avail ([{','.join(self.flow_ids)}])"
                )

        # deployed hooks — failures swallowed (index.js:92-122)
        try:
            if media.status == self._deployed_status:
                if self._telegram_enabled:
                    self.logger.info(
                        "informing telegram that media '%s' is available", media_id
                    )
                    self.telegram.notify_deployed(
                        self._telegram_channel, media.name, media.metadataId
                    )

                if self._emby_enabled:
                    self.logger.info(
                        "telling emby to refresh at %s", self._emby_host
                    )
                    self.emby.refresh_library()
        except Exception as err:  # noqa: BLE001 - parity with index.js:120-122
            self.logger.warning(f"failed to run deployed hooks: {err}")

        delivery.ack()  # index.js:124

    def handle_progress(self, delivery: Delivery) -> None:
        """v1.telemetry.progress (index.js:127-155)."""
        try:
            prepared = delivery.prepared
            if prepared is not None and "msg" in prepared:
                msg = prepared["msg"]
            else:
                msg = proto.decode(self._progress_proto, delivery.body)
            media_id, status = msg.mediaId, msg.status
            progress, host = msg.progress, msg.host

            self.logger.info(
                "processing progress update on media %s status %s percent %s",
                media_id,
                status,
                progress,
            )
            status_text = self._status_names.get(status)
            if status_text is None:
                status_text = self._status_names[status] = proto.enum_to_string(
                    self._progress_proto, "TelemetryStatusEntry", status
                )

            counter = self._progress_counters.get(status_text)
            if counter is None:
                counter = self.metrics.progress_updates_total.labels(
                    status=status_text.lower()
                )
                self._progress_counters[status_text] = counter
            counter.inc()

            if self.analytics is not None:
                try:
                    self.analytics.record(status, progress)
                except Exception as err:  # noqa: BLE001
                    # the extension must never break the parity path: on any
                    # sink failure (e.g. broken accelerator stack), disable
                    # analytics and keep consuming
                    self.logger.warning(
                        f"analytics sink failed ({err!r}); disabling analytics"
                    )
                    self.analytics = None

            media = self._read_media(prepared, media_id)

            if media.creator == self._creator_trello:
                comment_text = f"{status_text}: Progress **{progress}%**"
                if host:
                    comment_text += f" (_{host}_)"
                self.comment(media.creatorId, comment_text)
        except Exception as err:  # noqa: BLE001 - parity with index.js:149-152
            if self._at_least_once:
                # reliability mode: the error propagates to the
                # ReliableConsumer wrapper, which nacks for redelivery or
                # parks the message — ack-on-error would LOSE it
                raise
            self.logger.warning(f"failed to update media progress {err}")
            return delivery.ack()

        return delivery.ack()  # index.js:154


def init(
    config: ConfigNode | None = None,
    broker: Broker | None = None,
    db: Storage | None = None,
    metrics_port: int | None = None,
) -> BeholderService:
    """Bootstrap, mirroring index.js:23-48 step for step."""
    import os

    config = config or Config.load("events")

    metrics = Metrics()
    #: cache subsystem: optional /metrics response memoization (ETag +
    #: max-age; scrape storms render the exposition once per window)
    max_age = (
        config.get("instance.cache.httpd.metrics_max_age_s")
        if config.get("instance.cache.enabled")
        else None
    )
    metrics.expose(
        metrics_port,
        cache_max_age_s=float(max_age) if max_age else None,
    )

    service = None
    own_db = db is None
    own_broker = broker is None
    try:
        if db is None:
            target = os.environ.get("BEHOLDER_DB", "beholder.db")
            if target.startswith(("postgres://", "postgresql://")):
                from beholder_tpu.storage import PostgresStorage

                db = PostgresStorage(target)
            else:
                db = SqliteStorage(target)

        if broker is None:
            try:
                from beholder_tpu.mq.amqp import AmqpBroker
            except ImportError as err:  # pragma: no cover
                raise RuntimeError(
                    "the AMQP wire client is unavailable; pass an explicit "
                    "broker (e.g. InMemoryBroker) or fix the import"
                ) from err
            broker = AmqpBroker(dyn("rabbitmq"), prefetch=PREFETCH)

        service = BeholderService(config, broker, db, metrics=metrics)
        service.start()

        #: operator endpoints riding the metrics server (both gated on
        #: their knobs, so the default server stays /metrics-only):
        #: GET /slo renders attainment + budget burn, GET /debug/flight
        #: dumps the LIVE recorder ring as JSONL — no more waiting for
        #: the SIGTERM export to see the timeline
        if service.slo is not None:
            metrics.add_route("/slo", service.slo.route())
        if service.control_plane is not None:
            # GET /control: the declared policy + live per-tenant
            # admission state + actuator log (the acting half's /slo)
            metrics.add_route(
                "/control", service.control_plane.http_route()
            )
        if service.flight_recorder is not None:
            metrics.add_route(
                "/debug/flight", service.flight_recorder.route()
            )
        if service.flight_plane is not None:
            # GET /debug/cluster-flight: the LIVE skew-aligned merged
            # timeline (same ?since=/limit poll cursor as /debug/flight)
            metrics.add_route(
                "/debug/cluster-flight", service.flight_plane.route()
            )
        if service.trace_vault is not None:
            # GET /debug/traces: the tail-based vault index;
            # GET /debug/traces/<id>: one kept trace as Perfetto JSON
            # (prefix route — the trailing "/" key + wants_path)
            metrics.add_route(
                "/debug/traces", service.trace_vault.index_route()
            )
            metrics.add_route(
                "/debug/traces/", service.trace_vault.trace_route()
            )
        if service.sentinel is not None:
            # GET /debug/sentinel: the live regression verdict + the
            # ranked fast-vs-baseline attribution behind it
            metrics.add_route(
                "/debug/sentinel", service.sentinel.route()
            )

        #: optional /healthz + /readyz endpoint (extension; the reference
        #: delegates failure detection to its container orchestrator)
        from beholder_tpu.health import health_from_config

        service.health = health_from_config(config, service)
    except Exception:
        # a failed boot must release everything it acquired (metrics port,
        # broker threads, db handles), or a supervised restart would hit
        # Address-already-in-use / fd exhaustion forever.
        if service is not None:
            # consumers are already registered with handlers bound to this
            # service: the whole assembly must come down, INCLUDING a
            # caller-owned broker/db (they are poisoned by the dangling
            # registrations; a half-booted service must not keep consuming)
            try:
                service.close()
            except Exception:  # noqa: BLE001
                pass
        else:
            metrics.close()
            for resource, owned in ((broker, own_broker), (db, own_db)):
                if owned and resource is not None:
                    try:
                        resource.close()
                    except Exception:  # noqa: BLE001
                        pass
        raise
    return service


def main() -> None:  # pragma: no cover - process entrypoint
    import signal
    import threading

    import os

    supervised = bool(os.environ.get("BEHOLDER_SUPERVISE"))
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())

    if supervised:
        # elastic recovery: crash -> exponential backoff -> rebuild, and
        # recycle on sustained broker-liveness failure (health.py)
        from beholder_tpu.health import Supervisor

        supervisor = Supervisor(
            init,
            liveness=lambda svc: getattr(svc.broker, "connected", True),
            liveness_grace_s=float(os.environ.get("BEHOLDER_LIVENESS_GRACE", 60)),
        )
        supervisor.start()
        stop.wait()
        supervisor.stop()
        return

    service = init()
    stop.wait()
    service.close()


