"""Storage interface and the in-memory backend."""

from __future__ import annotations

import abc

from beholder_tpu import proto


class MediaNotFound(KeyError):
    """Raised by ``get_by_id`` for an unknown media id."""


class Storage(abc.ABC):
    """The two-method contract the reference exercises (index.js:68,76,140),
    plus ``add_media`` for the producer side (tests, tools)."""

    @abc.abstractmethod
    def update_status(self, media_id: str, status: int) -> None:
        """Persist a new lifecycle status for a media row (index.js:68)."""

    @abc.abstractmethod
    def get_by_id(self, media_id: str) -> proto.Media:
        """Fetch the full media row (index.js:76,140)."""

    @abc.abstractmethod
    def add_media(self, media: proto.Media) -> None:
        """Insert/replace a media row."""

    def update_status_batch(
        self, updates: list[tuple[str, int]]
    ) -> list[bool]:
        """Apply status updates IN ORDER; returns per-row found flags
        (``False`` where :meth:`update_status` would have raised
        :class:`MediaNotFound`).

        The batched-ingest storage hop: backends override this with a
        one-transaction implementation (one commit per drained batch
        instead of per message) — rows and per-row outcomes must be
        identical to the per-message loop, which is exactly what this
        default does."""
        found: list[bool] = []
        for media_id, status in updates:
            try:
                self.update_status(media_id, status)
                found.append(True)
            except MediaNotFound:
                found.append(False)
        return found

    def get_by_ids(self, media_ids) -> dict[str, proto.Media]:
        """Fetch several media rows at once; missing ids are simply
        absent from the result (callers keep :meth:`get_by_id`'s
        MediaNotFound semantics by falling back per id).

        The batched-ingest read hop: backends override this with a
        single-query implementation so a drained batch stops paying a
        storage round trip per message. This default is the per-id
        loop, semantics identical."""
        out: dict[str, proto.Media] = {}
        for media_id in media_ids:
            try:
                out[media_id] = self.get_by_id(media_id)
            except MediaNotFound:
                pass
        return out

    def close(self) -> None:  # pragma: no cover - trivial default
        pass


class MemoryStorage(Storage):
    """Dict-backed storage for tests."""

    def __init__(self):
        self._rows: dict[str, proto.Media] = {}

    def add_media(self, media: proto.Media) -> None:
        clone = proto.Media()
        clone.CopyFrom(media)
        self._rows[media.id] = clone

    def update_status(self, media_id: str, status: int) -> None:
        row = self._rows.get(media_id)
        if row is None:
            raise MediaNotFound(media_id)
        row.status = status

    def get_by_id(self, media_id: str) -> proto.Media:
        row = self._rows.get(media_id)
        if row is None:
            raise MediaNotFound(media_id)
        clone = proto.Media()
        clone.CopyFrom(row)
        return clone


def postgres_storage(url: str, **kwargs) -> Storage:
    """The Postgres backend the reference uses (via triton-core).

    Backed by the from-scratch wire client in :mod:`.pg_wire` — no
    external driver needed. Kept as a function for callers that predate
    :class:`.postgres.PostgresStorage`.
    """
    from .postgres import PostgresStorage

    return PostgresStorage(url, **kwargs)
