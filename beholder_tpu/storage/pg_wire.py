"""PostgreSQL v3 wire-protocol client, written from scratch.

The reference's storage is Postgres via triton-core's ``Storage()``
(/root/reference/index.js:19,42; the ``pg`` package at yarn.lock:2005).
No Postgres driver exists in this image, so — exactly like the AMQP stack
in :mod:`beholder_tpu.mq` — the transport layer is built from the public
protocol spec (PostgreSQL docs, "Frontend/Backend Protocol").

Implemented subset (everything the beholder path needs):

- startup + authentication: trust, cleartext, MD5, and SCRAM-SHA-256
  (the PG14+ default, RFC 5802/7677 client side with server-signature
  verification),
- the extended query protocol (Parse/Bind/Execute/Sync) with text-format
  parameters — real parameterization, no string splicing,
- simple query ('Q') for DDL,
- error surfacing with the server's SQLSTATE + message.

The client is synchronous and single-connection; the service's handlers
are sequential per consumer (like the reference's event loop), so one
connection guarded by a lock matches the actual concurrency.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import socket
import struct
import threading
from dataclasses import dataclass
from urllib.parse import unquote, urlparse

DEFAULT_PORT = 5432


class PostgresError(RuntimeError):
    """Server-reported error (severity, SQLSTATE code, message)."""

    def __init__(self, fields: dict[str, str]):
        self.fields = fields
        self.sqlstate = fields.get("C", "")
        super().__init__(
            f"{fields.get('S', 'ERROR')} {self.sqlstate}: {fields.get('M', '?')}"
        )


class ProtocolError(RuntimeError):
    pass


@dataclass
class PgUrl:
    host: str
    port: int
    user: str
    password: str
    database: str

    @classmethod
    def parse(cls, url: str) -> "PgUrl":
        parsed = urlparse(url)
        if parsed.scheme not in ("postgres", "postgresql", ""):
            raise ValueError(f"unsupported scheme {parsed.scheme!r} in {url!r}")
        db = unquote(parsed.path[1:]) if len(parsed.path) > 1 else "postgres"
        return cls(
            host=parsed.hostname or "127.0.0.1",
            port=parsed.port or DEFAULT_PORT,
            user=unquote(parsed.username) if parsed.username else "postgres",
            password=unquote(parsed.password) if parsed.password else "",
            database=db,
        )


def _message(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class PgConnection:
    """One authenticated connection; thread-safe via an internal lock."""

    def __init__(self, url: str, connect_timeout: float = 10.0):
        self.url = PgUrl.parse(url)
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._buf = b""
        self._timeout = connect_timeout

    # -- lifecycle ----------------------------------------------------------
    def connect(self) -> None:
        sock = socket.create_connection(
            (self.url.host, self.url.port), timeout=self._timeout
        )
        sock.settimeout(self._timeout)
        self._buf = b""  # a poisoned/closed connection may be re-connected
        self._sock = sock
        params = (
            struct.pack(">I", 196608)  # protocol 3.0
            + _cstr("user")
            + _cstr(self.url.user)
            + _cstr("database")
            + _cstr(self.url.database)
            + b"\x00"
        )
        sock.sendall(struct.pack(">I", len(params) + 4) + params)
        self._authenticate()
        # drain ParameterStatus/BackendKeyData until ReadyForQuery
        while True:
            tag, payload = self._recv()
            if tag == b"Z":
                return
            if tag == b"E":
                raise PostgresError(_error_fields(payload))
            # 'S' (parameter status), 'K' (backend key data), 'N' (notice)

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.sendall(_message(b"X", b""))  # Terminate
                except OSError:
                    pass
                self._sock.close()
                self._sock = None

    @property
    def closed(self) -> bool:
        return self._sock is None

    # -- queries ------------------------------------------------------------
    def query(
        self, sql: str, params: tuple = ()
    ) -> tuple[list[str], list[tuple[str | None, ...]], str]:
        """Run one parameterized statement via the extended protocol.

        Returns (column names, rows of text values, command tag). ``None``
        cells are SQL NULLs. Raises :class:`PostgresError` on server error.
        Any I/O error (timeout, reset) POISONS the connection — a partial
        response left in the buffer would otherwise be parsed as the NEXT
        query's result, silently returning wrong rows.
        """
        with self._lock:
            if self._sock is None:
                raise ProtocolError("connection is closed")
            out = bytearray()
            out += _message(b"P", _cstr("") + _cstr(sql) + struct.pack(">H", 0))
            bind = bytearray()
            bind += _cstr("") + _cstr("")  # portal, statement
            bind += struct.pack(">H", 0)  # all params text format
            bind += struct.pack(">H", len(params))
            for p in params:
                if p is None:
                    bind += struct.pack(">i", -1)
                else:
                    raw = str(p).encode()
                    bind += struct.pack(">I", len(raw)) + raw
            bind += struct.pack(">H", 0)  # all results text format
            out += _message(b"B", bytes(bind))
            out += _message(b"D", b"P" + _cstr(""))  # describe portal
            out += _message(b"E", _cstr("") + struct.pack(">I", 0))
            out += _message(b"S", b"")  # sync
            try:
                self._sock.sendall(bytes(out))
                return self._collect()
            except (OSError, TimeoutError) as err:
                self._poison()
                raise ProtocolError(f"connection lost mid-query: {err}") from err
            except ProtocolError:
                # server EOF mid-response surfaces as ProtocolError from
                # _fill(); a partial response may sit in the buffer, so the
                # stream can no longer be trusted — poison here too
                self._poison()
                raise

    def execute(self, sql: str) -> str:
        """Simple-query protocol for DDL; returns the command tag."""
        with self._lock:
            if self._sock is None:
                raise ProtocolError("connection is closed")
            try:
                self._sock.sendall(_message(b"Q", _cstr(sql)))
                return self._collect()[2]
            except (OSError, TimeoutError) as err:
                self._poison()
                raise ProtocolError(f"connection lost mid-query: {err}") from err
            except ProtocolError:
                self._poison()
                raise

    def _poison(self) -> None:
        """Invalidate the connection after an I/O fault; the response
        stream can no longer be trusted to align with requests."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._buf = b""

    # -- internals ----------------------------------------------------------
    def _collect(self):
        columns: list[str] = []
        rows: list[tuple[str | None, ...]] = []
        tag_text = ""
        error: PostgresError | None = None
        while True:
            tag, payload = self._recv()
            if tag == b"T":  # RowDescription
                n = struct.unpack(">H", payload[:2])[0]
                pos = 2
                columns = []
                for _ in range(n):
                    end = payload.index(b"\x00", pos)
                    columns.append(payload[pos:end].decode())
                    pos = end + 1 + 18  # fixed per-field trailer
            elif tag == b"D":  # DataRow
                n = struct.unpack(">H", payload[:2])[0]
                pos = 2
                row: list[str | None] = []
                for _ in range(n):
                    (ln,) = struct.unpack(">i", payload[pos : pos + 4])
                    pos += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(payload[pos : pos + ln].decode())
                        pos += ln
                rows.append(tuple(row))
            elif tag == b"C":  # CommandComplete
                tag_text = payload.rstrip(b"\x00").decode()
            elif tag == b"E":
                error = PostgresError(_error_fields(payload))
            elif tag == b"Z":  # ReadyForQuery — transaction boundary
                if error is not None:
                    raise error
                return columns, rows, tag_text
            # '1' parse-complete, '2' bind-complete, 'n' no-data,
            # 'N' notice, 'S' parameter status: all skippable

    def _authenticate(self) -> None:
        while True:
            tag, payload = self._recv()
            if tag == b"E":
                raise PostgresError(_error_fields(payload))
            if tag != b"R":
                raise ProtocolError(f"expected auth message, got {tag!r}")
            (code,) = struct.unpack(">I", payload[:4])
            if code == 0:  # AuthenticationOk
                return
            if code == 3:  # cleartext
                self._sock.sendall(_message(b"p", _cstr(self.url.password)))
            elif code == 5:  # MD5
                salt = payload[4:8]
                inner = hashlib.md5(
                    (self.url.password + self.url.user).encode()
                ).hexdigest()
                digest = hashlib.md5(inner.encode() + salt).hexdigest()
                self._sock.sendall(_message(b"p", _cstr("md5" + digest)))
            elif code == 10:  # SASL: pick SCRAM-SHA-256
                mechs = payload[4:].split(b"\x00")
                if b"SCRAM-SHA-256" not in mechs:
                    raise ProtocolError(f"no supported SASL mechanism in {mechs}")
                self._scram()
            else:
                raise ProtocolError(f"unsupported auth method {code}")

    def _scram(self) -> None:
        """SCRAM-SHA-256 (RFC 5802/7677), with server-signature check."""
        nonce = base64.b64encode(os.urandom(18)).decode()
        first_bare = f"n={_scram_name(self.url.user)},r={nonce}"
        client_first = ("n,," + first_bare).encode()
        init = (
            _cstr("SCRAM-SHA-256")
            + struct.pack(">I", len(client_first))
            + client_first
        )
        self._sock.sendall(_message(b"p", init))

        tag, payload = self._recv()
        if tag == b"E":
            raise PostgresError(_error_fields(payload))
        (code,) = struct.unpack(">I", payload[:4])
        if tag != b"R" or code != 11:  # SASLContinue
            raise ProtocolError(f"expected SASLContinue, got {tag!r}/{code}")
        server_first = payload[4:].decode()
        fields = dict(f.split("=", 1) for f in server_first.split(","))
        srv_nonce, salt_b64, iters = fields["r"], fields["s"], int(fields["i"])
        if not srv_nonce.startswith(nonce):
            raise ProtocolError("server nonce does not extend client nonce")

        salted = hashlib.pbkdf2_hmac(
            "sha256", self.url.password.encode(), base64.b64decode(salt_b64), iters
        )
        client_key = hmac.digest(salted, b"Client Key", "sha256")
        stored_key = hashlib.sha256(client_key).digest()
        final_wo_proof = f"c=biws,r={srv_nonce}"
        auth_message = ",".join([first_bare, server_first, final_wo_proof]).encode()
        signature = hmac.digest(stored_key, auth_message, "sha256")
        proof = bytes(a ^ b for a, b in zip(client_key, signature))
        final = f"{final_wo_proof},p={base64.b64encode(proof).decode()}"
        self._sock.sendall(_message(b"p", final.encode()))

        tag, payload = self._recv()
        if tag == b"E":
            raise PostgresError(_error_fields(payload))
        (code,) = struct.unpack(">I", payload[:4])
        if tag != b"R" or code != 12:  # SASLFinal
            raise ProtocolError(f"expected SASLFinal, got {tag!r}/{code}")
        sfields = dict(
            f.split("=", 1) for f in payload[4:].decode().split(",")
        )
        server_key = hmac.digest(salted, b"Server Key", "sha256")
        want = hmac.digest(server_key, auth_message, "sha256")
        if base64.b64decode(sfields.get("v", "")) != want:
            raise ProtocolError("server signature verification failed")

    def _recv(self) -> tuple[bytes, bytes]:
        while len(self._buf) < 5:
            self._fill()
        tag = self._buf[:1]
        (length,) = struct.unpack(">I", self._buf[1:5])
        total = 1 + length
        while len(self._buf) < total:
            self._fill()
        payload = self._buf[5:total]
        self._buf = self._buf[total:]
        return tag, payload

    def _fill(self) -> None:
        chunk = self._sock.recv(65536)
        if not chunk:
            raise ProtocolError("server closed the connection")
        self._buf += chunk


def _scram_name(name: str) -> str:
    return name.replace("=", "=3D").replace(",", "=2C")


def _error_fields(payload: bytes) -> dict[str, str]:
    fields: dict[str, str] = {}
    pos = 0
    while pos < len(payload) and payload[pos : pos + 1] != b"\x00":
        key = chr(payload[pos])
        end = payload.index(b"\x00", pos + 1)
        fields[key] = payload[pos + 1 : end].decode()
        pos = end + 1
    return fields
