"""SQLite-backed storage — the durable default for this rebuild.

The reference's rows live in an external Postgres owned by triton-core
(schema not in the reference repo); this backend persists the same
observable fields the handlers read, keyed by media id.
"""

from __future__ import annotations

import sqlite3
import threading

from beholder_tpu import proto

from .base import MediaNotFound, Storage

_SCHEMA = """
CREATE TABLE IF NOT EXISTS media (
    id          TEXT PRIMARY KEY,
    name        TEXT NOT NULL DEFAULT '',
    creator     INTEGER NOT NULL DEFAULT 0,
    creator_id  TEXT NOT NULL DEFAULT '',
    metadata_id TEXT NOT NULL DEFAULT '',
    status      INTEGER NOT NULL DEFAULT 0
);
"""


class SqliteStorage(Storage):
    def __init__(self, path: str = "beholder.db"):
        # The service's consumers run on one dispatch thread, but allow
        # cross-thread use (metrics server, tools) with a lock.
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock, self._conn:
            # WAL + NORMAL: one fsync per checkpoint instead of per commit.
            # Status updates are idempotent telemetry (the producer re-sends
            # state transitions), so power-loss durability of the last few
            # commits is not worth a ~50x throughput cliff on the hot path.
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(_SCHEMA)

    def add_media(self, media: proto.Media) -> None:
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT OR REPLACE INTO media "
                "(id, name, creator, creator_id, metadata_id, status) "
                "VALUES (?, ?, ?, ?, ?, ?)",
                (
                    media.id,
                    media.name,
                    media.creator,
                    media.creatorId,
                    media.metadataId,
                    media.status,
                ),
            )

    def update_status(self, media_id: str, status: int) -> None:
        with self._lock, self._conn:
            cur = self._conn.execute(
                "UPDATE media SET status = ? WHERE id = ?", (status, media_id)
            )
            if cur.rowcount == 0:
                raise MediaNotFound(media_id)

    def update_status_batch(
        self, updates: list[tuple[str, int]]
    ) -> list[bool]:
        """One transaction per drained ingest batch: the per-message
        loop pays a WAL commit per status update — the commit, not the
        UPDATE, is the storage hop's fixed cost. Rows execute in order
        (a later duplicate id wins, like the per-message loop) and
        per-row found flags preserve the MediaNotFound outcomes."""
        found: list[bool] = []
        with self._lock, self._conn:
            execute = self._conn.execute
            for media_id, status in updates:
                cur = execute(
                    "UPDATE media SET status = ? WHERE id = ?",
                    (status, media_id),
                )
                found.append(cur.rowcount != 0)
        return found

    def get_by_id(self, media_id: str) -> proto.Media:
        with self._lock:
            row = self._conn.execute(
                "SELECT id, name, creator, creator_id, metadata_id, status "
                "FROM media WHERE id = ?",
                (media_id,),
            ).fetchone()
        if row is None:
            raise MediaNotFound(media_id)
        return proto.Media(
            id=row[0],
            name=row[1],
            creator=row[2],
            creatorId=row[3],
            metadataId=row[4],
            status=row[5],
        )

    def get_by_ids(self, media_ids) -> dict[str, proto.Media]:
        """One ``IN`` query per drained ingest batch instead of one
        SELECT round trip per message (missing ids absent, per the base
        contract)."""
        ids = list(dict.fromkeys(media_ids))  # de-dupe, keep order
        if not ids:
            return {}
        placeholders = ",".join("?" * len(ids))
        with self._lock:
            rows = self._conn.execute(
                "SELECT id, name, creator, creator_id, metadata_id, status "
                f"FROM media WHERE id IN ({placeholders})",
                ids,
            ).fetchall()
        return {
            row[0]: proto.Media(
                id=row[0],
                name=row[1],
                creator=row[2],
                creatorId=row[3],
                metadataId=row[4],
                status=row[5],
            )
            for row in rows
        }

    def close(self) -> None:
        self._conn.close()
