"""A minimal in-process PostgreSQL wire-protocol server.

Speaks the v3 protocol subset :mod:`beholder_tpu.storage.pg_wire` uses —
startup, SCRAM-SHA-256 (or cleartext) auth, extended query
(Parse/Bind/Describe/Execute/Sync), simple query — so the from-scratch
client and :class:`PostgresStorage` are tested end-to-end over real TCP
sockets without a Postgres install, exactly like
:mod:`beholder_tpu.mq.server` does for AMQP.

The "SQL engine" executes the fixed statement shapes PostgresStorage
issues (CREATE TABLE / INSERT ... ON CONFLICT / UPDATE / SELECT) against
an in-memory dict; anything unrecognized gets a real ErrorResponse with
SQLSTATE 42601, which doubles as the client's error-path test surface.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import re
import socketserver
import struct
import threading
import time

SCRAM_ITERATIONS = 4096


class _PgMetrics:
    """Prometheus instrumentation for the test server (extension
    surface: registered only when a registry is handed to
    :class:`PgTestServer`, so the reference exposition stays
    byte-identical). Query timings are labelled by statement kind,
    auth timings by outcome — SCRAM's 4096 PBKDF2 iterations make
    auth a visible slice of short-lived-connection workloads."""

    #: sub-ms dict lookups up to PBKDF2-bound auth handshakes
    BUCKETS = (
        1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
        1e-2, 2.5e-2, 0.1,
    )

    def __init__(self, registry):
        from beholder_tpu.metrics import get_or_create

        self.query_seconds = get_or_create(
            registry, "histogram",
            "beholder_pg_query_seconds",
            "Statement execution wall time by statement kind",
            labelnames=["stmt"],
            buckets=self.BUCKETS,
        )
        self.auth_seconds = get_or_create(
            registry, "histogram",
            "beholder_pg_auth_seconds",
            "SCRAM-SHA-256 handshake wall time by outcome",
            labelnames=["outcome"],
            buckets=self.BUCKETS,
        )


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack(">I", len(payload) + 4) + payload


def _error(code: str, message: str) -> bytes:
    payload = (
        b"S" + _cstr("ERROR") + b"C" + _cstr(code) + b"M" + _cstr(message) + b"\x00"
    )
    return _msg(b"E", payload)


def _ready() -> bytes:
    return _msg(b"Z", b"I")


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):  # noqa: C901 - one protocol loop, clearer flat
        server: PgTestServer = self.server.owner  # type: ignore[attr-defined]
        sock = self.request
        server.active.add(sock)
        try:
            self._serve(server, sock)
        finally:
            server.active.discard(sock)

    def _serve(self, server: "PgTestServer", sock):
        buf = b""

        def need(n):
            nonlocal buf
            while len(buf) < n:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk

        def take(n):
            nonlocal buf
            need(n)
            out, buf = buf[:n], buf[n:]
            return out

        try:
            # startup (untagged)
            (length,) = struct.unpack(">I", take(4))
            startup = take(length - 4)
            (version,) = struct.unpack(">I", startup[:4])
            if version != 196608:
                sock.sendall(_error("08P01", f"bad protocol {version}"))
                return
            kv = startup[4:].split(b"\x00")
            params = dict(zip(kv[0:-2:2], kv[1:-2:2]))
            user = params.get(b"user", b"").decode()

            if server.password:
                if not self._auth_scram(sock, take, server, user):
                    return
            sock.sendall(_msg(b"R", struct.pack(">I", 0)))  # AuthenticationOk
            sock.sendall(_msg(b"S", _cstr("server_version") + _cstr("16.0-bh")))
            sock.sendall(_ready())

            pending_sql: str | None = None
            pending_params: tuple = ()
            while True:
                tag = take(1)
                (length,) = struct.unpack(">I", take(4))
                payload = take(length - 4)
                if tag == b"X":
                    return
                if tag == b"Q":
                    sql = payload.rstrip(b"\x00").decode()
                    sock.sendall(server.run_sql(sql, ()))
                    sock.sendall(_ready())
                elif tag == b"P":  # Parse: name, sql, n param types
                    end = payload.index(b"\x00")
                    sql_end = payload.index(b"\x00", end + 1)
                    pending_sql = payload[end + 1 : sql_end].decode()
                    sock.sendall(_msg(b"1", b""))
                elif tag == b"B":  # Bind
                    pos = payload.index(b"\x00") + 1
                    pos = payload.index(b"\x00", pos) + 1
                    (nfmt,) = struct.unpack(">H", payload[pos : pos + 2])
                    pos += 2 + 2 * nfmt
                    (nparams,) = struct.unpack(">H", payload[pos : pos + 2])
                    pos += 2
                    values = []
                    for _ in range(nparams):
                        (ln,) = struct.unpack(">i", payload[pos : pos + 4])
                        pos += 4
                        if ln == -1:
                            values.append(None)
                        else:
                            values.append(payload[pos : pos + ln].decode())
                            pos += ln
                    pending_params = tuple(values)
                    sock.sendall(_msg(b"2", b""))
                elif tag == b"D":
                    pass  # row description is sent with Execute
                elif tag == b"E":
                    sock.sendall(server.run_sql(pending_sql or "", pending_params))
                elif tag == b"S":
                    sock.sendall(_ready())
                elif tag == b"p":
                    sock.sendall(_error("08P01", "unexpected password message"))
                # ignore anything else
        except ConnectionError:
            return

    def _auth_scram(self, sock, take, server: "PgTestServer", user: str) -> bool:
        t0 = time.perf_counter()
        ok = self._auth_scram_inner(sock, take, server, user)
        if server._metrics is not None:
            server._metrics.auth_seconds.observe(
                time.perf_counter() - t0,
                outcome="ok" if ok else "failed",
            )
        return ok

    def _auth_scram_inner(
        self, sock, take, server: "PgTestServer", user: str
    ) -> bool:
        sock.sendall(
            _msg(b"R", struct.pack(">I", 10) + _cstr("SCRAM-SHA-256") + b"\x00")
        )
        tag = take(1)
        (length,) = struct.unpack(">I", take(4))
        payload = take(length - 4)
        if tag != b"p":
            sock.sendall(_error("28000", "expected SASLInitialResponse"))
            return False
        mech_end = payload.index(b"\x00")
        if payload[:mech_end] != b"SCRAM-SHA-256":
            sock.sendall(_error("28000", "unsupported mechanism"))
            return False
        (resp_len,) = struct.unpack(">I", payload[mech_end + 1 : mech_end + 5])
        client_first = payload[mech_end + 5 : mech_end + 5 + resp_len].decode()
        first_bare = client_first.split(",", 2)[2]
        client_nonce = dict(
            f.split("=", 1) for f in first_bare.split(",")
        )["r"]

        salt = server._scram_salt
        srv_nonce = client_nonce + base64.b64encode(os.urandom(9)).decode()
        server_first = (
            f"r={srv_nonce},s={base64.b64encode(salt).decode()},i={SCRAM_ITERATIONS}"
        )
        sock.sendall(
            _msg(b"R", struct.pack(">I", 11) + server_first.encode())
        )

        tag = take(1)
        (length,) = struct.unpack(">I", take(4))
        final = take(length - 4).decode()
        if tag != b"p":
            sock.sendall(_error("28000", "expected SASLResponse"))
            return False
        ffields = dict(f.split("=", 1) for f in final.split(","))
        proof = base64.b64decode(ffields["p"])
        final_wo_proof = final[: final.rindex(",p=")]
        auth_message = ",".join([first_bare, server_first, final_wo_proof]).encode()

        salted = hashlib.pbkdf2_hmac(
            "sha256", server.password.encode(), salt, SCRAM_ITERATIONS
        )
        client_key = hmac.digest(salted, b"Client Key", "sha256")
        stored_key = hashlib.sha256(client_key).digest()
        signature = hmac.digest(stored_key, auth_message, "sha256")
        recovered = bytes(a ^ b for a, b in zip(proof, signature))
        if (
            ffields.get("r") != srv_nonce
            or hashlib.sha256(recovered).digest() != stored_key
        ):
            sock.sendall(_error("28P01", f'password authentication failed for "{user}"'))
            return False

        server_key = hmac.digest(salted, b"Server Key", "sha256")
        server_sig = hmac.digest(server_key, auth_message, "sha256")
        sasl_final = f"v={base64.b64encode(server_sig).decode()}"
        sock.sendall(_msg(b"R", struct.pack(">I", 12) + sasl_final.encode()))
        return True


class PgTestServer:
    """In-process Postgres-wire server over an in-memory media table."""

    COLUMNS = ("id", "name", "creator", "creator_id", "metadata_id", "status")

    def __init__(self, password: str = "", metrics=None):
        #: empty password = trust auth; non-empty = SCRAM-SHA-256
        self.password = password
        #: optional Registry (or Metrics) for query/auth timing series
        self._metrics = (
            _PgMetrics(getattr(metrics, "registry", metrics))
            if metrics is not None
            else None
        )
        self._scram_salt = os.urandom(16)
        self.rows: dict[str, dict] = {}
        self.queries: list[tuple[str, tuple]] = []  # for assertions
        self.active: set = set()  # live client sockets, killed on stop()
        self._server: socketserver.ThreadingTCPServer | None = None
        self.port: int | None = None

    # -- lifecycle ----------------------------------------------------------
    def start(self, port: int = 0) -> int:
        """Listen on ``port`` (0 = ephemeral). Restarting on the same port
        after :meth:`stop` keeps ``rows`` — the crash-recovery tests kill
        and resurrect the server while clients reconnect."""

        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True  # same-port restart right after stop

        srv = _Srv(("127.0.0.1", port), _Handler)
        srv.daemon_threads = True
        srv.owner = self  # type: ignore[attr-defined]
        self._server = srv
        self.port = srv.server_address[1]
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return self.port

    def stop(self) -> None:
        """Stop listening AND sever every live connection (a real crash
        doesn't let handler threads keep answering)."""
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        for sock in list(self.active):
            try:
                sock.shutdown(2)  # SHUT_RDWR: wake any blocked recv
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def url(self, user: str = "beholder") -> str:
        auth = f"{user}:{self.password}@" if self.password else f"{user}@"
        return f"postgres://{auth}127.0.0.1:{self.port}/events"

    # -- the "SQL engine" ---------------------------------------------------
    def run_sql(self, sql: str, params: tuple) -> bytes:
        t0 = time.perf_counter()
        out, stmt = self._run_sql(sql, params)
        if self._metrics is not None:
            self._metrics.query_seconds.observe(
                time.perf_counter() - t0, stmt=stmt
            )
        return out

    def _run_sql(self, sql: str, params: tuple) -> tuple[bytes, str]:
        self.queries.append((sql, params))
        flat = " ".join(sql.split())
        try:
            if flat.upper().startswith("CREATE TABLE"):
                return _msg(b"C", _cstr("CREATE TABLE")), "create"
            # transaction statements: the batched-ingest storage hop
            # wraps a drained batch's updates in BEGIN/COMMIT (one
            # commit per batch); the in-memory engine applies rows
            # eagerly, so the control statements just tag-acknowledge
            # (the semantics the idempotent UPDATE replay relies on)
            if flat.upper() in ("BEGIN", "COMMIT", "ROLLBACK"):
                return _msg(b"C", _cstr(flat.upper())), "txn"
            if flat.startswith("INSERT INTO media"):
                row = dict(zip(self.COLUMNS, params))
                self.rows[row["id"]] = row
                return _msg(b"C", _cstr("INSERT 0 1")), "insert"
            if flat.startswith("UPDATE media SET status"):
                status, media_id = params
                row = self.rows.get(media_id)
                if row is None:
                    return _msg(b"C", _cstr("UPDATE 0")), "update"
                row["status"] = status
                return _msg(b"C", _cstr("UPDATE 1")), "update"
            m = re.match(r"SELECT (.+) FROM media WHERE id = \$1", flat)
            if m:
                cols = [c.strip() for c in m.group(1).split(",")]
                row = self.rows.get(params[0])
                out = self._row_description(cols)
                n = 0
                if row is not None:
                    out += self._data_row([row.get(c) for c in cols])
                    n = 1
                return out + _msg(b"C", _cstr(f"SELECT {n}")), "select"
            return (
                _error("42601", f"unrecognized statement: {flat[:80]}"),
                "unrecognized",
            )
        except Exception as err:  # noqa: BLE001 - report, don't die
            return _error("XX000", repr(err)), "error"

    def _row_description(self, cols) -> bytes:
        body = struct.pack(">H", len(cols))
        for c in cols:
            body += _cstr(c) + struct.pack(">IHIHiH", 0, 0, 25, 0xFFFF, -1, 0)
        return _msg(b"T", body)

    def _data_row(self, values) -> bytes:
        body = struct.pack(">H", len(values))
        for v in values:
            if v is None:
                body += struct.pack(">i", -1)
            else:
                raw = str(v).encode()
                body += struct.pack(">I", len(raw)) + raw
        return _msg(b"D", body)
