"""Memoized storage reads with writer-side invalidation.

EXTENSION BEYOND THE REFERENCE (the reference queries Postgres on every
message — index.js:76,140). :class:`CachingStorage` wraps any
:class:`~beholder_tpu.storage.base.Storage` backend and serves
``get_by_id`` from a TTL'd keyed cache
(:class:`beholder_tpu.cache.KeyedCache`):

- **Writer-side invalidation.** ``add_media`` / ``update_status`` write
  through to the backend, then invalidate the row's cache entry — the
  next read observes the write. The status consumer's own
  read-after-write (update_status -> get_by_id, index.js:68,76) is
  therefore never stale, while the progress consumer's pure reads (the
  hot path: one ``get_by_id`` per progress message, for rows that
  change only on status transitions) collapse onto the cache.
- **TTL bound on external writers.** A row changed by a DIFFERENT
  process (this service is not the only Postgres client in the triton
  stack) is stale for at most ``ttl_s``.
- **Singleflight.** Concurrent misses on one id issue ONE backend
  query; :class:`~beholder_tpu.storage.base.MediaNotFound` propagates
  to every collapsed caller and is never cached (a row inserted a
  moment later must be findable).

The service wires this behind ``instance.cache.storage`` (off unless
``instance.cache.enabled``); constructed directly it works over any
backend (the Postgres query-cache tests run it against the real wire
client + PgTestServer).
"""

from __future__ import annotations

from beholder_tpu import proto
from beholder_tpu.cache import KeyedCache

from .base import Storage


class CachingStorage(Storage):
    """Read-through cache over a ``Storage`` backend."""

    def __init__(
        self,
        inner: Storage,
        ttl_s: float = 30.0,
        max_entries: int = 1024,
        metrics=None,
        clock=None,
    ):
        self.inner = inner
        kwargs = {"clock": clock} if clock is not None else {}
        self._cache = KeyedCache(
            "storage.media",
            max_entries=max_entries,
            policy="ttl",
            ttl_s=ttl_s,
            metrics=metrics,
            **kwargs,
        )

    @property
    def cache(self) -> KeyedCache:
        return self._cache

    def add_media(self, media: proto.Media) -> None:
        self.inner.add_media(media)
        self._cache.invalidate(media.id)

    def update_status(self, media_id: str, status: int) -> None:
        self.inner.update_status(media_id, status)
        self._cache.invalidate(media_id)

    def update_status_batch(
        self, updates: list[tuple[str, int]]
    ) -> list[bool]:
        """The batched-ingest write hop, FORWARDED to the backend's
        one-transaction implementation with write-through invalidation
        per touched row — the base-class default would fall back to
        the per-row loop, silently unfolding exactly the transaction
        the native ingest path batched (the ROADMAP item-4 leftover).
        Rows invalidate whether found or not: a row inserted between
        this write and the next read must never be shadowed by a
        cached MISS-era value, and invalidating an absent key is
        free."""
        found = self.inner.update_status_batch(updates)
        for media_id, _ in updates:
            self._cache.invalidate(media_id)
        return found

    def get_by_id(self, media_id: str) -> proto.Media:
        # a defensive copy per call: Media is a mutable protobuf and a
        # caller mutating the returned row must not poison the cache
        row = self._cache.get_or_load(
            media_id, lambda: self.inner.get_by_id(media_id)
        )
        clone = proto.Media()
        clone.CopyFrom(row)
        return clone

    def get_by_ids(self, media_ids) -> dict[str, proto.Media]:
        """The batched-ingest read hop: cached rows serve from memory,
        the MISSES fetch in ONE backend ``get_by_ids`` round trip (the
        base default would loop ``get_by_id`` per id — correct, but
        per-row again), and every fetched row populates the cache for
        the per-message handlers that re-read it. Defensive copies
        both ways, same contract as :meth:`get_by_id`: the caller's
        mutations must not poison the cache, and missing ids are
        simply absent."""
        out: dict[str, proto.Media] = {}
        misses: list[str] = []
        for media_id in media_ids:
            row = self._cache.get(media_id)
            if row is None:
                misses.append(media_id)
            else:
                clone = proto.Media()
                clone.CopyFrom(row)
                out[media_id] = clone
        if misses:
            fetched = self.inner.get_by_ids(misses)
            for media_id, row in fetched.items():
                cached = proto.Media()
                cached.CopyFrom(row)
                self._cache.put(media_id, cached)
                clone = proto.Media()
                clone.CopyFrom(row)
                out[media_id] = clone
        return out

    def invalidate(self, media_id: str) -> None:
        """Explicit invalidation hook for out-of-band writers."""
        self._cache.invalidate(media_id)

    def close(self) -> None:
        self.inner.close()
