"""Postgres-backed Storage on the from-scratch wire client.

The reference's production storage (index.js:19,42 via triton-core's
``pg``). Same three-method contract as every backend here; the table is
reconstructed from the fields the reference reads/writes
(index.js:64,68,74-118,131-148: id, name, creator, creatorId,
metadataId, status).
"""

from __future__ import annotations

from beholder_tpu import proto

from .base import MediaNotFound, Storage
from .pg_wire import PgConnection

_SCHEMA = """
CREATE TABLE IF NOT EXISTS media (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL DEFAULT '',
    creator INT NOT NULL DEFAULT 0,
    creator_id TEXT NOT NULL DEFAULT '',
    metadata_id TEXT NOT NULL DEFAULT '',
    status INT NOT NULL DEFAULT 0
)
"""


class PostgresStorage(Storage):
    """``Storage`` over a real Postgres (or wire-compatible) server."""

    def __init__(self, url: str, connect_timeout: float = 10.0):
        self._conn = PgConnection(url, connect_timeout=connect_timeout)
        self._conn.connect()
        self._conn.execute(_SCHEMA)

    def add_media(self, media: proto.Media) -> None:
        self._conn.query(
            "INSERT INTO media (id, name, creator, creator_id, metadata_id, status) "
            "VALUES ($1, $2, $3, $4, $5, $6) "
            "ON CONFLICT (id) DO UPDATE SET name = $2, creator = $3, "
            "creator_id = $4, metadata_id = $5, status = $6",
            (
                media.id,
                media.name,
                int(media.creator),
                media.creatorId,
                media.metadataId,
                int(media.status),
            ),
        )

    def update_status(self, media_id: str, status: int) -> None:
        _, _, tag = self._conn.query(
            "UPDATE media SET status = $1 WHERE id = $2", (int(status), media_id)
        )
        if tag.endswith(" 0"):  # "UPDATE 0" — no row matched
            raise MediaNotFound(media_id)

    def get_by_id(self, media_id: str) -> proto.Media:
        _, rows, _ = self._conn.query(
            "SELECT id, name, creator, creator_id, metadata_id, status "
            "FROM media WHERE id = $1",
            (media_id,),
        )
        if not rows:
            raise MediaNotFound(media_id)
        row = rows[0]
        return proto.Media(
            id=row[0] or "",
            name=row[1] or "",
            creator=int(row[2] or 0),
            creatorId=row[3] or "",
            metadataId=row[4] or "",
            status=int(row[5] or 0),
        )

    def close(self) -> None:
        self._conn.close()
