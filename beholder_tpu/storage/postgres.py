"""Postgres-backed Storage on the from-scratch wire client.

The reference's production storage (index.js:19,42 via triton-core's
``pg``). Same three-method contract as every backend here; the table is
reconstructed from the fields the reference reads/writes
(index.js:64,68,74-118,131-148: id, name, creator, creatorId,
metadataId, status).

Elastic recovery: when the wire client poisons its connection (server
restart, network fault — any :class:`ProtocolError`), the storage
reconnects with bounded exponential backoff and re-runs the statement,
mirroring the AMQP client's reconnect design (``mq/amqp.py``). Retrying
is safe because every statement here is idempotent: the upsert, the
absolute status UPDATE, and the SELECT all converge on re-execution.
"""

from __future__ import annotations

import time

from beholder_tpu import proto

from .base import MediaNotFound, Storage
from .pg_wire import PgConnection, ProtocolError

_SCHEMA = """
CREATE TABLE IF NOT EXISTS media (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL DEFAULT '',
    creator INT NOT NULL DEFAULT 0,
    creator_id TEXT NOT NULL DEFAULT '',
    metadata_id TEXT NOT NULL DEFAULT '',
    status INT NOT NULL DEFAULT 0
)
"""


class PostgresStorage(Storage):
    """``Storage`` over a real Postgres (or wire-compatible) server."""

    def __init__(
        self,
        url: str,
        connect_timeout: float = 10.0,
        reconnect_attempts: int = 3,
        reconnect_delay: float = 0.05,
    ):
        self._conn = PgConnection(url, connect_timeout=connect_timeout)
        self._attempts = reconnect_attempts
        self._delay = reconnect_delay
        self._connect()

    def _connect(self) -> None:
        self._conn.connect()
        self._conn.execute(_SCHEMA)  # idempotent; safe on every reconnect

    def _run(self, fn):
        """Run a statement; on a poisoned connection, reconnect with
        bounded exponential backoff and re-run (statements here are all
        idempotent — see module docstring)."""
        try:
            return fn()
        except ProtocolError as err:
            last: Exception = err
        for attempt in range(self._attempts):
            time.sleep(self._delay * (2**attempt))
            try:
                self._conn.close()
                self._connect()
                return fn()
            except (ProtocolError, OSError) as err:
                last = err
        raise last

    def add_media(self, media: proto.Media) -> None:
        self._run(lambda: self._query_add(media))

    def _query_add(self, media: proto.Media) -> None:
        self._conn.query(
            "INSERT INTO media (id, name, creator, creator_id, metadata_id, status) "
            "VALUES ($1, $2, $3, $4, $5, $6) "
            "ON CONFLICT (id) DO UPDATE SET name = $2, creator = $3, "
            "creator_id = $4, metadata_id = $5, status = $6",
            (
                media.id,
                media.name,
                int(media.creator),
                media.creatorId,
                media.metadataId,
                int(media.status),
            ),
        )

    def update_status(self, media_id: str, status: int) -> None:
        _, _, tag = self._run(
            lambda: self._conn.query(
                "UPDATE media SET status = $1 WHERE id = $2",
                (int(status), media_id),
            )
        )
        if tag.endswith(" 0"):  # "UPDATE 0" — no row matched
            raise MediaNotFound(media_id)

    def update_status_batch(
        self, updates: list[tuple[str, int]]
    ) -> list[bool]:
        """One BEGIN/COMMIT per drained ingest batch instead of one
        autocommit per message. Statements run in order inside the
        transaction; per-row "UPDATE 0" tags become found flags (the
        MediaNotFound outcomes the per-message loop produces). The
        whole batch shares one :meth:`_run` retry scope — absolute
        status updates are idempotent, so a reconnect replays the batch
        safely."""

        def run() -> list[bool]:
            self._conn.execute("BEGIN")
            try:
                found: list[bool] = []
                for media_id, status in updates:
                    _, _, tag = self._conn.query(
                        "UPDATE media SET status = $1 WHERE id = $2",
                        (int(status), media_id),
                    )
                    found.append(not tag.endswith(" 0"))
            except BaseException:
                # roll back best-effort; a poisoned connection is
                # handled (and the batch replayed) by _run's reconnect
                try:
                    self._conn.execute("ROLLBACK")
                except ProtocolError:
                    pass
                raise
            self._conn.execute("COMMIT")
            return found

        return self._run(run)

    def get_by_id(self, media_id: str) -> proto.Media:
        _, rows, _ = self._run(
            lambda: self._conn.query(
                "SELECT id, name, creator, creator_id, metadata_id, status "
                "FROM media WHERE id = $1",
                (media_id,),
            )
        )
        if not rows:
            raise MediaNotFound(media_id)
        row = rows[0]
        return proto.Media(
            id=row[0] or "",
            name=row[1] or "",
            creator=int(row[2] or 0),
            creatorId=row[3] or "",
            metadataId=row[4] or "",
            status=int(row[5] or 0),
        )

    def close(self) -> None:
        self._conn.close()
