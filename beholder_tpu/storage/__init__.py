"""Media storage — the triton-core Storage contract.

The reference calls exactly two methods (``db.updateStatus(mediaId, status)``
at /root/reference/index.js:68 and ``db.getByID(mediaId)`` at
index.js:76,140) against an external Postgres. Backends here:

- :class:`MemoryStorage` — dict-backed, for tests.
- :class:`SqliteStorage` — durable default (psycopg2 is not in this image;
  a Postgres backend is gated behind :func:`postgres_storage`).

Rows are surfaced as ``api.Media`` protobuf messages so handler attribute
access (``media.creator``, ``media.creatorId``, ...) matches the reference.
"""

from .base import MediaNotFound, MemoryStorage, Storage, postgres_storage
from .sqlite import SqliteStorage

__all__ = [
    "Storage",
    "MemoryStorage",
    "SqliteStorage",
    "MediaNotFound",
    "postgres_storage",
]
