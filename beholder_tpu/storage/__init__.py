"""Media storage — the triton-core Storage contract.

The reference calls exactly two methods (``db.updateStatus(mediaId, status)``
at /root/reference/index.js:68 and ``db.getByID(mediaId)`` at
index.js:76,140) against an external Postgres. Backends here:

- :class:`MemoryStorage` — dict-backed, for tests.
- :class:`SqliteStorage` — durable single-file default.
- :class:`PostgresStorage` — the reference's production shape, over a
  from-scratch v3 wire-protocol client (:mod:`.pg_wire`; no Postgres
  driver exists in this image, so the transport is built from the spec,
  like the AMQP stack). Tested against :class:`.pg_server.PgTestServer`
  over real sockets.
- :class:`CachingStorage` — read-through TTL cache over any backend
  with writer-side invalidation + singleflight (:mod:`.cached`; the
  cache subsystem's storage wiring, ``instance.cache.storage``).

Rows are surfaced as ``api.Media`` protobuf messages so handler attribute
access (``media.creator``, ``media.creatorId``, ...) matches the reference.
"""

from .base import MediaNotFound, MemoryStorage, Storage, postgres_storage
from .cached import CachingStorage
from .postgres import PostgresStorage
from .sqlite import SqliteStorage

__all__ = [
    "Storage",
    "MemoryStorage",
    "SqliteStorage",
    "PostgresStorage",
    "CachingStorage",
    "MediaNotFound",
    "postgres_storage",
]
