"""The reliability subsystem's metric catalog.

Extension surface like the serving/broker instrumentation: nothing is
registered unless a component is handed a registry, so the reference
exposition stays byte-identical by default (pinned by
``tests/test_observability.py``). Every series uses
:func:`~beholder_tpu.metrics.get_or_create`, so retry policies,
breakers, consumers, and shedders sharing one registry share one set of
series instead of tripping the duplicate guard.

Catalog (all appear only when a reliability component gets a registry):

- ``beholder_retry_attempts_total{op}`` — re-attempts (not first tries)
- ``beholder_retry_give_ups_total{op, reason}`` — retry loops abandoned
  (``attempts`` / ``budget`` / ``deadline``)
- ``beholder_breaker_state{breaker}`` — 0 closed, 1 half-open, 2 open
- ``beholder_breaker_transitions_total{breaker, state}`` — transitions
  INTO each state
- ``beholder_breaker_rejections_total{breaker}`` — fast-failed calls
- ``beholder_dead_lettered_total{queue, reason}`` — messages parked
  (``max-retries`` consumer-side; ``rejected``/``expired`` broker-side)
- ``beholder_dedup_hits_total{topic}`` — redeliveries skipped by the
  idempotency window
"""

from __future__ import annotations

from beholder_tpu.metrics import get_or_create

#: numeric encoding of breaker states for the state gauge
STATE_VALUES = {"closed": 0, "half_open": 1, "open": 2}


class ReliabilityMetrics:
    """One bundle of the catalog above, find-or-registered on a shared
    registry (a :class:`~beholder_tpu.metrics.Registry`, or a
    :class:`~beholder_tpu.metrics.Metrics` whose registry is used)."""

    def __init__(self, registry):
        registry = getattr(registry, "registry", registry)
        self.registry = registry
        self.retry_attempts_total = get_or_create(
            registry, "counter",
            "beholder_retry_attempts_total",
            "Retry re-attempts by operation (first tries not counted)",
            labelnames=["op"],
        )
        self.retry_give_ups_total = get_or_create(
            registry, "counter",
            "beholder_retry_give_ups_total",
            "Retry loops abandoned, by operation and reason "
            "(attempts/budget/deadline)",
            labelnames=["op", "reason"],
        )
        self.breaker_state = get_or_create(
            registry, "gauge",
            "beholder_breaker_state",
            "Circuit breaker state (0 closed, 1 half-open, 2 open)",
            labelnames=["breaker"],
        )
        self.breaker_transitions_total = get_or_create(
            registry, "counter",
            "beholder_breaker_transitions_total",
            "Circuit breaker transitions into each state",
            labelnames=["breaker", "state"],
        )
        self.breaker_rejections_total = get_or_create(
            registry, "counter",
            "beholder_breaker_rejections_total",
            "Calls fast-failed because the breaker was open",
            labelnames=["breaker"],
        )
        self.dead_lettered_total = get_or_create(
            registry, "counter",
            "beholder_dead_lettered_total",
            "Messages parked on a dead-letter queue, by source queue and "
            "reason (max-retries/rejected/expired)",
            labelnames=["queue", "reason"],
        )
        self.dedup_hits_total = get_or_create(
            registry, "counter",
            "beholder_dedup_hits_total",
            "Redeliveries skipped by the idempotency window (already "
            "handled before the broker lost the ack)",
            labelnames=["topic"],
        )
