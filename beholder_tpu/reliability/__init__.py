"""Reliability subsystem: retries, circuit breakers, dead-letter
queues, and load shedding.

The measurement layer (PR 1) made failure visible; this package makes
the system survive it. Four composable pieces, each wired through the
I/O layer it protects:

- :mod:`.policy` — retry policies (bounded exponential backoff, full
  jitter, retry budgets) and deadline propagation.
- :mod:`.breaker` — a closed/open/half-open circuit breaker and the
  :class:`~.breaker.ResilientTransport` that puts it (plus retries and
  deadlines) in front of every outbound HTTP client.
- :mod:`.dlq` — consumer-side at-least-once delivery: bounded
  redelivery then dead-letter parking, with an idempotency window so
  redeliveries stay effectively-once. (Broker-side DLQ routing and
  message TTL live with the brokers in :mod:`beholder_tpu.mq`.)
- :mod:`.shed` — admission control: a bounded intake queue for the
  serving scheduler that sheds load with an explicit rejection outcome.

:mod:`.chaos` is the deterministic fault-injection harness the tests
drive; :mod:`.instruments` is the shared metric catalog (registered
only on request, so the reference exposition stays byte-identical).

Everything is opt-in: the service enables the consumer/transport story
behind ``instance.reliability.enabled`` (see ``service.py``), the
batcher takes an :class:`~.shed.IntakeQueue` explicitly.
"""

from .breaker import (
    BreakerOpenError,
    CircuitBreaker,
    ResilientTransport,
)
from .chaos import (
    FlakyHandler,
    FlakyTransport,
    drop_broker_connections,
    trip_allocator,
)
from .dlq import ReliableConsumer, default_dlq_topic
from .instruments import ReliabilityMetrics
from .policy import (
    Deadline,
    DeadlineExceeded,
    RetryBudget,
    RetryPolicy,
    current_deadline,
    deadline_scope,
)
from .shed import Admission, IntakeQueue, LoadShedError

__all__ = [
    "Admission",
    "BreakerOpenError",
    "CircuitBreaker",
    "Deadline",
    "DeadlineExceeded",
    "FlakyHandler",
    "FlakyTransport",
    "IntakeQueue",
    "LoadShedError",
    "ReliabilityMetrics",
    "ReliableConsumer",
    "ResilientTransport",
    "RetryBudget",
    "RetryPolicy",
    "current_deadline",
    "deadline_scope",
    "default_dlq_topic",
    "drop_broker_connections",
    "trip_allocator",
]
