"""Fault injection: the harness the reliability tests drive.

Chaos here is DETERMINISTIC and in-process — scripts, not randomness —
so every failure mode the subsystem claims to survive has a test that
injects exactly that failure:

- :class:`FlakyTransport` — scriptable HTTP faults: fail the next N
  requests (exception or 5xx status), add latency, or fail by
  predicate. Wraps any transport; drives breaker/retry tests and the
  Emby-outage leg of the chaos acceptance test.
- :class:`FlakyHandler` — a consumer handler that raises on its first N
  deliveries of each message, then delegates; drives
  redelivery/DLQ-parking tests.
- :func:`drop_broker_connections` — kills every client connection on an
  :class:`~beholder_tpu.mq.server.AmqpTestServer` mid-flight (the
  reconnect/redelivery leg).
- :func:`trip_allocator` — forces the paged serving state's sticky
  ``alloc_failed`` flag, exercising the scheduler's poisoning path
  without crafting a real pool exhaustion.
- :class:`WorkerFault` + :func:`inject_worker_fault` — cluster-serving
  faults on a failover-armed
  :class:`~beholder_tpu.cluster.router.ClusterScheduler`: ``kill`` a
  decode shard or prefill worker mid-dispatch (a typed
  ``WorkerKilled`` after N successful dispatches — genuinely
  mid-stream), ``hang`` one (heartbeats freeze; the monitor condemns
  it), or corrupt the next N page ``transfer``\\ s (absorbed by the
  transfer engine's bounded retry, or surfaced as a terminal
  ``TransferFailed`` the router recovers from).

Everything lives behind explicit calls; importing this module injects
nothing.
"""

from __future__ import annotations

import threading
import time

from beholder_tpu.clients.http import HttpResponse, HttpTransport
from beholder_tpu.log import get_logger


class FlakyTransport(HttpTransport):
    """Deterministic fault-injecting wrapper over any transport."""

    def __init__(self, inner: HttpTransport, logger=None):
        self.inner = inner
        self._lock = threading.Lock()
        self._fail_next = 0
        self._fail_exc: Exception | None = None
        self._fail_status: int | None = None
        self.delay_s = 0.0
        self.fail_predicate = None  # (method, url) -> bool
        self.requests_seen = 0
        self.faults_injected = 0
        self._log = logger or get_logger("reliability.chaos")

    def fail_next(
        self,
        n: int,
        exc: Exception | None = None,
        status: int | None = None,
    ) -> None:
        """Script the next ``n`` requests to fail — with ``exc`` (default
        ``ConnectionError``) or, if ``status`` is given, with a real
        response carrying that status instead of an exception."""
        with self._lock:
            self._fail_next = int(n)
            self._fail_exc = exc
            self._fail_status = status

    def request(self, method, url, *, params=None, json=None, timeout=10.0,
                headers=None):
        if self.delay_s:
            time.sleep(self.delay_s)
        with self._lock:
            self.requests_seen += 1
            inject = self._fail_next > 0
            if inject:
                self._fail_next -= 1
            status = self._fail_status
            exc = self._fail_exc
        if not inject and self.fail_predicate is not None:
            inject = bool(self.fail_predicate(method, url))
        if inject:
            self.faults_injected += 1
            if status is not None:
                return HttpResponse(status=status, body={"chaos": True})
            raise exc if exc is not None else ConnectionError(
                "chaos: injected transport fault"
            )
        # headers forwarded only when set: duck-typed transports
        # predating the headers kwarg keep working headerless
        extra = {"headers": headers} if headers is not None else {}
        return self.inner.request(
            method, url, params=params, json=json, timeout=timeout,
            **extra,
        )


class FlakyHandler:
    """A consumer handler that raises on the first ``fail_times``
    deliveries of EACH distinct body, then delegates to ``inner``.
    Mirrors a handler whose downstream dependency recovers."""

    def __init__(self, inner, fail_times: int, exc: Exception | None = None):
        self.inner = inner
        self.fail_times = int(fail_times)
        self.exc = exc
        self.failures: dict[bytes, int] = {}

    def __call__(self, delivery) -> None:
        seen = self.failures.get(delivery.body, 0)
        if seen < self.fail_times:
            self.failures[delivery.body] = seen + 1
            raise (
                self.exc
                if self.exc is not None
                else RuntimeError("chaos: injected handler fault")
            )
        self.inner(delivery)


def drop_broker_connections(server) -> None:
    """Abort every client connection on an AmqpTestServer — unacked
    deliveries requeue (redelivered=1) and clients must reconnect."""
    server.drop_all_connections()


#: cluster worker-fault kinds
WORKER_KILL = "kill"
WORKER_HANG = "hang"
WORKER_TRANSFER_CORRUPTION = "transfer_corruption"


class WorkerFault:
    """A declarative, deterministic cluster worker fault.

    - ``kill``: the worker's dispatch entry point (the decode shard's
      tick program / the prefill worker's forward) raises a typed
      ``WorkerKilled`` after ``after_dispatches`` SUCCESSFUL calls —
      a mid-stream death, not a refusal to start.
    - ``hang``: the worker's heartbeats freeze; the failover monitor's
      next sweep marks it down once the beat is stale past the
      configured miss window.
    - ``transfer_corruption``: the next ``transfer_failures`` page
      transfers through the cluster's
      :class:`~beholder_tpu.cluster.transfer.PageTransferEngine` fail
      — below the retry budget the hop self-heals, at/above it the
      terminal ``TransferFailed`` drives shard-level recovery.
    """

    def __init__(
        self,
        worker: str,
        kind: str = WORKER_KILL,
        after_dispatches: int = 0,
        transfer_failures: int = 3,
    ):
        if kind not in (
            WORKER_KILL, WORKER_HANG, WORKER_TRANSFER_CORRUPTION
        ):
            raise ValueError(f"unknown worker-fault kind {kind!r}")
        self.worker = worker
        self.kind = kind
        self.after_dispatches = int(after_dispatches)
        self.transfer_failures = int(transfer_failures)


def inject_worker_fault(scheduler, fault: WorkerFault) -> None:
    """Arm ``fault`` on a failover-enabled
    :class:`~beholder_tpu.cluster.router.ClusterScheduler`. Raises
    unless ``instance.cluster.failover`` is armed — without the
    recovery machinery a faulted cluster just dies, which is the
    fail-stop behavior the tests for THAT mode inject directly."""
    engine = getattr(scheduler, "failover", None)
    if engine is None:
        raise RuntimeError(
            "worker faults need a failover-armed cluster — build the "
            "ClusterScheduler with ClusterConfig(failover="
            "FailoverConfig(...))"
        )
    engine.inject_fault(fault)


def trip_allocator(batcher) -> None:
    """Force the paged pool's sticky ``alloc_failed`` flag on a
    :class:`~beholder_tpu.models.serving.ContinuousBatcher`: the next
    checked scheduler call must surface the allocator error instead of
    returning silently-wrong results."""
    import jax.numpy as jnp

    batcher.state = batcher.state._replace(
        alloc_failed=jnp.ones((), bool)
    )
