"""Consumer-side at-least-once delivery with a dead-letter parking lot.

The reference acks even when a handler throws (at-most-once: a transient
DB/Trello outage silently loses the message). :class:`ReliableConsumer`
upgrades a handler to at-least-once-with-a-floor:

- handler succeeds -> normal path (the handler acks, as in the
  reference); the message fingerprint enters the idempotency window.
- handler raises with attempts remaining -> ``nack(requeue=True)``: the
  broker redelivers (flagged ``redelivered``) and the side effects get
  another try.
- handler raises at the attempt cap -> the message is PARKED: published
  to the dead-letter topic (``<topic>.dlq`` by default) with
  ``x-beholder-death`` provenance headers, then acked — poison messages
  stop poisoning the queue but are never silently dropped.
- a REDELIVERY of a message the window has already seen succeed ->
  acked without re-running the handler (``dedup_hits_total``). This is
  what keeps redeliveries effectively-once: a broker connection drop
  between the handler's side effects and the ack's arrival must not
  re-run the side effects. Dedup fires ONLY for deliveries flagged
  ``redelivered`` — two legitimately identical fresh publishes both run.

Attempt counting prefers the broker-stamped ``x-delivery-count`` header
(the quorum-queue contract; both in-repo brokers stamp it on requeue)
and falls back to a bounded local map keyed by message fingerprint for
brokers that do not.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict

from beholder_tpu.log import get_logger
from beholder_tpu.mq.base import Broker, Delivery, Handler

#: provenance headers stamped onto parked messages
DEATH_QUEUE_HEADER = "x-beholder-death-queue"
DEATH_REASON_HEADER = "x-beholder-death-reason"
DEATH_ATTEMPTS_HEADER = "x-beholder-death-attempts"
DEATH_TIME_HEADER = "x-beholder-death-unix-s"


def default_dlq_topic(topic: str) -> str:
    return f"{topic}.dlq"


def fingerprint(topic: str, body: bytes) -> bytes:
    """Stable identity of one message for attempt counting + dedup."""
    digest = hashlib.blake2b(body, digest_size=16)
    digest.update(topic.encode())
    return digest.digest()


class _LruSet:
    """Bounded insertion-ordered map (used as set and as counter map)."""

    def __init__(self, maxlen: int):
        self.maxlen = int(maxlen)
        self._data: OrderedDict = OrderedDict()

    def __contains__(self, key) -> bool:
        return key in self._data

    def get(self, key, default=0):
        return self._data.get(key, default)

    def put(self, key, value=True) -> None:
        self._data[key] = value
        self._data.move_to_end(key)
        while len(self._data) > self.maxlen:
            self._data.popitem(last=False)

    def pop(self, key) -> None:
        self._data.pop(key, None)


class ReliableConsumer:
    """Wrap ``handler`` for ``topic`` with bounded-retry-then-park.

    Register the WRAPPER with the broker (outermost, so it sees the
    handler's exceptions after tracing/timing wrappers ran). The wrapped
    handler keeps its own ack discipline on success; this wrapper only
    settles deliveries the handler left unsettled on failure.

    ``max_attempts`` counts deliveries of one message, first included.
    """

    def __init__(
        self,
        broker: Broker,
        topic: str,
        handler: Handler,
        max_attempts: int = 3,
        dlq_topic: str | None = None,
        dedup_window: int = 4096,
        metrics=None,
        logger=None,
        clock=time.time,
    ):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.broker = broker
        self.topic = topic
        self.handler = handler
        self.max_attempts = int(max_attempts)
        self.dlq_topic = dlq_topic or default_dlq_topic(topic)
        self._metrics = metrics
        self._log = logger or get_logger("reliability.consumer")
        self._clock = clock
        self._lock = threading.Lock()
        self._done = _LruSet(dedup_window)
        self._attempts = _LruSet(dedup_window)
        #: observability for tests/ops: messages parked by this consumer
        self.parked = 0
        # the parking lot must EXIST before the first park: publishing to
        # an undeclared queue is silently unroutable on a real AMQP
        # broker (and nobody listen()s on a DLQ, so nothing else
        # declares it) — an unroutable park followed by the ack would
        # LOSE the message, the one thing this wrapper exists to prevent
        self.broker.declare(self.dlq_topic)

    # -- internals -----------------------------------------------------------
    def _attempt_number(self, fp: bytes, delivery: Delivery) -> int:
        """This delivery's 1-based attempt number: broker-stamped
        delivery count when present, else the local fallback map."""
        with self._lock:
            local = self._attempts.get(fp, 0)
        return 1 + max(delivery.delivery_count, local)

    def _park(self, delivery: Delivery, attempts: int, err: Exception) -> None:
        headers = dict(delivery.headers)
        headers.update(
            {
                DEATH_QUEUE_HEADER: self.topic,
                DEATH_REASON_HEADER: "max-retries",
                DEATH_ATTEMPTS_HEADER: attempts,
                DEATH_TIME_HEADER: int(self._clock()),
            }
        )
        self.broker.publish(self.dlq_topic, delivery.body, headers=headers)
        delivery.ack()
        self.parked += 1
        if self._metrics is not None:
            self._metrics.dead_lettered_total.inc(
                queue=self.topic, reason="max-retries"
            )
        self._log.warning(
            f"parked message from {self.topic!r} on {self.dlq_topic!r} "
            f"after {attempts} attempts: {err!r}"
        )

    # -- the wrapper ---------------------------------------------------------
    def __call__(self, delivery: Delivery) -> None:
        fp = fingerprint(delivery.topic, delivery.body)
        if delivery.redelivered:
            with self._lock:
                done = fp in self._done
            if done:
                # the handler already finished this message once; only
                # the ack was lost. Re-running side effects would double
                # Trello comments / Telegram posts.
                delivery.ack()
                if self._metrics is not None:
                    self._metrics.dedup_hits_total.inc(topic=self.topic)
                return
        try:
            self.handler(delivery)
        except Exception as err:  # noqa: BLE001 - every failure is counted
            attempts = self._attempt_number(fp, delivery)
            with self._lock:
                self._attempts.put(fp, attempts)
            if delivery.settled:
                # the handler settled before failing; nothing to decide
                raise
            if attempts >= self.max_attempts:
                self._park(delivery, attempts, err)
                with self._lock:
                    self._attempts.pop(fp)
            else:
                if self._metrics is not None:
                    self._metrics.retry_attempts_total.inc(
                        op=f"consume.{self.topic}"
                    )
                delivery.nack(requeue=True)
            raise
        else:
            with self._lock:
                self._done.put(fp)
                self._attempts.pop(fp)
