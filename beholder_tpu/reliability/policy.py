"""Retry policies and deadline propagation.

The building blocks every I/O layer shares:

- :class:`Deadline` — an absolute time budget for one logical operation,
  carried across retries (and, via :func:`deadline_scope`, down through
  nested calls on a contextvar) so a retried call can never outlive the
  budget its caller set. "Retry until the deadline", not "retry N times
  and hope".
- :class:`RetryBudget` — a token bucket shared across call sites: each
  first attempt earns a fraction of a retry token, each retry spends
  one. Under a full outage the budget drains and retries are DENIED
  (fail fast) instead of multiplying offered load by max_attempts — the
  retry-storm guard (SRE workbook's ~10% retry-budget rule).
- :class:`RetryPolicy` — bounded exponential backoff with FULL jitter
  (``uniform(0, min(cap, base * mult**attempt))``, the AWS-architecture
  jitter that decorrelates synchronized retry waves), composed with the
  budget and the deadline.

Nothing here is wired by default; the service enables it behind
``instance.reliability.enabled`` (see service.py) and the chaos tests
drive it directly.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from contextlib import contextmanager
from typing import Callable

from beholder_tpu.log import get_logger


class DeadlineExceeded(RuntimeError):
    """The operation's time budget ran out (before or between attempts)."""


class Deadline:
    """An absolute expiry on the monotonic clock.

    Constructed once at the edge (``Deadline.after(seconds)``) and passed
    down — every layer measures the REMAINING budget instead of applying
    its own full timeout, so a slow first hop cannot silently grant later
    hops more total time than the caller allowed.
    """

    __slots__ = ("expires_at", "_clock")

    def __init__(self, expires_at: float, clock: Callable[[], float] = time.monotonic):
        self.expires_at = expires_at
        self._clock = clock

    @classmethod
    def after(
        cls, seconds: float, clock: Callable[[], float] = time.monotonic
    ) -> "Deadline":
        return cls(clock() + float(seconds), clock)

    def remaining(self) -> float:
        """Seconds left; negative when already expired."""
        return self.expires_at - self._clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def cap(self, timeout_s: float) -> float:
        """``timeout_s`` clipped to the remaining budget (for per-attempt
        socket timeouts). Raises :class:`DeadlineExceeded` when nothing
        remains — a zero-second socket timeout would surface as a
        misleading transport error."""
        remaining = self.remaining()
        if remaining <= 0:
            raise DeadlineExceeded(
                f"deadline exceeded ({-remaining:.3f}s past expiry)"
            )
        return min(float(timeout_s), remaining)

    def __repr__(self) -> str:
        return f"Deadline(remaining={self.remaining():.3f}s)"


_current_deadline: contextvars.ContextVar[Deadline | None] = contextvars.ContextVar(
    "beholder_deadline", default=None
)


def current_deadline() -> Deadline | None:
    """The innermost active :func:`deadline_scope` deadline, if any."""
    return _current_deadline.get()


@contextmanager
def deadline_scope(deadline: Deadline | float):
    """Propagate ``deadline`` (a :class:`Deadline` or seconds-from-now)
    to everything called inside the block via a contextvar. Nested
    scopes keep the TIGHTER deadline — an inner layer may shrink the
    budget, never extend it."""
    if not isinstance(deadline, Deadline):
        deadline = Deadline.after(float(deadline))
    outer = _current_deadline.get()
    if outer is not None and outer.expires_at <= deadline.expires_at:
        deadline = outer
    token = _current_deadline.set(deadline)
    try:
        yield deadline
    finally:
        _current_deadline.reset(token)


class RetryBudget:
    """Token-bucket retry budget shared across call sites.

    Each first attempt deposits ``deposit_per_call`` tokens (clipped at
    ``capacity``); each retry spends one. When the bucket is empty,
    :meth:`try_spend` denies the retry — under a sustained outage the
    steady-state retry rate converges to ``deposit_per_call`` retries
    per call (e.g. 0.1 = at most ~10% extra load) instead of
    ``max_attempts``x amplification."""

    def __init__(self, capacity: float = 10.0, deposit_per_call: float = 0.1):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        self.capacity = float(capacity)
        self.deposit_per_call = float(deposit_per_call)
        self._tokens = float(capacity)  # start full: cold starts may retry
        self._lock = threading.Lock()

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def record_call(self) -> None:
        with self._lock:
            self._tokens = min(self.capacity, self._tokens + self.deposit_per_call)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


class RetryPolicy:
    """Bounded exponential backoff with full jitter + budget + deadline.

    ``call(fn, op=...)`` runs ``fn`` up to ``max_attempts`` times. A
    retry happens only when ALL of: the exception is an instance of
    ``retry_on`` and passes ``should_retry`` (if given); attempts
    remain; the shared ``budget`` (if any) grants a token; and the
    active deadline (argument, else the ambient
    :func:`current_deadline`) has room for the backoff sleep. Give-ups
    re-raise the last exception and are counted by reason on the
    reliability metrics (``metrics``, optional).

    Deterministic tests: inject ``sleep`` and ``rng`` (``rng()`` must
    return uniform [0, 1))."""

    def __init__(
        self,
        max_attempts: int = 3,
        base_delay_s: float = 0.05,
        max_delay_s: float = 2.0,
        multiplier: float = 2.0,
        retry_on: tuple[type[BaseException], ...] = (Exception,),
        budget: RetryBudget | None = None,
        metrics=None,
        sleep: Callable[[float], None] = time.sleep,
        rng: Callable[[], float] = random.random,
        logger=None,
    ):
        if max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.retry_on = retry_on
        self.budget = budget
        self._metrics = metrics
        self._sleep = sleep
        self._rng = rng
        self._log = logger or get_logger("reliability.retry")

    def backoff_s(self, attempt: int) -> float:
        """Full-jitter backoff before retry number ``attempt`` (1-based):
        uniform over [0, min(max_delay, base * multiplier**(attempt-1)))."""
        cap = min(
            self.max_delay_s,
            self.base_delay_s * self.multiplier ** max(attempt - 1, 0),
        )
        return self._rng() * cap

    def _give_up(self, op: str, reason: str) -> None:
        if self._metrics is not None:
            self._metrics.retry_give_ups_total.inc(op=op, reason=reason)

    def call(
        self,
        fn: Callable[[], object],
        *,
        op: str = "call",
        deadline: Deadline | None = None,
        should_retry: Callable[[BaseException], bool] | None = None,
    ):
        deadline = deadline or current_deadline()
        if self.budget is not None:
            self.budget.record_call()
        attempt = 1
        while True:
            if deadline is not None and deadline.expired:
                self._give_up(op, "deadline")
                raise DeadlineExceeded(
                    f"{op}: deadline exceeded before attempt {attempt}"
                )
            try:
                return fn()
            except self.retry_on as err:
                if should_retry is not None and not should_retry(err):
                    raise
                if attempt >= self.max_attempts:
                    self._give_up(op, "attempts")
                    raise
                if self.budget is not None and not self.budget.try_spend():
                    self._give_up(op, "budget")
                    raise
                delay = self.backoff_s(attempt)
                if deadline is not None and deadline.remaining() <= delay:
                    # sleeping past the deadline only delays the failure
                    self._give_up(op, "deadline")
                    raise
                if self._metrics is not None:
                    self._metrics.retry_attempts_total.inc(op=op)
                self._log.warning(
                    f"{op}: attempt {attempt}/{self.max_attempts} failed "
                    f"({err!r}); retrying in {delay * 1e3:.0f}ms"
                )
                self._sleep(delay)
                attempt += 1
