"""Circuit breaker + the resilient HTTP transport it wraps.

:class:`CircuitBreaker` is the standard three-state machine over a
sliding window of outcomes:

- **closed** — calls flow; outcomes land in the window. When the window
  holds at least ``min_calls`` outcomes and the failure rate reaches
  ``failure_threshold``, the breaker OPENS.
- **open** — calls are rejected instantly (:class:`BreakerOpenError`)
  without touching the sick dependency; after ``reset_timeout_s`` the
  next allowed call transitions to half-open.
- **half-open** — up to ``half_open_probes`` concurrent probe calls are
  let through. ``half_open_successes`` consecutive successes close the
  breaker (window reset); ANY probe failure re-opens it and restarts
  the cooldown.

:class:`ResilientTransport` stacks the whole reliability story onto any
:class:`~beholder_tpu.clients.http.HttpTransport`: breaker admission,
per-attempt timeouts capped by the propagated deadline, retries (with
full jitter + budget) on transport faults and 5xx responses, and the
shared reliability metrics. The service wires it around the outbound
transport behind ``instance.reliability.enabled``, so Trello, Telegram,
and Emby all inherit it (they already share one transport).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

from beholder_tpu.clients.http import HttpResponse, HttpTransport
from beholder_tpu.log import get_logger

from .instruments import STATE_VALUES
from .policy import Deadline, RetryPolicy, current_deadline

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class BreakerOpenError(RuntimeError):
    """Fast failure: the breaker is open and the call was not attempted."""

    def __init__(self, name: str, retry_after_s: float):
        super().__init__(
            f"circuit breaker {name!r} is open "
            f"(retry in {max(retry_after_s, 0.0):.2f}s)"
        )
        self.breaker = name
        self.retry_after_s = retry_after_s


class CircuitBreaker:
    """Windowed-failure-rate breaker, thread-safe.

    Use either :meth:`call` (wraps a callable) or the explicit
    :meth:`allow` / :meth:`record_success` / :meth:`record_failure`
    triple when success is decided by inspecting a response."""

    def __init__(
        self,
        name: str = "default",
        window: int = 20,
        min_calls: int = 5,
        failure_threshold: float = 0.5,
        reset_timeout_s: float = 30.0,
        half_open_probes: int = 1,
        half_open_successes: int = 2,
        clock: Callable[[], float] = time.monotonic,
        metrics=None,
        logger=None,
    ):
        if not 0 < failure_threshold <= 1:
            raise ValueError(
                f"failure_threshold must be in (0, 1], got {failure_threshold}"
            )
        self.name = name
        self.window = int(window)
        self.min_calls = int(min_calls)
        self.failure_threshold = float(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_probes = int(half_open_probes)
        self.half_open_successes = int(half_open_successes)
        self._clock = clock
        self._metrics = metrics
        self._log = logger or get_logger("reliability.breaker")
        self._lock = threading.Lock()
        self._state = CLOSED
        self._outcomes: deque[bool] = deque(maxlen=self.window)  # True = failure
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self._probe_successes = 0
        if self._metrics is not None:
            self._metrics.breaker_state.set(STATE_VALUES[CLOSED], breaker=name)

    # -- introspection ------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def failure_rate(self) -> float:
        with self._lock:
            if not self._outcomes:
                return 0.0
            return sum(self._outcomes) / len(self._outcomes)

    # -- state machine (lock held) ------------------------------------------
    def _transition(self, state: str) -> None:
        if state == self._state:
            return
        self._log.warning(f"breaker {self.name!r}: {self._state} -> {state}")
        self._state = state
        if state == OPEN:
            self._opened_at = self._clock()
        if state in (OPEN, CLOSED):
            self._probes_in_flight = 0
            self._probe_successes = 0
        if state == CLOSED:
            self._outcomes.clear()
        if self._metrics is not None:
            self._metrics.breaker_state.set(
                STATE_VALUES[state], breaker=self.name
            )
            self._metrics.breaker_transitions_total.inc(
                breaker=self.name, state=state
            )

    # -- admission + outcomes ----------------------------------------------
    def allow(self) -> bool:
        """May a call proceed right now? (Half-open admissions count as
        probes; callers MUST report the outcome via record_*.)"""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at < self.reset_timeout_s:
                    if self._metrics is not None:
                        self._metrics.breaker_rejections_total.inc(
                            breaker=self.name
                        )
                    return False
                self._transition(HALF_OPEN)
            # half-open: admit a bounded number of concurrent probes
            if self._probes_in_flight < self.half_open_probes:
                self._probes_in_flight += 1
                return True
            if self._metrics is not None:
                self._metrics.breaker_rejections_total.inc(breaker=self.name)
            return False

    def retry_after_s(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return self.reset_timeout_s - (self._clock() - self._opened_at)

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._probes_in_flight = max(self._probes_in_flight - 1, 0)
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_successes:
                    self._transition(CLOSED)
                return
            self._outcomes.append(False)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # a sick dependency is still sick: back to open, new cooldown
                self._transition(OPEN)
                return
            if self._state == OPEN:
                return
            self._outcomes.append(True)
            if (
                len(self._outcomes) >= self.min_calls
                and sum(self._outcomes) / len(self._outcomes)
                >= self.failure_threshold
            ):
                self._transition(OPEN)

    def call(self, fn: Callable[[], Any]):
        """Run ``fn`` under the breaker: admission, then outcome by
        exception (any exception = failure)."""
        if not self.allow():
            raise BreakerOpenError(self.name, self.retry_after_s())
        try:
            result = fn()
        except BaseException:
            self.record_failure()
            raise
        self.record_success()
        return result


class ResilientTransport(HttpTransport):
    """Breaker + retry + deadline wrapper over any transport.

    Per request: admission through ``breaker`` (fail fast when open),
    per-attempt timeout capped to the active deadline (the ``deadline``
    argument of one request is the ambient
    :func:`~.policy.current_deadline`, else ``default_deadline_s``,
    else just the per-attempt ``timeout``), retries via ``retry`` on
    transport faults and 5xx responses. 4xx responses are the server
    SPEAKING — they count as breaker successes and never retry.

    A 5xx that survives all retries is RETURNED (not raised): clients
    own ``raise_for_status``, and swallowing the response body here
    would lose the error detail the reference logs."""

    def __init__(
        self,
        inner: HttpTransport,
        breaker: CircuitBreaker | None = None,
        retry: RetryPolicy | None = None,
        default_deadline_s: float | None = None,
        logger=None,
    ):
        self.inner = inner
        self.breaker = breaker or CircuitBreaker(name="http")
        self.retry = retry or RetryPolicy(
            retry_on=(OSError, ConnectionError, TimeoutError, _Retry5xx)
        )
        self.default_deadline_s = default_deadline_s
        self._log = logger or get_logger("reliability.transport")

    def request(self, method, url, *, params=None, json=None, timeout=10.0,
                headers=None):
        deadline = current_deadline()
        if deadline is None and self.default_deadline_s is not None:
            deadline = Deadline.after(self.default_deadline_s)

        def attempt() -> HttpResponse:
            # deadline BEFORE admission: allow() may hand out a half-open
            # probe slot that only record_* returns — a cap() raise after
            # taking the slot would leak it and wedge the breaker in
            # half-open (no time-based escape) until restart
            per_attempt = deadline.cap(timeout) if deadline is not None else timeout
            if not self.breaker.allow():
                raise BreakerOpenError(
                    self.breaker.name, self.breaker.retry_after_s()
                )
            # headers forwarded only when set: duck-typed transports
            # predating the headers kwarg keep working headerless
            extra = {"headers": headers} if headers is not None else {}
            try:
                resp = self.inner.request(
                    method, url, params=params, json=json,
                    timeout=per_attempt, **extra,
                )
            except BaseException:
                self.breaker.record_failure()
                raise
            if resp.status >= 500:
                # the dependency is erroring: a breaker failure AND
                # retryable (the carried response is returned on give-up)
                self.breaker.record_failure()
                raise _Retry5xx(resp)
            self.breaker.record_success()
            return resp

        def should_retry(err: BaseException) -> bool:
            # an open breaker or a spent deadline is a decision, not a
            # transient fault — retrying would just burn the backoff
            return not isinstance(err, BreakerOpenError)

        try:
            return self.retry.call(
                attempt,
                op=f"http.{method.lower()}",
                deadline=deadline,
                should_retry=should_retry,
            )
        except _Retry5xx as err:
            return err.response


class _Retry5xx(RuntimeError):
    """Internal marker: a 5xx response riding the retry loop."""

    def __init__(self, response: HttpResponse):
        super().__init__(f"HTTP {response.status}")
        self.response = response
