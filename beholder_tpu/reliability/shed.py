"""Load shedding: bounded intake for the serving scheduler.

Unbounded queueing converts overload into unbounded latency and an
eventual OOM; admission control converts it into an EXPLICIT, cheap
rejection the caller can act on (back off, divert, degrade). This is
the serving-side counterpart of the broker's prefetch window.

:class:`IntakeQueue` is the policy object: a bounded pending queue
(depth and, optionally, total page-cost) with an accept/shed outcome
per offer. :class:`~beholder_tpu.models.serving.ContinuousBatcher`
wires one in front of its schedulers (``submit()`` / ``run_pending()``)
and reports sheds on ``beholder_serving_shed_total{reason}``.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, NamedTuple

from beholder_tpu.metrics import get_or_create

#: buckets for the time-in-queue histogram: intake waits span
#: sub-ms drains to seconds of backlog under pressure
WAIT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0,
)

#: per-process counter behind IntakeQueue's default names
_default_names = itertools.count()

#: shed reasons (the rejection outcome's vocabulary)
SHED_QUEUE_FULL = "queue_full"
SHED_COST_BACKLOG = "cost_backlog"
SHED_OVERSIZED = "oversized"
#: failover: the request would fit SOME shard, but every shard that
#: could hold it is down/draining — surviving capacity is insufficient
SHED_SHARD_DOWN = "shard_down"


class Admission(NamedTuple):
    """The explicit outcome of one intake offer."""

    accepted: bool
    reason: str | None = None  # set when shed


class LoadShedError(RuntimeError):
    """Raised by callers that prefer an exception to an outcome value."""

    def __init__(self, reason: str):
        super().__init__(f"request shed: {reason}")
        self.reason = reason


class IntakeQueue:
    """Bounded FIFO intake with explicit shedding.

    - ``max_depth`` bounds the number of pending requests.
    - ``max_cost`` (optional) bounds the SUM of per-request costs (the
      serving layer uses worst-case KV pages, so backlog is bounded in
      the resource that actually runs out, not just in count).
    - ``cost_fn`` computes one request's cost (required with
      ``max_cost``). A request whose own cost exceeds ``max_cost`` can
      never be admitted and sheds as ``oversized``.

    ``metrics`` (a Registry or Metrics) exports
    ``beholder_serving_shed_total{reason}``,
    ``beholder_serving_intake_depth``,
    ``beholder_serving_admitted_total``, and — naming this queue via
    ``name`` — the LABELLED ``beholder_intake_queue_depth{queue}``
    series, the serving-side counterpart of the broker's per-queue
    ``beholder_mq_queue_depth{queue}`` (PR 1 instrumented MQ depth but
    left the serving intake path an unlabelled singleton; multiple
    intakes in one process now chart side by side).

    Time-in-queue is measured too: every :meth:`take_all` drain stamps
    each item's wait into ``beholder_intake_wait_seconds{queue}``
    (registered lazily on the FIRST drain — the default exposition
    stays untouched until intake wait actually exists) and exposes the
    drained items' waits as :attr:`last_drain_waits`, which the serving
    schedulers fold into per-request timeline queue-wait.

    ``labelled_sheds`` (off by default so the existing exposition is
    untouched) additionally attributes every shed to THIS queue on the
    labelled ``beholder_intake_shed_total{queue, reason}`` series —
    the shed twin of the labelled depth gauge. The cluster router
    turns it on for its per-shard intakes (uniquely named
    ``cluster.decode-<i>``), so shed attribution survives the move
    from one queue to N: which SHARD said no stays chartable after
    the reason-only ``beholder_serving_shed_total`` series folds all
    shards together.
    """

    def __init__(
        self,
        max_depth: int,
        max_cost: float | None = None,
        cost_fn: Callable[[Any], float] | None = None,
        metrics=None,
        name: str | None = None,
        labelled_sheds: bool = False,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        if name is None:
            # default names stay unique per process: two unnamed queues
            # sharing a registry must not silently overwrite each
            # other's depth series (the first keeps the bare name so the
            # common single-queue case charts stably)
            n = next(_default_names)
            name = "serving.intake" if n == 0 else f"serving.intake-{n + 1}"
        if max_cost is not None and cost_fn is None:
            raise ValueError("max_cost needs a cost_fn")
        self.max_depth = int(max_depth)
        self.max_cost = max_cost
        self.cost_fn = cost_fn
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._pending: list = []
        #: per-item enqueue stamps, parallel to ``_pending`` — the
        #: time-in-queue source (``beholder_intake_wait_seconds``)
        self._enqueued_at: list[float] = []
        self._pending_cost = 0.0
        #: waits (seconds) of the items the LAST take_all drained, in
        #: drain order — the scheduler feeds these into the request
        #: timelines (queue-wait is measured at claim, not inferred) —
        #: plus the raw enqueue stamps for restock round trips
        self.last_drain_waits: list[float] = []
        self.last_drain_stamps: list[float] = []
        self.shed_counts: dict[str, int] = {}
        self._shed_total = None
        self._depth_gauge = None
        self._labelled_depth = None
        self._labelled_sheds = None
        self._admitted_total = None
        self._wait_hist = None
        self._registry = None
        if metrics is not None:
            registry = getattr(metrics, "registry", metrics)
            self._registry = registry
            self._shed_total = get_or_create(
                registry, "counter",
                "beholder_serving_shed_total",
                "Serving requests rejected at the intake queue, by reason",
                labelnames=["reason"],
            )
            self._admitted_total = get_or_create(
                registry, "counter",
                "beholder_serving_admitted_total",
                "Serving requests admitted through the intake queue",
            )
            self._depth_gauge = get_or_create(
                registry, "gauge",
                "beholder_serving_intake_depth",
                "Requests waiting in the serving intake queue",
            )
            self._labelled_depth = get_or_create(
                registry, "gauge",
                "beholder_intake_queue_depth",
                "Requests waiting in a bounded intake queue, by queue "
                "name (serving-side twin of beholder_mq_queue_depth)",
                labelnames=["queue"],
            )
            self._labelled_depth.set(0, queue=self.name)
            if labelled_sheds:
                self._labelled_sheds = get_or_create(
                    registry, "counter",
                    "beholder_intake_shed_total",
                    "Requests shed at a bounded intake queue, by queue "
                    "name and reason (per-queue twin of "
                    "beholder_serving_shed_total)",
                    labelnames=["queue", "reason"],
                )

    # -- introspection -------------------------------------------------------
    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    @property
    def pending_cost(self) -> float:
        with self._lock:
            return self._pending_cost

    # -- intake --------------------------------------------------------------
    def _shed(self, reason: str) -> Admission:
        self.shed_counts[reason] = self.shed_counts.get(reason, 0) + 1
        if self._shed_total is not None:
            self._shed_total.inc(reason=reason)
        if self._labelled_sheds is not None:
            self._labelled_sheds.inc(queue=self.name, reason=reason)
        return Admission(False, reason)

    def shed(self, reason: str) -> Admission:
        """Record an externally-decided rejection (e.g. the scheduler
        judged the request unservable at any load) on the same counters."""
        with self._lock:
            return self._shed(reason)

    def offer(self, item: Any, cost: float | None = None) -> Admission:
        """Try to enqueue ``item``; never blocks, never grows past the
        bounds — the whole point is that saying no is O(1). A caller
        that already computed the item's cost passes it via ``cost`` to
        skip the second ``cost_fn`` evaluation."""
        if cost is None:
            cost = float(self.cost_fn(item)) if self.cost_fn is not None else 0.0
        with self._lock:
            if self.max_cost is not None and cost > self.max_cost:
                return self._shed(SHED_OVERSIZED)
            if len(self._pending) >= self.max_depth:
                return self._shed(SHED_QUEUE_FULL)
            if (
                self.max_cost is not None
                and self._pending_cost + cost > self.max_cost
            ):
                return self._shed(SHED_COST_BACKLOG)
            self._pending.append(item)
            self._enqueued_at.append(self._clock())
            self._pending_cost += cost
            if self._admitted_total is not None:
                self._admitted_total.inc()
            if self._depth_gauge is not None:
                self._depth_gauge.set(len(self._pending))
            if self._labelled_depth is not None:
                self._labelled_depth.set(len(self._pending), queue=self.name)
            return Admission(True)

    def take_all(self, record_waits: bool = True) -> list:
        """Drain every pending item (the scheduler's batch pull).

        Each drained item's time-in-queue is stamped HERE — the claim
        moment — into ``beholder_intake_wait_seconds{queue}``
        (registered on first observation, so the default exposition is
        untouched until a drain actually happens) and kept in
        :attr:`last_drain_waits` for the request-timeline layer.

        ``record_waits=False`` is for drain-then-restock ROUND TRIPS
        (the cluster rebalance / graceful drain): the items are not
        being claimed, only re-packed, so their partial waits must not
        land on the histogram — the eventual claiming drain observes
        the one true wait. Stamps and waits are still computed (the
        re-pack hands the stamps back via ``restock(enqueued_at=)``)."""
        items, _, _ = self.drain_all(record_waits=record_waits)
        return items

    def drain_all(
        self, record_waits: bool = True
    ) -> tuple[list, list[float], list[float]]:
        """:meth:`take_all` returning ``(items, waits, enqueue_stamps)``
        as ONE atomic read — callers that restock with the original
        stamps (the re-pack paths) or attach the waits to request
        timelines must not read ``last_drain_waits``/
        ``last_drain_stamps`` as a second step: a concurrent drain in
        between would clobber them, and a zip over mismatched lists
        silently drops items."""
        with self._lock:
            items, self._pending = self._pending, []
            stamps, self._enqueued_at = self._enqueued_at, []
            self._pending_cost = 0.0
            now = self._clock()
            self.last_drain_waits = [now - ts for ts in stamps]
            # the raw stamps ride along so a drain-then-restock (the
            # cluster rebalance / graceful drain) can hand them back
            # via restock(enqueued_at=...) — queue time actually
            # waited must survive a re-pack
            self.last_drain_stamps = stamps
            if self._depth_gauge is not None:
                self._depth_gauge.set(0)
            if self._labelled_depth is not None:
                self._labelled_depth.set(0, queue=self.name)
            waits = self.last_drain_waits
        if record_waits:
            self._observe_waits(waits)
        return items, waits, stamps

    def _observe_waits(self, waits: list[float]) -> None:
        if self._registry is None or not waits:
            return
        if self._wait_hist is None:
            self._wait_hist = get_or_create(
                self._registry, "histogram",
                "beholder_intake_wait_seconds",
                "Time from intake admission to scheduler claim, by "
                "queue (the queue-wait leg of a request's timeline)",
                labelnames=["queue"],
                buckets=WAIT_BUCKETS,
            )
        for wait in waits:
            self._wait_hist.observe(wait, queue=self.name)

    def restock(self, items: list, enqueued_at: list[float] | None = None) -> None:
        """Put back items previously drained by :meth:`take_all` (the
        cluster router's rebalance re-packs queued work across shard
        queues). Bypasses the bounds and the admitted/shed counters —
        every item here was already admitted exactly once; rebalancing
        must neither re-count nor re-shed it. Restocked items land at
        the FRONT in order, so a drain sees them before newer offers
        (FIFO is preserved across a rebalance).

        ``enqueued_at`` hands back the items' ORIGINAL enqueue stamps
        (``last_drain_stamps`` from the drain, item-parallel) so the
        eventual claim still measures the queue time actually waited;
        without it items re-stamp at restock time (a rebalance-sized
        underestimate — the conservative fallback)."""
        if not items:
            return
        if enqueued_at is not None and len(enqueued_at) != len(items):
            raise ValueError(
                f"enqueued_at has {len(enqueued_at)} stamps for "
                f"{len(items)} items"
            )
        with self._lock:
            cost = sum(
                float(self.cost_fn(item)) if self.cost_fn is not None
                else 0.0
                for item in items
            )
            self._pending = list(items) + self._pending
            self._enqueued_at = (
                list(enqueued_at)
                if enqueued_at is not None
                else [self._clock()] * len(items)
            ) + self._enqueued_at
            self._pending_cost += cost
            if self._depth_gauge is not None:
                self._depth_gauge.set(len(self._pending))
            if self._labelled_depth is not None:
                self._labelled_depth.set(
                    len(self._pending), queue=self.name
                )
