"""Int8 weight quantization for serving.

EXTENSION BEYOND THE REFERENCE (no tensors there — SURVEY.md §0).
Weight-only, per-output-channel symmetric int8:

- :func:`quantize_params` maps every 2-D matmul kernel in a trained
  params tree to ``{"qvalues": int8, "scale": f32 per column}`` —
  the tree's HBM footprint drops ~4x vs f32 (2x vs bf16). Biases,
  LayerNorms, and embeddings stay in full precision (they are tiny and
  precision-critical).
- :func:`dequantize_params` reconstructs the original tree structure
  INSIDE jit: the dequant is elementwise, so XLA fuses it into each
  consumer matmul — int8 stays the HBM-resident representation, the
  bf16 weight tile exists only in VMEM on its way to the MXU. Decode
  steps are weight-bandwidth-bound, so halving weight bytes is a direct
  serving-latency lever (the standard weight-only-quant argument).

Per-channel scales bound the quantization error: for column j,
``scale_j = max_i |w_ij| / 127``, so the roundoff per weight is at most
``scale_j / 2`` — outlier columns don't poison the whole matrix the way
one per-tensor scale would.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

_QKEYS = ("qvalues", "scale")


def quantize_symmetric(x: jax.Array, axis: int) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization reducing ``axis``: returns (q int8,
    scale f32 with ``axis`` removed), ``x ≈ q * scale`` (scale
    re-broadcast on ``axis``). One definition serves weights (per output
    channel), KV page chunks (per token), and decode-tick columns — the
    copies MUST stay numerically identical for paged-vs-dense cache
    equivalence, so there is exactly one."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / jnp.expand_dims(scale, axis)),
        -127, 127,
    )
    return q.astype(jnp.int8), scale


def quantize_weight(w: jax.Array) -> dict[str, jax.Array]:
    """(in, out) matmul kernel -> symmetric int8 with per-OUTPUT-channel
    scales. ``w ≈ qvalues.astype(f32) * scale``."""
    if w.ndim != 2:
        raise ValueError(f"expected a 2-D kernel, got shape {w.shape}")
    q, scale = quantize_symmetric(w, axis=0)
    return {"qvalues": q, "scale": scale}


def dequantize_weight(q: dict[str, jax.Array], dtype=jnp.bfloat16) -> jax.Array:
    """Inverse of :func:`quantize_weight`. Elementwise — inside jit XLA
    fuses this into the consumer matmul, so the full-precision weight
    never lands in HBM."""
    return (q["qvalues"].astype(jnp.float32) * q["scale"]).astype(dtype)


def _is_quantizable(path_names: tuple[str, ...], leaf) -> bool:
    """Quantize only 2-D matmul kernels, and skip the embedding/head
    projections (input featurization and the scalar output head are
    precision-critical and tiny)."""
    if not (path_names and path_names[-1] == "kernel" and leaf.ndim == 2):
        return False
    return not any(n in ("embed", "head") for n in path_names)


def quantize_params(params: Any) -> Any:
    """Trained params tree -> same-structure tree with every eligible
    kernel leaf replaced by its ``{"qvalues", "scale"}`` dict. Works on
    arbitrary pytree containers (dict/list/tuple) — the replacement dict
    is grafted at the leaf position."""
    from jax.tree_util import tree_map_with_path

    def one(path, leaf):
        names = tuple(
            str(getattr(p, "key", getattr(p, "name", ""))) for p in path
        )
        return quantize_weight(leaf) if _is_quantizable(names, leaf) else leaf

    return tree_map_with_path(one, params)


def dequantize_params(qparams: Any, dtype=jnp.bfloat16) -> Any:
    """Quantized tree -> apply-ready params (call INSIDE jit; see module
    docstring for why that keeps int8 as the HBM representation)."""
    if isinstance(qparams, dict):
        if set(qparams.keys()) == set(_QKEYS):
            return dequantize_weight(qparams, dtype)
        return {k: dequantize_params(v, dtype) for k, v in qparams.items()}
    if isinstance(qparams, (list, tuple)):
        return type(qparams)(dequantize_params(v, dtype) for v in qparams)
    return qparams


def quantized_nbytes(tree: Any) -> int:
    """Total bytes of a (possibly quantized) params tree."""
    return sum(leaf.nbytes for leaf in jax.tree.leaves(tree))
