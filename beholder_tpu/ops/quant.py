"""Int8 / fp8 quantization for serving.

EXTENSION BEYOND THE REFERENCE (no tensors there — SURVEY.md §0).
Weight-only, per-output-channel symmetric int8:

- :func:`quantize_params` maps every 2-D matmul kernel in a trained
  params tree to ``{"qvalues": int8, "scale": f32 per column}`` —
  the tree's HBM footprint drops ~4x vs f32 (2x vs bf16). Biases,
  LayerNorms, and embeddings stay in full precision (they are tiny and
  precision-critical).
- :func:`dequantize_params` reconstructs the original tree structure
  INSIDE jit: the dequant is elementwise, so XLA fuses it into each
  consumer matmul — int8 stays the HBM-resident representation, the
  bf16 weight tile exists only in VMEM on its way to the MXU. Decode
  steps are weight-bandwidth-bound, so halving weight bytes is a direct
  serving-latency lever (the standard weight-only-quant argument).

Per-channel scales bound the quantization error: for column j,
``scale_j = max_i |w_ij| / 127``, so the roundoff per weight is at most
``scale_j / 2`` — outlier columns don't poison the whole matrix the way
one per-tensor scale would.

KV PAGE quantization comes in two flavors, one definition each:

- **int8** (:func:`quantize_symmetric`): int8 values + f32 per-block
  scales — 1 byte per element plus 4 scale bytes per (head, token)
  block.
- **fp8 shared-exponent** (:func:`quantize_fp8_block`): ``float8_e4m3fn``
  values + **E8M0** per-block scales — a uint8 biased power-of-2
  exponent (``scale = 2**(e - 127)``, the MX block format's scale
  encoding). Values stay 8-bit like int8; what shrinks is the SCALE
  side-channel (4 bytes → 1 byte per block), and what power-of-2
  scales buy numerically is EXACTNESS: ``q_f32 * 2**e`` is a float32
  exponent shift with no mantissa rounding, so every dequant site
  (kernel, oracle, debug gather) reproduces identical bits by
  construction — the fused-vs-dense bitwise contract carries over to
  fp8 pools without any per-site tolerance argument.

:func:`pool_quantize` / :func:`pool_scales_f32` are the ONE dispatch
pair every pool write / dequant site shares (serving chunk writes,
decode-tick columns, the paged kernels, the dense oracles): the pool's
value dtype picks the scheme, the scale dtype picks the decoding.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

_QKEYS = ("qvalues", "scale")


def quantize_symmetric(x: jax.Array, axis: int) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization reducing ``axis``: returns (q int8,
    scale f32 with ``axis`` removed), ``x ≈ q * scale`` (scale
    re-broadcast on ``axis``). One definition serves weights (per output
    channel), KV page chunks (per token), and decode-tick columns — the
    copies MUST stay numerically identical for paged-vs-dense cache
    equivalence, so there is exactly one."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / jnp.expand_dims(scale, axis)),
        -127, 127,
    )
    return q.astype(jnp.int8), scale


#: float8_e4m3fn's largest finite value (no inf encoding — hence "fn")
FP8_MAX = 448.0

#: E8M0 exponent bias (scale = 2**(int(e) - 127), e stored uint8)
E8M0_BIAS = 127


def quantize_fp8_block(
    x: jax.Array, axis: int
) -> tuple[jax.Array, jax.Array]:
    """Shared-exponent fp8 block quantization reducing ``axis``:
    returns (q ``float8_e4m3fn``, e8m0 scales uint8 with ``axis``
    removed), ``x ≈ q_f32 * 2**(e - 127)``.

    The block scale is the smallest power of two bringing the block's
    amax inside fp8 range (``amax / 2**e <= 448``), clamped to f32's
    exact-exponent window so the dequant multiply is a pure exponent
    shift — see the module docstring for why that exactness is the
    point. An all-zero block gets the identity scale (e = bias)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis)
    # ceil(log2(amax / 448)) via frexp (exact — no transcendental
    # rounding at block boundaries): amax = m * 2**exp, m in [0.5, 1),
    # so 2**e >= amax/448 first holds at e = exp - 9, +1 when the
    # mantissa still spills (m * 2**9 > 448, i.e. m > 0.875)
    m, exp = jnp.frexp(jnp.maximum(amax, jnp.float32(1e-30)))
    e = exp - 9 + (m > jnp.float32(0.875)).astype(exp.dtype)
    e = jnp.where(amax > 0, e, 0)
    e = jnp.clip(e, -E8M0_BIAS + 1, E8M0_BIAS)  # f32-exact scale range
    inv = jnp.exp2(-e.astype(jnp.float32))
    q = jnp.clip(
        xf * jnp.expand_dims(inv, axis), -FP8_MAX, FP8_MAX
    ).astype(jnp.float8_e4m3fn)
    return q, (e + E8M0_BIAS).astype(jnp.uint8)


def pool_quantize(
    x: jax.Array, axis: int, values_dtype
) -> tuple[jax.Array, jax.Array]:
    """Quantize a KV block for a pool of ``values_dtype`` — the ONE
    dispatch every pool write shares (admit chunk scatters and
    decode-tick column writes must quantize identically or paged-vs-
    dense equivalence breaks)."""
    if values_dtype == jnp.int8:
        return quantize_symmetric(x, axis)
    if values_dtype == jnp.float8_e4m3fn:
        return quantize_fp8_block(x, axis)
    raise ValueError(f"no pool quantizer for {values_dtype}")


def pool_scales_f32(scales: jax.Array) -> jax.Array:
    """Decode a pool's per-block scales to f32 multipliers: f32 scales
    (int8 pools) pass through; uint8 scales are E8M0 biased exponents
    (fp8 pools) — ``2**(e - 127)``, exact in f32 across the clamped
    range :func:`quantize_fp8_block` emits. Every dequant site (both
    paged-kernel transports, the dense oracles, debug gathers) must
    decode through here so the arithmetic cannot drift."""
    if scales.dtype == jnp.uint8:
        # 2**(e - 127) EXACTLY: e is the f32 exponent FIELD, so build
        # the float from its bits (exp2() is a transcendental on some
        # backends and lands 1 ulp off for negative exponents, which
        # would silently break the exact-shift contract above).
        # quantize_fp8_block clamps e to [1, 254] — always a normal
        return jax.lax.bitcast_convert_type(
            scales.astype(jnp.uint32) << 23, jnp.float32
        )
    return scales


def quantize_weight(w: jax.Array) -> dict[str, jax.Array]:
    """(in, out) matmul kernel -> symmetric int8 with per-OUTPUT-channel
    scales. ``w ≈ qvalues.astype(f32) * scale``."""
    if w.ndim != 2:
        raise ValueError(f"expected a 2-D kernel, got shape {w.shape}")
    q, scale = quantize_symmetric(w, axis=0)
    return {"qvalues": q, "scale": scale}


def dequantize_weight(q: dict[str, jax.Array], dtype=jnp.bfloat16) -> jax.Array:
    """Inverse of :func:`quantize_weight`. Elementwise — inside jit XLA
    fuses this into the consumer matmul, so the full-precision weight
    never lands in HBM."""
    return (q["qvalues"].astype(jnp.float32) * q["scale"]).astype(dtype)


def _is_quantizable(path_names: tuple[str, ...], leaf) -> bool:
    """Quantize only 2-D matmul kernels, and skip the embedding/head
    projections (input featurization and the scalar output head are
    precision-critical and tiny)."""
    if not (path_names and path_names[-1] == "kernel" and leaf.ndim == 2):
        return False
    return not any(n in ("embed", "head") for n in path_names)


def quantize_params(params: Any) -> Any:
    """Trained params tree -> same-structure tree with every eligible
    kernel leaf replaced by its ``{"qvalues", "scale"}`` dict. Works on
    arbitrary pytree containers (dict/list/tuple) — the replacement dict
    is grafted at the leaf position."""
    from jax.tree_util import tree_map_with_path

    def one(path, leaf):
        names = tuple(
            str(getattr(p, "key", getattr(p, "name", ""))) for p in path
        )
        return quantize_weight(leaf) if _is_quantizable(names, leaf) else leaf

    return tree_map_with_path(one, params)


def dequantize_params(qparams: Any, dtype=jnp.bfloat16) -> Any:
    """Quantized tree -> apply-ready params (call INSIDE jit; see module
    docstring for why that keeps int8 as the HBM representation)."""
    if isinstance(qparams, dict):
        if set(qparams.keys()) == set(_QKEYS):
            return dequantize_weight(qparams, dtype)
        return {k: dequantize_params(v, dtype) for k, v in qparams.items()}
    if isinstance(qparams, (list, tuple)):
        return type(qparams)(dequantize_params(v, dtype) for v in qparams)
    return qparams


def quantized_nbytes(tree: Any) -> int:
    """Total bytes of a (possibly quantized) params tree."""
    return sum(leaf.nbytes for leaf in jax.tree.leaves(tree))
