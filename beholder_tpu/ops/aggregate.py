"""Batched telemetry aggregation as fused XLA programs.

Design notes (TPU-first):
- The per-status reductions are phrased as a one-hot matmul so XLA lowers
  them onto the MXU for large batches instead of scatter-adds.
- Everything is fixed-shape and jittable; batch size is the only traced
  dimension, so one compilation serves a given buffer size.
- dtypes: accumulation in float32 (progress is 0-100, so bfloat16 inputs
  are safe and halve HBM traffic; sums stay exact in f32).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from beholder_tpu.proto import TelemetryStatusEntry

#: Size of the status enum (QUEUED..ERRORED, proto/api.proto).
NUM_STATUSES = len(TelemetryStatusEntry.keys())


@partial(jax.jit, static_argnames=("num_statuses",))
def status_counts(statuses: jax.Array, num_statuses: int = NUM_STATUSES) -> jax.Array:
    """Count observations per status. (B,) int -> (S,) int32.

    Counts accumulate in int32 (exact for any batch size that fits in
    memory); a float32 accumulator would silently lose exactness past
    2^24 observations of one status.
    """
    one_hot = jax.nn.one_hot(statuses, num_statuses, dtype=jnp.int32)
    return one_hot.sum(axis=0)


@partial(jax.jit, static_argnames=("num_statuses",))
def aggregate_telemetry(
    statuses: jax.Array,
    progress: jax.Array,
    num_statuses: int = NUM_STATUSES,
) -> dict[str, jax.Array]:
    """Per-status counts + progress statistics in one fused program.

    Args:
        statuses: (B,) int status ids.
        progress: (B,) progress percentages (any float/int dtype).

    Returns dict of (S,)-shaped arrays: ``count``, ``mean_progress``,
    ``max_progress``, ``min_progress`` (min/max are 0 where count==0).
    """
    one_hot = jax.nn.one_hot(statuses, num_statuses, dtype=jnp.float32)  # (B,S)
    progress = progress.astype(jnp.float32)

    # counts in int32 for exactness (f32 drifts past 2^24 events/status);
    # progress sums stay f32 (values <= 100, relative error ~1e-7 — fine
    # for a mean even at hundred-million-event batches)
    counts_i = one_hot.astype(jnp.int32).sum(axis=0)  # (S,)
    counts = counts_i.astype(jnp.float32)
    sums = one_hot.T @ progress  # (S,) — MXU-friendly contraction
    mean = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), 0.0)

    big = jnp.float32(1e9)
    per_status = jnp.where(one_hot > 0, progress[:, None], -big)
    maxes = jnp.where(counts > 0, per_status.max(axis=0), 0.0)
    per_status_min = jnp.where(one_hot > 0, progress[:, None], big)
    mins = jnp.where(counts > 0, per_status_min.min(axis=0), 0.0)

    return {
        "count": counts_i,
        "mean_progress": mean,
        "max_progress": maxes,
        "min_progress": mins,
    }


@jax.jit
def ewma(series: jax.Array, alpha: float | jax.Array = 0.1) -> jax.Array:
    """Exponentially weighted moving average over a time series.

    Sequential dependence is expressed with ``lax.scan`` (compiler-friendly
    control flow — no Python loop under jit).
    """
    alpha = jnp.asarray(alpha, dtype=jnp.float32)
    series = series.astype(jnp.float32)

    def step(carry, x):
        carry = alpha * x + (1.0 - alpha) * carry
        return carry, carry

    _, out = jax.lax.scan(step, series[0], series)
    return out
