"""Flash attention as a Pallas TPU kernel (forward) + blocked XLA backward.

EXTENSION BEYOND THE REFERENCE (which has no attention or tensors of any
kind — SURVEY.md §0/§5). This is the single-device fast path behind the
sequence models' ``attention="flash"`` backend; ring attention
(:mod:`beholder_tpu.ops.attention`) distributes the same online-softmax
recurrence across chips.

Design (see /opt/skills/guides/pallas_guide.md):

- Forward kernel: grid over (batch*heads, q blocks). Each step holds one
  (block_q, d) q tile plus the full (T, d) k/v for its batch-head in VMEM
  and runs the online-softmax recurrence over k/v blocks with a
  ``fori_loop`` — running max m, normalizer l, and unnormalized
  accumulator — so the (T, T) score matrix never exists. For causal
  masking the loop stops after the q block's diagonal.
- The kernel also emits the row logsumexp, which makes the backward
  recomputation exact.
- Backward: a custom-VJP rule in blocked XLA (scan over k/v blocks,
  recomputing probabilities from the saved logsumexp — the standard flash
  backward). Memory stays O(T * block) instead of O(T^2); XLA keeps the
  einsums on the MXU.
- Head dim is zero-padded to the 128-lane width and T to a block
  multiple; padded k/v columns are masked with -inf so they contribute
  nothing, and padded d columns contribute zeros to every dot product.
- On non-TPU backends the kernel runs in interpreter mode, so the same
  code path is exercised by the CPU-mesh tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30
_LANES = 128
_BLOCK = 128  # q/kv block rows; also the T padding granule


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, t_real, causal, scale):
    """One (block_q, d) q tile against all k/v blocks of its batch-head."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (bq, d)
    bq, d = q.shape
    t_pad = k_ref.shape[1]
    n_kv = t_pad // _BLOCK
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, _BLOCK), 0)

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * _BLOCK, _BLOCK), :]
        vb = v_ref[0, pl.ds(j * _BLOCK, _BLOCK), :]
        s = jax.lax.dot_general(
            q,
            kb.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, BLOCK)
        cols = j * _BLOCK + jax.lax.broadcasted_iota(jnp.int32, (bq, _BLOCK), 1)
        valid = cols < t_real
        if causal:
            valid = valid & (rows >= cols)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        scale_old = jnp.exp(m - m_new)
        l_new = l * scale_old + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * scale_old + jax.lax.dot_general(
            p,
            vb.astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        # blocks past the diagonal are fully masked; skip them. bq ==
        # _BLOCK always (T is padded to a block multiple), so q tile qi's
        # diagonal k/v block is exactly block qi.
        hi = jnp.minimum(n_kv, qi + 1)
    else:
        hi = n_kv
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))

    # fully-masked rows (q padding) have l=0; emit 0 output, -inf lse
    safe_l = jnp.maximum(l, 1e-37)
    o_ref[0] = (acc / safe_l).astype(o_ref.dtype)
    lse = jnp.where(l[:, 0] > 0, m[:, 0] + jnp.log(safe_l[:, 0]), _NEG_INF)
    # lse is broadcast over 8 sublanes purely to satisfy the (8, 128) f32
    # tile rule for output blocks; the wrapper reads sublane 0
    lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


@functools.partial(jax.jit, static_argnames=("causal", "interpret", "t_real", "scale"))
def _flash_fwd_padded(q, k, v, *, causal, interpret, t_real, scale):
    """(BH, T_pad, d_pad) inputs -> (o, lse) with the same padding."""
    bh, t_pad, d_pad = q.shape
    grid = (bh, t_pad // _BLOCK)
    o, lse = pl.pallas_call(
        functools.partial(
            _flash_kernel, t_real=t_real, causal=causal, scale=scale
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, _BLOCK, d_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t_pad, d_pad), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t_pad, d_pad), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, _BLOCK, d_pad), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 8, _BLOCK), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, 8, t_pad), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse[:, 0, :]


def _pad_to(x, t_pad, d_pad):
    t, d = x.shape[-2], x.shape[-1]
    return jnp.pad(x, ((0, 0), (0, t_pad - t), (0, d_pad - d)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, causal):
    return _flash_fwd_res(q, k, v, causal)[0]


def _flash_fwd_res(q, k, v, causal):
    bh, t, d = q.shape
    t_pad = -(-t // _BLOCK) * _BLOCK
    d_pad = -(-d // _LANES) * _LANES
    scale = float(1.0 / (d**0.5))
    interpret = jax.devices()[0].platform != "tpu"
    qp, kp, vp = (_pad_to(a, t_pad, d_pad) for a in (q, k, v))
    o, lse = _flash_fwd_padded(
        qp, kp, vp, causal=causal, interpret=interpret, t_real=t, scale=scale
    )
    return o[:, :t, :d], lse[:, :t]


def _flash_fwd(q, k, v, causal):
    o, lse = _flash_fwd_res(q, k, v, causal)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, res, do):
    """Blocked flash backward in XLA: scan over k/v blocks, recomputing
    probabilities from the saved logsumexp. O(T * block) memory."""
    q, k, v, o, lse = res
    bh, t, d = q.shape
    scale = 1.0 / (d**0.5)

    # pad T to a block multiple (same discipline as the forward) so the
    # scan below never degenerates to one full (T, T) block. Padded q rows
    # get lse=+BIG so their probabilities underflow to exactly 0 (an -inf
    # pad would make exp(0 - lse) blow up); padded k/v columns are masked
    # in the scores; padded do/o rows are zero so every gradient term from
    # padding vanishes.
    block = min(_BLOCK, t)
    t_pad = -(-t // block) * block
    pad = ((0, 0), (0, t_pad - t), (0, 0))
    qf = jnp.pad(q.astype(jnp.float32), pad)
    do_f = jnp.pad(do.astype(jnp.float32), pad)
    of = jnp.pad(o.astype(jnp.float32), pad)
    kf = jnp.pad(k.astype(jnp.float32), pad)
    vf = jnp.pad(v.astype(jnp.float32), pad)
    lse_p = jnp.pad(lse, ((0, 0), (0, t_pad - t)), constant_values=1e30)

    delta = jnp.sum(do_f * of, axis=-1)  # (BH, T_pad)
    rows = jnp.arange(t_pad)

    n_blocks = t_pad // block
    kb = kf.reshape(bh, n_blocks, block, d).transpose(1, 0, 2, 3)
    vb = vf.reshape(bh, n_blocks, block, d).transpose(1, 0, 2, 3)

    def body(dq, blk):
        j, kj, vj = blk
        cols = j * block + jnp.arange(block)
        s = jnp.einsum("bqd,bkd->bqk", qf, kj) * scale
        valid = (cols < t)[None, :]
        if causal:
            valid = valid & (rows[:, None] >= cols[None, :])
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse_p[..., None])  # masked/-inf entries -> exactly 0
        dv_j = jnp.einsum("bqk,bqd->bkd", p, do_f)
        dp = jnp.einsum("bqd,bkd->bqk", do_f, vj)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, kj)
        dk_j = jnp.einsum("bqk,bqd->bkd", ds, qf)
        return dq, (dk_j, dv_j)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_b, dv_b) = jax.lax.scan(body, dq0, (jnp.arange(n_blocks), kb, vb))
    dk = dk_b.transpose(1, 0, 2, 3).reshape(bh, t_pad, d)[:, :t]
    dv = dv_b.transpose(1, 0, 2, 3).reshape(bh, t_pad, d)[:, :t]
    return dq[:, :t].astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> jax.Array:
    """Memory-efficient attention. (..., T, d) -> (..., T, d).

    Matches :func:`beholder_tpu.ops.attention.full_attention` to float
    tolerance; never materializes the (T, T) score matrix in either pass.
    """
    shape = q.shape
    t, d = shape[-2], shape[-1]
    q3, k3, v3 = (a.reshape(-1, t, d) for a in (q, k, v))
    return _flash(q3, k3, v3, causal).reshape(shape)
