"""Flash attention as Pallas TPU kernels (forward AND backward).

EXTENSION BEYOND THE REFERENCE (which has no attention or tensors of any
kind — SURVEY.md §0/§5). This is the single-device fast path behind the
sequence models' ``attention="flash"`` backend; ring attention
(:mod:`beholder_tpu.ops.attention`) distributes the same online-softmax
recurrence across chips.

Design (see /opt/skills/guides/pallas_guide.md):

- Forward kernel: grid (batch*heads, q blocks, kv blocks), kv innermost.
  Each q tile stays resident while (block_k, d) k/v tiles STREAM through
  VMEM — Pallas double-buffers the next tile's DMA behind the current
  tile's compute, so VMEM holds O(block) rows regardless of T. The
  online-softmax state (running max m, normalizer l, f32 accumulator)
  lives in VMEM scratch across the kv grid steps.
- All matmuls run in the INPUT dtype on the MXU with float32
  accumulation (``preferred_element_type``): bf16 inputs use the MXU's
  double-rate bf16 path, exactly matching ``full_attention``'s dtype mix
  (bf16 score matmul, f32 softmax, bf16 probability @ v).
- Causal masking skips work at GRID granularity in the forward: the grid
  is a packed triangular (bh, n_live) enumeration of only the live
  (qi >= kj) block pairs, driven by scalar-prefetched (qi, kj) lookup
  tables (``PrefetchScalarGridSpec``) — fully masked pairs never iterate,
  so the causal forward does ~half the work of the full grid and the
  advantage grows with T (see ROOFLINE.md). The causal backward kernels
  use the same packed grids (qi-major for dq's resident q tile, kj-major
  for dk/dv's resident kv tile); non-causal keeps plain rectangular
  grids. All three kernels mask only where it can bite — the causal
  diagonal block and, when T was padded, the last kv block.
- The kernel emits the per-row logsumexp, making the backward
  recomputation exact.
- Backward: TWO Pallas kernels with the same streaming discipline —
  one accumulates dq over kv blocks (q tile resident), one accumulates
  dk/dv over q blocks (kv tile resident) — recomputing probabilities
  from the saved logsumexp (the standard flash backward). Memory stays
  O(T * block) end to end; the (T, T) matrix never exists in either
  pass.
- Head dim is zero-padded to the 128-lane width and T to a block
  multiple; padded kv columns are masked with -inf so they contribute
  nothing, padded q rows carry zero cotangents, and padded d columns
  contribute zeros to every dot product.
- Grouped-query attention (GQA/MQA) is native: k/v may carry fewer
  heads than q (H = G * Hkv). Flattening keeps heads innermost, so q's
  flat index ``b`` reads kv flat index ``b // G`` — GQA costs ONE
  integer divide in the k/v BlockSpec index maps and nothing else; the
  kv tiles for a group's G q-heads are the same VMEM blocks. The
  backward computes per-q-head dk/dv partials and reduces the G-sized
  group axis in one fused XLA sum.
- On non-TPU backends the kernels run in interpreter mode, so the same
  code path is exercised by the CPU-mesh tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128
_MIN_BLOCK = 128   # T padding granule; smallest tile
_MAX_BLOCK = 1024  # preferred q/kv block rows when T allows


def _pick_block(t_pad: int) -> int:
    """Largest power-of-two block in [128, 512] dividing t_pad."""
    b = _MAX_BLOCK
    while b > _MIN_BLOCK and t_pad % b:
        b //= 2
    return b


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _masked_dispatch(step, *, causal, qi, kj, n_blk, padded):
    """Run ``step(masked)`` with masking only where it can bite: the causal
    diagonal block and (when T was padded) the last kv block. Interior
    blocks skip the iota/compare/select entirely. Padded q ROWS never need
    a mask in the backward kernels: their lse is +BIG so the recomputed
    probabilities underflow to exactly 0."""
    needs_mask = (qi == kj) if causal else False
    if padded:
        needs_mask = needs_mask | (kj == n_blk - 1)
    if needs_mask is False:
        step(False)
    else:
        pl.when(needs_mask)(lambda: step(True))
        pl.when(jnp.logical_not(needs_mask))(lambda: step(False))


def _tri_tables(n_blk):
    """Host-side (qi, kj) lookup tables for the packed causal grid.

    Enumerates (0,0),(1,0),(1,1),(2,0),... so the causal grid contains ONLY
    live blocks — a rectangular grid would spend ~40% of its steps on fully
    masked (qi < kj) pairs that still pay grid/DMA-sync overhead. The tables
    ride scalar prefetch (SMEM): index maps do one table load per step
    instead of recomputing a triangular decode on the scalar core.
    """
    import numpy as np

    qi = np.repeat(np.arange(n_blk), np.arange(1, n_blk + 1))
    kj = np.concatenate([np.arange(i + 1) for i in range(n_blk)])
    return jnp.asarray(qi, jnp.int32), jnp.asarray(kj, jnp.int32)


def _tri_tables_kv_major(n_blk):
    """(kj, qi) tables for the dk/dv kernel's packed grid: kv-tile-resident,
    so the enumeration is kj-major with qi running kj..n_blk-1 —
    (0,0),(0,1),...,(0,n-1),(1,1),... Only live (qi >= kj) pairs appear."""
    import numpy as np

    kj = np.repeat(np.arange(n_blk), np.arange(n_blk, 0, -1))
    qi = np.concatenate([np.arange(j, n_blk) for j in range(n_blk)])
    return jnp.asarray(kj, jnp.int32), jnp.asarray(qi, jnp.int32)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


# kv sub-chunk rows inside one grid step. Empirically on v5e the
# monolithic block (sub == block) wins: Mosaic does not overlap the
# 1-ahead pipelined chunks, and per-chunk softmax-state updates cost
# more VPU work than the overlap recovers (27.1 vs 17-22 TFLOP/s).
_SUB = 1024


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
    qi_kj, *, t_real, t_pad, causal, scale, block,
):
    """One (block, d) q tile x one streamed (block, d) kv tile.

    The kv tile is processed as unrolled _SUB-row chunks so Mosaic can
    overlap each chunk's softmax (VPU) with the next chunk's score
    matmul (MXU); at d=128 flash attention is VPU-bound otherwise.
    Masking is only computed where it can bite: the causal diagonal
    block and (when T was padded) the last kv block — interior blocks
    skip the iota/compare/select entirely.

    Causal runs on a PACKED triangular grid (bh, n_live): (qi, kj) come
    from scalar-prefetched lookup tables so fully-masked pairs never
    iterate. Non-causal keeps the rectangular (bh, nq, nkv) grid.
    """
    n_blk = t_pad // block
    if causal:
        qi, kj = qi_kj            # read from the scalar-prefetch tables
        last_kv = qi              # the diagonal block ends row qi
    else:
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        last_kv = pl.num_programs(2) - 1

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    sub = min(_SUB, block)
    n_sub = block // sub

    def _chunks(masked: bool):
        # fold the softmax scale into q once per tile — one (bq, d) pass
        # instead of a (bq, bk) f32 multiply per kv block
        q = (q_ref[0].astype(jnp.float32) * scale).astype(q_ref.dtype)

        def score(j2):
            kc = k_ref[0, j2 * sub:(j2 + 1) * sub, :]
            s = jax.lax.dot_general(
                q, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                              # (bq, sub) f32
            if masked:
                rows = qi * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, sub), 0
                )
                cols = kj * block + j2 * sub + jax.lax.broadcasted_iota(
                    jnp.int32, (block, sub), 1
                )
                valid = cols < t_real
                if causal:
                    valid = valid & (rows >= cols)
                s = jnp.where(valid, s, _NEG_INF)
            return s

        # 1-ahead software pipeline: the NEXT chunk's score matmul is
        # issued to the MXU before this chunk's softmax runs on the VPU,
        # so the two units overlap instead of serializing
        s = score(0)
        for j2 in range(n_sub):
            s_next = score(j2 + 1) if j2 + 1 < n_sub else None
            vc = v_ref[0, j2 * sub:(j2 + 1) * sub, :]
            m_prev = m_ref[:, :1]          # (bq, 1); lanes hold copies
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)         # (bq, sub) f32
            alpha = jnp.exp(m_prev - m_new)
            l_ref[:] = jnp.broadcast_to(
                l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
                l_ref.shape,
            )
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
                p.astype(vc.dtype), vc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
            s = s_next

    # the packed causal grid contains only live (qi >= kj) pairs, so no
    # liveness guard is needed
    _masked_dispatch(
        _chunks, causal=causal, qi=qi, kj=kj, n_blk=n_blk,
        padded=t_pad != t_real,
    )

    @pl.when(kj == last_kv)
    def _finalize():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        safe_l = jnp.maximum(l, 1e-37)     # fully-masked (padded) rows: l=0
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(safe_l), _NEG_INF)
        # per-q-row logsumexp lives on the SUBLANE dim with 128 lanes of
        # copies (the official TPU flash layout): the backward can read a
        # (block, 1) column directly, no in-kernel transpose
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


@functools.partial(
    jax.jit, static_argnames=("causal", "interpret", "t_real", "scale")
)
def _flash_fwd_padded(q, k, v, *, causal, interpret, t_real, scale):
    """(BH, T_pad, d_pad) q + (BHkv, T_pad, d_pad) k/v -> (o, lse) with
    q's padding. GQA: q head ``b`` attends kv head ``b // group``."""
    bh, t_pad, d_pad = q.shape
    group = bh // k.shape[0]
    block = _pick_block(t_pad)
    n_blk = t_pad // block

    scratch = [
        pltpu.VMEM((block, _LANES), jnp.float32),  # m
        pltpu.VMEM((block, _LANES), jnp.float32),  # l
        pltpu.VMEM((block, d_pad), jnp.float32),   # acc
    ]

    out_shape = [
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((bh, t_pad, _LANES), jnp.float32),
    ]

    if causal:
        # packed triangular grid: one step per LIVE (qi, kj) block pair,
        # driven by scalar-prefetched lookup tables (index maps do one SMEM
        # load per step; a computed decode would run on the scalar core and
        # stall DMA issue)
        qi_tab, kj_tab = _tri_tables(n_blk)
        q_map = lambda b, l, qt, kt: (b, qt[l], 0)
        kv_map = lambda b, l, qt, kt: (b // group, kt[l], 0)

        def kernel(qt_ref, kt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_ref, l_ref, acc_ref):
            lin = pl.program_id(1)
            _fwd_kernel(
                q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                (qt_ref[lin], kt_ref[lin]),
                t_real=t_real, t_pad=t_pad, causal=causal, scale=scale,
                block=block,
            )

        o, lse = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(bh, n_blk * (n_blk + 1) // 2),
                in_specs=[
                    pl.BlockSpec((1, block, d_pad), q_map),
                    pl.BlockSpec((1, block, d_pad), kv_map),
                    pl.BlockSpec((1, block, d_pad), kv_map),
                ],
                out_specs=[
                    pl.BlockSpec((1, block, d_pad), q_map),
                    pl.BlockSpec((1, block, _LANES), q_map),
                ],
                scratch_shapes=scratch,
            ),
            out_shape=out_shape,
            interpret=interpret,
        )(qi_tab, kj_tab, q, k, v)
        return o, lse[:, :, 0]

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref):
        _fwd_kernel(
            q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
            None,
            t_real=t_real, t_pad=t_pad, causal=causal, scale=scale,
            block=block,
        )

    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_blk, n_blk),
        in_specs=[
            pl.BlockSpec((1, block, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block, d_pad), lambda b, i, j: (b // group, j, 0)),
            pl.BlockSpec((1, block, d_pad), lambda b, i, j: (b // group, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)
    return o, lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward: dq kernel (q tile resident, kv streams)
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref,
    qi_kj, *, t_real, t_pad, causal, scale, block,
):
    n_blk = t_pad // block
    if causal:
        qi, kj = qi_kj            # packed triangular grid (see forward)
        last_kv = qi
    else:
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        last_kv = pl.num_programs(2) - 1

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _step(masked: bool):
        q = q_ref[0]
        kb = k_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if masked:
            rows = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0
            )
            cols = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1
            )
            valid = cols < t_real
            if causal:
                valid = valid & (rows >= cols)
            s = jnp.where(valid, s, _NEG_INF)
        # p: exact probabilities recomputed from the saved logsumexp
        # (padded q rows carry lse=+BIG so p underflows to exactly 0)
        p = jnp.exp(s - lse_ref[0][:, :1])             # (bq, bk) f32
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # (bq, bk) f32
        ds = p * (dp - delta_ref[0][:, :1]) * scale     # (bq, bk) f32
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _masked_dispatch(
        _step, causal=causal, qi=qi, kj=kj, n_blk=n_blk,
        padded=t_pad != t_real,
    )

    @pl.when(kj == last_kv)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dk/dv kernel (kv tile resident, q streams)
# ---------------------------------------------------------------------------


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, kj_qi, *, t_real, t_pad, causal, scale, block,
):
    n_blk = t_pad // block
    if causal:
        kj, qi = kj_qi            # packed upper-triangle grid, q innermost
        first_q = kj              # row kj's first contributing q block
    else:
        kj = pl.program_id(1)
        qi = pl.program_id(2)
        first_q = 0
    n_q = n_blk

    @pl.when(qi == first_q)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _step(masked: bool):
        q = q_ref[0]
        kb = k_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if masked:
            rows = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0
            )
            cols = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1
            )
            valid = cols < t_real
            if causal:
                valid = valid & (rows >= cols)
            s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])              # (bq, bk) f32
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # (bk, d)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # (bk, d)

    _masked_dispatch(
        _step, causal=causal, qi=qi, kj=kj, n_blk=n_blk,
        padded=t_pad != t_real,
    )

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "interpret", "t_real", "scale")
)
def _flash_bwd_padded(q, k, v, o, lse, do, *, causal, interpret, t_real, scale):
    """Padded (BH, T_pad, d_pad) residuals + cotangent -> (dq, dk, dv).

    GQA (k/v lead BHkv = BH / group): dk/dv come back with q's BH lead —
    one per-q-head partial per group member, reduced by the caller."""
    bh, t_pad, d_pad = q.shape
    group = bh // k.shape[0]
    block = _pick_block(t_pad)
    n_blk = t_pad // block

    # delta_i = sum_d do_i * o_i — one cheap fused XLA pass. Both lse and
    # delta take the lane-broadcast (BH, T_pad, 128) layout so the kernels
    # read a (block, 1) sublane column with no transpose.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta_b = jnp.broadcast_to(delta[:, :, None], (bh, t_pad, _LANES))
    lse_b = jnp.broadcast_to(lse[:, :, None], (bh, t_pad, _LANES))

    tile = lambda index_map: pl.BlockSpec((1, block, d_pad), index_map)
    rows = lambda index_map: pl.BlockSpec((1, block, _LANES), index_map)
    dq_scratch = [pltpu.VMEM((block, d_pad), jnp.float32)]
    dkv_scratch = [
        pltpu.VMEM((block, d_pad), jnp.float32),
        pltpu.VMEM((block, d_pad), jnp.float32),
    ]
    # dk/dv carry q's BH lead (per-q-head partials under GQA; identical to
    # the kv lead when group == 1)
    dkv_out_shape = [
        jax.ShapeDtypeStruct((bh,) + k.shape[1:], k.dtype),
        jax.ShapeDtypeStruct((bh,) + v.shape[1:], v.dtype),
    ]

    if causal:
        # packed triangular grids (same trick as the forward): one grid
        # step per LIVE (qi, kj) pair, (qi, kj) scalar-prefetched
        n_live = n_blk * (n_blk + 1) // 2
        qi_tab, kj_tab = _tri_tables(n_blk)
        q_map = lambda b, l, at, bt: (b, at[l], 0)
        kv_map = lambda b, l, at, bt: (b // group, bt[l], 0)

        def dq_kernel(at_ref, bt_ref, *refs):
            lin = pl.program_id(1)
            _dq_kernel(
                *refs, (at_ref[lin], bt_ref[lin]),
                t_real=t_real, t_pad=t_pad, causal=causal, scale=scale,
                block=block,
            )

        dq = pl.pallas_call(
            dq_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(bh, n_live),
                in_specs=[
                    tile(q_map), tile(kv_map), tile(kv_map),
                    tile(q_map), rows(q_map), rows(q_map),
                ],
                out_specs=tile(q_map),
                scratch_shapes=dq_scratch,
            ),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
        )(qi_tab, kj_tab, q, k, v, do, lse_b, delta_b)

        # dk/dv: kv tile resident -> kj-major enumeration, q innermost.
        # Inputs read kv head b // group; outputs write q head b (per-
        # q-head partials, group-reduced by the caller).
        kj_tab2, qi_tab2 = _tri_tables_kv_major(n_blk)
        kv_map2 = lambda b, l, kt, qt: (b // group, kt[l], 0)
        dkv_map2 = lambda b, l, kt, qt: (b, kt[l], 0)
        q_map2 = lambda b, l, kt, qt: (b, qt[l], 0)

        def dkv_kernel(kt_ref, qt_ref, *refs):
            lin = pl.program_id(1)
            _dkv_kernel(
                *refs, (kt_ref[lin], qt_ref[lin]),
                t_real=t_real, t_pad=t_pad, causal=causal, scale=scale,
                block=block,
            )

        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(bh, n_live),
                in_specs=[
                    tile(q_map2), tile(kv_map2), tile(kv_map2),
                    tile(q_map2), rows(q_map2), rows(q_map2),
                ],
                out_specs=[tile(dkv_map2), tile(dkv_map2)],
                scratch_shapes=dkv_scratch,
            ),
            out_shape=dkv_out_shape,
            interpret=interpret,
        )(kj_tab2, qi_tab2, q, k, v, do, lse_b, delta_b)
        return dq, dk, dv

    q_res = lambda b, i, j: (b, i, 0)        # follows the resident tile
    kv_stream = lambda b, i, j: (b // group, j, 0)

    dq = pl.pallas_call(
        lambda *refs: _dq_kernel(
            *refs, None, t_real=t_real, t_pad=t_pad, causal=causal,
            scale=scale, block=block,
        ),
        grid=(bh, n_blk, n_blk),
        in_specs=[
            tile(q_res), tile(kv_stream), tile(kv_stream),
            tile(q_res), rows(q_res), rows(q_res),
        ],
        out_specs=tile(q_res),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=dq_scratch,
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b)

    kv_res = lambda b, j, i: (b // group, j, 0)   # resident kv tile
    dkv_res = lambda b, j, i: (b, j, 0)           # per-q-head partial out
    q_stream = lambda b, j, i: (b, i, 0)

    dk, dv = pl.pallas_call(
        lambda *refs: _dkv_kernel(
            *refs, None, t_real=t_real, t_pad=t_pad, causal=causal,
            scale=scale, block=block,
        ),
        grid=(bh, n_blk, n_blk),
        in_specs=[
            tile(q_stream), tile(kv_res), tile(kv_res),
            tile(q_stream), rows(q_stream), rows(q_stream),
        ],
        out_specs=[tile(dkv_res), tile(dkv_res)],
        out_shape=dkv_out_shape,
        scratch_shapes=dkv_scratch,
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-VJP wrapper
# ---------------------------------------------------------------------------


def _pad_to(x, t_pad, d_pad):
    t, d = x.shape[-2], x.shape[-1]
    return jnp.pad(x, ((0, 0), (0, t_pad - t), (0, d_pad - d)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, causal):
    return _flash_fwd_res(q, k, v, causal)[0]


def _flash_fwd_res(q, k, v, causal):
    bh, t, d = q.shape
    t_pad = -(-t // _MIN_BLOCK) * _MIN_BLOCK
    d_pad = -(-d // _LANES) * _LANES
    scale = float(1.0 / (d**0.5))
    qp, kp, vp = (_pad_to(a, t_pad, d_pad) for a in (q, k, v))
    o, lse = _flash_fwd_padded(
        qp, kp, vp, causal=causal, interpret=_interpret(), t_real=t,
        scale=scale,
    )
    return o[:, :t, :d], lse[:, :t]


def _flash_fwd(q, k, v, causal):
    o, lse = _flash_fwd_res(q, k, v, causal)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, res, do):
    q, k, v, o, lse = res
    bh, t, d = q.shape
    group = bh // k.shape[0]
    t_pad = -(-t // _MIN_BLOCK) * _MIN_BLOCK
    d_pad = -(-d // _LANES) * _LANES
    scale = float(1.0 / (d**0.5))
    qp, kp, vp, op, dop = (_pad_to(a, t_pad, d_pad) for a in (q, k, v, o, do))
    # padded q rows get lse=+BIG so their recomputed probabilities
    # underflow to exactly 0 (an -inf pad would make exp(0 - lse) blow
    # up: padded q rows are zeros, not masked, so their s entries are 0);
    # their cotangent rows are zero-padded too, killing every grad term
    lse_p = jnp.pad(lse, ((0, 0), (0, t_pad - t)), constant_values=1e30)
    dq, dk, dv = _flash_bwd_padded(
        qp, kp, vp, op, lse_p, dop, causal=causal, interpret=_interpret(),
        t_real=t, scale=scale,
    )
    if group > 1:
        # per-q-head partials -> kv heads: flat q index = kv_index*G + g,
        # so a C-order reshape exposes the group axis directly
        dk = dk.reshape(k.shape[0], group, t_pad, d_pad)
        dk = dk.astype(jnp.float32).sum(axis=1).astype(k.dtype)
        dv = dv.reshape(v.shape[0], group, t_pad, d_pad)
        dv = dv.astype(jnp.float32).sum(axis=1).astype(v.dtype)
    return dq[:, :t, :d], dk[:, :t, :d], dv[:, :t, :d]


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> jax.Array:
    """Memory-efficient attention. (..., T, d) -> (..., T, d).

    Matches :func:`beholder_tpu.ops.attention.full_attention` to float
    tolerance; never materializes the (T, T) score matrix in either pass.

    Grouped-query attention: k/v may carry FEWER heads than q on the -3
    dim (H = G * Hkv, MQA at Hkv=1); each group of G consecutive q heads
    attends the same kv head. All other leading dims must match.
    """
    shape = q.shape
    t, d = shape[-2], shape[-1]
    if k.shape != q.shape:
        if (
            q.ndim < 3
            or k.shape[:-3] != q.shape[:-3]
            or k.shape[-2:] != q.shape[-2:]
            or q.shape[-3] % k.shape[-3]
        ):
            raise ValueError(
                f"GQA shapes must differ only in heads (-3 dim), with "
                f"q heads a multiple of kv heads; got {q.shape} vs {k.shape}"
            )
    if k.shape != v.shape:
        raise ValueError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    q3 = q.reshape(-1, t, d)
    k3, v3 = (a.reshape(-1, t, d) for a in (k, v))
    return _flash(q3, k3, v3, causal).reshape(shape)
