"""Flash attention as Pallas TPU kernels (forward AND backward).

EXTENSION BEYOND THE REFERENCE (which has no attention or tensors of any
kind — SURVEY.md §0/§5). This is the single-device fast path behind the
sequence models' ``attention="flash"`` backend; ring attention
(:mod:`beholder_tpu.ops.attention`) distributes the same online-softmax
recurrence across chips.

Design (see /opt/skills/guides/pallas_guide.md):

- Forward kernel: grid (batch*heads, q blocks, kv blocks), kv innermost.
  Each q tile stays resident while (block_k, d) k/v tiles STREAM through
  VMEM — Pallas double-buffers the next tile's DMA behind the current
  tile's compute, so VMEM holds O(block) rows regardless of T. The
  online-softmax state (running max m, normalizer l, f32 accumulator)
  lives in VMEM scratch across the kv grid steps.
- All matmuls run in the INPUT dtype on the MXU with float32
  accumulation (``preferred_element_type``): bf16 inputs use the MXU's
  double-rate bf16 path, exactly matching ``full_attention``'s dtype mix
  (bf16 score matmul, f32 softmax, bf16 probability @ v).
- Causal masking skips work at GRID granularity in the forward: the grid
  is a packed triangular (bh, n_live) enumeration of only the live
  (qi >= kj) block pairs, driven by scalar-prefetched (qi, kj) lookup
  tables (``PrefetchScalarGridSpec``) — fully masked pairs never iterate,
  so the causal forward does ~half the work of the full grid and the
  advantage grows with T (see ROOFLINE.md). The causal backward kernels
  use the same packed grids (qi-major for dq's resident q tile, kj-major
  for dk/dv's resident kv tile); non-causal keeps plain rectangular
  grids. All three kernels mask only where it can bite — the causal
  diagonal block and, when T was padded, the last kv block.
- The kernel emits the per-row logsumexp, making the backward
  recomputation exact.
- Backward: TWO Pallas kernels with the same streaming discipline —
  one accumulates dq over kv blocks (q tile resident), one accumulates
  dk/dv over q blocks (kv tile resident) — recomputing probabilities
  from the saved logsumexp (the standard flash backward). Memory stays
  O(T * block) end to end; the (T, T) matrix never exists in either
  pass.
- Head dim is zero-padded to the 128-lane width and T to a block
  multiple; padded kv columns are masked with -inf so they contribute
  nothing, padded q rows carry zero cotangents, and padded d columns
  contribute zeros to every dot product.
- Grouped-query attention (GQA/MQA) is native: k/v may carry fewer
  heads than q (H = G * Hkv). Flattening keeps heads innermost, so q's
  flat index ``b`` reads kv flat index ``b // G`` — GQA costs ONE
  integer divide in the k/v BlockSpec index maps and nothing else; the
  kv tiles for a group's G q-heads are the same VMEM blocks. The
  backward computes per-q-head dk/dv partials and reduces the G-sized
  group axis in one fused XLA sum.
- Sliding-window attention generalizes the packed triangular grid to a
  packed BANDED grid: the same scalar-prefetched tables enumerate only
  in-band (qi, kj) pairs (with first/last flags driving state init and
  output write-out), so forward AND backward cost scales with
  T * window instead of T^2.
- Segment ids (packed-sequence training) mask cross-segment attention
  inside the kernels: the q-side ids ride the lane-broadcast lse
  layout, the kv-side ids a sublane-broadcast row layout, so the
  (block, block) segment-equality mask is one broadcast compare with no
  in-kernel transpose. Blocks can then be fully masked at runtime, so
  the softmax zeroes masked probabilities explicitly.
- On non-TPU backends the kernels run in interpreter mode, so the same
  code path is exercised by the CPU-mesh tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128
_MIN_BLOCK = 128   # T padding granule; smallest tile
_MAX_BLOCK = 1024  # preferred q/kv block rows when T allows


def _pick_block(t_pad: int, window: int | None = None) -> int:
    """Largest power-of-two block in [128, 1024] dividing t_pad — capped
    near ``window`` when sliding-window attention is on. With block >>
    window every live block sits on the band edge and pays the full
    (block, block) mask compute; with block ~ window each q row touches
    ~2 small blocks and the mask shrinks quadratically, trading into
    fixed per-step grid overhead instead. Round-5 slope-timed
    measurement (10 alternating rounds, min estimator — see
    BENCH_NOTES.md on why block-until-ready timing lied here): block =
    window and block = window/2 are within 3% at w=1024@T=16k, so the
    cap keeps the simple rule; its real job is keeping the live-step
    count — and VMEM footprint — proportional to the window rather
    than to T."""
    b = _MAX_BLOCK
    if window is not None:
        cap = max(_MIN_BLOCK, 1 << (window - 1).bit_length())
        b = min(b, cap)
    while b > _MIN_BLOCK and t_pad % b:
        b //= 2
    return b


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _masked_dispatch(step, *, causal, qi, kj, n_blk, padded, window=None,
                     block=None, has_seg=False, has_off=False):
    """Run ``step(masked)`` with masking only where it can bite: the causal
    diagonal block, (when T was padded) the last kv block, and (under a
    sliding window) the band's trailing-edge blocks. Interior blocks skip
    the iota/compare/select entirely. Segment ids are runtime data, so
    with ``has_seg`` every block masks — and likewise global row/col
    OFFSETS (``has_off``, the ring-attention block-pair path): the mask
    position depends on traced scalars, so no block's liveness is known
    at trace time. Padded q ROWS never need a mask in the backward
    kernels: their lse is +BIG so the recomputed probabilities underflow
    to exactly 0."""
    if has_seg or has_off:
        step(True)
        return
    needs_mask = (qi == kj) if causal else False
    if window is not None:
        # fully-live needs max(row-col) = (qi-kj+1)*block - 1 < window
        needs_mask = needs_mask | ((qi - kj + 1) * block - 1 >= window)
    if padded:
        needs_mask = needs_mask | (kj == n_blk - 1)
    if needs_mask is False:
        step(False)
    else:
        pl.when(needs_mask)(lambda: step(True))
        pl.when(jnp.logical_not(needs_mask))(lambda: step(False))


def _first_kj(qi: int, block: int, window: int | None) -> int:
    """First kv block holding any live column for q tile ``qi`` under a
    causal (+ optional sliding-window) mask. Row r attends cols in
    [r-window+1, r]; the tile's first row qi*block reaches back furthest."""
    if window is None:
        return 0
    return max(0, (qi * block - window + 1) // block)


def _last_qi(kj: int, n_blk: int, block: int, window: int | None) -> int:
    """Last q tile with any live row for kv block ``kj`` (dual of
    :func:`_first_kj`): (qi-kj-1)*block + 1 <= window-1 must hold."""
    if window is None:
        return n_blk - 1
    return min(n_blk - 1, kj + 1 + (window - 2) // block) if window > 1 else kj


def _band_tables(n_blk, block, window):
    """Host-side lookup tables for the packed causal/banded grid, qi-major.

    Enumerates only LIVE (qi, kj) block pairs — kj in
    [_first_kj(qi), qi] — so fully masked pairs never iterate: a
    rectangular grid would spend ~40% (causal) to ~95% (short sliding
    window at long T) of its steps on dead pairs that still pay
    grid/DMA-sync overhead. Every enumerated block holds at least one
    live (row, col) pair, which the online softmax requires (a fully
    masked block would turn exp(s - m) into ones). The tables ride scalar
    prefetch (SMEM): index maps do one table load per step instead of
    recomputing a banded decode on the scalar core.

    Returns (qi, kj, first, last): per-step block coordinates plus flags
    marking the first/last kv step of each q tile's run — the kernels
    init their online-softmax state on ``first`` and write the tile's
    output on ``last`` (with a full causal band these degenerate to the
    classic ``kj == 0`` / ``kj == qi`` conditions).
    """
    qi, kj, first, last = [], [], [], []
    for i in range(n_blk):
        lo = _first_kj(i, block, window)
        for j in range(lo, i + 1):
            qi.append(i)
            kj.append(j)
            first.append(1 if j == lo else 0)
            last.append(1 if j == i else 0)
    return tuple(jnp.asarray(t, jnp.int32) for t in (qi, kj, first, last))


def _band_tables_kv_major(n_blk, block, window):
    """(kj, qi, first, last) tables for the dk/dv kernel's packed grid:
    kv-tile-resident, so the enumeration is kj-major with qi running
    kj.._last_qi(kj). Only live pairs appear; ``first``/``last`` flag the
    first/last q step of each kv tile's run."""
    kj, qi, first, last = [], [], [], []
    for j in range(n_blk):
        hi = _last_qi(j, n_blk, block, window)
        for i in range(j, hi + 1):
            kj.append(j)
            qi.append(i)
            first.append(1 if i == j else 0)
            last.append(1 if i == hi else 0)
    return tuple(jnp.asarray(t, jnp.int32) for t in (kj, qi, first, last))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


# kv sub-chunk rows inside one grid step. Empirically on v5e the
# monolithic block (sub == block) wins: Mosaic does not overlap the
# 1-ahead pipelined chunks, and per-chunk softmax-state updates cost
# more VPU work than the overlap recovers (27.1 vs 17-22 TFLOP/s).
_SUB = 1024

# NEGATIVE RESULT (round 5): rounds 3-4 carried a "precomputed mask-bias
# tile" path here — per-block-offset (block, block) f32 tiles in VMEM
# scratch, added to masked steps' scores instead of running the inline
# iota/compare/select mask — on the theory that the inline mask's VPU
# passes dominated the banded grid (the recorded w=1024@T=16k speedup
# was stuck at 1.73x of an ~8x FLOP saving). Round-5 re-measurement with
# tunnel-robust slope timing (see BENCH_NOTES.md "the serving 100x was
# the tunnel") showed the premise was a measurement artifact: the old
# timing charged a ~65 ms device->host readback constant across 20 reps
# (~3.2 ms) onto a ~1.4 ms kernel. Measured honestly and interleaved on
# v5e, the INLINE mask wins or ties at every shape tried — w=1024@T=16k
# tiles-at-block-512 2.6x SLOWER, tiles-at-block-1024 (raised budget)
# ~1.2x slower, T=4096 causal diagonal tile ~1.2x slower, T=16k causal
# a wash — and the window speedup with the plain inline mask is ~4x
# (ROOFLINE.md). The tile machinery was deleted rather than kept behind
# a flag: it costs VMEM, a guard, and a silent-veto failure mode
# (round-4 advisor finding) for a path that never pays.


def _fwd_kernel(
    q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref, lse_ref,
    m_ref, l_ref, acc_ref, band, *, t_real, t_pad, causal, scale, block,
    window, qoff=None, kvoff=None,
):
    """One (block, d) q tile x one streamed (block, d) kv tile.

    The kv tile is processed as unrolled _SUB-row chunks so Mosaic can
    overlap each chunk's softmax (VPU) with the next chunk's score
    matmul (MXU); at d=128 flash attention is VPU-bound otherwise.
    Masking is only computed where it can bite: the causal diagonal
    block, the sliding-window band edge, and (when T was padded) the
    last kv block — interior blocks skip the iota/compare/select
    entirely. Segment ids (``qseg_ref``/``kseg_ref`` non-None) mask
    every block, plus a p-zeroing guard because a block can then be
    fully masked at runtime (exp(s - m) would otherwise turn into ones).

    Causal runs on a PACKED banded grid (bh, n_live): (qi, kj, first,
    last) come from scalar-prefetched lookup tables so fully-masked
    pairs never iterate. Non-causal keeps the rectangular (bh, nq, nkv)
    grid.
    """
    n_blk = t_pad // block
    has_seg = qseg_ref is not None
    if band is not None:  # packed causal grid (no band in offset mode)
        qi, kj, is_first, is_last = band  # scalar-prefetch table reads
    else:
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        is_first = kj == 0
        is_last = kj == pl.num_programs(2) - 1

    @pl.when(is_first)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    sub = min(_SUB, block)
    n_sub = block // sub

    def _chunks(masked: bool):
        # fold the softmax scale into q once per tile — one (bq, d) pass
        # instead of a (bq, bk) f32 multiply per kv block
        q = (q_ref[0].astype(jnp.float32) * scale).astype(q_ref.dtype)

        def score(j2):
            kc = k_ref[0, j2 * sub:(j2 + 1) * sub, :]
            s = jax.lax.dot_general(
                q, kc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                              # (bq, sub) f32
            if masked:
                rows = qi * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, sub), 0
                )
                cols = kj * block + j2 * sub + jax.lax.broadcasted_iota(
                    jnp.int32, (block, sub), 1
                )
                valid = cols < t_real  # padding is LOCAL to this shard
                if qoff is not None:
                    # ring block pair: causal/window run on GLOBAL
                    # positions (traced per-device offsets)
                    rows = rows + qoff
                    cols = cols + kvoff
                if causal:
                    valid = valid & (rows >= cols)
                if window is not None:
                    valid = valid & (rows - cols < window)
                if has_seg:
                    qseg = qseg_ref[0][:, :1]                  # (bq, 1)
                    kseg = kseg_ref[0][:1, j2 * sub:(j2 + 1) * sub]
                    valid = valid & (qseg == kseg)             # (bq, sub)
                s = jnp.where(valid, s, _NEG_INF)
            return s

        # 1-ahead software pipeline: the NEXT chunk's score matmul is
        # issued to the MXU before this chunk's softmax runs on the VPU,
        # so the two units overlap instead of serializing
        s = score(0)
        for j2 in range(n_sub):
            s_next = score(j2 + 1) if j2 + 1 < n_sub else None
            vc = v_ref[0, j2 * sub:(j2 + 1) * sub, :]
            m_prev = m_ref[:, :1]          # (bq, 1); lanes hold copies
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)         # (bq, sub) f32
            if has_seg or qoff is not None:
                # a fully-masked block (runtime segments, or a ring pair
                # wholly dead/out-of-band at these offsets) leaves m_new
                # at -inf and p at exp(0)=1; zero explicitly
                p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[:] = jnp.broadcast_to(
                l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
                l_ref.shape,
            )
            acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
                p.astype(vc.dtype), vc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
            s = s_next

    # the packed banded grid contains only live block pairs, so no
    # liveness guard is needed
    _masked_dispatch(
        _chunks, causal=causal, qi=qi, kj=kj, n_blk=n_blk,
        padded=t_pad != t_real, window=window, block=block, has_seg=has_seg,
        has_off=qoff is not None,
    )

    @pl.when(is_last)
    def _finalize():
        l = l_ref[:, :1]
        m = m_ref[:, :1]
        safe_l = jnp.maximum(l, 1e-37)     # fully-masked (padded) rows: l=0
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m + jnp.log(safe_l), _NEG_INF)
        # per-q-row logsumexp lives on the SUBLANE dim with 128 lanes of
        # copies (the official TPU flash layout): the backward can read a
        # (block, 1) column directly, no in-kernel transpose
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


_SEG_SUB = 8  # sublane-broadcast rows for the kv-side segment layout


def _seg_operands(seg, t_pad):
    """Kernel-friendly segment layouts from the (B, T) batch-lead ids:
    the q side lane-broadcast (B, T_pad, LANES) so a (block, 1) column
    reads straight off the sublane dim (the lse trick), the kv side
    sublane-broadcast (B, 8, T_pad) so a (1, block) ROW vector reads
    without any in-kernel transpose. Both stay BATCH-lead — segments
    don't vary by head, so the BlockSpec index maps divide the flat
    (B*H) grid index by the head count instead of materializing H
    copies in HBM. Padded positions get segment -1 (matches nothing;
    padded columns are already masked by ``cols < t_real``)."""
    t = seg.shape[-1]
    s = jnp.pad(seg.astype(jnp.int32), ((0, 0), (0, t_pad - t)),
                constant_values=-1)
    q_op = jnp.broadcast_to(s[:, :, None], (*s.shape, _LANES))
    k_op = jnp.broadcast_to(s[:, None, :], (s.shape[0], _SEG_SUB, s.shape[1]))
    return q_op, k_op


def _seg_specs(has_seg, block, qseg_map, kseg_map):
    """The two segment-operand BlockSpecs (q-side lane-broadcast column,
    kv-side sublane-broadcast row), or [] when segments are off."""
    if not has_seg:
        return []
    return [
        pl.BlockSpec((1, block, _LANES), qseg_map),
        pl.BlockSpec((1, _SEG_SUB, block), kseg_map),
    ]


@functools.partial(
    jax.jit,
    static_argnames=("causal", "interpret", "t_real", "scale", "window"),
)
def _flash_fwd_padded(
    q, k, v, qseg=None, kseg=None, offsets=None, *, causal, interpret,
    t_real, scale, window=None,
):
    """(BH, T_pad, d_pad) q + (BHkv, T_pad, d_pad) k/v -> (o, lse) with
    q's padding. GQA: q head ``b`` attends kv head ``b // group``.
    ``qseg``/``kseg`` are the pre-broadcast segment operands from
    :func:`_seg_operands`; ``window`` is the causal sliding-window span.
    ``offsets`` (a traced (2,) int32 [q_offset, kv_offset]) switches to
    the ring BLOCK-PAIR mode: causal/window masks run on global
    positions, every block masks (liveness is runtime data), and the
    grid is the plain rectangular one (a packed triangular grid assumes
    the diagonal sits at equal offsets).
    """
    bh, t_pad, d_pad = q.shape
    group = bh // k.shape[0]
    block = _pick_block(t_pad, window)
    n_blk = t_pad // block
    has_seg = qseg is not None
    has_off = offsets is not None
    if has_seg and has_off:
        raise NotImplementedError("segment ids + ring offsets unsupported")
    seg_in = [qseg, kseg] if has_seg else []
    # segment operands are BATCH-lead (see _seg_operands): divide the
    # flat (B*H) grid index down to the batch
    seg_div = bh // qseg.shape[0] if has_seg else 1

    scratch = [
        pltpu.VMEM((block, _LANES), jnp.float32),  # m
        pltpu.VMEM((block, _LANES), jnp.float32),  # l
        pltpu.VMEM((block, d_pad), jnp.float32),   # acc
    ]

    out_shape = [
        jax.ShapeDtypeStruct(q.shape, q.dtype),
        jax.ShapeDtypeStruct((bh, t_pad, _LANES), jnp.float32),
    ]

    if has_off:
        # ring block-pair mode: rectangular grid, offsets scalar-
        # prefetched into SMEM, every block masked on global positions
        def kernel(offs_ref, q_ref, k_ref, v_ref, *rest):
            o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
            _fwd_kernel(
                q_ref, k_ref, v_ref, None, None, o_ref, lse_ref,
                m_ref, l_ref, acc_ref, None,
                t_real=t_real, t_pad=t_pad, causal=causal, scale=scale,
                block=block, window=window, qoff=offs_ref[0],
                kvoff=offs_ref[1],
            )

        o, lse = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(bh, n_blk, n_blk),
                in_specs=[
                    pl.BlockSpec((1, block, d_pad), lambda b, i, j, o_: (b, i, 0)),
                    pl.BlockSpec(
                        (1, block, d_pad),
                        lambda b, i, j, o_: (b // group, j, 0),
                    ),
                    pl.BlockSpec(
                        (1, block, d_pad),
                        lambda b, i, j, o_: (b // group, j, 0),
                    ),
                ],
                out_specs=[
                    pl.BlockSpec((1, block, d_pad), lambda b, i, j, o_: (b, i, 0)),
                    pl.BlockSpec((1, block, _LANES), lambda b, i, j, o_: (b, i, 0)),
                ],
                scratch_shapes=scratch,
            ),
            out_shape=out_shape,
            interpret=interpret,
        )(offsets, q, k, v)
        return o, lse[:, :, 0]

    if causal:
        # packed banded grid: one step per LIVE (qi, kj) block pair,
        # driven by scalar-prefetched lookup tables (index maps do one SMEM
        # load per step; a computed decode would run on the scalar core and
        # stall DMA issue)
        qi_tab, kj_tab, first_tab, last_tab = _band_tables(
            n_blk, block, window
        )
        q_map = lambda b, l, *tabs: (b, tabs[0][l], 0)
        kv_map = lambda b, l, *tabs: (b // group, tabs[1][l], 0)
        seg_specs = _seg_specs(
            has_seg, block,
            lambda b, l, *tabs: (b // seg_div, tabs[0][l], 0),
            lambda b, l, *tabs: (b // seg_div, 0, tabs[1][l]),
        )

        def kernel(qt_ref, kt_ref, ft_ref, lt_ref, q_ref, k_ref, v_ref,
                   *rest):
            qseg_ref, kseg_ref = (rest[0], rest[1]) if has_seg else (None, None)
            rest = rest[2 if has_seg else 0:]
            o_ref, lse_ref, m_ref, l_ref, acc_ref = rest
            lin = pl.program_id(1)
            _fwd_kernel(
                q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref, lse_ref,
                m_ref, l_ref, acc_ref,
                (qt_ref[lin], kt_ref[lin], ft_ref[lin] == 1, lt_ref[lin] == 1),
                t_real=t_real, t_pad=t_pad, causal=causal, scale=scale,
                block=block, window=window,
            )

        o, lse = pl.pallas_call(
            kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=4,
                grid=(bh, qi_tab.shape[0]),
                in_specs=[
                    pl.BlockSpec((1, block, d_pad), q_map),
                    pl.BlockSpec((1, block, d_pad), kv_map),
                    pl.BlockSpec((1, block, d_pad), kv_map),
                    *seg_specs,
                ],
                out_specs=[
                    pl.BlockSpec((1, block, d_pad), q_map),
                    pl.BlockSpec((1, block, _LANES), q_map),
                ],
                scratch_shapes=scratch,
            ),
            out_shape=out_shape,
            interpret=interpret,
        )(qi_tab, kj_tab, first_tab, last_tab, q, k, v, *seg_in)
        return o, lse[:, :, 0]

    seg_specs = _seg_specs(
        has_seg, block,
        lambda b, i, j: (b // seg_div, i, 0),
        lambda b, i, j: (b // seg_div, 0, j),
    )

    def kernel(q_ref, k_ref, v_ref, *rest):
        qseg_ref, kseg_ref = (rest[0], rest[1]) if has_seg else (None, None)
        o_ref, lse_ref, m_ref, l_ref, acc_ref = rest[2 if has_seg else 0:]
        _fwd_kernel(
            q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref, lse_ref,
            m_ref, l_ref, acc_ref, None,
            t_real=t_real, t_pad=t_pad, causal=causal, scale=scale,
            block=block, window=window,
        )

    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, n_blk, n_blk),
        in_specs=[
            pl.BlockSpec((1, block, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block, d_pad), lambda b, i, j: (b // group, j, 0)),
            pl.BlockSpec((1, block, d_pad), lambda b, i, j: (b // group, j, 0)),
            *seg_specs,
        ],
        out_specs=[
            pl.BlockSpec((1, block, d_pad), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block, _LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v, *seg_in)
    return o, lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward: dq kernel (q tile resident, kv streams)
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref, kseg_ref,
    dq_ref, acc_ref, band, *, t_real, t_pad, causal, scale, block, window,
    qoff=None, kvoff=None,
):
    n_blk = t_pad // block
    has_seg = qseg_ref is not None
    if band is not None:  # packed causal grid (no band in offset mode)
        qi, kj, is_first, is_last = band  # packed banded grid (see forward)
    else:
        qi = pl.program_id(1)
        kj = pl.program_id(2)
        is_first = kj == 0
        is_last = kj == pl.num_programs(2) - 1

    @pl.when(is_first)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _step(masked: bool):
        q = q_ref[0]
        kb = k_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if masked:
            rows = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0
            )
            cols = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1
            )
            valid = cols < t_real  # padding is LOCAL to this shard
            if qoff is not None:
                rows = rows + qoff
                cols = cols + kvoff
            if causal:
                valid = valid & (rows >= cols)
            if window is not None:
                valid = valid & (rows - cols < window)
            if has_seg:
                valid = valid & (qseg_ref[0][:, :1] == kseg_ref[0][:1, :])
            s = jnp.where(valid, s, _NEG_INF)
        # p: exact probabilities recomputed from the saved logsumexp
        # (padded q rows carry lse=+BIG so p underflows to exactly 0)
        p = jnp.exp(s - lse_ref[0][:, :1])             # (bq, bk) f32
        if has_seg:
            # rows with NO live columns anywhere carry lse=-BIG, making
            # exp(s - lse) ones on their masked entries; zero explicitly
            p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # (bq, bk) f32
        ds = p * (dp - delta_ref[0][:, :1]) * scale     # (bq, bk) f32
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _masked_dispatch(
        _step, causal=causal, qi=qi, kj=kj, n_blk=n_blk,
        padded=t_pad != t_real, window=window, block=block, has_seg=has_seg,
        has_off=qoff is not None,
    )

    @pl.when(is_last)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dk/dv kernel (kv tile resident, q streams)
# ---------------------------------------------------------------------------


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref, kseg_ref,
    dk_ref, dv_ref, dk_acc, dv_acc, band, *, t_real, t_pad, causal, scale,
    block, window, qoff=None, kvoff=None,
):
    n_blk = t_pad // block
    has_seg = qseg_ref is not None
    if band is not None:  # packed causal grid (no band in offset mode)
        kj, qi, is_first, is_last = band  # packed banded grid, q innermost
    else:
        kj = pl.program_id(1)
        qi = pl.program_id(2)
        is_first = qi == 0
        is_last = qi == pl.num_programs(2) - 1

    @pl.when(is_first)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _step(masked: bool):
        q = q_ref[0]
        kb = k_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if masked:
            rows = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 0
            )
            cols = kj * block + jax.lax.broadcasted_iota(
                jnp.int32, (block, block), 1
            )
            valid = cols < t_real  # padding is LOCAL to this shard
            if qoff is not None:
                rows = rows + qoff
                cols = cols + kvoff
            if causal:
                valid = valid & (rows >= cols)
            if window is not None:
                valid = valid & (rows - cols < window)
            if has_seg:
                valid = valid & (qseg_ref[0][:, :1] == kseg_ref[0][:1, :])
            s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0][:, :1])              # (bq, bk) f32
        if has_seg:
            p = jnp.where(s <= _NEG_INF / 2, 0.0, p)    # see _dq_kernel
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # (bk, d)
        dp = jax.lax.dot_general(
            do, v_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                               # (bk, d)

    _masked_dispatch(
        _step, causal=causal, qi=qi, kj=kj, n_blk=n_blk,
        padded=t_pad != t_real, window=window, block=block, has_seg=has_seg,
        has_off=qoff is not None,
    )

    @pl.when(is_last)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "interpret", "t_real", "scale", "window"),
)
def _flash_bwd_padded(
    q, k, v, o, lse, do, qseg=None, kseg=None, offsets=None, *, causal,
    interpret, t_real, scale, window=None,
):
    """Padded (BH, T_pad, d_pad) residuals + cotangent -> (dq, dk, dv).

    GQA (k/v lead BHkv = BH / group): dk/dv come back with q's BH lead —
    one per-q-head partial per group member, reduced by the caller.
    ``qseg``/``kseg`` are :func:`_seg_operands` layouts; ``window`` is the
    causal sliding-window span (the packed banded grids then skip all
    out-of-band blocks in BOTH backward kernels)."""
    bh, t_pad, d_pad = q.shape
    group = bh // k.shape[0]
    block = _pick_block(t_pad, window)
    n_blk = t_pad // block
    has_seg = qseg is not None

    # delta_i = sum_d do_i * o_i — one cheap fused XLA pass. Both lse and
    # delta take the lane-broadcast (BH, T_pad, 128) layout so the kernels
    # read a (block, 1) sublane column with no transpose.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta_b = jnp.broadcast_to(delta[:, :, None], (bh, t_pad, _LANES))
    lse_b = jnp.broadcast_to(lse[:, :, None], (bh, t_pad, _LANES))

    tile = lambda index_map: pl.BlockSpec((1, block, d_pad), index_map)
    rows = lambda index_map: pl.BlockSpec((1, block, _LANES), index_map)
    dq_scratch = [pltpu.VMEM((block, d_pad), jnp.float32)]
    dkv_scratch = [
        pltpu.VMEM((block, d_pad), jnp.float32),
        pltpu.VMEM((block, d_pad), jnp.float32),
    ]
    # dk/dv carry q's BH lead (per-q-head partials under GQA; identical to
    # the kv lead when group == 1)
    dkv_out_shape = [
        jax.ShapeDtypeStruct((bh,) + k.shape[1:], k.dtype),
        jax.ShapeDtypeStruct((bh,) + v.shape[1:], v.dtype),
    ]
    seg_in = [qseg, kseg] if has_seg else []
    # segment operands are BATCH-lead (see _seg_operands)
    seg_div = bh // qseg.shape[0] if has_seg else 1

    def seg_specs(qseg_map, kseg_map):
        return _seg_specs(has_seg, block, qseg_map, kseg_map)

    def unpack(refs):
        """(inputs..., [qseg, kseg], outputs..., scratch...) -> canonical
        kernel arg order with None seg refs when segments are off."""
        ins, rest = refs[:6], refs[6:]
        segs = (rest[0], rest[1]) if has_seg else (None, None)
        tail = rest[2:] if has_seg else rest
        return (*ins, *segs, *tail)

    if offsets is not None:
        # ring block-pair mode (see _flash_fwd_padded): rectangular
        # grids, offsets scalar-prefetched, every block masked globally
        if has_seg:
            raise NotImplementedError(
                "segment ids + ring offsets unsupported"
            )

        def dq_kernel(offs_ref, *refs):
            _dq_kernel(
                *unpack(refs), None, t_real=t_real, t_pad=t_pad,
                causal=causal, scale=scale, block=block, window=window,
                qoff=offs_ref[0], kvoff=offs_ref[1],
            )

        q_res = lambda b, i, j, o_: (b, i, 0)
        kv_stream = lambda b, i, j, o_: (b // group, j, 0)
        dq = pl.pallas_call(
            dq_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(bh, n_blk, n_blk),
                in_specs=[
                    tile(q_res), tile(kv_stream), tile(kv_stream),
                    tile(q_res), rows(q_res), rows(q_res),
                ],
                out_specs=tile(q_res),
                scratch_shapes=dq_scratch,
            ),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
        )(offsets, q, k, v, do, lse_b, delta_b)

        def dkv_kernel(offs_ref, *refs):
            _dkv_kernel(
                *unpack(refs), None, t_real=t_real, t_pad=t_pad,
                causal=causal, scale=scale, block=block, window=window,
                qoff=offs_ref[0], kvoff=offs_ref[1],
            )

        kv_res = lambda b, j, i, o_: (b // group, j, 0)
        dkv_res = lambda b, j, i, o_: (b, j, 0)
        q_stream = lambda b, j, i, o_: (b, i, 0)
        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(bh, n_blk, n_blk),
                in_specs=[
                    tile(q_stream), tile(kv_res), tile(kv_res),
                    tile(q_stream), rows(q_stream), rows(q_stream),
                ],
                out_specs=[tile(dkv_res), tile(dkv_res)],
                scratch_shapes=dkv_scratch,
            ),
            out_shape=dkv_out_shape,
            interpret=interpret,
        )(offsets, q, k, v, do, lse_b, delta_b)
        return dq, dk, dv

    if causal:
        # packed banded grids (same trick as the forward): one grid step
        # per LIVE (qi, kj) pair, coordinates + first/last scalar-prefetched
        qi_tab, kj_tab, first_tab, last_tab = _band_tables(
            n_blk, block, window
        )
        q_map = lambda b, l, *t: (b, t[0][l], 0)
        kv_map = lambda b, l, *t: (b // group, t[1][l], 0)

        def dq_kernel(at_ref, bt_ref, ft_ref, lt_ref, *refs):
            lin = pl.program_id(1)
            _dq_kernel(
                *unpack(refs),
                (at_ref[lin], bt_ref[lin], ft_ref[lin] == 1, lt_ref[lin] == 1),
                t_real=t_real, t_pad=t_pad, causal=causal, scale=scale,
                block=block, window=window,
            )

        dq = pl.pallas_call(
            dq_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=4,
                grid=(bh, qi_tab.shape[0]),
                in_specs=[
                    tile(q_map), tile(kv_map), tile(kv_map),
                    tile(q_map), rows(q_map), rows(q_map),
                    *seg_specs(
                        lambda b, l, *t: (b // seg_div, t[0][l], 0),
                        lambda b, l, *t: (b // seg_div, 0, t[1][l]),
                    ),
                ],
                out_specs=tile(q_map),
                scratch_shapes=dq_scratch,
            ),
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
        )(qi_tab, kj_tab, first_tab, last_tab, q, k, v, do, lse_b, delta_b,
          *seg_in)

        # dk/dv: kv tile resident -> kj-major enumeration, q innermost.
        # Inputs read kv head b // group; outputs write q head b (per-
        # q-head partials, group-reduced by the caller).
        kj_tab2, qi_tab2, first_tab2, last_tab2 = _band_tables_kv_major(
            n_blk, block, window
        )
        kv_map2 = lambda b, l, *t: (b // group, t[0][l], 0)
        dkv_map2 = lambda b, l, *t: (b, t[0][l], 0)
        q_map2 = lambda b, l, *t: (b, t[1][l], 0)

        def dkv_kernel(kt_ref, qt_ref, ft_ref, lt_ref, *refs):
            lin = pl.program_id(1)
            _dkv_kernel(
                *unpack(refs),
                (kt_ref[lin], qt_ref[lin], ft_ref[lin] == 1, lt_ref[lin] == 1),
                t_real=t_real, t_pad=t_pad, causal=causal, scale=scale,
                block=block, window=window,
            )

        dk, dv = pl.pallas_call(
            dkv_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=4,
                grid=(bh, kj_tab2.shape[0]),
                in_specs=[
                    tile(q_map2), tile(kv_map2), tile(kv_map2),
                    tile(q_map2), rows(q_map2), rows(q_map2),
                    *seg_specs(
                        lambda b, l, *t: (b // seg_div, t[1][l], 0),
                        lambda b, l, *t: (b // seg_div, 0, t[0][l]),
                    ),
                ],
                out_specs=[tile(dkv_map2), tile(dkv_map2)],
                scratch_shapes=dkv_scratch,
            ),
            out_shape=dkv_out_shape,
            interpret=interpret,
        )(kj_tab2, qi_tab2, first_tab2, last_tab2, q, k, v, do, lse_b,
          delta_b, *seg_in)
        return dq, dk, dv

    q_res = lambda b, i, j: (b, i, 0)        # follows the resident tile
    kv_stream = lambda b, i, j: (b // group, j, 0)

    dq = pl.pallas_call(
        lambda *refs: _dq_kernel(
            *unpack(refs), None, t_real=t_real, t_pad=t_pad,
            causal=causal, scale=scale, block=block, window=window,
        ),
        grid=(bh, n_blk, n_blk),
        in_specs=[
            tile(q_res), tile(kv_stream), tile(kv_stream),
            tile(q_res), rows(q_res), rows(q_res),
            *seg_specs(
                lambda b, i, j: (b // seg_div, i, 0),
                lambda b, i, j: (b // seg_div, 0, j),
            ),
        ],
        out_specs=tile(q_res),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=dq_scratch,
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b, *seg_in)

    kv_res = lambda b, j, i: (b // group, j, 0)   # resident kv tile
    dkv_res = lambda b, j, i: (b, j, 0)           # per-q-head partial out
    q_stream = lambda b, j, i: (b, i, 0)

    dk, dv = pl.pallas_call(
        lambda *refs: _dkv_kernel(
            *unpack(refs), None, t_real=t_real, t_pad=t_pad,
            causal=causal, scale=scale, block=block, window=window,
        ),
        grid=(bh, n_blk, n_blk),
        in_specs=[
            tile(q_stream), tile(kv_res), tile(kv_res),
            tile(q_stream), rows(q_stream), rows(q_stream),
            *seg_specs(
                lambda b, j, i: (b // seg_div, i, 0),
                lambda b, j, i: (b // seg_div, 0, j),
            ),
        ],
        out_specs=[tile(dkv_res), tile(dkv_res)],
        out_shape=dkv_out_shape,
        scratch_shapes=dkv_scratch,
        interpret=interpret,
    )(q, k, v, do, lse_b, delta_b, *seg_in)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-VJP wrapper
# ---------------------------------------------------------------------------


def _pad_to(x, t_pad, d_pad):
    t, d = x.shape[-2], x.shape[-1]
    return jnp.pad(x, ((0, 0), (0, t_pad - t), (0, d_pad - d)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _flash(q, k, v, seg, causal, window):
    return _flash_fwd_res(q, k, v, seg, causal, window)[0]


def _flash_fwd_res(q, k, v, seg, causal, window):
    bh, t, d = q.shape
    t_pad = -(-t // _MIN_BLOCK) * _MIN_BLOCK
    d_pad = -(-d // _LANES) * _LANES
    scale = float(1.0 / (d**0.5))
    qp, kp, vp = (_pad_to(a, t_pad, d_pad) for a in (q, k, v))
    qso, kso = (
        _seg_operands(seg, t_pad) if seg is not None else (None, None)
    )
    o, lse = _flash_fwd_padded(
        qp, kp, vp, qso, kso, causal=causal, interpret=_interpret(),
        t_real=t, scale=scale, window=window,
    )
    return o[:, :t, :d], lse[:, :t]


def _flash_fwd(q, k, v, seg, causal, window):
    o, lse = _flash_fwd_res(q, k, v, seg, causal, window)
    return o, (q, k, v, seg, o, lse)


def _flash_bwd(causal, window, res, do):
    q, k, v, seg, o, lse = res
    bh, t, d = q.shape
    group = bh // k.shape[0]
    t_pad = -(-t // _MIN_BLOCK) * _MIN_BLOCK
    d_pad = -(-d // _LANES) * _LANES
    scale = float(1.0 / (d**0.5))
    qp, kp, vp, op, dop = (_pad_to(a, t_pad, d_pad) for a in (q, k, v, o, do))
    qso, kso = (
        _seg_operands(seg, t_pad) if seg is not None else (None, None)
    )
    # padded q rows get lse=+BIG so their recomputed probabilities
    # underflow to exactly 0 (an -inf pad would make exp(0 - lse) blow
    # up: padded q rows are zeros, not masked, so their s entries are 0);
    # their cotangent rows are zero-padded too, killing every grad term
    lse_p = jnp.pad(lse, ((0, 0), (0, t_pad - t)), constant_values=1e30)
    dq, dk, dv = _flash_bwd_padded(
        qp, kp, vp, op, lse_p, dop, qso, kso, causal=causal,
        interpret=_interpret(), t_real=t, scale=scale, window=window,
    )
    if group > 1:
        # per-q-head partials -> kv heads: flat q index = kv_index*G + g,
        # so a C-order reshape exposes the group axis directly
        dk = dk.reshape(k.shape[0], group, t_pad, d_pad)
        dk = dk.astype(jnp.float32).sum(axis=1).astype(k.dtype)
        dv = dv.reshape(v.shape[0], group, t_pad, d_pad)
        dv = dv.astype(jnp.float32).sum(axis=1).astype(v.dtype)
    dseg = None if seg is None else _int_zero_tangent(seg)
    return dq[:, :t, :d], dk[:, :t, :d], dv[:, :t, :d], dseg


def _int_zero_tangent(x):
    """float0 cotangent for integer primal inputs (segment ids)."""
    import numpy as np

    return np.zeros(x.shape, jax.dtypes.float0)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_block_attend(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    window: int | None = None,
    q_offset=None,
    kv_offset=None,
):
    """One block pair's attention + logsumexp — the ring-attention local
    step, on the kernel.

    q is (..., Tq, d), k/v (..., Tk, d) (GQA: fewer kv heads on -3).
    With ``q_offset``/``kv_offset`` (traced per-device scalars) the
    causal/window masks run on GLOBAL row/col positions — a rotated kv
    block knows where it came from; fully dead pairs yield o=0,
    lse=-inf, which the online-softmax combine neutralizes. Returns
    (o (..., Tq, d) in q's dtype, lse (..., Tq) f32, both UNnormalized
    across pairs — combine with the flash recurrence)."""
    shape = q.shape
    t, d = shape[-2], shape[-1]
    q3 = q.reshape(-1, t, d)
    k3, v3 = (a.reshape(-1, a.shape[-2], d) for a in (k, v))
    t_pad = -(-t // _MIN_BLOCK) * _MIN_BLOCK
    d_pad = -(-d // _LANES) * _LANES
    scale = float(1.0 / (d**0.5))
    qp, kp, vp = (_pad_to(a, t_pad, d_pad) for a in (q3, k3, v3))
    offs = None
    eff_causal = causal
    if q_offset is not None:
        offs = jnp.stack(
            [
                jnp.asarray(q_offset, jnp.int32),
                jnp.asarray(kv_offset, jnp.int32),
            ]
        )
    o, lse = _flash_fwd_padded(
        qp, kp, vp, None, None, offs, causal=eff_causal,
        interpret=_interpret(), t_real=t, scale=scale, window=window,
    )
    return (
        o[:, :t, :d].reshape(shape),
        lse[:, :t].reshape(shape[:-1]),
    )


def flash_block_backward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    o: jax.Array,
    lse: jax.Array,
    do: jax.Array,
    *,
    causal: bool = True,
    window: int | None = None,
    q_offset=None,
    kv_offset=None,
):
    """Block-pair gradients for the ring backward: recompute this pair's
    probabilities from the GLOBAL logsumexp (``lse``, as saved by the
    ring forward) and return (dq, dk, dv) — dk/dv group-reduced to kv
    heads under GQA. ``o``/``do`` are the device's (global) output and
    cotangent; offsets as in :func:`flash_block_attend`."""
    shape = q.shape
    t, d = shape[-2], shape[-1]
    q3 = q.reshape(-1, t, d)
    k3, v3 = (a.reshape(-1, a.shape[-2], d) for a in (k, v))
    o3, do3 = (a.reshape(-1, t, d) for a in (o, do))
    lse3 = lse.reshape(-1, t)
    bh = q3.shape[0]
    group = bh // k3.shape[0]
    t_pad = -(-t // _MIN_BLOCK) * _MIN_BLOCK
    d_pad = -(-d // _LANES) * _LANES
    scale = float(1.0 / (d**0.5))
    qp, kp, vp, op, dop = (
        _pad_to(a, t_pad, d_pad) for a in (q3, k3, v3, o3, do3)
    )
    lse_p = jnp.pad(lse3, ((0, 0), (0, t_pad - t)), constant_values=1e30)
    offs = None
    if q_offset is not None:
        offs = jnp.stack(
            [
                jnp.asarray(q_offset, jnp.int32),
                jnp.asarray(kv_offset, jnp.int32),
            ]
        )
    dq, dk, dv = _flash_bwd_padded(
        qp, kp, vp, op, lse_p, dop, None, None, offs, causal=causal,
        interpret=_interpret(), t_real=t, scale=scale, window=window,
    )
    if group > 1:
        dk = dk.reshape(k3.shape[0], group, t_pad, d_pad)
        dk = dk.astype(jnp.float32).sum(axis=1).astype(k.dtype)
        dv = dv.reshape(v3.shape[0], group, t_pad, d_pad)
        dv = dv.astype(jnp.float32).sum(axis=1).astype(v.dtype)
    return (
        dq[:, :t, :d].reshape(shape),
        dk[:, :t, :d].reshape(k.shape),
        dv[:, :t, :d].reshape(v.shape),
    )


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    window: int | None = None,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Memory-efficient attention. (..., T, d) -> (..., T, d).

    Matches :func:`beholder_tpu.ops.attention.full_attention` to float
    tolerance; never materializes the (T, T) score matrix in either pass.

    Grouped-query attention: k/v may carry FEWER heads than q on the -3
    dim (H = G * Hkv, MQA at Hkv=1); each group of G consecutive q heads
    attends the same kv head. All other leading dims must match.

    ``window`` (requires ``causal``) restricts each row to the previous
    ``window`` positions (itself included) — sliding-window attention.
    The packed banded grids then ONLY iterate in-band blocks, so cost
    scales with T * window instead of T^2 in forward AND backward.

    ``segment_ids`` (batch-shaped: ``q.shape[:-3] + (T,)``, integers)
    masks cross-segment attention for packed-sequence training; rows in
    different segments never attend each other. Block skipping does not
    apply (segments are runtime data) — combine with ``causal`` to keep
    the triangular skip.
    """
    shape = q.shape
    t, d = shape[-2], shape[-1]
    if k.shape != q.shape:
        if (
            q.ndim < 3
            or k.shape[:-3] != q.shape[:-3]
            or k.shape[-2:] != q.shape[-2:]
            or q.shape[-3] % k.shape[-3]
        ):
            raise ValueError(
                f"GQA shapes must differ only in heads (-3 dim), with "
                f"q heads a multiple of kv heads; got {q.shape} vs {k.shape}"
            )
    if k.shape != v.shape:
        raise ValueError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    q3 = q.reshape(-1, t, d)
    k3, v3 = (a.reshape(-1, t, d) for a in (k, v))
    seg = None
    if segment_ids is not None:
        want = (*shape[:-3], shape[-2]) if q.ndim >= 3 else (t,)
        if segment_ids.shape != want:
            raise ValueError(
                f"segment_ids must be batch-shaped {want} (no head dim); "
                f"got {segment_ids.shape}"
            )
        # stays batch-lead end to end: the kernels' BlockSpec index maps
        # divide the flat (B*H) grid index by the head count, so the ids
        # are never replicated per head in HBM
        seg = segment_ids.reshape(-1, t)
    return _flash(q3, k3, v3, seg, causal, window).reshape(shape)
