"""JAX telemetry-aggregation ops.

EXTENSION BEYOND THE REFERENCE: tritonmedia/beholder has no compute path of
any kind (SURVEY.md §0 — it processes one message at a time on a JS event
loop). This package adds a batch analytics layer for high-volume telemetry:
given arrays of status/progress observations, it computes per-status counts,
progress statistics, and EWMA rates as single fused XLA programs, so an
operator can aggregate millions of buffered telemetry events on a TPU chip
instead of row-by-row in Python. Nothing here is attributed to the
reference; parity components live in the sibling packages.
"""

from .aggregate import NUM_STATUSES, aggregate_telemetry, ewma, status_counts
from .moe import SwitchFFN, expert_shardings, expert_specs
from .paged_attention import PagedInfo, QuantizedPool, paged_decode_attention
from .pallas_aggregate import aggregate_telemetry_pallas
from .quant import (
    dequantize_params,
    dequantize_weight,
    quantize_params,
    quantize_weight,
    quantized_nbytes,
)

__all__ = [
    "NUM_STATUSES",
    "aggregate_telemetry",
    "aggregate_telemetry_pallas",
    "status_counts",
    "ewma",
    "SwitchFFN",
    "expert_shardings",
    "expert_specs",
    "PagedInfo",
    "QuantizedPool",
    "paged_decode_attention",
    "dequantize_params",
    "dequantize_weight",
    "quantize_params",
    "quantize_weight",
    "quantized_nbytes",
]
