"""Attention ops: full reference, ring attention (context parallelism),
and Ulysses all-to-all sequence parallelism.

EXTENSION BEYOND THE REFERENCE (which has no attention, no sequences, no
tensors — SURVEY.md §5 "Long-context / sequence parallelism: Absent").
Built for scoring long telemetry streams with the sequence models in
:mod:`beholder_tpu.models.sequence`.

Ring attention (context parallelism over a mesh axis):
- q, k, v are sharded along the sequence dimension across the ``sp`` mesh
  axis; each device holds one block.
- P-1 rotation steps pass k/v blocks around the ring with ``ppermute``
  (riding ICI on TPU hardware) while each device accumulates attention of
  its local q block against every k/v block using the online-softmax
  (flash) recurrence — running max ``m``, normalizer ``l``, and
  unnormalized output ``o`` — so the full (T, T) score matrix never
  materializes and per-device memory stays O(T/P * d).
- Causal masking works on global positions: block offsets are rotated
  alongside the blocks, so each device always knows which global rows its
  current k/v block came from.

The same code runs single-device (P=1 degenerates to flash attention over
one block) and on the virtual CPU mesh used by the tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG_INF = -1e30


def full_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> jax.Array:
    """Reference O(T^2) attention. Shapes: (..., T, d) -> (..., T, d)."""
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k) / jnp.sqrt(jnp.float32(d))
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        scores = jnp.where(mask, scores, _NEG_INF)
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("...qk,...kd->...qd", weights.astype(q.dtype), v)


def _block_attend(q, k, v, q_offset, kv_offset, causal):
    """Scores of a local q block vs one k/v block + flash partials.

    Returns (m, p_sum, pv): row max, exp-sum, and exp-weighted values of
    this block, for the online-softmax combine.
    """
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k) / jnp.sqrt(jnp.float32(d))
    scores = scores.astype(jnp.float32)
    if causal:
        tq, tk = q.shape[-2], k.shape[-2]
        rows = q_offset + jnp.arange(tq)[:, None]
        cols = kv_offset + jnp.arange(tk)[None, :]
        scores = jnp.where(rows >= cols, scores, _NEG_INF)
    m = jnp.max(scores, axis=-1)  # (..., tq)
    p = jnp.exp(scores - m[..., None])
    p_sum = jnp.sum(p, axis=-1)
    pv = jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v).astype(jnp.float32)
    return m, p_sum, pv


def _combine(state, block):
    """Online-softmax combine of running (m, l, o) with a new block."""
    m, l, o = state
    bm, bl, bo = block
    m_new = jnp.maximum(m, bm)
    scale_old = jnp.exp(m - m_new)
    scale_new = jnp.exp(bm - m_new)
    l_new = l * scale_old + bl * scale_new
    o_new = o * scale_old[..., None] + bo * scale_new[..., None]
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
) -> jax.Array:
    """Context-parallel attention over the ``axis`` dimension of ``mesh``.

    Inputs are (..., T, d) global arrays; T must divide evenly by the axis
    size. Output matches :func:`full_attention` up to float tolerance.
    """
    p_size = mesh.shape[axis]
    t = q.shape[-2]
    if t % p_size:
        raise ValueError(f"sequence length {t} not divisible by {axis}={p_size}")
    block = t // p_size

    def local(qb, kb, vb):
        idx = jax.lax.axis_index(axis)
        q_offset = idx * block

        m = jnp.full(qb.shape[:-1], _NEG_INF, jnp.float32)
        l = jnp.zeros(qb.shape[:-1], jnp.float32)
        o = jnp.zeros(qb.shape, jnp.float32)
        kc, vc, kv_idx = kb, vb, idx

        # static unroll over the (known) ring size: p_size block attends
        # with p_size-1 rotations — the last block needs no further hop,
        # and XLA overlaps each ppermute with the next step's compute
        perm = [(j, (j + 1) % p_size) for j in range(p_size)]
        for step in range(p_size):
            blk = _block_attend(qb, kc, vc, q_offset, kv_idx * block, causal)
            m, l, o = _combine((m, l, o), blk)
            if step < p_size - 1:
                kc = jax.lax.ppermute(kc, axis, perm)
                vc = jax.lax.ppermute(vc, axis, perm)
                kv_idx = jax.lax.ppermute(kv_idx, axis, perm)

        # under causal self-attention every row sees at least its own
        # position, so l >= 1 always; divide directly
        return (o / l[..., None]).astype(q.dtype)

    spec = P(*([None] * (q.ndim - 2)), axis, None)
    sharded = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return sharded(q, k, v)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    backend: str = "flash",
) -> jax.Array:
    """DeepSpeed-Ulysses sequence parallelism: all-to-all head scatter.

    Inputs are (B, H, T, d) global arrays sharded along T on ``axis``.
    Each device trades its T/P sequence slice of all H heads for the FULL
    sequence of H/P heads (one ``all_to_all``, riding ICI on hardware),
    runs ordinary attention on those whole-sequence heads — flash by
    default, so the (T, T) matrix never exists — then reverses the
    exchange. Two all-to-alls per call vs ring attention's P-1 ppermutes;
    the tradeoff is H % P == 0 and O(T) k/v memory per device (vs ring's
    O(T/P)), which buys much better compute locality for moderate T.
    """
    p_size = mesh.shape[axis]
    b, h, t, d = q.shape
    if h % p_size:
        raise ValueError(f"heads {h} not divisible by {axis}={p_size}")
    if t % p_size:
        raise ValueError(f"sequence length {t} not divisible by {axis}={p_size}")

    if backend == "flash":
        from beholder_tpu.ops.flash_attention import flash_attention as attend
    else:
        attend = full_attention

    def local(qb, kb, vb):
        # (B, H, T/P, d) -> (B, H/P, T, d): split heads, gather sequence
        qh, kh, vh = (
            jax.lax.all_to_all(a, axis, split_axis=1, concat_axis=2, tiled=True)
            for a in (qb, kb, vb)
        )
        att = attend(qh, kh, vh, causal=causal)
        # (B, H/P, T, d) -> (B, H, T/P, d)
        return jax.lax.all_to_all(att, axis, split_axis=2, concat_axis=1, tiled=True)

    spec = P(None, None, axis, None)
    sharded = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return sharded(q, k, v)


def sequence_sharding(mesh: Mesh, ndim: int, axis: str = "sp") -> NamedSharding:
    """NamedSharding placing the (-2) sequence dim on ``axis``."""
    return NamedSharding(mesh, P(*([None] * (ndim - 2)), axis, None))
