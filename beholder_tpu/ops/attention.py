"""Attention ops: full reference, ring attention (context parallelism),
and Ulysses all-to-all sequence parallelism.

EXTENSION BEYOND THE REFERENCE (which has no attention, no sequences, no
tensors — SURVEY.md §5 "Long-context / sequence parallelism: Absent").
Built for scoring long telemetry streams with the sequence models in
:mod:`beholder_tpu.models.sequence`.

Ring attention (context parallelism over a mesh axis):
- q, k, v are sharded along the sequence dimension across the ``sp`` mesh
  axis; each device holds one block.
- P-1 rotation steps pass k/v blocks around the ring with ``ppermute``
  (riding ICI on TPU hardware) while each device accumulates attention of
  its local q block against every k/v block using the online-softmax
  (flash) recurrence — running max ``m``, normalizer ``l``, and
  unnormalized output ``o`` — so the full (T, T) score matrix never
  materializes and per-device memory stays O(T/P * d).
- Causal masking works on global positions: block offsets are rotated
  alongside the blocks, so each device always knows which global rows its
  current k/v block came from.

The same code runs single-device (P=1 degenerates to flash attention over
one block) and on the virtual CPU mesh used by the tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from beholder_tpu.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG_INF = -1e30


def full_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    window: int | None = None,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Reference O(T^2) attention. Shapes: (..., T, d) -> (..., T, d).

    Grouped-query attention: k/v may carry fewer heads than q on the -3
    dim (H = G * Hkv); group g of G consecutive q heads reads kv head
    ``h // G``, matching :func:`~beholder_tpu.ops.flash_attention.
    flash_attention`'s layout. MHA is the G=1 case of the same path.

    ``window`` (with ``causal``) keeps only the previous ``window``
    positions per row; ``segment_ids`` (batch-shaped ``q.shape[:-3] +
    (T,)``) masks cross-segment attention — both matching
    :func:`~beholder_tpu.ops.flash_attention.flash_attention`."""
    d = q.shape[-1]
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if q.ndim >= 3:
        if q.shape[-3] % k.shape[-3]:
            raise ValueError(
                f"GQA q heads must be a multiple of kv heads; got "
                f"{q.shape} vs {k.shape}"
            )
        hkv = k.shape[-3]
        g = q.shape[-3] // hkv  # 1 = ordinary MHA, same code path
        qg = q.reshape(*q.shape[:-3], hkv, g, *q.shape[-2:])
    else:
        qg = q[..., None, :, :]  # rank-2 (T, d): one group of one "head"
    scores = jnp.einsum("...gqd,...kd->...gqk", qg, k) / jnp.sqrt(
        jnp.float32(d)
    )
    tq, tk = scores.shape[-2], scores.shape[-1]
    rows = jnp.arange(tq)[:, None]
    cols = jnp.arange(tk)[None, :]
    if causal:
        scores = jnp.where(rows >= cols, scores, _NEG_INF)
    if window is not None:
        scores = jnp.where(rows - cols < window, scores, _NEG_INF)
    if segment_ids is not None:
        seg_mask = segment_ids[..., :, None] == segment_ids[..., None, :]
        scores = jnp.where(
            seg_mask[..., None, None, :, :], scores, _NEG_INF
        )
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    out = jnp.einsum("...gqk,...kd->...gqd", weights.astype(q.dtype), v)
    # merge (hkv, g) back into the head dim, keeping any leading dims the
    # einsum broadcast (e.g. q with batch 1 against a batched k/v)
    out = out.reshape(*out.shape[:-4], -1, *out.shape[-2:])
    return out if q.ndim >= 3 else out[0]


def _grouped(q, k):
    """GQA group view: (..., H, t, d) q against (..., Hkv, t, d) kv ->
    q reshaped (..., Hkv, G, t, d). G=1 is plain MHA, same path; rank-2
    (t, d) inputs get a singleton group axis (one headless "group")."""
    if q.ndim == 2:
        return q[None], 1
    hkv = k.shape[-3]
    g = q.shape[-3] // hkv
    return q.reshape(*q.shape[:-3], hkv, g, *q.shape[-2:]), g


def _block_attend(q, k, v, q_offset, kv_offset, causal, window=None):
    """Scores of a local q block vs one k/v block + flash partials.

    Returns (m, p_sum, pv): row max, exp-sum, and exp-weighted values of
    this block, for the online-softmax combine — all carrying the
    grouped (..., Hkv, G, tq, ...) head layout (GQA-native: k/v may have
    fewer heads than q; the kv block never replicates per group).
    """
    d = q.shape[-1]
    qg, _ = _grouped(q, k)
    scores = jnp.einsum("...gqd,...kd->...gqk", qg, k) / jnp.sqrt(
        jnp.float32(d)
    )
    scores = scores.astype(jnp.float32)
    if causal:
        tq, tk = q.shape[-2], k.shape[-2]
        rows = q_offset + jnp.arange(tq)[:, None]
        cols = kv_offset + jnp.arange(tk)[None, :]
        live = rows >= cols
        if window is not None:
            live = live & (rows - cols < window)
        scores = jnp.where(live, scores, _NEG_INF)
    m = jnp.max(scores, axis=-1)  # (..., Hkv, G, tq)
    p = jnp.exp(scores - m[..., None])
    p_sum = jnp.sum(p, axis=-1)
    pv = jnp.einsum(
        "...gqk,...kd->...gqd", p.astype(v.dtype), v
    ).astype(jnp.float32)
    return m, p_sum, pv


def _combine(state, block):
    """Online-softmax combine of running (m, l, o) with a new block."""
    m, l, o = state
    bm, bl, bo = block
    m_new = jnp.maximum(m, bm)
    scale_old = jnp.exp(m - m_new)
    scale_new = jnp.exp(bm - m_new)
    l_new = l * scale_old + bl * scale_new
    o_new = o * scale_old[..., None] + bo * scale_new[..., None]
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    window: int | None = None,
    backend: str = "flash",
) -> jax.Array:
    """Context-parallel attention over the ``axis`` dimension of ``mesh``.

    Inputs are (..., T, d) global arrays; T must divide evenly by the axis
    size. Output matches :func:`full_attention` up to float tolerance.

    Memory: O(T/P * d) per device in BOTH passes. The forward saves only
    (q, k, v, o, logsumexp) — all O(T/P * d) shards — and the custom VJP
    re-rotates k/v around the ring, recomputing each (T/P, T/P)
    probability block transiently from the saved per-row logsumexp (the
    flash backward, distributed). dk/dv partial sums travel WITH their
    blocks and complete a full ring circle, arriving home with every
    device's contribution accumulated.

    Grouped-query attention is native: k/v may carry fewer heads on the
    -3 dim (H = G * Hkv) — the rotating kv blocks stay at kv-head width,
    so GQA shrinks ring traffic by the group factor too.

    ``window`` (requires ``causal``) bounds the reach: rotations stop
    once every further block would be fully out of band, so both compute
    AND ring communication scale with the window instead of the ring
    size (the backward completes the gradient circle with one multi-hop
    permutation).

    ``backend="flash"`` (default) runs each rotation's local block
    attend INSIDE the Pallas flash kernels — the masks take the rotated
    block's global row offsets. Measured honestly (BENCH r05
    ``ring_block``, slope-timed on v5e at T/P=2048, both rotation
    types): the ratios move with chip contention. On a heavily shared
    chip the kernel sits at parity with the XLA einsum block-attend on
    both the fully-live mid-ring rotation and the half-masked diagonal
    (~0.96x each); on a quiet chip the kernel wins the mid-ring
    rotation ~1.7x while the einsum wins the packed-causal diagonal
    ~1.7x (kernel 0.58x there). A P-device causal ring runs ONE
    diagonal and up to P-1 mid-ring rotations per device — and the
    diagonal carries half the FLOPs — so the kernel is the better net
    choice for P >= 2 whenever it wins the rotations, and no worse than
    ~6% off at parity. It is ALWAYS the memory-safe choice: O(block)
    VMEM, while the einsum materializes the (T/P, T/P) f32 score block
    per head group (134 MB at T/P=2048, growing quadratically with the
    shard). The forward combines each pair's (o, logsumexp) with the
    online-softmax recurrence; the backward recomputes each pair's
    probabilities from the saved GLOBAL logsumexp inside the flash
    backward kernels. ``backend="einsum"`` keeps the transparent XLA
    reference path.
    """
    p_size = mesh.shape[axis]
    t = q.shape[-2]
    if t % p_size:
        raise ValueError(f"sequence length {t} not divisible by {axis}={p_size}")
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if q.ndim >= 3 and k.shape[-3] != q.shape[-3]:
        if q.shape[-3] % k.shape[-3]:
            raise ValueError(
                f"GQA q heads must be a multiple of kv heads; got "
                f"{q.shape} vs {k.shape}"
            )
        if (
            q.ndim >= 4
            and "tp" in mesh.axis_names
            and k.shape[-3] % mesh.shape["tp"]
        ):
            raise ValueError(
                f"ring GQA shards kv heads over tp: kv heads "
                f"{k.shape[-3]} must be divisible by tp="
                f"{mesh.shape['tp']} — pick kv_heads as a multiple of tp "
                f"(or repeat kv heads before the call)"
            )
    return _ring_vjp(mesh, axis, causal, q.ndim, window, backend)(q, k, v)


def _ring_steps(p_size: int, block: int, causal: bool, window) -> int:
    """Ring rotations that can ever hit live blocks. Causal sliding
    windows bound the reach: block pair (qi, kj) is live only while
    (qi-kj-1)*block + 1 < window, so rotations past the band carry
    blocks that are fully masked on EVERY device — skip them entirely.
    This makes ring comms scale with the window, not the ring size."""
    if not causal or window is None:
        return p_size
    reach = 0 if window <= 1 else 1 + (window - 2) // block
    return min(p_size, reach + 1)


def _ring_local_fwd(
    qb, kb, vb, *, axis, p_size, block, causal, want_lse, window=None,
    backend="flash",
):
    """Per-device forward: online-softmax over the live ring rotations.

    Returns (o, lse) where lse is the per-row logsumexp the backward
    needs to recompute probabilities exactly. Fully masked blocks (a
    device holding a wrapped future block, or one beyond the window) are
    neutralized by the combine: step 0 is the device's own (live)
    diagonal block, so the running max is finite and a -inf block max
    scales its contribution to exactly zero.

    ``backend="flash"`` computes each pair on the Pallas kernel
    (:func:`~beholder_tpu.ops.flash_attention.flash_block_attend`): the
    diagonal step runs the packed causal grid, rotated steps take the
    traced global offsets; each pair's normalized (o, lse) enters the
    same combine as a (m=lse, l=1, o) pseudo-block.
    """
    idx = jax.lax.axis_index(axis)
    q_offset = idx * block

    kc, vc, kv_idx = kb, vb, idx
    # static unroll over the (known) live step count: the last block
    # needs no further hop, and XLA overlaps each ppermute with the next
    # step's compute
    n_steps = _ring_steps(p_size, block, causal, window)
    perm = [(j, (j + 1) % p_size) for j in range(p_size)]

    if backend == "flash":
        from beholder_tpu.ops.flash_attention import flash_block_attend

        m = l = o = None
        for step in range(n_steps):
            if causal and step == 0:
                ob, lb = flash_block_attend(
                    qb, kc, vc, causal=True, window=window
                )
            else:
                # rotated pair: global offsets drive the masks (None for
                # the non-causal ring, which has no mask to place)
                offs = (
                    dict(q_offset=q_offset, kv_offset=kv_idx * block)
                    if causal
                    else {}
                )
                ob, lb = flash_block_attend(
                    qb, kc, vc, causal=causal, window=window, **offs
                )
            blk = (lb, jnp.ones_like(lb), ob.astype(jnp.float32))
            m, l, o = blk if step == 0 else _combine((m, l, o), blk)
            if step < n_steps - 1:
                kc = jax.lax.ppermute(kc, axis, perm)
                vc = jax.lax.ppermute(vc, axis, perm)
                kv_idx = jax.lax.ppermute(kv_idx, axis, perm)
        out = (o / l[..., None]).astype(qb.dtype)
        if not want_lse:
            return out
        return out, m + jnp.log(jnp.maximum(l, 1e-37))

    qg, _ = _grouped(qb, kb)
    m = jnp.full(qg.shape[:-1], _NEG_INF, jnp.float32)
    l = jnp.zeros(qg.shape[:-1], jnp.float32)
    o = jnp.zeros(qg.shape, jnp.float32)
    for step in range(n_steps):
        blk = _block_attend(
            qb, kc, vc, q_offset, kv_idx * block, causal, window
        )
        m, l, o = _combine((m, l, o), blk)
        if step < n_steps - 1:
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            kv_idx = jax.lax.ppermute(kv_idx, axis, perm)

    # under causal self-attention every row sees at least its own
    # position, so l >= 1 always; divide directly. Non-causal visits
    # every block, so l > 0 there too.
    out = (o / l[..., None]).reshape(qb.shape).astype(qb.dtype)
    if not want_lse:
        return out
    lse = (m + jnp.log(jnp.maximum(l, 1e-37))).reshape(
        *qb.shape[:-1]
    )
    return out, lse


def _ring_local_bwd(
    qb, kb, vb, ob, lse, dob, *, axis, p_size, block, causal, window=None,
    backend="flash",
):
    """Per-device flash-style backward over a second ring pass.

    dq accumulates locally; (dk, dv) partials rotate alongside their k/v
    block until every LIVE pairing has been computed, then jump the rest
    of the circle home in ONE multi-hop ppermute — so with a sliding
    window the gradient comms also scale with the window. GQA-native:
    dk/dv accumulate at kv-head width (the group dim contracts in the
    einsums); fully masked rows recompute p as exp(-inf - lse) = 0, so
    dead (wrapped/out-of-band) blocks contribute exact zeros.

    ``backend="flash"`` computes each pair's (dq, dk, dv) inside the
    flash backward kernels from the saved GLOBAL logsumexp
    (:func:`~beholder_tpu.ops.flash_attention.flash_block_backward`),
    with the same offset-driven masks as the forward.
    """
    if backend == "flash":
        from beholder_tpu.ops.flash_attention import flash_block_backward

        idx = jax.lax.axis_index(axis)
        q_offset = idx * block
        kc, vc, kv_idx = kb, vb, idx
        dq = jnp.zeros(qb.shape, jnp.float32)
        dkc = jnp.zeros(kb.shape, jnp.float32)
        dvc = jnp.zeros(vb.shape, jnp.float32)
        n_steps = _ring_steps(p_size, block, causal, window)
        perm = [(j, (j + 1) % p_size) for j in range(p_size)]
        for step in range(n_steps):
            if causal and step == 0:
                dq_s, dk_s, dv_s = flash_block_backward(
                    qb, kc, vc, ob, lse, dob, causal=True, window=window
                )
            else:
                offs = (
                    dict(q_offset=q_offset, kv_offset=kv_idx * block)
                    if causal
                    else {}
                )
                dq_s, dk_s, dv_s = flash_block_backward(
                    qb, kc, vc, ob, lse, dob, causal=causal,
                    window=window, **offs
                )
            dq = dq + dq_s.astype(jnp.float32)
            dkc = dkc + dk_s.astype(jnp.float32)
            dvc = dvc + dv_s.astype(jnp.float32)
            if step < n_steps - 1:
                kc = jax.lax.ppermute(kc, axis, perm)
                vc = jax.lax.ppermute(vc, axis, perm)
                kv_idx = jax.lax.ppermute(kv_idx, axis, perm)
                dkc = jax.lax.ppermute(dkc, axis, perm)
                dvc = jax.lax.ppermute(dvc, axis, perm)
        shift = p_size - (n_steps - 1)
        if shift % p_size:
            jump = [(j, (j + shift) % p_size) for j in range(p_size)]
            dkc = jax.lax.ppermute(dkc, axis, jump)
            dvc = jax.lax.ppermute(dvc, axis, jump)
        return (
            dq.astype(qb.dtype),
            dkc.astype(kb.dtype),
            dvc.astype(vb.dtype),
        )

    d = qb.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    idx = jax.lax.axis_index(axis)
    q_offset = idx * block
    qg, _ = _grouped(qb, kb)
    dof = dob.astype(jnp.float32)
    delta = jnp.sum(dof * ob.astype(jnp.float32), axis=-1)  # (..., H, T/P)
    dog = dof.reshape(qg.shape)
    deltag = delta.reshape(qg.shape[:-1])
    lseg = lse.reshape(qg.shape[:-1])

    dq = jnp.zeros(qg.shape, jnp.float32)
    kc, vc, kv_idx = kb, vb, idx
    dkc = jnp.zeros(kb.shape, jnp.float32)
    dvc = jnp.zeros(vb.shape, jnp.float32)

    n_steps = _ring_steps(p_size, block, causal, window)
    perm = [(j, (j + 1) % p_size) for j in range(p_size)]
    for step in range(n_steps):
        kv_offset = kv_idx * block
        s = jnp.einsum("...gqd,...kd->...gqk", qg, kc).astype(
            jnp.float32
        ) * scale
        if causal:
            tq, tk = qb.shape[-2], kc.shape[-2]
            rows = q_offset + jnp.arange(tq)[:, None]
            cols = kv_offset + jnp.arange(tk)[None, :]
            live = rows >= cols
            if window is not None:
                live = live & (rows - cols < window)
            s = jnp.where(live, s, _NEG_INF)
        p = jnp.exp(s - lseg[..., None])       # transient (T/P, T/P) block
        dvc = dvc + jnp.einsum("...gqk,...gqd->...kd", p, dog)
        dp = jnp.einsum("...gqd,...kd->...gqk", dog, vc.astype(jnp.float32))
        ds = (p * (dp - deltag[..., None]) * scale).astype(qb.dtype)
        dq = dq + jnp.einsum("...gqk,...kd->...gqd", ds, kc).astype(
            jnp.float32
        )
        dkc = dkc + jnp.einsum(
            "...gqk,...gqd->...kd", ds.astype(jnp.float32),
            qg.astype(jnp.float32),
        )
        if step < n_steps - 1:
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            kv_idx = jax.lax.ppermute(kv_idx, axis, perm)
            # gradient partials hop with their block
            dkc = jax.lax.ppermute(dkc, axis, perm)
            dvc = jax.lax.ppermute(dvc, axis, perm)

    # complete the circle home in ONE multi-hop permutation: the partials
    # have hopped n_steps-1 times and need p_size total (a full ring's
    # final hop is the shift=1 case of the same collective)
    shift = p_size - (n_steps - 1)
    if shift % p_size:
        jump = [(j, (j + shift) % p_size) for j in range(p_size)]
        dkc = jax.lax.ppermute(dkc, axis, jump)
        dvc = jax.lax.ppermute(dvc, axis, jump)

    return (
        dq.reshape(qb.shape).astype(qb.dtype),
        dkc.astype(kb.dtype),
        dvc.astype(vb.dtype),
    )


def _lead_axes(mesh: Mesh, ndim: int) -> list:
    """Sharding names for the leading (batch, heads) dims of a (..., T, d)
    attention operand, so ring/Ulysses compose with dp (batch) and megatron
    tp (heads are column-sharded over tp) on a 3-D ("dp","tp","sp") mesh.
    Rank-3 (merged batch*heads) operands keep leading dims replicated."""
    lead = [None] * (ndim - 2)
    if ndim >= 4:
        if "dp" in mesh.axis_names:
            lead[0] = "dp"
        if "tp" in mesh.axis_names:
            lead[1] = "tp"
    return lead


@functools.lru_cache(maxsize=None)
def _ring_vjp(
    mesh: Mesh, axis: str, causal: bool, ndim: int, window=None,
    backend="flash",
):
    """custom-VJP ring attention bound to (mesh, axis, causal, rank,
    window, backend)."""
    p_size = mesh.shape[axis]
    lead = _lead_axes(mesh, ndim)
    spec = P(*lead, axis, None)
    lse_spec = P(*lead, axis)

    def shard(fn, in_specs, out_specs):
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )

    @jax.custom_vjp
    def f(q, k, v):
        block = q.shape[-2] // p_size
        return shard(
            functools.partial(
                _ring_local_fwd, axis=axis, p_size=p_size, block=block,
                causal=causal, want_lse=False, window=window,
                backend=backend,
            ),
            (spec, spec, spec), spec,
        )(q, k, v)

    def f_fwd(q, k, v):
        block = q.shape[-2] // p_size
        o, lse = shard(
            functools.partial(
                _ring_local_fwd, axis=axis, p_size=p_size, block=block,
                causal=causal, want_lse=True, window=window,
                backend=backend,
            ),
            (spec, spec, spec), (spec, lse_spec),
        )(q, k, v)
        return o, (q, k, v, o, lse)

    def f_bwd(res, do):
        q, k, v, o, lse = res
        block = q.shape[-2] // p_size
        return shard(
            functools.partial(
                _ring_local_bwd, axis=axis, p_size=p_size, block=block,
                causal=causal, window=window, backend=backend,
            ),
            (spec, spec, spec, spec, lse_spec, spec),
            (spec, spec, spec),
        )(q, k, v, o, lse, do)

    f.defvjp(f_fwd, f_bwd)
    return f


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    backend: str = "flash",
    window: int | None = None,
) -> jax.Array:
    """DeepSpeed-Ulysses sequence parallelism: all-to-all head scatter.

    Inputs are (B, H, T, d) global arrays sharded along T on ``axis``.
    Each device trades its T/P sequence slice of all H heads for the FULL
    sequence of H/P heads (one ``all_to_all``, riding ICI on hardware),
    runs ordinary attention on those whole-sequence heads — flash by
    default, so the (T, T) matrix never exists — then reverses the
    exchange. Two all-to-alls per call vs ring attention's P-1 ppermutes;
    the tradeoff is H % P == 0 and O(T) k/v memory per device (vs ring's
    O(T/P)), which buys much better compute locality for moderate T.

    Grouped-query attention: k/v may carry fewer heads (Hkv) than q. When
    the per-tp-shard kv head count (Hkv, or Hkv/tp on a tp mesh) is
    divisible by the axis size, the kv all-to-all runs at kv-head width —
    GQA's traffic saving survives the exchange (flash and full are
    GQA-native on whole-sequence heads). Otherwise kv heads broadcast to
    H first (the pre-round-4 fallback; also when Hkv can't shard over tp
    at all). ``window`` (requires ``causal``) is the sliding-window span,
    handled by the local backend's banded grid once each device holds
    whole sequences.
    """
    p_size = mesh.shape[axis]
    b, h, t, d = q.shape
    hkv = k.shape[1]
    if h % hkv:
        raise ValueError(
            f"GQA q heads must be a multiple of kv heads; got {h} vs {hkv}"
        )
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    # heads local to one device after any tp (megatron column) sharding:
    # the all-to-all splits THAT dim, so it must divide by sp
    tp = mesh.shape.get("tp", 1) if "tp" in mesh.axis_names else 1
    h_local = h // tp
    if h_local % p_size:
        raise ValueError(
            f"per-device heads {h_local} not divisible by {axis}={p_size}"
        )
    if t % p_size:
        raise ValueError(f"sequence length {t} not divisible by {axis}={p_size}")
    if hkv % tp:
        # kv heads can't shard over tp at all: broadcast to full head
        # width BEFORE shard_map (the in_specs put tp on the head dim, so
        # a late repeat inside the body would be too late)
        k = jnp.repeat(k, h // hkv, axis=1)
        v = jnp.repeat(v, h // hkv, axis=1)
        hkv = h
    # kv all-to-all stays at kv-head width only if the LOCAL (per-tp-
    # shard) kv heads split evenly over sp; otherwise broadcast groups
    # inside the body (group boundaries stay shard-aligned since
    # hkv % tp == 0 here)
    kv_native = (hkv // tp) % p_size == 0

    if backend == "flash":
        from beholder_tpu.ops.flash_attention import flash_attention as attend
    else:
        attend = full_attention

    def local(qb, kb, vb):
        # (B, H, T/P, d) -> (B, H/P, T, d): split heads, gather sequence
        if not kv_native:
            kb = jnp.repeat(kb, h // hkv, axis=1)
            vb = jnp.repeat(vb, h // hkv, axis=1)
        qh, kh, vh = (
            jax.lax.all_to_all(a, axis, split_axis=1, concat_axis=2, tiled=True)
            for a in (qb, kb, vb)
        )
        att = attend(qh, kh, vh, causal=causal, window=window)
        # (B, H/P, T, d) -> (B, H, T/P, d)
        return jax.lax.all_to_all(att, axis, split_axis=2, concat_axis=1, tiled=True)

    spec = P(*_lead_axes(mesh, 4), axis, None)
    sharded = shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return sharded(q, k, v)


def sequence_sharding(mesh: Mesh, ndim: int, axis: str = "sp") -> NamedSharding:
    """NamedSharding placing the (-2) sequence dim on ``axis``."""
    return NamedSharding(mesh, P(*([None] * (ndim - 2)), axis, None))
