"""Micro-profiling-guided block-size autotuner for the paged kernels.

"Optimizing CUDA like a Human" (PAPERS.md) argues kernel block sizes
should come from measurement on the target machine, not from folklore —
and BENCH_NOTES.md's drift doctrine says the only trustworthy clock on
this shared host is the slope harness (k chained calls + ONE readback;
the readback constant cancels). This module applies both to the fused
paged chunk-attention kernel (:func:`beholder_tpu.ops.paged_attention.
paged_chunk_attention`):

- a **search** (:func:`search`) slope-times the kernel at every
  candidate ``(slots_per_block, pages_per_block)`` config for one
  shape class and keeps the fastest;
- the winners persist to a JSON **table** (``artifacts/
  autotune_paged.json`` by default — committed, so CI and every later
  session build the same kernels) keyed by :func:`shape_key`;
- kernel **build time** resolves the config through
  :func:`resolve_config`: explicit config > table hit > ``DEFAULTS``
  (a cold miss silently falls back — an untuned shape must run, just
  not optimally).

The search space is restricted BY CONSTRUCTION to numerics-neutral
knobs: ``slots_per_block`` (bq — how many slots' query rows one grid
step processes; per-slot attention is independent, so blocking the
batch dim cannot change any value) and ``pages_per_block`` (the kv
block granularity — how many pages each double-buffered DMA round
moves; DMA grouping never touches the math). A tuned kernel is
therefore bitwise-identical to the default-config kernel — the
autotuner moves wall time only (pinned by
``tests/test_paged_chunk_kernel.py``).

Table schema v2 (``validate_table`` is the checker) groups entries per
DTYPE FAMILY — the pool encoding (bf16 / int8 / fp8) changes the
kernel's DMA bytes and dequant arithmetic, so each family earns its own
measured winners and its own ``kernel_ceiling_frac:paged_chunk:<f>``
band in the perf gate::

    {"schema": "beholder-autotune-table", "schema_version": 2,
     "families": {"bf16": {"<base_key>": {
                      "config": {"slots_per_block": 4,
                                 "pages_per_block": 2},
                      "per_call_s": 1.2e-4,
                      "candidates": {"<cfg>": s, ...},
                      "measured_unix_s": ...}},
                  "int8": {...}, "fp8": {...}}}

``<base_key>`` is :func:`shape_key` minus its trailing ``/<dtype>``
segment; runtime lookups still use the FULL key (the in-memory view is
flat — ``base_key/family``), so kernel builds are untouched by the
restructure. v1 tables (flat ``entries``) still load: the fallback
direction must stay "old table reads fine", never "old table crashes
the build". A malformed table no longer falls back in silence — the
first bad read logs one warning (and emits an ``autotune.table_bad``
recorder instant when a flight recorder is armed via
:func:`set_recorder`), so a corrupt committed table cannot quietly
serve :data:`DEFAULTS` forever.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable

SCHEMA = "beholder-autotune-table"
SCHEMA_VERSION = 2

#: the dtype families a pool can resolve to (see
#: :func:`beholder_tpu.ops.paged_attention.pool_dtype_family`)
FAMILIES = ("bf16", "int8", "fp8")

#: the cold-miss fallback: safe everywhere (divisor-clamped at build),
#: measured-reasonable on the CPU interpreter and small TPU shapes
DEFAULTS: dict[str, int] = {"slots_per_block": 4, "pages_per_block": 2}

#: env override for the table location (CI / alternate hosts)
TABLE_ENV = "BEHOLDER_AUTOTUNE_TABLE"

#: default committed location: <repo>/artifacts/autotune_paged.json
DEFAULT_TABLE_PATH = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
    "artifacts",
    "autotune_paged.json",
)

_lock = threading.Lock()
_table: dict[str, Any] | None = None
_table_path: str | None = None
_recorder: Any = None
_warned_paths: set[str] = set()


def set_recorder(recorder: Any) -> None:
    """Arm (or with ``None`` disarm) the flight recorder malformed-table
    reads report to. Process-global like :func:`configure` — the table
    is a property of the host, and the read that discovers corruption
    happens once per process, not once per batcher."""
    global _recorder
    with _lock:
        _recorder = recorder


def shape_key(
    family: str,
    *,
    slots: int,
    width: int,
    max_pages: int,
    page: int,
    kv_heads: int,
    head_dim: int,
    dtype: str,
    group: int = 1,
) -> str:
    """One shape class = one table row. Exact-keyed (no bucketing): a
    near-miss silently tuned for a DIFFERENT shape is worse than the
    defaults; the fallback direction is explicit instead.

    ``group`` is the GROUP LAYOUT dimension (group-parallel decode —
    :mod:`beholder_tpu.cluster.group`): a group-of-N member runs the
    kernel over its ``kv_heads / N`` head slice, a different working
    set per grid step than the full-head single-device shape, so its
    measured winners live under their own ``<dtype>:g<N>`` family
    (``paged_chunk/.../bf16:g2``). ``group=1`` keeps the plain
    ``<dtype>`` family — the single-device key space is unchanged, and
    legacy tables (no group segment anywhere) keep resolving as the
    ``g1`` entries they are."""
    dtype_seg = dtype if group == 1 else f"{dtype}:g{group}"
    return (
        f"{family}/s{slots}w{width}p{max_pages}x{page}"
        f"h{kv_heads}d{head_dim}/{dtype_seg}"
    )


def configure(path: str | None) -> None:
    """Point the lazy table load at ``path`` (``instance.serving.
    autotune.table`` wiring) and drop any cached table so the next
    lookup re-reads. ``None`` restores the default resolution
    ($BEHOLDER_AUTOTUNE_TABLE, then the committed artifact)."""
    global _table, _table_path
    with _lock:
        _table_path = path
        _table = None


def table_path() -> str:
    return (
        _table_path
        or os.environ.get(TABLE_ENV)
        or DEFAULT_TABLE_PATH
    )


def load_table(path: str | None = None) -> dict[str, Any]:
    """The table's ``entries`` dict; a missing or malformed file is an
    EMPTY table (cold start must serve, never crash), cached after the
    first read."""
    global _table
    if path is not None:
        return _read_entries(path)
    with _lock:
        if _table is None:
            _table = _read_entries(table_path())
        return _table


def _read_entries(path: str) -> dict[str, Any]:
    try:
        with open(path) as f:
            raw = f.read()
    except OSError:
        return {}  # genuinely absent — the expected cold start
    try:
        obj = json.loads(raw)
        validate_table(obj)
        return flat_entries(obj)
    except (ValueError, KeyError, TypeError) as err:
        # json.JSONDecodeError is a ValueError: unparseable counts as
        # malformed (loud), not absent (silent)
        _warn_malformed(path, err)
        return {}


def _warn_malformed(path: str, err: Exception) -> None:
    """One warning per path per process (the read retries on every
    ``configure``, and a corrupt file would otherwise spam), plus an
    ``autotune.table_bad`` instant when a recorder is armed — the
    satellite contract: a malformed COMMITTED table must be loud, not
    a silent permanent fallback to :data:`DEFAULTS`."""
    if path in _warned_paths:
        return
    _warned_paths.add(path)
    from beholder_tpu.log import get_logger

    get_logger("ops.autotune").warning(
        "autotune table %s is malformed (%s); serving DEFAULTS for "
        "every shape until it is regenerated",
        path,
        err,
    )
    if _recorder is not None:
        try:
            _recorder.instant(
                "autotune.table_bad", path=path, error=str(err)
            )
        except Exception:
            pass  # observability must never take the build down


def flat_entries(obj: dict[str, Any]) -> dict[str, Any]:
    """A validated table object's entries as the FLAT runtime view
    (``base_key/family`` -> entry): v2 families are joined back onto
    their base keys; v1 flat entries pass through."""
    if "families" in obj:
        return {
            f"{base}/{_canon_family(family)}": entry
            for family, rows in obj["families"].items()
            for base, entry in rows.items()
        }
    return dict(obj["entries"])


def _validate_entry(key: str, entry: Any) -> None:
    if not isinstance(entry, dict) or not isinstance(
        entry.get("config"), dict
    ):
        raise ValueError(f"entry {key!r} must carry a config dict")
    for knob, value in entry["config"].items():
        if not isinstance(value, int) or value < 1:
            raise ValueError(
                f"entry {key!r} config {knob}={value!r} must be a "
                "positive int"
            )
    if not isinstance(entry.get("per_call_s"), (int, float)):
        raise ValueError(f"entry {key!r} needs a numeric per_call_s")


def validate_table(obj: Any) -> None:
    """Raise ``ValueError`` unless ``obj`` is a well-formed table —
    the CI artifact gate's check on the committed file. Accepts both
    layouts: v2 (``families`` -> family -> base-key entries) and the
    legacy v1 flat ``entries`` dict."""
    if not isinstance(obj, dict):
        raise ValueError("autotune table must be a dict")
    if obj.get("schema") != SCHEMA:
        raise ValueError(f"schema must be {SCHEMA!r}, got {obj.get('schema')!r}")
    if not isinstance(obj.get("schema_version"), int):
        raise ValueError("schema_version must be an int")
    if "families" in obj:
        families = obj["families"]
        if not isinstance(families, dict):
            raise ValueError("families must be a dict")
        for family, rows in families.items():
            _canon_family(family)  # raises on unknown family / bad :gN
            if not isinstance(rows, dict):
                raise ValueError(f"family {family!r} must map to a dict")
            for base, entry in rows.items():
                _validate_entry(f"{base}/{family}", entry)
        return
    entries = obj.get("entries")
    if not isinstance(entries, dict):
        raise ValueError("entries must be a dict")
    for key, entry in entries.items():
        _validate_entry(key, entry)


#: legacy v1 dtype spellings -> v2 family names (a v1 table loaded and
#: re-saved migrates its keys instead of crashing the save)
_FAMILY_ALIASES = {"bfloat16": "bf16"}


def _canon_family(family: str) -> str:
    """Canonical spelling of a dtype family, including its optional
    group layout suffix: legacy v1 dtype spellings migrate to their
    family name, and an explicit ``:g1`` suffix collapses onto the
    plain family — legacy keys (no suffix) ARE the ``g1`` entries, so
    both spellings must land on the same table row. Raises
    ``ValueError`` for anything that is not ``<family>[:g<N>]``."""
    base, sep, grp = family.partition(":g")
    base = _FAMILY_ALIASES.get(base, base)
    if base not in FAMILIES:
        raise ValueError(
            f"unknown dtype family {family!r} (want one of {FAMILIES},"
            " optionally suffixed :g<N>)"
        )
    if not sep:
        return base
    if not grp.isdigit() or int(grp) < 1:
        raise ValueError(
            f"family {family!r} has a malformed group suffix (want"
            " :g<N> with N >= 1)"
        )
    return base if int(grp) == 1 else f"{base}:g{int(grp)}"


def _split_family(key: str) -> tuple[str, str]:
    """``base/family`` from a full shape key (the dtype family is the
    last ``/``-segment by :func:`shape_key`'s construction, optionally
    carrying a ``:g<N>`` group layout suffix); legacy v1 dtype
    spellings migrate to their family name and explicit ``:g1``
    collapses to the plain family."""
    base, _, family = key.rpartition("/")
    if not base:
        raise ValueError(
            f"key {key!r} does not end in a dtype family {FAMILIES}"
        )
    return base, _canon_family(family)


def save_table(
    entries: dict[str, Any], path: str | None = None
) -> str:
    """Persist ``entries`` — the FLAT runtime view, regrouped into the
    v2 per-family layout on disk — and, when writing the ACTIVE table,
    refresh the cache so builds in this process see the new winners
    immediately (a side copy saved to an explicit other path must not
    hijack what :func:`resolve_config` resolves). Returns the path."""
    global _table
    path = path or table_path()
    families: dict[str, dict[str, Any]] = {}
    for key, entry in entries.items():
        base, family = _split_family(key)
        families.setdefault(family, {})[base] = entry
    obj = {
        "schema": SCHEMA,
        "schema_version": SCHEMA_VERSION,
        "families": families,
    }
    validate_table(obj)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(obj, f, indent=1, sort_keys=True)
        f.write("\n")
    if os.path.abspath(path) == os.path.abspath(table_path()):
        with _lock:
            _table = dict(entries)
    return path


def resolve_config(
    key: str, explicit: dict[str, int] | None = None
) -> dict[str, int]:
    """The config one kernel build uses: explicit wins, then the
    persisted table, then :data:`DEFAULTS`. Deterministic — the same
    table yields the same config yields the same compiled kernel (the
    jit cache keys on the normalized config tuple)."""
    if explicit is not None:
        return {**DEFAULTS, **explicit}
    table = load_table()
    entry = table.get(key)
    if entry is None:
        # legacy dtype spellings resolve to their canonical family
        # (".../bfloat16" finds the migrated ".../bf16" entry); keys
        # outside any family are plain misses, not errors
        try:
            base, family = _split_family(key)
        except ValueError:
            pass
        else:
            entry = table.get(f"{base}/{family}")
    if entry is not None and isinstance(entry.get("config"), dict):
        return {**DEFAULTS, **entry["config"]}
    return dict(DEFAULTS)


def normalize(config: dict[str, int], slots: int, max_pages: int) -> tuple[int, int]:
    """Clamp a config to what the shape admits: ``slots_per_block``
    becomes the largest divisor of ``slots`` not above it (the grid
    must tile the batch exactly — padding a slot block would change
    the einsum shapes the bitwise contract is built on) and never
    above ``slots // 2`` for multi-slot batches — the kernel's
    no-dense-transient guarantee is a CONTRACT, not a tuning
    preference, so no table entry (or explicit config) may buy wall
    time by growing the per-step working set back into the full
    ``(slots, Hkv, max_pages*page, Dh)`` gather the kernel exists to
    kill. ``pages_per_block`` is capped at the table width."""
    sb = max(1, int(config.get("slots_per_block", DEFAULTS["slots_per_block"])))
    sb = min(sb, max(1, slots // 2))
    while slots % sb:
        sb -= 1
    pb = max(1, int(config.get("pages_per_block", DEFAULTS["pages_per_block"])))
    pb = min(pb, max(1, max_pages))
    return sb, pb


def candidate_configs(slots: int, max_pages: int) -> list[dict[str, int]]:
    """The search grid for one shape: slot-block sizes over the
    divisors of ``slots`` up to the no-transient cap (``slots // 2``
    — see :func:`normalize`), page-block sizes over small powers of
    two capped at the table width."""
    cap = max(1, slots // 2)
    sbs = [d for d in (1, 2, 4, 8, 16) if d <= cap and slots % d == 0]
    pbs = [p for p in (1, 2, 4, 8) if p <= max(1, max_pages)]
    return [
        {"slots_per_block": sb, "pages_per_block": pb}
        for sb in sbs
        for pb in pbs
    ]


def search(
    key: str,
    build_fn: Callable[[dict[str, int]], Callable[[Any], Any]],
    candidates: list[dict[str, int]],
    *,
    k1: int = 4,
    k2: int = 16,
    rounds: int = 2,
) -> tuple[dict[str, int], dict[str, float]]:
    """Slope-time every candidate and return (winner, per-candidate
    seconds). ``build_fn(config)`` returns a chainable ``fn(prev) ->
    out`` for the slope harness (:func:`beholder_tpu.obs.roofline.
    _slope_seconds` — k chained calls + one scalar readback, min over
    rounds; the harness the flight recorder's ceilings already trust
    on this host)."""
    from beholder_tpu.obs.roofline import _slope_seconds

    timings: dict[str, float] = {}
    best: dict[str, int] | None = None
    best_s = float("inf")
    for config in candidates:
        fn = build_fn(config)
        per_call = _slope_seconds(fn, k1, k2, rounds)
        label = ",".join(f"{k}={v}" for k, v in sorted(config.items()))
        timings[label] = per_call
        if per_call < best_s:
            best_s = per_call
            best = config
    assert best is not None, "search needs at least one candidate"
    return best, timings


def autotune_entry(
    key: str,
    build_fn: Callable[[dict[str, int]], Callable[[Any], Any]],
    candidates: list[dict[str, int]],
    **search_kw: Any,
) -> dict[str, Any]:
    """One table entry for ``key``: run :func:`search` and package the
    winner with its evidence (every candidate's slope time rides along
    — the table is an artifact, and artifacts carry raw numbers)."""
    import time

    best, timings = search(key, build_fn, candidates, **search_kw)
    label = ",".join(f"{k}={v}" for k, v in sorted(best.items()))
    return {
        "config": best,
        "per_call_s": timings[label],
        "candidates": timings,
        "measured_unix_s": time.time(),
    }
