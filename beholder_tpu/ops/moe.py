"""Switch-style mixture-of-experts FFN with expert parallelism (``ep``).

EXTENSION BEYOND THE REFERENCE (which has no models or tensors of any kind
— SURVEY.md §0/§5). Adds sparse capacity to the sequence models in
:mod:`beholder_tpu.models.sequence`.

TPU-first design notes:

- Routing is top-1 (Switch) with a fixed per-expert capacity, so every
  shape is static: dispatch and combine are dense one-hot tensors and the
  expert compute is three einsums — all MXU work, no gather/scatter, no
  data-dependent shapes for XLA to choke on.
- Routing is grouped (GShard-style): tokens are split into fixed-size
  groups and capacity is enforced per group, so dispatch/combine are
  (G, S, E, C) with C ∝ S/E — memory stays LINEAR in total tokens
  instead of the quadratic (N, E, C) of ungrouped dense dispatch, which
  matters for the long-context sequence models this layer plugs into.
- Expert weights carry a leading expert dim sharded ``P("ep", ...)``; the
  dispatch einsum contracts tokens against that dim, so GSPMD lowers the
  exchange to an all-to-all over the ``ep`` axis (ICI on hardware).
- Expert matmuls run in bfloat16 with float32 router/combine math.
- The standard load-balance auxiliary loss is sown into the
  ``intermediates`` collection; training code picks it up via
  ``mutable="intermediates"`` (see ``seq_loss``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from beholder_tpu.parallel.sharding import (
    leading_axis_spec,
    path_key_names,
    path_specs,
    shardings_from_specs,
)


class SwitchFFN(nn.Module):
    """Top-1 routed mixture-of-experts feed-forward block.

    (B, T, D) -> (B, T, D). Tokens beyond an expert's capacity are dropped
    (contribute zero), as in Switch Transformers; the residual connection
    around the block carries them through unchanged.
    """

    dim: int
    ff_dim: int
    num_experts: int
    capacity_factor: float = 2.0
    #: tokens per routing group; capacity is enforced within each group so
    #: dispatch memory is O(N·capacity_factor·group_size), linear in N
    group_size: int = 1024

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        n = b * t
        e = self.num_experts
        # pad to a whole number of fixed-size groups (shapes are static at
        # trace time). Padding rows are zeros appended AFTER every real
        # token, so within the one partial group their cumsum queue
        # positions come last — they can only take capacity slots real
        # tokens left unused — and their output rows are sliced off below.
        s = min(self.group_size, n)
        g = -(-n // s)
        n_pad = g * s
        cap = max(1, int(self.capacity_factor * s / e))
        xf = x.reshape(n, d)
        if n_pad != n:
            xf = jnp.pad(xf, ((0, n_pad - n), (0, 0)))
        xg = xf.reshape(g, s, d)

        logits = nn.Dense(e, name="router", dtype=jnp.float32)(
            xg.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)  # (G, S, E)
        gate = jnp.max(probs, axis=-1)  # (G, S)
        choice = jnp.argmax(probs, axis=-1)  # (G, S)
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # (G, S, E)

        # queue position of each token within its chosen expert's per-group
        # queue; -1 where the token did not choose that expert (one_hot of
        # -1 is all-zero)
        pos = jnp.cumsum(onehot, axis=1) * onehot - 1.0
        within_cap = (pos >= 0.0) & (pos < cap)
        dispatch = (
            jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)
            * within_cap[..., None]
        )  # (G, S, E, C)
        combine = dispatch * gate[..., None, None]

        w_up = self.param(
            "expert_up", nn.initializers.lecun_normal(), (e, d, self.ff_dim)
        )
        b_up = self.param("expert_up_bias", nn.initializers.zeros, (e, self.ff_dim))
        w_down = self.param(
            "expert_down", nn.initializers.lecun_normal(), (e, self.ff_dim, d)
        )
        b_down = self.param("expert_down_bias", nn.initializers.zeros, (e, d))

        xin = jnp.einsum("gsec,gsd->gecd", dispatch, xg.astype(jnp.float32))
        h = jnp.einsum(
            "gecd,edf->gecf", xin.astype(jnp.bfloat16), w_up.astype(jnp.bfloat16)
        ).astype(jnp.float32) + b_up[None, :, None, :]
        h = jax.nn.gelu(h)
        out = jnp.einsum(
            "gecf,efd->gecd", h.astype(jnp.bfloat16), w_down.astype(jnp.bfloat16)
        ).astype(jnp.float32) + b_down[None, :, None, :]
        y = jnp.einsum("gsec,gecd->gsd", combine, out)

        # Switch load-balance loss: E * sum_e f_e * p_e, minimized (=1) at
        # uniform routing; scaled in by the training loss, not here.
        # Padding rows are excluded so a partial final group can't skew it.
        if n_pad != n:
            valid = (jnp.arange(n_pad) < n).astype(jnp.float32).reshape(g, s, 1)
            frac_tokens = (onehot * valid).sum(axis=(0, 1)) / n
            frac_probs = (probs * valid).sum(axis=(0, 1)) / n
        else:
            frac_tokens = onehot.mean(axis=(0, 1))
            frac_probs = probs.mean(axis=(0, 1))
        aux = e * jnp.sum(frac_tokens * frac_probs)
        self.sow("intermediates", "aux_loss", aux)

        return y.reshape(n_pad, d)[:n].reshape(b, t, d).astype(x.dtype)


def _is_expert_path(path: tuple) -> bool:
    return any(name.startswith("expert_") for name in path_key_names(path))


def expert_specs(tree: Any, axis: str = "ep") -> Any:
    """PartitionSpec pytree: expert-stacked leaves on ``axis``, rest
    replicated. Works for params and for optimizer states that mirror the
    param tree (optax moments keep the leaf paths)."""
    return path_specs(
        tree,
        lambda path, leaf: (
            leading_axis_spec(leaf, axis) if _is_expert_path(path) else P()
        ),
    )


def expert_shardings(tree: Any, mesh: Mesh, axis: str = "ep") -> Any:
    """NamedSharding pytree for :func:`expert_specs` on ``mesh``."""
    return shardings_from_specs(expert_specs(tree, axis), mesh)
