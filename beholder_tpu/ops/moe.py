"""Switch-style mixture-of-experts FFN with expert parallelism (``ep``).

EXTENSION BEYOND THE REFERENCE (which has no models or tensors of any kind
— SURVEY.md §0/§5). Adds sparse capacity to the sequence models in
:mod:`beholder_tpu.models.sequence`.

TPU-first design notes:

- Routing is top-1 (Switch) with a fixed per-expert capacity, so every
  shape is static: dispatch and combine are dense one-hot tensors and the
  expert compute is three einsums — all MXU work, no gather/scatter, no
  data-dependent shapes for XLA to choke on.
- Expert weights carry a leading expert dim sharded ``P("ep", ...)``; the
  dispatch einsum contracts tokens against that dim, so GSPMD lowers the
  exchange to an all-to-all over the ``ep`` axis (ICI on hardware).
- Expert matmuls run in bfloat16 with float32 router/combine math.
- The standard load-balance auxiliary loss is sown into the
  ``intermediates`` collection; training code picks it up via
  ``mutable="intermediates"`` (see ``seq_loss``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from beholder_tpu.parallel.sharding import (
    leading_axis_spec,
    path_key_names,
    path_specs,
    shardings_from_specs,
)


class SwitchFFN(nn.Module):
    """Top-1 routed mixture-of-experts feed-forward block.

    (B, T, D) -> (B, T, D). Tokens beyond an expert's capacity are dropped
    (contribute zero), as in Switch Transformers; the residual connection
    around the block carries them through unchanged.
    """

    dim: int
    ff_dim: int
    num_experts: int
    capacity_factor: float = 2.0

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        n = b * t
        e = self.num_experts
        cap = max(1, int(self.capacity_factor * n / e))
        xf = x.reshape(n, d)

        logits = nn.Dense(e, name="router", dtype=jnp.float32)(
            xf.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)  # (N, E)
        gate = jnp.max(probs, axis=-1)  # (N,)
        choice = jnp.argmax(probs, axis=-1)  # (N,)
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # (N, E)

        # queue position of each token within its chosen expert; -1 where
        # the token did not choose that expert (one_hot of -1 is all-zero)
        pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0
        within_cap = (pos >= 0.0) & (pos < cap)
        dispatch = (
            jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)
            * within_cap[..., None]
        )  # (N, E, C)
        combine = dispatch * gate[:, None, None]

        w_up = self.param(
            "expert_up", nn.initializers.lecun_normal(), (e, d, self.ff_dim)
        )
        b_up = self.param("expert_up_bias", nn.initializers.zeros, (e, self.ff_dim))
        w_down = self.param(
            "expert_down", nn.initializers.lecun_normal(), (e, self.ff_dim, d)
        )
        b_down = self.param("expert_down_bias", nn.initializers.zeros, (e, d))

        xin = jnp.einsum("nec,nd->ecd", dispatch, xf.astype(jnp.float32))
        h = jnp.einsum(
            "ecd,edf->ecf", xin.astype(jnp.bfloat16), w_up.astype(jnp.bfloat16)
        ).astype(jnp.float32) + b_up[:, None, :]
        h = jax.nn.gelu(h)
        out = jnp.einsum(
            "ecf,efd->ecd", h.astype(jnp.bfloat16), w_down.astype(jnp.bfloat16)
        ).astype(jnp.float32) + b_down[:, None, :]
        y = jnp.einsum("nec,ecd->nd", combine, out)

        # Switch load-balance loss: E * sum_e f_e * p_e, minimized (=1) at
        # uniform routing; scaled in by the training loss, not here
        frac_tokens = onehot.mean(axis=0)
        frac_probs = probs.mean(axis=0)
        aux = e * jnp.sum(frac_tokens * frac_probs)
        self.sow("intermediates", "aux_loss", aux)

        return y.reshape(b, t, d).astype(x.dtype)


def _is_expert_path(path: tuple) -> bool:
    return any(name.startswith("expert_") for name in path_key_names(path))


def expert_specs(tree: Any, axis: str = "ep") -> Any:
    """PartitionSpec pytree: expert-stacked leaves on ``axis``, rest
    replicated. Works for params and for optimizer states that mirror the
    param tree (optax moments keep the leaf paths)."""
    return path_specs(
        tree,
        lambda path, leaf: (
            leading_axis_spec(leaf, axis) if _is_expert_path(path) else P()
        ),
    )


def expert_shardings(tree: Any, mesh: Mesh, axis: str = "ep") -> Any:
    """NamedSharding pytree for :func:`expert_specs` on ``mesh``."""
    return shardings_from_specs(expert_specs(tree, axis), mesh)
