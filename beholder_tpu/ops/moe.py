"""Switch-style mixture-of-experts FFN with expert parallelism (``ep``).

EXTENSION BEYOND THE REFERENCE (which has no models or tensors of any kind
— SURVEY.md §0/§5). Adds sparse capacity to the sequence models in
:mod:`beholder_tpu.models.sequence`.

TPU-first design notes:

- Routing is top-1 (Switch) with a fixed per-expert capacity, so every
  shape is static: dispatch and combine are dense one-hot tensors and the
  expert compute is three einsums — all MXU work, no gather/scatter, no
  data-dependent shapes for XLA to choke on.
- Routing is grouped (GShard-style): tokens are split into fixed-size
  groups and capacity is enforced per group, so dispatch/combine are
  (G, S, E, C) with C ∝ S/E — memory stays LINEAR in total tokens
  instead of the quadratic (N, E, C) of ungrouped dense dispatch, which
  matters for the long-context sequence models this layer plugs into.
- Expert weights carry a leading expert dim sharded ``P("ep", ...)``; the
  dispatch einsum contracts tokens against that dim, so GSPMD lowers the
  exchange to an all-to-all over the ``ep`` axis (ICI on hardware).
- Expert matmuls run in bfloat16 with float32 router/combine math.
- The standard load-balance auxiliary loss is sown into the
  ``intermediates`` collection; training code picks it up via
  ``mutable="intermediates"`` (see ``seq_loss``).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

from beholder_tpu.parallel.sharding import (
    leading_axis_spec,
    path_key_names,
    path_specs,
    shardings_from_specs,
)


class SwitchFFN(nn.Module):
    """Top-1 routed mixture-of-experts feed-forward block.

    (B, T, D) -> (B, T, D). Tokens beyond an expert's capacity are dropped
    (contribute zero), as in Switch Transformers; the residual connection
    around the block carries them through unchanged.
    """

    dim: int
    ff_dim: int
    num_experts: int
    capacity_factor: float = 2.0
    #: tokens per routing group; capacity is enforced within each group so
    #: dispatch memory is O(N·capacity_factor·group_size), linear in N
    group_size: int = 1024
    #: top-k routing: 1 = Switch, 2 = GShard-style top-2 (second choice
    #: queues behind every first choice in the group)
    router_topk: int = 1
    #: "tokens" (default): tokens pick experts (Switch/GShard top-k,
    #: capacity overflow drops). "experts": expert-choice routing (Zhou
    #: et al. 2022) — each expert picks its top-capacity tokens, so load
    #: balance is PERFECT by construction, no aux loss is needed, and no
    #: capacity slot is wasted; tokens may land on 0..E experts. CAVEAT:
    #: the top-k over the sequence lets routing see future tokens — use
    #: for scoring/encoder workloads, not autoregressive generation.
    router_type: str = "tokens"
    #: when set (and the mesh has ``ep_axis``), the layer follows the
    #: GShard dispatch layout: routing groups sharded over ``token_axes``,
    #: expert tensors sharded over ``ep_axis``, with sharding constraints
    #: on both sides of the exchange so GSPMD lowers it to an ALL-TO-ALL
    #: over ``ep`` instead of all-gathering tokens or expert weights
    #: (verified in tests/test_moe.py::test_ep_dispatch_lowers_to_all_to_all)
    mesh: Mesh | None = None
    ep_axis: str = "ep"
    #: mesh axes the token/group dim is sharded over (filtered to axes the
    #: mesh actually has); groups are padded to a multiple of their shards
    token_axes: tuple = ("dp", "ep")

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        n = b * t
        e = self.num_experts
        # pad to a whole number of fixed-size groups (shapes are static at
        # trace time). Padding rows are zeros appended AFTER every real
        # token, so within the one partial group their cumsum queue
        # positions come last — they can only take capacity slots real
        # tokens left unused — and their output rows are sliced off below.
        mesh_axes = set(self.mesh.axis_names) if self.mesh is not None else set()
        tok_axes = tuple(a for a in self.token_axes if a in mesh_axes)
        tok_shards = 1
        for a in tok_axes:
            tok_shards *= self.mesh.shape[a]
        s = min(self.group_size, n)
        g = -(-n // s)
        if tok_shards > 1:
            # GShard layout: the group dim is sharded over the token axes,
            # so it must be a multiple of their shard count
            g = -(-g // tok_shards) * tok_shards
            s = -(-n // g)
        n_pad = g * s
        cap = max(1, int(self.capacity_factor * self.router_topk * s / e))
        xf = x.reshape(n, d)
        if n_pad != n:
            xf = jnp.pad(xf, ((0, n_pad - n), (0, 0)))
        xg = xf.reshape(g, s, d)

        def on_tok(arr):
            """Group dim sharded over the token axes (no-op without mesh)."""
            if tok_axes:
                from jax.sharding import NamedSharding

                spec = P(tok_axes, *([None] * (arr.ndim - 1)))
                return jax.lax.with_sharding_constraint(
                    arr, NamedSharding(self.mesh, spec)
                )
            return arr

        xg = on_tok(xg)

        # validity mask for the zero-padding rows appended above; padding
        # is excluded from routing entirely (it must never consume a
        # capacity slot or skew count1/aux/drop statistics)
        if n_pad != n:
            valid = (jnp.arange(n_pad) < n).astype(jnp.float32).reshape(g, s, 1)
        else:
            valid = jnp.ones((g, s, 1), jnp.float32)

        logits = nn.Dense(e, name="router", dtype=jnp.float32)(
            xg.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)  # (G, S, E)

        # router z-loss (ST-MoE): keeps router logits from drifting large,
        # which otherwise saturates the softmax and destabilizes bf16 —
        # applies to BOTH routing directions
        z = jax.scipy.special.logsumexp(logits, axis=-1)  # (G, S)
        z_loss = jnp.sum(z**2 * valid[..., 0]) / n
        self.sow("intermediates", "router_z_loss", z_loss)

        if self.router_type == "experts":
            if self.router_topk != 1:
                raise ValueError(
                    "router_topk is a token-choice setting; expert-choice "
                    "capacity comes from capacity_factor alone — set "
                    "router_topk=1 (or scale capacity_factor instead)"
                )
            y = self._expert_choice(
                xg, probs, valid, on_tok, mesh_axes, tok_axes, n
            )
            return y.reshape(n_pad, d)[:n].reshape(b, t, d).astype(x.dtype)
        if self.router_type != "tokens":
            raise ValueError(
                f"router_type must be 'tokens' or 'experts', got "
                f"{self.router_type!r}"
            )
        gate = jnp.max(probs, axis=-1)  # (G, S)
        choice = jnp.argmax(probs, axis=-1)  # (G, S)
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.float32) * valid  # (G, S, E)

        # queue position of each token within its chosen expert's per-group
        # queue; -1 where the token did not choose that expert (one_hot of
        # -1 is all-zero)
        pos = jnp.cumsum(onehot, axis=1) * onehot - 1.0
        within_cap = (pos >= 0.0) & (pos < cap)
        dispatch = (
            jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=jnp.float32)
            * within_cap[..., None]
        )  # (G, S, E, C)
        if self.router_topk == 2:
            # GShard top-2: second choice = argmax with the first masked
            # out; its queue positions start AFTER every first choice in
            # the group; gates renormalized over the two picks
            probs2 = probs * (1.0 - onehot)
            gate2 = jnp.max(probs2, axis=-1)
            onehot2 = (
                jax.nn.one_hot(jnp.argmax(probs2, axis=-1), e, dtype=jnp.float32)
                * valid
            )
            count1 = jnp.sum(onehot, axis=1, keepdims=True)  # (G, 1, E)
            pos2 = (jnp.cumsum(onehot2, axis=1) + count1) * onehot2 - 1.0
            within2 = (pos2 >= 0.0) & (pos2 < cap)
            d2 = (
                jax.nn.one_hot(pos2.astype(jnp.int32), cap, dtype=jnp.float32)
                * within2[..., None]
            )
            denom = jnp.maximum(gate + gate2, 1e-9)
            combine = (
                dispatch * (gate / denom)[..., None, None]
                + d2 * (gate2 / denom)[..., None, None]
            )
            dispatch = dispatch + d2
        elif self.router_topk == 1:
            combine = dispatch * gate[..., None, None]
        else:
            raise ValueError(f"router_topk must be 1 or 2, got {self.router_topk}")

        # dispatch locally on each group shard FIRST (on_tok), then
        # reshard to the expert layout (on_ep): the double constraint
        # keeps GSPMD from fusing the layout change into the einsum
        # (which would all-gather the inputs) — the reshard itself is
        # the token exchange, lowered to an all-to-all over ep
        on_ep = self._on_ep(mesh_axes, tok_axes)
        xin = on_ep(
            on_tok(jnp.einsum("gsec,gsd->gecd", dispatch, xg.astype(jnp.float32)))
        )
        out = self._expert_mlp(xin, on_ep)
        y = on_tok(jnp.einsum("gsec,gecd->gsd", combine, out))

        # Switch load-balance loss: E * sum_e f_e * p_e, minimized (=1) at
        # uniform routing; scaled in by the training loss, not here.
        # Padding rows are excluded so a partial final group can't skew it.
        if n_pad != n:
            valid = (jnp.arange(n_pad) < n).astype(jnp.float32).reshape(g, s, 1)
            frac_tokens = (onehot * valid).sum(axis=(0, 1)) / n
            frac_probs = (probs * valid).sum(axis=(0, 1)) / n
        else:
            valid = jnp.ones((g, s, 1), jnp.float32)
            frac_tokens = onehot.mean(axis=(0, 1))
            frac_probs = probs.mean(axis=(0, 1))
        aux = e * jnp.sum(frac_tokens * frac_probs)
        self.sow("intermediates", "aux_loss", aux)

        # dropped-token fraction: a METRIC, not a loss term (seq_loss
        # skips it) — capacity overflow is silent otherwise. Each real
        # token owes router_topk assignments; count how many landed.
        assigned = jnp.sum(dispatch, axis=(2, 3)) * valid[..., 0]  # (G, S)
        drop_frac = 1.0 - jnp.sum(assigned) / (n * self.router_topk)
        self.sow("intermediates", "drop_fraction", drop_frac)

        return y.reshape(n_pad, d)[:n].reshape(b, t, d).astype(x.dtype)

    def _on_ep(self, mesh_axes, tok_axes):
        """Expert dim (axis 1) pinned onto the ep mesh axis; the group
        dim keeps any token axes that are NOT the ep axis (dp rows).
        The transition from on_tok to on_ep layout IS the token
        exchange — GSPMD lowers it to an all-to-all over ep."""

        def on_ep(arr):
            if self.mesh is not None and self.ep_axis in mesh_axes:
                from jax.sharding import NamedSharding

                g_axes = tuple(a for a in tok_axes if a != self.ep_axis)
                spec = P(
                    g_axes if g_axes else None,
                    self.ep_axis,
                    *([None] * (arr.ndim - 2)),
                )
                return jax.lax.with_sharding_constraint(
                    arr, NamedSharding(self.mesh, spec)
                )
            return arr

        return on_ep

    def _expert_mlp(self, xin, on_ep):
        """(G, E, C, D) dispatched tokens -> (G, E, C, D) expert outputs;
        bf16 matmuls against the ep-sharded expert stacks."""
        e, d = self.num_experts, self.dim
        w_up = self.param(
            "expert_up", nn.initializers.lecun_normal(), (e, d, self.ff_dim)
        )
        b_up = self.param(
            "expert_up_bias", nn.initializers.zeros, (e, self.ff_dim)
        )
        w_down = self.param(
            "expert_down", nn.initializers.lecun_normal(), (e, self.ff_dim, d)
        )
        b_down = self.param("expert_down_bias", nn.initializers.zeros, (e, d))
        h = on_ep(
            jnp.einsum(
                "gecd,edf->gecf", xin.astype(jnp.bfloat16),
                w_up.astype(jnp.bfloat16),
            ).astype(jnp.float32)
            + b_up[None, :, None, :]
        )
        h = jax.nn.gelu(h)
        return on_ep(
            jnp.einsum(
                "gecf,efd->gecd", h.astype(jnp.bfloat16),
                w_down.astype(jnp.bfloat16),
            ).astype(jnp.float32)
            + b_down[None, :, None, :]
        )

    def _expert_choice(
        self, xg, probs, valid, on_tok, mesh_axes, tok_axes, n
    ):
        """Expert-choice routing: each expert top-k's its tokens.

        Dispatch is (G, E, C, S) — expert e's slot c holds its c-th best
        token — so every capacity slot is filled and per-expert load is
        exactly C by construction: no aux loss, no overflow drops. The
        expert pipeline and the ep all-to-all layout are identical to the
        token-choice path; only the selection direction differs.
        """
        _, s, e = probs.shape
        cap = min(s, max(1, int(self.capacity_factor * s / e)))
        # padding rows never get picked while any real token remains:
        # their selection score is forced below every real softmax prob
        scores = jnp.where(valid > 0, probs, -1.0)
        _, idx = jax.lax.top_k(jnp.swapaxes(scores, 1, 2), cap)  # (G,E,C)
        dispatch = jax.nn.one_hot(idx, s, dtype=jnp.float32)     # (G,E,C,S)
        # combine weight of slot (e, c) = its token's affinity for e
        # (padding-picked slots get 0 and contribute nothing)
        gv = jnp.einsum("gecs,gse->gec", dispatch, probs * valid)

        on_ep = self._on_ep(mesh_axes, tok_axes)
        xin = on_ep(
            on_tok(
                jnp.einsum("gecs,gsd->gecd", dispatch, xg.astype(jnp.float32))
            )
        )
        out = self._expert_mlp(xin, on_ep)
        y = on_tok(jnp.einsum("gecs,gec,gecd->gsd", dispatch, gv, out))

        # no aux loss — load balance is structural. The health metric
        # flips: how many REAL tokens were picked by no expert at all?
        picked = jnp.clip(jnp.einsum("gecs->gs", dispatch), 0.0, 1.0)
        unrouted = 1.0 - jnp.sum(picked * valid[..., 0]) / n
        self.sow("intermediates", "unrouted_fraction", unrouted)
        return y


def moe_metrics(sown: Any) -> dict[str, float]:
    """Pull routing health metrics out of a ``mutable="intermediates"``
    apply: mean drop_fraction / aux_loss / router_z_loss across layers."""
    from jax.tree_util import tree_flatten_with_path

    sums: dict[str, list] = {}
    for path, leaf in tree_flatten_with_path(sown)[0]:
        names = path_key_names(path)
        for key in ("drop_fraction", "aux_loss", "router_z_loss", "unrouted_fraction"):
            if key in names:
                sums.setdefault(key, []).append(leaf)
    return {k: float(sum(v) / len(v)) for k, v in sums.items()}


def _is_expert_path(path: tuple) -> bool:
    return any(name.startswith("expert_") for name in path_key_names(path))


def expert_specs(tree: Any, axis: str = "ep") -> Any:
    """PartitionSpec pytree: expert-stacked leaves on ``axis``, rest
    replicated. Works for params and for optimizer states that mirror the
    param tree (optax moments keep the leaf paths)."""
    return path_specs(
        tree,
        lambda path, leaf: (
            leading_axis_spec(leaf, axis) if _is_expert_path(path) else P()
        ),
    )


def expert_shardings(tree: Any, mesh: Mesh, axis: str = "ep") -> Any:
    """NamedSharding pytree for :func:`expert_specs` on ``mesh``."""
    return shardings_from_specs(expert_specs(tree, axis), mesh)
