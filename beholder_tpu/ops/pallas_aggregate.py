"""Fused telemetry aggregation as a Pallas TPU kernel.

MEASURED VERDICT (TPU via axon tunnel, 8M-event batch, 2026-07-29,
host-readback barrier — ``block_until_ready`` does not actually block
under the tunnel, which inflated an earlier measurement ~100x): the XLA
path (:func:`beholder_tpu.ops.aggregate_telemetry`) runs at ~1.6 B
events/s because XLA fully fuses the one-hot contraction and never
materializes the (B, S) intermediate. This kernel reaches ~0.46 B
events/s (VPU-bound: S masked reductions per tile). The XLA path
therefore REMAINS THE DEFAULT; this module is kept as a tested, working
example of the Pallas toolchain (grid accumulation, ``pl.when`` init,
padding, interpret-mode CPU tests) and as the starting point if the op
ever grows a compute-bound inner loop XLA can't fuse. (The Pallas kernel
that DOES win on TPU is :mod:`beholder_tpu.ops.flash_attention` — ~1.9x
over XLA full attention at T=4096, causal bf16; see bench.py's
``flash_attention_tflops`` secondary metric for the live number.)

Mechanics: each grid step loads a (512, 128) tile of statuses+progress
into VMEM and updates per-lane accumulators (count/sum/max/min per
status) held in VMEM across the whole sequential grid; only the tiny
(4*S, 128) accumulator block is ever written back.

Layout notes (see /opt/skills/guides/pallas_guide.md):
- float32/int32 tiles are (8, 128) — the batch is padded to 1024-element
  multiples and viewed as (M, 128).
- The output BlockSpec maps every grid step to the same block, which is
  the standard sequential-accumulation pattern (TPU grids iterate in
  order); step 0 initializes the accumulators via ``pl.when``.
- Cross-lane (axis=1) reduction of the (4*S, 128) accumulators happens
  outside the kernel — it is 24*128 values, negligible.

On non-TPU backends the kernel runs in interpreter mode so tests exercise
the same code path on the virtual CPU mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .aggregate import NUM_STATUSES

_LANES = 128
_SUBLANES = 512  # rows per grid step (multiple of the 8-row f32 tile);
# bigger blocks amortize per-step overhead: 512*128*4B*2 inputs = 512 KiB
# of VMEM, well under the ~16 MiB budget
_TILE = _LANES * _SUBLANES  # 65536 events per grid step
_BIG = 1e9  # plain Python float: a jnp scalar would be a captured constant


def _kernel(status_ref, progress_ref, out_ref):
    """Accumulate per-status/per-lane stats over one (8, 128) tile.

    out_ref rows: [0,S) counts, [S,2S) sums, [2S,3S) maxes, [3S,4S) mins.
    """
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        s = NUM_STATUSES
        out_ref[0 : 2 * s, :] = jnp.zeros((2 * s, _LANES), jnp.float32)
        out_ref[2 * s : 3 * s, :] = jnp.full((s, _LANES), -_BIG)
        out_ref[3 * s : 4 * s, :] = jnp.full((s, _LANES), _BIG)

    statuses = status_ref[:]  # (8, 128) int32; padding rows hold -1
    progress = progress_ref[:]  # (8, 128) float32

    for s in range(NUM_STATUSES):  # static unroll: S small and fixed
        mask = statuses == s
        count = jnp.sum(mask.astype(jnp.float32), axis=0)  # (128,)
        total = jnp.sum(jnp.where(mask, progress, 0.0), axis=0)
        hi = jnp.max(jnp.where(mask, progress, -_BIG), axis=0)
        lo = jnp.min(jnp.where(mask, progress, _BIG), axis=0)
        out_ref[s, :] += count
        out_ref[NUM_STATUSES + s, :] += total
        out_ref[2 * NUM_STATUSES + s, :] = jnp.maximum(
            out_ref[2 * NUM_STATUSES + s, :], hi
        )
        out_ref[3 * NUM_STATUSES + s, :] = jnp.minimum(
            out_ref[3 * NUM_STATUSES + s, :], lo
        )


@partial(jax.jit, static_argnames=("interpret",))
def _run(statuses_2d: jax.Array, progress_2d: jax.Array, interpret: bool):
    m = statuses_2d.shape[0]
    grid = (m // _SUBLANES,)
    acc = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((_SUBLANES, _LANES), lambda i: (i, 0)),
        ],
        # every step accumulates into the same block
        out_specs=pl.BlockSpec((4 * NUM_STATUSES, _LANES), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((4 * NUM_STATUSES, _LANES), jnp.float32),
        interpret=interpret,
    )(statuses_2d, progress_2d)

    s = NUM_STATUSES
    counts = acc[0:s].sum(axis=1)
    sums = acc[s : 2 * s].sum(axis=1)
    maxes = acc[2 * s : 3 * s].max(axis=1)
    mins = acc[3 * s : 4 * s].min(axis=1)
    present = counts > 0
    return {
        "count": counts.astype(jnp.int32),
        "mean_progress": jnp.where(present, sums / jnp.maximum(counts, 1.0), 0.0),
        "max_progress": jnp.where(present, maxes, 0.0),
        "min_progress": jnp.where(present, mins, 0.0),
    }


def aggregate_telemetry_pallas(
    statuses: jax.Array, progress: jax.Array
) -> dict[str, jax.Array]:
    """Pallas-fused equivalent of :func:`aggregate_telemetry`.

    Accepts any (B,) batch; pads to a 1024 multiple with status=-1 rows
    (matching no real status, so padding contributes nothing).
    """
    b = statuses.shape[0]
    if b == 0:
        # grid=(0,) never runs the init step; match aggregate_telemetry's
        # all-zeros semantics directly
        s = NUM_STATUSES
        return {
            "count": jnp.zeros(s, jnp.int32),
            "mean_progress": jnp.zeros(s, jnp.float32),
            "max_progress": jnp.zeros(s, jnp.float32),
            "min_progress": jnp.zeros(s, jnp.float32),
        }
    padded = ((b + _TILE - 1) // _TILE) * _TILE
    statuses = jnp.pad(
        statuses.astype(jnp.int32), (0, padded - b), constant_values=-1
    )
    progress = jnp.pad(progress.astype(jnp.float32), (0, padded - b))
    interpret = jax.devices()[0].platform != "tpu"
    return _run(
        statuses.reshape(-1, _LANES), progress.reshape(-1, _LANES), interpret
    )
