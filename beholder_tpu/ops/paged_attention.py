"""Paged-KV decode attention as a Pallas TPU kernel.

EXTENSION BEYOND THE REFERENCE (which has no inference of any kind —
SURVEY.md §0). This is the compute half of vLLM-style paged serving
(:mod:`beholder_tpu.models.serving` owns the pool/page-table data
structures): each slot's single query attends its OWN pages read IN
PLACE from the HBM pool via the page table — the round-3 implementation
instead gathered every slot's pages into a dense transient
``(slots, Hkv, max_pages*page, Dh)`` view per layer per tick, so HBM
traffic scaled with the maximum page span and "paged" was only true of
the persistent storage, not the compute.

Kernel design:

- The pools stay in HBM (``memory_space=ANY``); the kernel walks each
  slot's LIVE pages (``lens[s] // page + 1`` of them, minus any fully
  out-of-window leading pages) with double-buffered ``make_async_copy``
  DMAs — pages the slot does not own are never touched, so per-tick HBM
  traffic scales with tokens actually in flight.
- One kernel invocation serves ALL slots (a static unrolled loop, one
  dynamic ``fori_loop`` over pages per slot) — there is no per-slot grid
  step, so the whole tick pays ONE kernel dispatch per layer. Decode at
  telemetry-model sizes is latency-bound; grid-step fixed costs would
  dominate a (slots, pages) grid. The flip side: trace/compile time,
  Mosaic code size, and the semaphore array all grow LINEARLY with the
  slot count, so the design is sized for slot counts in the tens
  (benchmarked at 8; compiles were still comfortable at 16). Past ~32
  slots, move slots onto a grid dimension instead of widening the
  unroll.
- The page table and lengths ride SMEM (they index the DMAs; the scalar
  core reads them directly).
- The online-softmax state (m, l, acc) is a tiny per-slot register
  carry; the (H, page) score block exists only in VMEM. Positions past
  ``lens[s]`` (and, under a sliding window, at or before
  ``lens[s] - window``) are masked with -inf, matching the dense cache
  path's mask in :class:`beholder_tpu.models.sequence.Block`.
- Grouped-query attention is native: q carries H = G * Hkv heads, the
  pools carry Hkv; q head h reads pool head h // G (a static slice — the
  group loop is unrolled).
- Int8 pools (``k_scale``/``v_scale`` given): pages are stored int8 with
  per-(token, head) float32 scales and dequantized IN the kernel right
  after the DMA — int8 is the HBM-resident representation, so the
  cache's HBM FOOTPRINT halves vs bf16 (the capacity lever; composes
  with GQA). The throughput effect is shape-dependent and measured, not
  assumed: at the headline serving shape int8 decode runs ~1.2x bf16
  (BENCH r05 ``serving.int8_value``), but at long context the kernel is
  DMA-issue/VPU-bound, not bandwidth-bound, and the in-kernel dequant
  makes int8 ~0.8x there (``serving.long_context_t3584``) — see
  BENCH_NOTES.md for the attribution.
- Pool layout is (N, Hkv, Dh, page) — TOKENS ON LANES. Mosaic requires
  HBM DMA slices to be lane-aligned (128) on the minor dim; head dims
  are 64-ish but a page of tokens is naturally 128+, and this layout is
  also exactly what both kernel matmuls want: scores contract q's Dh
  against the page's leading Dh (no transpose), PV contracts the page
  axis directly. On real TPUs ``page`` must be a multiple of 128 (the
  interpreter used by CPU tests has no such constraint, so tests keep
  tiny pages).
- On non-TPU backends the kernel runs in interpreter mode — the CPU-mesh
  tests exercise the same code path.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


class QuantizedPool(NamedTuple):
    """Int8 KV page pool: ``values`` (N, Hkv, Dh, page) int8 plus
    per-(head, token) symmetric ``scales`` (N, Hkv, page) f32 —
    ``k ≈ values * scales`` with tokens on lanes. The decode kernel
    dequantizes right after each page DMA, so int8 is the HBM-resident
    representation (half the cache bytes AND half the page traffic)."""

    values: jax.Array
    scales: jax.Array


class PagedInfo(NamedTuple):
    """Per-tick paged-cache bookkeeping handed to the model's blocks.

    ``lens[s]`` is the number of tokens already in slot ``s``'s pages;
    the tick's new kv column is written at position ``lens[s]`` (page
    ``write_pages[s]``, row ``write_offsets[s]`` — pre-resolved by the
    scheduler, with an out-of-bounds page id for inactive slots so the
    write drops).
    """

    page_table: jax.Array     # (S, P) int32 pool page ids
    lens: jax.Array           # (S,) int32
    write_pages: jax.Array    # (S,) int32 (OOB -> dropped write)
    write_offsets: jax.Array  # (S,) int32 row inside the write page


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


# jax renamed pltpu.TPUMemorySpace -> pltpu.MemorySpace around 0.5; the
# members (ANY/VMEM/SMEM) are identical — accept either so the kernel
# keeps working across the versions this repo meets (the CI image pins a
# newer jax than some dev hosts carry)
_MEMORY_SPACE = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace


def _paged_kernel(
    table_ref, lens_ref, q_ref, kp_ref, vp_ref, ks_ref, vs_ref, o_ref,
    kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref, acc_ref, sems, *, page,
    window, slots, group, scale,
):
    """See module docstring. ``ks_ref``/``vs_ref``/``ksbuf``/``vsbuf``
    are None for bf16 pools. ``sems`` is a (4, 2, slots) DMA semaphore
    array: [k, v, kscale, vscale] x [buffer] x [slot].

    Slots advance in LOCKSTEP page rounds: round ``i`` issues every
    live slot's page-``i`` DMA together (they overlap in the memory
    system, so HBM latency amortizes across slots — a slot-serial walk
    pays it ``slots`` times over), double-buffered against round
    ``i+1``. Rounds where a slot is dead (page out of its live
    [p_lo, n_pages) range) skip its DMA and mask its whole score row;
    the explicit p-zero guard keeps a dead round's exp(-inf - -inf)
    from turning into ones before the slot's first live round.

    The online-softmax state lives in VMEM SCRATCH (``m_ref``/``l_ref``
    lane-broadcast (slots*H, 128), ``acc_ref`` (slots*H, Dh) — the same
    layout discipline as the flash kernels) rather than in the fori
    carry: a carry of 3*slots tiny (H, 1)-shaped arrays forces Mosaic
    into per-iteration relayouts that cost ~50x the round's actual
    compute (measured on v5e).
    """
    h = q_ref.shape[1]
    hkv = kp_ref.shape[1]
    dh = q_ref.shape[2]
    quant = ks_ref is not None

    length = [lens_ref[s] for s in range(slots)]
    # live pages hold positions 0..len inclusive; clamp to the page
    # table's width so a scheduler bug (a slot grown past its table) can
    # never drive a DMA from an out-of-bounds table read — the state's
    # alloc_failed flag is the error signal for that case
    max_pages = table_ref.shape[1]
    n_hi = [
        jnp.minimum(length[s] // page + 1, max_pages) for s in range(slots)
    ]
    if window is None:
        p_lo = [jnp.int32(0)] * slots
    else:
        p_lo = [
            jnp.maximum(length[s] - (window - 1), 0) // page
            for s in range(slots)
        ]
    lo, hi = p_lo[0], n_hi[0]
    for s in range(1, slots):
        lo = jnp.minimum(lo, p_lo[s])
        hi = jnp.maximum(hi, n_hi[s])

    def round_live(s, i):
        return (i >= p_lo[s]) & (i < n_hi[s])

    def start(i, buf):
        for s in range(slots):
            @pl.when(round_live(s, i))
            def _(s=s):
                pid = table_ref[s, i]
                pltpu.make_async_copy(
                    kp_ref.at[pid], kbuf.at[buf, s], sems.at[0, buf, s]
                ).start()
                pltpu.make_async_copy(
                    vp_ref.at[pid], vbuf.at[buf, s], sems.at[1, buf, s]
                ).start()
                if quant:
                    pltpu.make_async_copy(
                        ks_ref.at[pid], ksbuf.at[buf, s], sems.at[2, buf, s]
                    ).start()
                    pltpu.make_async_copy(
                        vs_ref.at[pid], vsbuf.at[buf, s], sems.at[3, buf, s]
                    ).start()

    def wait(i, buf):
        for s in range(slots):
            @pl.when(round_live(s, i))
            def _(s=s):
                pid = table_ref[s, i]
                pltpu.make_async_copy(
                    kp_ref.at[pid], kbuf.at[buf, s], sems.at[0, buf, s]
                ).wait()
                pltpu.make_async_copy(
                    vp_ref.at[pid], vbuf.at[buf, s], sems.at[1, buf, s]
                ).wait()
                if quant:
                    pltpu.make_async_copy(
                        ks_ref.at[pid], ksbuf.at[buf, s], sems.at[2, buf, s]
                    ).wait()
                    pltpu.make_async_copy(
                        vs_ref.at[pid], vsbuf.at[buf, s], sems.at[3, buf, s]
                    ).wait()

    start(lo, 0)
    m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)
    qs = [q_ref[s].astype(jnp.float32) for s in range(slots)]  # (H, Dh)

    def body(i, _):
        buf = jax.lax.rem(i - lo, 2)

        @pl.when(i + 1 < hi)
        def _():
            start(i + 1, jax.lax.rem(i + 1 - lo, 2))

        wait(i, buf)
        pos = i * page + jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)

        for s in range(slots):
            rows = slice(s * h, (s + 1) * h)
            m = m_ref[rows, :1]  # (H, 1); lanes hold copies
            if quant:  # dequant right after the DMA: per-(head, token)
                # scales broadcast over Dh. Dequantized pages are cast
                # to bf16 so BOTH dots run at bf16 MXU rate (an f32 dot
                # costs ~4 MXU passes). bf16 rounding is noise next to
                # the int8 quantization error already present.
                kpage = (
                    kbuf[buf, s].astype(jnp.float32)
                    * ksbuf[buf, s][:, None, :]
                ).astype(jnp.bfloat16)
                vpage = (
                    vbuf[buf, s].astype(jnp.float32)
                    * vsbuf[buf, s][:, None, :]
                ).astype(jnp.bfloat16)
            else:
                # cache dtype (bf16) on the MXU with f32 accumulation,
                # scores ROUNDED back to the cache dtype before the f32
                # softmax — the exact dtype mix of the dense cache path
                # in models.sequence.Block, so paged == dense to ULPs
                kpage = kbuf[buf, s][...]
                vpage = vbuf[buf, s][...]

            live = (pos <= length[s]) & round_live(s, i)
            if window is not None:
                live = live & (pos > length[s] - window)

            # per kv head: (G, Dh) x (Dh, page) -> (G, page) — the
            # tokens-on-lanes pool layout feeds the dot directly; the
            # group loop is static (GQA: q head h reads pool head h//G)
            parts = []
            for hh in range(hkv):
                qh = qs[s][hh * group:(hh + 1) * group, :]
                s_h = jax.lax.dot_general(
                    qh.astype(kpage.dtype), kpage[hh],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if not quant:
                    s_h = s_h.astype(kpage.dtype).astype(jnp.float32)
                parts.append(s_h * scale)
            s_all = jnp.concatenate(parts, axis=0) if hkv > 1 else parts[0]
            s_all = jnp.where(live, s_all, _NEG_INF)  # (H, page)

            m_new = jnp.maximum(m, jnp.max(s_all, axis=-1, keepdims=True))
            p = jnp.exp(s_all - m_new)
            # before a slot's first live round m is still -inf and the
            # fully-masked row would exp(0) to ones — zero it explicitly
            p = jnp.where(s_all <= _NEG_INF / 2, 0.0, p)
            alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
            l_ref[rows] = jnp.broadcast_to(
                l_ref[rows, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
                (h, l_ref.shape[1]),
            )
            pv_parts = []
            for hh in range(hkv):  # (G, page) x (Dh, page) -> (G, Dh)
                pv_parts.append(
                    jax.lax.dot_general(
                        # dense path casts softmax weights back to the
                        # cache dtype before the PV matmul; match it
                        p[hh * group:(hh + 1) * group, :].astype(
                            vpage.dtype
                        ),
                        vpage[hh],
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )
            pv = (
                jnp.concatenate(pv_parts, axis=0) if hkv > 1 else pv_parts[0]
            )
            # dead rounds (window p_lo > global lo) never DMA'd this
            # buffer: p is all-zero but vpage may be uninitialized NaN
            # garbage, and 0 * NaN would poison the accumulator
            pv = jnp.where(round_live(s, i), pv, 0.0)
            acc_ref[rows] = acc_ref[rows] * alpha + pv
            m_ref[rows] = jnp.broadcast_to(m_new, (h, m_ref.shape[1]))
        return 0

    jax.lax.fori_loop(lo, hi, body, 0)
    for s in range(slots):
        rows = slice(s * h, (s + 1) * h)
        # position `length[s]` is always live, so l >= its probability
        # > 0 — except in the table-overflow error case (alloc_failed
        # set, every round clamped away); the floor keeps that 0/0 from
        # minting NaNes into an output nobody should read
        o_ref[s] = (
            acc_ref[rows] / jnp.maximum(l_ref[rows, :1], 1e-37)
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("window", "interpret"))
def _paged_call(
    q, k_pool, v_pool, page_table, lens, k_scale, v_scale, *, window,
    interpret,
):
    slots, h, dh = q.shape
    _, hkv, _, page = k_pool.shape
    group = h // hkv
    quant = k_scale is not None
    scale = float(1.0 / (dh**0.5))

    smem = pl.BlockSpec(memory_space=_MEMORY_SPACE.SMEM)
    hbm = pl.BlockSpec(memory_space=_MEMORY_SPACE.ANY)
    vmem = pl.BlockSpec(memory_space=_MEMORY_SPACE.VMEM)

    scratch = [
        pltpu.VMEM((2, slots, hkv, dh, page), k_pool.dtype),  # kbuf
        pltpu.VMEM((2, slots, hkv, dh, page), v_pool.dtype),  # vbuf
        pltpu.VMEM((2, slots, hkv, page), jnp.float32) if quant else None,
        pltpu.VMEM((2, slots, hkv, page), jnp.float32) if quant else None,
        pltpu.VMEM((slots * h, 128), jnp.float32),  # m (lane-broadcast)
        pltpu.VMEM((slots * h, 128), jnp.float32),  # l
        pltpu.VMEM((slots * h, dh), jnp.float32),   # acc
        pltpu.SemaphoreType.DMA((4, 2, slots)),
    ]
    in_specs = [smem, smem, vmem, hbm, hbm]
    args = [page_table, lens, q, k_pool, v_pool]
    if quant:
        in_specs += [hbm, hbm]
        args += [k_scale, v_scale]

    def kernel(table_ref, lens_ref, q_ref, kp_ref, vp_ref, *rest):
        if quant:
            ks_ref, vs_ref = rest[0], rest[1]
            o_ref, kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref, acc_ref, sems = (
                rest[2:]
            )
        else:
            ks_ref = vs_ref = ksbuf = vsbuf = None
            (o_ref, kbuf, vbuf, m_ref, l_ref, acc_ref, sems) = rest
        _paged_kernel(
            table_ref, lens_ref, q_ref, kp_ref, vp_ref, ks_ref, vs_ref,
            o_ref, kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref, acc_ref, sems,
            page=page, window=window, slots=slots, group=group,
            scale=scale,
        )

    return pl.pallas_call(
        kernel,
        in_specs=in_specs,
        out_specs=vmem,
        out_shape=jax.ShapeDtypeStruct((slots, h, dh), q.dtype),
        scratch_shapes=[sh for sh in scratch if sh is not None],
        interpret=interpret,
    )(*args)


def paged_decode_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    lens: jax.Array,
    *,
    window: int | None = None,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Single-token decode attention over a paged KV pool, in place.

    - ``q``: (S, H, Dh) — slot ``s``'s query for position ``lens[s]``
      (whose kv column must already be scattered into the pool).
    - ``k_pool``/``v_pool``: (N, Hkv, Dh, page) page pools — tokens on
      the minor (lane) dim, see module docstring (bf16, or int8 with
      ``k_scale``/``v_scale`` (N, Hkv, page) f32 per-token scales). On
      real TPUs ``page`` must be a multiple of 128 (lane alignment for
      the in-place page DMAs).
    - ``page_table``: (S, P); entry ``(s, i)`` is the pool page holding
      slot ``s``'s positions ``[i*page, (i+1)*page)``.
    - ``lens``: (S,) — slot ``s`` attends positions ``0..lens[s]``
      inclusive (minus anything at or before ``lens[s] - window``).
      ``lens[s] == -1`` marks a DEAD slot: its live page range is empty,
      so it issues no page DMAs at all (the scheduler passes this for
      released slots whose stale ``page_table`` rows would otherwise
      cost one wasted page DMA per layer per tick) and its output row is
      all zeros.

    Returns (S, H, Dh) in q's dtype. Matches the dense cache path of
    :class:`~beholder_tpu.models.sequence.Block` to float tolerance; no
    dense (S, P*page) view of the cache ever materializes (pinned by
    ``tests/test_paged_attention.py``).
    """
    if q.ndim != 3:
        raise ValueError(f"q must be (slots, heads, head_dim), got {q.shape}")
    slots, h, dh = q.shape
    n, hkv, dh_p, page = k_pool.shape
    if dh_p != dh:
        raise ValueError(f"head_dim mismatch: q {dh} vs pool {dh_p}")
    if not _interpret() and page % 128:
        raise ValueError(
            f"page size {page} must be a multiple of 128 on TPU (pages "
            f"are lane-aligned token columns; pick page_size=128)"
        )
    if h % hkv:
        raise ValueError(f"q heads {h} must be a multiple of kv heads {hkv}")
    if k_pool.shape != v_pool.shape:
        raise ValueError(f"pool shape mismatch: {k_pool.shape} vs {v_pool.shape}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if k_scale is not None and k_scale.shape != (n, hkv, page):
        raise ValueError(
            f"scales must be {(n, hkv, page)}, got {k_scale.shape}"
        )
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return _paged_call(
        q, k_pool, v_pool, page_table.astype(jnp.int32),
        lens.astype(jnp.int32), k_scale, v_scale, window=window,
        interpret=_interpret(),
    )
