"""Paged-KV decode attention as a Pallas TPU kernel.

EXTENSION BEYOND THE REFERENCE (which has no inference of any kind —
SURVEY.md §0). This is the compute half of vLLM-style paged serving
(:mod:`beholder_tpu.models.serving` owns the pool/page-table data
structures): each slot's single query attends its OWN pages read IN
PLACE from the HBM pool via the page table — the round-3 implementation
instead gathered every slot's pages into a dense transient
``(slots, Hkv, max_pages*page, Dh)`` view per layer per tick, so HBM
traffic scaled with the maximum page span and "paged" was only true of
the persistent storage, not the compute.

Kernel design:

- The pools stay in HBM (``memory_space=ANY``); the kernel walks each
  slot's LIVE pages (``lens[s] // page + 1`` of them, minus any fully
  out-of-window leading pages) with double-buffered ``make_async_copy``
  DMAs — pages the slot does not own are never touched, so per-tick HBM
  traffic scales with tokens actually in flight.
- One kernel invocation serves ALL slots (a static unrolled loop, one
  dynamic ``fori_loop`` over pages per slot) — there is no per-slot grid
  step, so the whole tick pays ONE kernel dispatch per layer. Decode at
  telemetry-model sizes is latency-bound; grid-step fixed costs would
  dominate a (slots, pages) grid. The flip side: trace/compile time,
  Mosaic code size, and the semaphore array all grow LINEARLY with the
  slot count, so the design is sized for slot counts in the tens
  (benchmarked at 8; compiles were still comfortable at 16). Past ~32
  slots, move slots onto a grid dimension instead of widening the
  unroll.
- The page table and lengths ride SMEM (they index the DMAs; the scalar
  core reads them directly).
- The online-softmax state (m, l, acc) is a tiny per-slot register
  carry; the (H, page) score block exists only in VMEM. Positions past
  ``lens[s]`` (and, under a sliding window, at or before
  ``lens[s] - window``) are masked with -inf, matching the dense cache
  path's mask in :class:`beholder_tpu.models.sequence.Block`.
- Grouped-query attention is native: q carries H = G * Hkv heads, the
  pools carry Hkv; q head h reads pool head h // G (a static slice — the
  group loop is unrolled).
- Quantized pools (``k_scale``/``v_scale`` given): pages are stored
  8-bit — int8 values with per-(token, head) float32 scales, or
  ``float8_e4m3fn`` values with uint8 E8M0 shared-exponent scales
  (``scale = 2**(e - 127)``; see :mod:`beholder_tpu.ops.quant`) — and
  dequantized IN the kernel right after the DMA: 8-bit stays the
  HBM-resident representation, so the cache's HBM FOOTPRINT halves vs
  bf16 (the capacity lever; composes with GQA), and fp8's 1-byte
  scales shave the scale side-channel on top (4 bytes → 1 per
  (head, token) block). E8M0 dequant is a pure f32 exponent shift —
  exact — so the bitwise kernel-vs-oracle contract needs no new
  tolerance argument for fp8. The throughput effect is shape-dependent and measured, not
  assumed: at the headline serving shape int8 decode runs ~1.2x bf16
  (BENCH r05 ``serving.int8_value``), but at long context the kernel is
  DMA-issue/VPU-bound, not bandwidth-bound, and the in-kernel dequant
  makes int8 ~0.8x there (``serving.long_context_t3584``) — see
  BENCH_NOTES.md for the attribution.
- Pool layout is (N, Hkv, Dh, page) — TOKENS ON LANES. Mosaic requires
  HBM DMA slices to be lane-aligned (128) on the minor dim; head dims
  are 64-ish but a page of tokens is naturally 128+, and this layout is
  also exactly what both kernel matmuls want: scores contract q's Dh
  against the page's leading Dh (no transpose), PV contracts the page
  axis directly. On real TPUs ``page`` must be a multiple of 128 (the
  interpreter used by CPU tests has no such constraint, so tests keep
  tiny pages).
- On non-TPU backends the kernel runs in interpreter mode — the CPU-mesh
  tests exercise the same code path.

This module also carries the fused paged CHUNK kernel
(:func:`paged_chunk_attention` + :class:`ChunkPagedInfo`, further
down): the t>=1 twin of the decode kernel that serves spec-verify
rounds and prefix-hit admissions in place of the dense-gather
transient those paths used to materialize, bitwise-identical to the
dense oracle by construction and block-size-autotuned via
:mod:`beholder_tpu.ops.autotune`.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from beholder_tpu.ops.quant import pool_scales_f32

_NEG_INF = -1e30


class QuantizedPool(NamedTuple):
    """Quantized KV page pool: ``values`` (N, Hkv, Dh, page) plus
    per-(head, token) ``scales`` (N, Hkv, page) with tokens on lanes —
    ``k ≈ dequant(values) * pool_scales_f32(scales)``. Two encodings
    share this ONE container (so export/import, drain migration, forks
    and the prefix cache move either byte-identically with zero new
    code paths):

    - **int8**: int8 values, f32 symmetric scales (PR 4/10);
    - **fp8**: ``float8_e4m3fn`` values, uint8 E8M0 shared-exponent
      scales (``cache_dtype="fp8"`` — the scale side-channel drops
      4 bytes → 1 per block; see :mod:`beholder_tpu.ops.quant`).

    The kernels dequantize right after each page DMA, so the 8-bit
    form is the HBM-resident representation (half the cache bytes AND
    half the page traffic vs bf16)."""

    values: jax.Array
    scales: jax.Array


def pool_dtype_family(pool_values: jax.Array, *, quantized: bool) -> str:
    """The autotune-table dtype family of a pool: ``"bf16"``,
    ``"int8"``, or ``"fp8"`` (anything else keys by its dtype name —
    exact keys, never bucketing)."""
    if quantized:
        return (
            "fp8" if pool_values.dtype == jnp.float8_e4m3fn else "int8"
        )
    return (
        "bf16"
        if pool_values.dtype == jnp.bfloat16
        else str(pool_values.dtype)
    )


class PagedInfo(NamedTuple):
    """Per-tick paged-cache bookkeeping handed to the model's blocks.

    ``lens[s]`` is the number of tokens already in slot ``s``'s pages;
    the tick's new kv column is written at position ``lens[s]`` (page
    ``write_pages[s]``, row ``write_offsets[s]`` — pre-resolved by the
    scheduler, with an out-of-bounds page id for inactive slots so the
    write drops).
    """

    page_table: jax.Array     # (S, P) int32 pool page ids
    lens: jax.Array           # (S,) int32
    write_pages: jax.Array    # (S,) int32 (OOB -> dropped write)
    write_offsets: jax.Array  # (S,) int32 row inside the write page


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


# jax renamed pltpu.TPUMemorySpace -> pltpu.MemorySpace around 0.5; the
# members (ANY/VMEM/SMEM) are identical — accept either so the kernel
# keeps working across the versions this repo meets (the CI image pins a
# newer jax than some dev hosts carry)
_MEMORY_SPACE = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace


def _paged_kernel(
    table_ref, lens_ref, q_ref, kp_ref, vp_ref, ks_ref, vs_ref, o_ref,
    kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref, acc_ref, sems, *, page,
    window, slots, group, scale,
):
    """See module docstring. ``ks_ref``/``vs_ref``/``ksbuf``/``vsbuf``
    are None for bf16 pools. ``sems`` is a (4, 2, slots) DMA semaphore
    array: [k, v, kscale, vscale] x [buffer] x [slot].

    Slots advance in LOCKSTEP page rounds: round ``i`` issues every
    live slot's page-``i`` DMA together (they overlap in the memory
    system, so HBM latency amortizes across slots — a slot-serial walk
    pays it ``slots`` times over), double-buffered against round
    ``i+1``. Rounds where a slot is dead (page out of its live
    [p_lo, n_pages) range) skip its DMA and mask its whole score row;
    the explicit p-zero guard keeps a dead round's exp(-inf - -inf)
    from turning into ones before the slot's first live round.

    The online-softmax state lives in VMEM SCRATCH (``m_ref``/``l_ref``
    lane-broadcast (slots*H, 128), ``acc_ref`` (slots*H, Dh) — the same
    layout discipline as the flash kernels) rather than in the fori
    carry: a carry of 3*slots tiny (H, 1)-shaped arrays forces Mosaic
    into per-iteration relayouts that cost ~50x the round's actual
    compute (measured on v5e).
    """
    h = q_ref.shape[1]
    hkv = kp_ref.shape[1]
    dh = q_ref.shape[2]
    quant = ks_ref is not None

    length = [lens_ref[s] for s in range(slots)]
    # live pages hold positions 0..len inclusive; clamp to the page
    # table's width so a scheduler bug (a slot grown past its table) can
    # never drive a DMA from an out-of-bounds table read — the state's
    # alloc_failed flag is the error signal for that case
    max_pages = table_ref.shape[1]
    n_hi = [
        jnp.minimum(length[s] // page + 1, max_pages) for s in range(slots)
    ]
    if window is None:
        p_lo = [jnp.int32(0)] * slots
    else:
        p_lo = [
            jnp.maximum(length[s] - (window - 1), 0) // page
            for s in range(slots)
        ]
    lo, hi = p_lo[0], n_hi[0]
    for s in range(1, slots):
        lo = jnp.minimum(lo, p_lo[s])
        hi = jnp.maximum(hi, n_hi[s])

    def round_live(s, i):
        return (i >= p_lo[s]) & (i < n_hi[s])

    def start(i, buf):
        for s in range(slots):
            @pl.when(round_live(s, i))
            def _(s=s):
                pid = table_ref[s, i]
                pltpu.make_async_copy(
                    kp_ref.at[pid], kbuf.at[buf, s], sems.at[0, buf, s]
                ).start()
                pltpu.make_async_copy(
                    vp_ref.at[pid], vbuf.at[buf, s], sems.at[1, buf, s]
                ).start()
                if quant:
                    pltpu.make_async_copy(
                        ks_ref.at[pid], ksbuf.at[buf, s], sems.at[2, buf, s]
                    ).start()
                    pltpu.make_async_copy(
                        vs_ref.at[pid], vsbuf.at[buf, s], sems.at[3, buf, s]
                    ).start()

    def wait(i, buf):
        for s in range(slots):
            @pl.when(round_live(s, i))
            def _(s=s):
                pid = table_ref[s, i]
                pltpu.make_async_copy(
                    kp_ref.at[pid], kbuf.at[buf, s], sems.at[0, buf, s]
                ).wait()
                pltpu.make_async_copy(
                    vp_ref.at[pid], vbuf.at[buf, s], sems.at[1, buf, s]
                ).wait()
                if quant:
                    pltpu.make_async_copy(
                        ks_ref.at[pid], ksbuf.at[buf, s], sems.at[2, buf, s]
                    ).wait()
                    pltpu.make_async_copy(
                        vs_ref.at[pid], vsbuf.at[buf, s], sems.at[3, buf, s]
                    ).wait()

    start(lo, 0)
    m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[:] = jnp.zeros_like(l_ref)
    acc_ref[:] = jnp.zeros_like(acc_ref)
    qs = [q_ref[s].astype(jnp.float32) for s in range(slots)]  # (H, Dh)

    def body(i, _):
        buf = jax.lax.rem(i - lo, 2)

        @pl.when(i + 1 < hi)
        def _():
            start(i + 1, jax.lax.rem(i + 1 - lo, 2))

        wait(i, buf)
        pos = i * page + jax.lax.broadcasted_iota(jnp.int32, (1, page), 1)

        for s in range(slots):
            rows = slice(s * h, (s + 1) * h)
            m = m_ref[rows, :1]  # (H, 1); lanes hold copies
            if quant:  # dequant right after the DMA: per-(head, token)
                # scales broadcast over Dh (f32 for int8 pools; uint8
                # E8M0 exponents for fp8 pools — pool_scales_f32 is the
                # shared decoder). Dequantized pages are cast to bf16 so
                # BOTH dots run at bf16 MXU rate (an f32 dot costs ~4
                # MXU passes). bf16 rounding is noise next to the 8-bit
                # quantization error already present.
                kpage = (
                    kbuf[buf, s].astype(jnp.float32)
                    * pool_scales_f32(ksbuf[buf, s])[:, None, :]
                ).astype(jnp.bfloat16)
                vpage = (
                    vbuf[buf, s].astype(jnp.float32)
                    * pool_scales_f32(vsbuf[buf, s])[:, None, :]
                ).astype(jnp.bfloat16)
            else:
                # cache dtype (bf16) on the MXU with f32 accumulation,
                # scores ROUNDED back to the cache dtype before the f32
                # softmax — the exact dtype mix of the dense cache path
                # in models.sequence.Block, so paged == dense to ULPs
                kpage = kbuf[buf, s][...]
                vpage = vbuf[buf, s][...]

            live = (pos <= length[s]) & round_live(s, i)
            if window is not None:
                live = live & (pos > length[s] - window)

            # per kv head: (G, Dh) x (Dh, page) -> (G, page) — the
            # tokens-on-lanes pool layout feeds the dot directly; the
            # group loop is static (GQA: q head h reads pool head h//G)
            parts = []
            for hh in range(hkv):
                qh = qs[s][hh * group:(hh + 1) * group, :]
                s_h = jax.lax.dot_general(
                    qh.astype(kpage.dtype), kpage[hh],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                if not quant:
                    s_h = s_h.astype(kpage.dtype).astype(jnp.float32)
                parts.append(s_h * scale)
            s_all = jnp.concatenate(parts, axis=0) if hkv > 1 else parts[0]
            s_all = jnp.where(live, s_all, _NEG_INF)  # (H, page)

            m_new = jnp.maximum(m, jnp.max(s_all, axis=-1, keepdims=True))
            p = jnp.exp(s_all - m_new)
            # before a slot's first live round m is still -inf and the
            # fully-masked row would exp(0) to ones — zero it explicitly
            p = jnp.where(s_all <= _NEG_INF / 2, 0.0, p)
            alpha = jnp.exp(jnp.minimum(m - m_new, 0.0))
            l_ref[rows] = jnp.broadcast_to(
                l_ref[rows, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True),
                (h, l_ref.shape[1]),
            )
            pv_parts = []
            for hh in range(hkv):  # (G, page) x (Dh, page) -> (G, Dh)
                pv_parts.append(
                    jax.lax.dot_general(
                        # dense path casts softmax weights back to the
                        # cache dtype before the PV matmul; match it
                        p[hh * group:(hh + 1) * group, :].astype(
                            vpage.dtype
                        ),
                        vpage[hh],
                        (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                )
            pv = (
                jnp.concatenate(pv_parts, axis=0) if hkv > 1 else pv_parts[0]
            )
            # dead rounds (window p_lo > global lo) never DMA'd this
            # buffer: p is all-zero but vpage may be uninitialized NaN
            # garbage, and 0 * NaN would poison the accumulator
            pv = jnp.where(round_live(s, i), pv, 0.0)
            acc_ref[rows] = acc_ref[rows] * alpha + pv
            m_ref[rows] = jnp.broadcast_to(m_new, (h, m_ref.shape[1]))
        return 0

    jax.lax.fori_loop(lo, hi, body, 0)
    for s in range(slots):
        rows = slice(s * h, (s + 1) * h)
        # position `length[s]` is always live, so l >= its probability
        # > 0 — except in the table-overflow error case (alloc_failed
        # set, every round clamped away); the floor keeps that 0/0 from
        # minting NaNes into an output nobody should read
        o_ref[s] = (
            acc_ref[rows] / jnp.maximum(l_ref[rows, :1], 1e-37)
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("window", "interpret"))
def _paged_call(
    q, k_pool, v_pool, page_table, lens, k_scale, v_scale, *, window,
    interpret,
):
    slots, h, dh = q.shape
    _, hkv, _, page = k_pool.shape
    group = h // hkv
    quant = k_scale is not None
    scale = float(1.0 / (dh**0.5))

    smem = pl.BlockSpec(memory_space=_MEMORY_SPACE.SMEM)
    hbm = pl.BlockSpec(memory_space=_MEMORY_SPACE.ANY)
    vmem = pl.BlockSpec(memory_space=_MEMORY_SPACE.VMEM)

    scratch = [
        pltpu.VMEM((2, slots, hkv, dh, page), k_pool.dtype),  # kbuf
        pltpu.VMEM((2, slots, hkv, dh, page), v_pool.dtype),  # vbuf
        # scale staging buffers match the pool's scale dtype (f32 for
        # int8 pools, uint8 E8M0 for fp8 pools) — the DMA moves raw
        # scale bytes; decoding happens at the dequant site
        pltpu.VMEM((2, slots, hkv, page), k_scale.dtype) if quant else None,
        pltpu.VMEM((2, slots, hkv, page), v_scale.dtype) if quant else None,
        pltpu.VMEM((slots * h, 128), jnp.float32),  # m (lane-broadcast)
        pltpu.VMEM((slots * h, 128), jnp.float32),  # l
        pltpu.VMEM((slots * h, dh), jnp.float32),   # acc
        pltpu.SemaphoreType.DMA((4, 2, slots)),
    ]
    in_specs = [smem, smem, vmem, hbm, hbm]
    args = [page_table, lens, q, k_pool, v_pool]
    if quant:
        in_specs += [hbm, hbm]
        args += [k_scale, v_scale]

    def kernel(table_ref, lens_ref, q_ref, kp_ref, vp_ref, *rest):
        if quant:
            ks_ref, vs_ref = rest[0], rest[1]
            o_ref, kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref, acc_ref, sems = (
                rest[2:]
            )
        else:
            ks_ref = vs_ref = ksbuf = vsbuf = None
            (o_ref, kbuf, vbuf, m_ref, l_ref, acc_ref, sems) = rest
        _paged_kernel(
            table_ref, lens_ref, q_ref, kp_ref, vp_ref, ks_ref, vs_ref,
            o_ref, kbuf, vbuf, ksbuf, vsbuf, m_ref, l_ref, acc_ref, sems,
            page=page, window=window, slots=slots, group=group,
            scale=scale,
        )

    return pl.pallas_call(
        kernel,
        in_specs=in_specs,
        out_specs=vmem,
        out_shape=jax.ShapeDtypeStruct((slots, h, dh), q.dtype),
        scratch_shapes=[sh for sh in scratch if sh is not None],
        interpret=interpret,
    )(*args)


def paged_decode_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    lens: jax.Array,
    *,
    window: int | None = None,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Single-token decode attention over a paged KV pool, in place.

    - ``q``: (S, H, Dh) — slot ``s``'s query for position ``lens[s]``
      (whose kv column must already be scattered into the pool).
    - ``k_pool``/``v_pool``: (N, Hkv, Dh, page) page pools — tokens on
      the minor (lane) dim, see module docstring (bf16; int8 with
      ``k_scale``/``v_scale`` (N, Hkv, page) f32 per-token scales; or
      fp8 with uint8 E8M0 scales of the same shape). On
      real TPUs ``page`` must be a multiple of 128 (lane alignment for
      the in-place page DMAs).
    - ``page_table``: (S, P); entry ``(s, i)`` is the pool page holding
      slot ``s``'s positions ``[i*page, (i+1)*page)``.
    - ``lens``: (S,) — slot ``s`` attends positions ``0..lens[s]``
      inclusive (minus anything at or before ``lens[s] - window``).
      ``lens[s] == -1`` marks a DEAD slot: its live page range is empty,
      so it issues no page DMAs at all (the scheduler passes this for
      released slots whose stale ``page_table`` rows would otherwise
      cost one wasted page DMA per layer per tick) and its output row is
      all zeros.

    Returns (S, H, Dh) in q's dtype. Matches the dense cache path of
    :class:`~beholder_tpu.models.sequence.Block` to float tolerance; no
    dense (S, P*page) view of the cache ever materializes (pinned by
    ``tests/test_paged_attention.py``).
    """
    if q.ndim != 3:
        raise ValueError(f"q must be (slots, heads, head_dim), got {q.shape}")
    slots, h, dh = q.shape
    n, hkv, dh_p, page = k_pool.shape
    if dh_p != dh:
        raise ValueError(f"head_dim mismatch: q {dh} vs pool {dh_p}")
    if not _interpret() and page % 128:
        raise ValueError(
            f"page size {page} must be a multiple of 128 on TPU (pages "
            f"are lane-aligned token columns; pick page_size=128)"
        )
    if h % hkv:
        raise ValueError(f"q heads {h} must be a multiple of kv heads {hkv}")
    if k_pool.shape != v_pool.shape:
        raise ValueError(f"pool shape mismatch: {k_pool.shape} vs {v_pool.shape}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if k_scale is not None and k_scale.shape != (n, hkv, page):
        raise ValueError(
            f"scales must be {(n, hkv, page)}, got {k_scale.shape}"
        )
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    return _paged_call(
        q, k_pool, v_pool, page_table.astype(jnp.int32),
        lens.astype(jnp.int32), k_scale, v_scale, window=window,
        interpret=_interpret(),
    )


# -- fused paged CHUNK attention (verify / prefix-suffix prefill) ------------


#: tests flip this to route non-TPU paged_chunk_attention calls through
#: the pallas kernel in interpreter mode instead of the portable
#: :func:`_chunk_reference` transport. By itself this pins the pallas
#: body's MATH stages (overlay + attend + masking, the shared
#: ``_chunk_block_math``) bitwise against the reference twin and the
#: dense oracle — the interpreted body assembles its context as a
#: value gather, NOT via the zero+double-buffered-DMA pipeline a real
#: TPU compiles. Flip :data:`FORCE_PALLAS_INTERPRET_DMA` as well to
#: drive that DMA staging assembly itself (start/wait rounds, int8
#: stage+dequant) through the interpreter. Never set in production:
#: the interpreter materializes a whole-pool copy per grid step.
FORCE_PALLAS_INTERPRET = False

#: with :data:`FORCE_PALLAS_INTERPRET`, additionally runs the kernel's
#: REAL assembly stage — the zeroed VMEM scratch, the 1-ahead
#: double-buffered ``make_async_copy`` rounds, the post-wait int8
#: dequant — under the interpreter instead of the value-gather
#: shortcut, so the TPU DMA pipeline is itself pinned bitwise in CI.
#: Interpreter DMA descriptors cost ~50 us each: tiny pools only.
FORCE_PALLAS_INTERPRET_DMA = False


class ChunkPagedInfo(NamedTuple):
    """Cache index marking a FUSED chunk-attention forward
    (:class:`beholder_tpu.models.sequence.Block` dispatches on it, the
    way :class:`PagedInfo` marks the paged decode tick): the ``t >= 1``
    chunk attends its slot's pool pages IN PLACE via
    :func:`paged_chunk_attention` — no dense
    ``(slots, Hkv, max_pages*page, Dh)`` gather ever materializes —
    and the block returns the chunk's OWN (k, v) projections instead
    of a full-width updated cache, so the caller scatters exactly the
    chunk's columns into the pool (spec verify commits the accepted
    prefix; prefix-hit admission scatters the suffix).

    - ``page_table``: (S, P) pool page ids; only pages holding
      positions ``< lens[s]`` are read (the chunk's own positions come
      from the overlay, never from pages).
    - ``lens``: (S,) — row ``s``'s chunk occupies positions
      ``lens[s]..lens[s]+t-1`` and row ``j`` attends positions
      ``<= lens[s]+j`` (the dense path's per-row causal-offset mask).
    - ``ctx_len``: static attention width. The fused kernel matches
      the dense-gather oracle BITWISE, and XLA reassociates masked
      softmax reductions differently per width, so the width must be
      the oracle's exactly: ``P*page`` for spec verify (the dense path
      gathers the whole table row), ``P*page + t`` for prefix-hit
      admission (cached context plus the appended suffix chunk).
    - ``live_pages``: OPTIONAL static bound on table columns actually
      READ (None = all, what the serving scheduler passes — one
      compiled program per chunk width, no per-occupancy recompiles).
      The kernel's page traffic is bounded DYNAMICALLY regardless:
      the TPU transport's DMA rounds are gated on each slot's real
      ``ceil(lens[s]/page)``, so pages past the committed length are
      never moved (the paged promise — traffic follows tokens in
      flight), and dead positions are exact zeros — a masked lane
      contributes -inf to the max and an exact 0.0 to the softmax sum
      and PV either way, so neither bound changes values. The static
      bound additionally trims compiled code size / gather width for
      callers that know a hard cap (tests and the bench exercise it).
    """

    page_table: jax.Array
    lens: jax.Array
    ctx_len: int
    live_pages: int | None = None


class GroupSpec(NamedTuple):
    """Static description of the group-parallel layout a model forward
    runs under (:mod:`beholder_tpu.cluster.group`): inside a
    ``shard_map`` over a mesh axis named ``axis`` of size ``size``,
    each of the ``size`` group members holds a ``1/size`` KV-head
    slice of every paged pool and computes attention over its own
    slice, then tile-``all_gather``\\ s the per-member outputs back to
    the full head dim. Hashable and fully static — it rides the jit
    closure like :class:`PagedInfo` rides the cache argument, never
    the trace. ``size=1`` (or passing ``None`` instead of a spec) is
    the single-device engine, bit for bit."""

    axis: str
    size: int


def _chunk_kernel(
    table_ref, lens_ref, q_ref, kc_ref, vc_ref, kp_ref, vp_ref, ks_ref,
    vs_ref, o_ref, kctx, vctx, kstage, vstage, ksstage, vsstage, sems, *,
    page, window, sb, pb, max_pages, live_pages, ctx_len, group,
    dma,
):
    """One grid step = one block of ``sb`` slots (see
    :func:`paged_chunk_attention`). Three stages:

    1. **Assemble** each slot's bf16 context in VMEM: zero the block's
       (sb, Hkv, Dh, ctx_len) scratch, then move the slot's COMMITTED
       pages (``ceil(lens[s]/page)`` of them — freshly-popped or stale
       table entries past the committed length are never touched) in
       rounds of ``pb`` pages with a 1-ahead pipeline (round ``r+1``'s
       DMAs issue before round ``r``'s wait — the memory system
       overlaps them; ``pb`` is the autotuned DMA granularity). Int8
       pools stage into int8/f32 scratch and dequantize right after
       the wait — int8 stays the HBM representation, the bf16
       inflation exists only page-at-a-time in VMEM. With ``dma``
       off (the interpreted test transport's default; the
       FORCE_PALLAS_INTERPRET_DMA tests re-enable it) the context is
       instead built as a VALUE — per-page ref reads
       concatenated, which XLA fuses into one gather-shaped copy. The
       interpreter pays real costs for the TPU-shaped alternatives
       (a ``make_async_copy`` descriptor is ~50 us of semaphore
       bookkeeping, and every indexed scratch STORE re-materializes
       the whole functional buffer — measured 10-30x over the math at
       the serving shape), while per-page READS are cheap dynamic
       slices. Identical bytes either way; only the transport differs
       per backend. Dead positions past a block's live pages hold
       stale pool bytes here exactly as the dense oracle's gather
       does — every such lane is masked to -1e30 before softmax and
       its PV weight is an exact f32 zero, so values there never
       reach the output.
    2. **Overlay** the chunk's own (k, v) columns at positions
       ``lens[s]+j`` — the same scatter the dense oracle performs on
       its gathered buffer, so chunk self-attention reads the same
       values.
    3. **Attend** with the dense path's EXACT op sequence and shapes
       (bf16 score einsum, f32 ``/sqrt(Dh)``, -1e30 mask, f32 softmax,
       bf16 PV einsum): per-slot attention is batch-independent and
       masked lanes contribute exact zeros, so the fused output is
       BITWISE the dense-gather output — the property the serving
       knob's byte-identity contract rests on (pinned by
       ``tests/test_paged_chunk_kernel.py``).
    """
    quant = ks_ref is not None
    i = pl.program_id(0)
    s0 = i * sb
    w = q_ref.shape[2]
    hkv = kp_ref.shape[1]

    length = [lens_ref[s0 + s] for s in range(sb)]
    # committed pages only: positions >= lens[s] come from the overlay
    n_hi = [
        jnp.minimum((length[s] + page - 1) // page, live_pages)
        for s in range(sb)
    ]
    if window is None:
        p_lo = [jnp.int32(0)] * sb
    else:
        # the lowest position any chunk row can see is row 0's
        # lens[s] - (window - 1); wholly earlier pages are masked out
        # either way, so their DMAs are pure waste
        p_lo = [
            jnp.maximum(length[s] - (window - 1), 0) // page
            for s in range(sb)
        ]

    def page_live(s, p):
        return (p >= p_lo[s]) & (p < n_hi[s])

    n_rounds = -(-live_pages // pb) if live_pages else 0

    def start(r, buf):
        for s in range(sb):
            for j in range(pb):
                p = r * pb + j
                if p >= live_pages:
                    continue

                @pl.when(page_live(s, p))
                def _(s=s, j=j, p=p):
                    pid = table_ref[s0 + s, p]
                    dst = pl.ds(p * page, page)
                    if quant:
                        pltpu.make_async_copy(
                            kp_ref.at[pid], kstage.at[buf, s, j],
                            sems.at[0, buf, s, j],
                        ).start()
                        pltpu.make_async_copy(
                            vp_ref.at[pid], vstage.at[buf, s, j],
                            sems.at[1, buf, s, j],
                        ).start()
                        pltpu.make_async_copy(
                            ks_ref.at[pid], ksstage.at[buf, s, j],
                            sems.at[2, buf, s, j],
                        ).start()
                        pltpu.make_async_copy(
                            vs_ref.at[pid], vsstage.at[buf, s, j],
                            sems.at[3, buf, s, j],
                        ).start()
                    else:
                        pltpu.make_async_copy(
                            kp_ref.at[pid], kctx.at[s, :, :, dst],
                            sems.at[0, buf, s, j],
                        ).start()
                        pltpu.make_async_copy(
                            vp_ref.at[pid], vctx.at[s, :, :, dst],
                            sems.at[1, buf, s, j],
                        ).start()

    def wait(r, buf):
        for s in range(sb):
            for j in range(pb):
                p = r * pb + j
                if p >= live_pages:
                    continue

                @pl.when(page_live(s, p))
                def _(s=s, j=j, p=p):
                    pid = table_ref[s0 + s, p]
                    dst = pl.ds(p * page, page)
                    if quant:
                        pltpu.make_async_copy(
                            kp_ref.at[pid], kstage.at[buf, s, j],
                            sems.at[0, buf, s, j],
                        ).wait()
                        pltpu.make_async_copy(
                            vp_ref.at[pid], vstage.at[buf, s, j],
                            sems.at[1, buf, s, j],
                        ).wait()
                        pltpu.make_async_copy(
                            ks_ref.at[pid], ksstage.at[buf, s, j],
                            sems.at[2, buf, s, j],
                        ).wait()
                        pltpu.make_async_copy(
                            vs_ref.at[pid], vsstage.at[buf, s, j],
                            sems.at[3, buf, s, j],
                        ).wait()
                        # dequant right after the DMA: per-(head, token)
                        # scales broadcast over Dh (decoded through
                        # pool_scales_f32 — f32 pass-through for int8,
                        # exact E8M0 exponent shift for fp8), rounded
                        # to bf16 — the EXACT arithmetic of the dense
                        # oracle's _gather_dense, so quantized fused ==
                        # quantized dense for both families
                        kctx[s, :, :, dst] = (
                            kstage[buf, s, j].astype(jnp.float32)
                            * pool_scales_f32(ksstage[buf, s, j])[:, None, :]
                        ).astype(jnp.bfloat16)
                        vctx[s, :, :, dst] = (
                            vstage[buf, s, j].astype(jnp.float32)
                            * pool_scales_f32(vsstage[buf, s, j])[:, None, :]
                        ).astype(jnp.bfloat16)
                    else:
                        pltpu.make_async_copy(
                            kp_ref.at[pid], kctx.at[s, :, :, dst],
                            sems.at[0, buf, s, j],
                        ).wait()
                        pltpu.make_async_copy(
                            vp_ref.at[pid], vctx.at[s, :, :, dst],
                            sems.at[1, buf, s, j],
                        ).wait()

    if not dma:
        # interpreter assembly (the force-pallas TEST transport; the
        # production non-TPU route is :func:`_chunk_reference`, which
        # never enters pallas — see paged_chunk_attention): build the
        # block's context as a VALUE via one gather off the whole-ref
        # read. The interpreter materializes that read at POOL size
        # per grid step, so this path is only for the small pools the
        # kernel tests use — its job is pinning the pallas body's MATH
        # stages bitwise against the reference twin, not speed; the
        # DMA assembly itself is pinned separately through
        # FORCE_PALLAS_INTERPRET_DMA.
        dh = kp_ref.shape[2]
        tail = ctx_len - live_pages * page
        block_tab = table_ref[pl.ds(s0, sb), :][:, :live_pages]

        def assemble(pool_ref, scale_ref):
            g = pool_ref[...][block_tab]  # (sb, P', Hkv, Dh, page)
            if quant:
                g = (
                    g.astype(jnp.float32)
                    * pool_scales_f32(
                        scale_ref[...][block_tab]
                    )[:, :, :, None, :]
                ).astype(jnp.bfloat16)
            g = g.transpose(0, 2, 3, 1, 4).reshape(
                sb, hkv, dh, live_pages * page
            )
            if tail:
                g = jnp.concatenate(
                    [g, jnp.zeros((sb, hkv, dh, tail), jnp.bfloat16)],
                    axis=-1,
                )
            return g                             # (sb, Hkv, Dh, L) bf16

        k_lanes = assemble(kp_ref, ks_ref)
        v_lanes = assemble(vp_ref, vs_ref)
    else:
        # stage 1 (TPU): zero + DMA into the persistent VMEM scratch
        # (dead positions must be real finite zeros — a masked 0-weight
        # times stale-NaN scratch would poison the PV accumulator)
        kctx[...] = jnp.zeros(kctx.shape, kctx.dtype)
        vctx[...] = jnp.zeros(vctx.shape, vctx.dtype)
        if n_rounds:
            start(0, 0)
            for r in range(n_rounds):
                if r + 1 < n_rounds:
                    start(r + 1, (r + 1) % 2)
                wait(r, r % 2)
        k_lanes = kctx[...]                      # (sb, Hkv, Dh, L) bf16
        v_lanes = vctx[...]

    o_ref[...] = _chunk_block_math(
        q_ref[...], kc_ref[...], vc_ref[...], k_lanes, v_lanes,
        jnp.stack(length), window=window, ctx_len=ctx_len, group=group,
    )


def _chunk_block_math(
    q, kc, vc, k_lanes, v_lanes, lens_vec, *, window, ctx_len, group
):
    """Stages 2+3 of the fused chunk attention, shared VERBATIM by the
    pallas kernel body and the reference twin (one op sequence = the
    bitwise contract cannot drift between transports):

    2. **Overlay** the chunk's own (k, v) columns at positions
       ``lens[s]+j`` — the same scatter the dense oracle performs on
       its gathered buffer, so chunk self-attention reads the same
       values.
    3. **Attend** with the dense cache path's EXACT op sequence and
       shapes (bf16 score einsum, f32 ``/sqrt(Dh)``, -1e30 mask, f32
       softmax, bf16 PV einsum — models.sequence.Block's vector-index
       t>1 branch, op for op): per-slot attention is batch-independent
       and masked lanes contribute exact zeros, so the fused output is
       BITWISE the dense-gather output — the property the serving
       knob's byte-identity contract rests on (pinned by
       ``tests/test_paged_chunk_kernel.py``)."""
    sb, h, w, dh = q.shape
    hkv = k_lanes.shape[1]
    kall = k_lanes.transpose(0, 1, 3, 2)         # (sb, Hkv, L, Dh) bf16
    vall = v_lanes.transpose(0, 1, 3, 2)
    rows = jnp.arange(sb)
    pos_w = lens_vec[:, None] + jnp.arange(w)                   # (sb, W)
    kall = kall.at[rows[:, None], :, pos_w, :].set(
        kc.transpose(0, 2, 1, 3).astype(kall.dtype), mode="drop"
    )
    vall = vall.at[rows[:, None], :, pos_w, :].set(
        vc.transpose(0, 2, 1, 3).astype(vall.dtype), mode="drop"
    )
    qg = q.astype(kall.dtype).reshape(sb, hkv, group, w, dh)
    scores = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kall) / jnp.sqrt(
        jnp.float32(dh)
    )
    positions = jnp.arange(ctx_len)
    live = positions[None, None, :] <= pos_w[:, :, None]      # (sb, W, L)
    if window is not None:
        live = live & (positions[None, None, :] > pos_w[:, :, None] - window)
    scores = jnp.where(live[:, None, None, :, :], scores, _NEG_INF)
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum(
        "bhgqk,bhkd->bhgqd", weights.astype(q.dtype), vall
    ).reshape(sb, h, w, dh)


@functools.partial(
    jax.jit,
    static_argnames=("ctx_len", "live_pages", "window", "sb"),
)
def _chunk_reference(
    q, k_chunk, v_chunk, k_pool, v_pool, page_table, lens, k_scale,
    v_scale, *, ctx_len, live_pages, window, sb,
):
    """The kernel's PORTABLE transport (every non-TPU backend): the
    same block-streamed algorithm — assemble one slot block's context,
    overlay, attend, next block — expressed as plain XLA ops. The
    pallas interpreter taxes TPU-shaped constructs with real copies (a
    whole-pool materialization per whole-ref read, a full functional
    buffer per indexed scratch store, ~50 us per DMA descriptor —
    all measured), so on CPU the honest instantiation of the SAME
    per-block working-set contract is a value-level gather per block:
    XLA's gather reads only the indexed pages, whatever the pool size.
    Stages 2+3 are :func:`_chunk_block_math`, the identical code the
    pallas body runs — the two transports cannot drift."""
    slots, h, w, dh = q.shape
    hkv = k_pool.shape[1]
    page = k_pool.shape[3]
    quant = k_scale is not None
    tail = ctx_len - live_pages * page

    def assemble(pool, scales, block_tab):
        g = pool[block_tab]           # (sb, P', Hkv, Dh, page) gather
        if quant:
            # dequant AFTER the gather: only gathered pages pay the
            # bf16 inflation (the dense oracle inflates the WHOLE pool
            # first); per-element arithmetic is identical (the shared
            # pool_scales_f32 decoder handles both f32 and E8M0
            # scales), so values still match the oracle bitwise
            g = (
                g.astype(jnp.float32)
                * pool_scales_f32(scales[block_tab])[:, :, :, None, :]
            ).astype(jnp.bfloat16)
        else:
            g = g.astype(jnp.bfloat16)
        g = g.transpose(0, 2, 3, 1, 4).reshape(
            sb, hkv, dh, live_pages * page
        )
        if tail:
            g = jnp.concatenate(
                [g, jnp.zeros((sb, hkv, dh, tail), jnp.bfloat16)],
                axis=-1,
            )
        return g                                 # (sb, Hkv, Dh, L) bf16

    outs = []
    for i in range(slots // sb):
        rows = slice(i * sb, (i + 1) * sb)
        block_tab = page_table[rows, :live_pages]
        outs.append(
            _chunk_block_math(
                q[rows], k_chunk[rows], v_chunk[rows],
                assemble(k_pool, k_scale, block_tab),
                assemble(v_pool, v_scale, block_tab),
                lens[rows], window=window, ctx_len=ctx_len,
                group=h // hkv,
            )
        )
    return jnp.concatenate(outs, axis=0)


@functools.partial(
    jax.jit,
    static_argnames=(
        "ctx_len", "live_pages", "window", "sb", "pb", "interpret",
        "dma",
    ),
)
def _chunk_call(
    q, k_chunk, v_chunk, k_pool, v_pool, page_table, lens, k_scale,
    v_scale, *, ctx_len, live_pages, window, sb, pb, interpret, dma,
):
    slots, h, w, dh = q.shape
    _, hkv, _, page = k_pool.shape
    max_pages = page_table.shape[1]
    quant = k_scale is not None

    smem = pl.BlockSpec(memory_space=_MEMORY_SPACE.SMEM)
    hbm = pl.BlockSpec(memory_space=_MEMORY_SPACE.ANY)

    def row_block(shape):
        return pl.BlockSpec(
            (sb, *shape), lambda i: (i, *(0 for _ in shape))
        )

    staged = quant and dma
    scratch = [
        pltpu.VMEM((sb, hkv, dh, ctx_len), jnp.bfloat16),  # kctx
        pltpu.VMEM((sb, hkv, dh, ctx_len), jnp.bfloat16),  # vctx
        # staging buffers carry the pool's raw value/scale dtypes
        # (int8 + f32, or fp8 + uint8 E8M0); dequant decodes post-wait
        pltpu.VMEM((2, sb, pb, hkv, dh, page), k_pool.dtype)
        if staged else None,
        pltpu.VMEM((2, sb, pb, hkv, dh, page), v_pool.dtype)
        if staged else None,
        pltpu.VMEM((2, sb, pb, hkv, page), k_scale.dtype)
        if staged else None,
        pltpu.VMEM((2, sb, pb, hkv, page), v_scale.dtype)
        if staged else None,
        pltpu.SemaphoreType.DMA((4, 2, sb, pb)) if dma else None,
    ]
    in_specs = [
        smem, smem, row_block((h, w, dh)), row_block((hkv, w, dh)),
        row_block((hkv, w, dh)), hbm, hbm,
    ]
    args = [page_table, lens, q, k_chunk, v_chunk, k_pool, v_pool]
    if quant:
        in_specs += [hbm, hbm]
        args += [k_scale, v_scale]

    def kernel(table_ref, lens_ref, q_ref, kc_ref, vc_ref, kp_ref,
               vp_ref, *rest):
        kstage = vstage = ksstage = vsstage = sems = None
        if quant:
            ks_ref, vs_ref = rest[0], rest[1]
            rest = rest[2:]
        else:
            ks_ref = vs_ref = None
        if staged:
            (o_ref, kctx, vctx, kstage, vstage, ksstage, vsstage,
             sems) = rest
        elif dma:
            o_ref, kctx, vctx, sems = rest
        else:
            o_ref, kctx, vctx = rest
        _chunk_kernel(
            table_ref, lens_ref, q_ref, kc_ref, vc_ref, kp_ref, vp_ref,
            ks_ref, vs_ref, o_ref, kctx, vctx, kstage, vstage, ksstage,
            vsstage, sems, page=page, window=window, sb=sb, pb=pb,
            max_pages=max_pages, live_pages=live_pages, ctx_len=ctx_len,
            group=h // hkv, dma=dma,
        )

    return pl.pallas_call(
        kernel,
        grid=(slots // sb,),
        in_specs=in_specs,
        out_specs=row_block((h, w, dh)),
        out_shape=jax.ShapeDtypeStruct((slots, h, w, dh), jnp.bfloat16),
        scratch_shapes=[s for s in scratch if s is not None],
        interpret=interpret,
    )(*args)


def paged_chunk_attention(
    q: jax.Array,
    k_chunk: jax.Array,
    v_chunk: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    lens: jax.Array,
    *,
    ctx_len: int | None = None,
    live_pages: int | None = None,
    window: int | None = None,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    config: dict | None = None,
    group: int = 1,
) -> jax.Array:
    """Fused chunk attention DIRECTLY against the paged pools: each
    slot's ``W``-token query chunk (spec-verify drafts, or a
    prefix-hit admission's suffix) attends the slot's committed pages
    in place plus the chunk's own freshly projected (k, v) — replacing
    the dense-gather transient the verify/prefix paths used to
    materialize per layer (``(slots, Hkv, max_pages*page, Dh)`` in
    HBM, written then read, dequantized BEFORE attention under int8
    pools; the round-3 story all over again, one level up).

    - ``q``: (S, H, W, Dh); row ``j`` of slot ``s`` sits at position
      ``lens[s] + j`` and attends positions ``<= lens[s] + j`` (minus
      anything at or before ``pos - window``).
    - ``k_chunk``/``v_chunk``: (S, Hkv, W, Dh) — the chunk's own kv
      projections (NOT yet in the pool; the kernel overlays them, so
      verify needs no tentative pool writes at all).
    - ``k_pool``/``v_pool``/``k_scale``/``v_scale``: the page pools —
      bf16, int8 with f32 scales, or fp8 (``float8_e4m3fn``) with
      uint8 E8M0 scales — exactly as :func:`paged_decode_attention`
      takes them; quantized pools dequantize inside the kernel, page
      at a time.
    - ``page_table``: (S, P); ``lens``: (S,) committed tokens per slot.
    - ``ctx_len``: static attention width — MUST equal the dense
      oracle's buffer width for the bitwise contract (defaults to
      ``P * page``, the spec-verify case; prefix-hit admission passes
      ``P * page + W``).
    - ``live_pages``: optional static bound on table columns moved
      (None = the full table width; the TPU transport's DMA rounds
      are dynamically gated on each slot's real length either way).
      Bounding is TRAFFIC/code-size-only — the attention width stays
      ``ctx_len`` and skipped columns are exact zeros behind the
      mask, so values never change (see :class:`ChunkPagedInfo`).
    - ``config``: explicit ``{slots_per_block, pages_per_block}``
      override; by default the shape's autotuned entry
      (:mod:`beholder_tpu.ops.autotune`) or its defaults. Block sizes
      are numerics-neutral by construction — they move wall time only.
    - ``group``: the GROUP LAYOUT this call runs under (group-parallel
      decode, :mod:`beholder_tpu.cluster.group`): a group-of-N member
      calls with its ``Hkv/N`` head slice, which is a different shape
      class than the single-device full-head call even when the padded
      dims coincide, so its autotune lookup keys onto the
      ``<dtype>:g<N>`` family. Numerics-neutral — it only selects
      which measured block sizes serve the call.

    Returns (S, H, W, Dh) bf16, BITWISE-identical to running the dense
    cache path over the gathered context (pinned by
    ``tests/test_paged_chunk_kernel.py``); no ``(slots, ..,
    max_pages*page, ..)`` buffer exists anywhere in the program — the
    per-grid-step working set is ``slots_per_block/slots`` of it, in
    VMEM."""
    if q.ndim != 4:
        raise ValueError(
            f"q must be (slots, heads, width, head_dim), got {q.shape}"
        )
    slots, h, w, dh = q.shape
    n, hkv, dh_p, page = k_pool.shape
    if dh_p != dh:
        raise ValueError(f"head_dim mismatch: q {dh} vs pool {dh_p}")
    if h % hkv:
        raise ValueError(f"q heads {h} must be a multiple of kv heads {hkv}")
    if k_chunk.shape != (slots, hkv, w, dh):
        raise ValueError(
            f"k_chunk must be {(slots, hkv, w, dh)}, got {k_chunk.shape}"
        )
    if k_pool.shape != v_pool.shape:
        raise ValueError(
            f"pool shape mismatch: {k_pool.shape} vs {v_pool.shape}"
        )
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if k_scale is not None and k_scale.shape != (n, hkv, page):
        raise ValueError(
            f"scales must be {(n, hkv, page)}, got {k_scale.shape}"
        )
    if not _interpret() and page % 128:
        raise ValueError(
            f"page size {page} must be a multiple of 128 on TPU (pages "
            f"are lane-aligned token columns; pick page_size=128)"
        )
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    max_pages = page_table.shape[1]
    if ctx_len is None:
        ctx_len = max_pages * page
    if ctx_len < max_pages * page:
        raise ValueError(
            f"ctx_len {ctx_len} cannot be narrower than the table span "
            f"{max_pages * page}"
        )
    if live_pages is None:
        live_pages = max_pages
    if not 0 <= live_pages <= max_pages:
        raise ValueError(
            f"live_pages {live_pages} must be in [0, {max_pages}]"
        )
    from beholder_tpu.ops import autotune

    if group < 1:
        raise ValueError(f"group must be >= 1, got {group}")
    dtype = pool_dtype_family(k_pool, quantized=k_scale is not None)
    resolved = autotune.resolve_config(
        autotune.shape_key(
            "paged_chunk", slots=slots, width=w, max_pages=max_pages,
            page=page, kv_heads=hkv, head_dim=dh, dtype=dtype,
            group=group,
        ),
        explicit=config,
    )
    sb, pb = autotune.normalize(resolved, slots, max_pages)
    if _interpret() and not FORCE_PALLAS_INTERPRET:
        # non-TPU backends take the portable block-streamed transport
        # (see _chunk_reference); the pallas body stays test-covered
        # through the FORCE_PALLAS_INTERPRET(_DMA) flags
        return _chunk_reference(
            q, k_chunk, v_chunk, k_pool, v_pool,
            page_table.astype(jnp.int32), lens.astype(jnp.int32),
            k_scale, v_scale, ctx_len=int(ctx_len),
            live_pages=int(live_pages), window=window, sb=sb,
        )
    return _chunk_call(
        q, k_chunk, v_chunk, k_pool, v_pool,
        page_table.astype(jnp.int32), lens.astype(jnp.int32), k_scale,
        v_scale, ctx_len=int(ctx_len), live_pages=int(live_pages),
        window=window, sb=sb, pb=pb, interpret=_interpret(),
        dma=not _interpret() or FORCE_PALLAS_INTERPRET_DMA,
    )
