"""The cluster scheduler: the batcher promoted to an admission router.

One :class:`ClusterScheduler` owns N decode shards (each a full
:class:`~beholder_tpu.models.serving.ContinuousBatcher` over its own
per-shard paged pool on its own mesh device) and, optionally, M
dedicated prefill workers (:class:`~beholder_tpu.cluster.transfer.
PrefillWorker`). The caller-facing API is the batcher's own —
``run(requests)`` / ``submit(request)`` + ``run_pending()`` — so the
cluster layer is invisible to callers: same contract, same bitwise
outputs under exact greedy, N× the pool.

Scheduling structure:

- **Routing** (:meth:`_route`): by pool pressure per shard (most free
  worst-case pages; deterministic tie-break) or round-robin. Every
  decision lands on ``beholder_cluster_routes_total{reason}`` and as a
  recorder-only ``route`` phase event.
- **Claiming**: every lane claims (slot, request) pairs through the
  ONE shared ``ContinuousBatcher._claim_admissions`` loop — colocated
  shards via their untouched ``run()``/``run_spec()`` (so prefix-cache
  pins and spec rollback refcounts hold per shard exactly as the
  single-engine tests pin them), the disaggregated loop by calling it
  directly with its own headroom/commit closures before handoff
  admission.
- **Disaggregation** (:meth:`_run_disaggregated`): claimed requests
  prefill on a prefill worker, the KV hands off page-granularly to the
  owning shard (:class:`~beholder_tpu.cluster.transfer.
  PageTransferEngine`), and the decode loop ticks on the shard's own
  pool — long prefills occupy prefill-worker FLOPs, not the decode
  shard's tick cadence. Shards with a prefix cache or spec config
  serve colocated (their scheduler composes those subsystems; the
  handoff path is the plain exact-decode fast lane).
- **Rebalance on horizon** (:meth:`_rebalance`): at drain time —
  i.e. after retirements freed capacity — queued requests that no
  longer fit their shard migrate to the least-pressure shard
  (``reason="rebalance"``), so one hot shard cannot starve while
  another idles.
- **Cluster memory fabric** (:mod:`.fabric`, optional): admission on
  any shard consults the global prefix index and pulls remotely warm
  chains over the transfer engine; between serves the engine keeps a
  dark standby shard mirrored so a worker death promotes it instead
  of replaying prefill.

Instrumentation is host-side only (zero device reads, the serving
discipline): cluster series register only when a registry is wired,
``route``/``transfer``/``prefill`` are recorder-only events (the
round-histogram label set stays exactly the single-engine one), and
per-shard shed attribution rides each shard's uniquely named intake
queue (``beholder_intake_shed_total{queue, reason}``).
"""

from __future__ import annotations

import time

import numpy as np

from . import ROUTE_ROUND_ROBIN, ClusterConfig
from .failover import (
    WORKER_UP,
    FailoverEngine,
    NoHealthyShards,
)
from .pool import ShardedPoolView, ShardPool, place_paged_state
from .transfer import PageTransferEngine, PrefillWorker


class _Shard:
    """One decode shard: pool view + batcher + bounded intake."""

    def __init__(self, pool: ShardPool, batcher, intake):
        self.pool = pool
        self.batcher = batcher
        self.intake = intake
        #: lazily built colocated-fallback prefill worker (failover:
        #: every dedicated prefill worker down ⇒ prefill on the shard)
        self.local_prefill = None


class ClusterScheduler:
    """Cluster-level serving over sharded paged pools.

    ``batcher_kwargs`` are the per-shard
    :class:`~beholder_tpu.models.serving.ContinuousBatcher` knobs
    (``num_pages`` — PER SHARD — ``page_size``, ``slots``,
    ``max_prefix``, ``max_pages_per_seq``, ``cache_dtype``).
    ``prefix_cache_factory`` builds one
    :class:`~beholder_tpu.cache.PrefixCache` PER SHARD (page ids are
    shard-local, so shards cannot share an index); ``spec`` is a
    shared :class:`~beholder_tpu.spec.SpecConfig` (per-shard drafters
    and controllers build lazily inside each batcher)."""

    def __init__(
        self,
        model,
        params,
        cluster: ClusterConfig,
        *,
        metrics=None,
        tracer=None,
        flight_recorder=None,
        prefix_cache_factory=None,
        spec=None,
        control_plane=None,
        **batcher_kwargs,
    ):
        from beholder_tpu.parallel.mesh import serving_shard_devices

        self.cluster = cluster
        self.model = model
        self.params = params
        self.flight_recorder = flight_recorder
        self._metrics = metrics
        self._tracer = tracer
        self._prefix_cache_factory = prefix_cache_factory
        self._spec = spec
        self._batcher_kwargs = dict(batcher_kwargs)
        #: optional SLO-acting control plane
        #: (:class:`beholder_tpu.control.ControlPlane`; None — the
        #: default — keeps routing, intakes and shard count exactly the
        #: pre-control cluster, byte-identically): shard intakes become
        #: tenant-fair DRR queues, routing consults the deadline/tail
        #: policy, spec controllers shed k under burn, and run_pending
        #: boundaries evaluate the autoscaler
        self.control_plane = control_plane
        self._registry = (
            getattr(metrics, "registry", metrics)
            if metrics is not None
            else None
        )
        self.instruments = None
        if self._registry is not None:
            from .instruments import ClusterMetrics

            self.instruments = ClusterMetrics(self._registry)
            self.instruments.shards.set(cluster.n_decode_workers)

        if cluster.group is not None:
            # group-parallel decode: each decode shard owns a
            # CONTIGUOUS block of group.size devices; prefill workers
            # stay single-device, continuing the cycle after the
            # decode blocks
            gsz = cluster.group.size
            decode_devices = serving_shard_devices(
                cluster.n_decode_workers, group_size=gsz
            )
            singles = serving_shard_devices(
                cluster.n_decode_workers * gsz + cluster.n_prefill_workers
            )
            prefill_devices = singles[cluster.n_decode_workers * gsz:]
            #: group blocks handed out so far — scale_up() continues
            #: the BLOCK cycle (prefill singles may co-locate round-
            #: robin with a block, the same accepted co-location rule
            #: as an oversubscribed single-device cluster)
            self._devices_used = cluster.n_decode_workers
        else:
            n_workers = cluster.n_decode_workers + cluster.n_prefill_workers
            devices = serving_shard_devices(n_workers)
            decode_devices = devices[: cluster.n_decode_workers]
            prefill_devices = devices[cluster.n_decode_workers :]
            #: devices handed out so far — scale_up() continues the cycle
            self._devices_used = n_workers

        self.shards: list[_Shard] = []
        for i in range(cluster.n_decode_workers):
            self.shards.append(self._build_shard(i, decode_devices[i]))
        self.pool_view = ShardedPoolView([s.pool for s in self.shards])

        self.prefill_workers: list[PrefillWorker] = [
            PrefillWorker(
                model,
                params,
                batcher_kwargs.get("page_size", 16),
                device=prefill_devices[j],
                name=f"prefill-{j}",
            )
            for j in range(cluster.n_prefill_workers)
        ]
        # the device hop is always retried (transient fabric faults
        # absorb; persistent ones surface as a typed TransferFailed)
        from beholder_tpu.reliability.policy import RetryPolicy

        self.transfer = PageTransferEngine(
            instruments=self.instruments,
            flight_recorder=flight_recorder,
            retry=RetryPolicy(
                max_attempts=3, base_delay_s=0.005, max_delay_s=0.05
            ),
        )
        #: fault tolerance (None = the fail-stop cluster, byte-identical
        #: to pre-failover behavior)
        self.failover = (
            FailoverEngine(
                self, cluster.failover,
                registry=self._registry,
                flight_recorder=flight_recorder,
            )
            if cluster.failover is not None
            else None
        )
        #: cluster memory fabric (None — the default — keeps every
        #: shard's prefix cache private and failover on the replay
        #: path, byte-identically): the global prefix index + the
        #: standby-replica mirror, sharing the transfer engine
        self.fabric = None
        if cluster.fabric is not None:
            from .fabric.engine import FabricEngine

            self.fabric = FabricEngine(
                cluster.fabric, self.transfer,
                flight_recorder=flight_recorder,
            )
            for shard in self.shards:
                self.fabric.attach_shard(shard)
        #: admission-order results decided outside a serve (drain-time
        #: shard_down drops), merged by run_pending
        self._pending_drops: dict[int, object] = {}
        self._rr = 0
        self._pf_rr = 0
        #: monotone submit sequence — the admission-order key
        self._seq = 0
        #: timeline gid epoch: one per _serve_pairs call, so request
        #: gids recur NEVER across calls but stay stable across a
        #: call's recovery passes (a recovered request's re-claim lands
        #: on the same timeline as a new leg)
        self._gid_epoch = 0

    # -- shard construction / scaling ------------------------------------

    def _build_shard(
        self, shard_id: int, device, name: str | None = None
    ) -> _Shard:
        """One decode shard exactly as ``__init__`` builds them — also
        the autoscaler's :meth:`scale_up` path, so a spawned shard is
        indistinguishable from a boot-time one (same batcher knobs,
        same placement, same intake policy). ``name`` overrides the
        ``decode-<id>`` pool name (the fabric's dark standby lives
        outside the decode id space until promotion)."""
        from beholder_tpu.models.serving import ContinuousBatcher
        from beholder_tpu.reliability.shed import IntakeQueue

        shared_kwargs = dict(
            metrics=self._metrics,
            tracer=self._tracer,
            flight_recorder=self.flight_recorder,
            prefix_cache=(
                self._prefix_cache_factory()
                if self._prefix_cache_factory is not None
                else None
            ),
            spec=self._spec,
            **self._batcher_kwargs,
        )
        if isinstance(device, tuple):
            # group-parallel decode shard: the device tuple IS the
            # group; the GroupBatcher places its own state (pools
            # sharded by KV head over the group mesh, params in the
            # megatron tp shardings), so the single-device
            # place_paged_state below must not touch it. The pool's
            # routable device is the group's wire endpoint (member 0).
            from .group.engine import GroupBatcher

            gname = name if name is not None else f"decode-g{shard_id}"
            batcher = GroupBatcher(
                self.model,
                self.params,
                devices=device,
                axis=(
                    self.cluster.group.axis
                    if self.cluster.group is not None
                    else "tp"
                ),
                name=gname,
                **shared_kwargs,
            )
            pool = ShardPool(
                shard_id, batcher.num_pages,
                device=batcher.transfer_device,
            )
            pool.name = gname
        else:
            batcher = ContinuousBatcher(
                self.model, self.params, **shared_kwargs
            )
            # the pool partition IS the placement: this shard's pages,
            # page table and params live on their own mesh device, so
            # every dispatch the shard runs lands there
            batcher.state = place_paged_state(batcher.state, device)
            batcher.params = place_paged_state(batcher.params, device)
            pool = ShardPool(shard_id, batcher.num_pages, device=device)
            if name is not None:
                pool.name = name
        # the router owns the shard intakes: queued items are
        # (submit sequence, request) pairs so run_pending() can
        # hand results back in ADMISSION order across the whole
        # cluster (the batcher's own contract) no matter how
        # routing and rebalance interleaved the shards
        intake_kwargs = dict(
            max_cost=(
                self.cluster.max_pending_pages_per_shard
                if self.cluster.max_pending_pages_per_shard is not None
                else batcher.num_pages
            ),
            cost_fn=lambda item, b=batcher: b._need_pages(item[1]),
            metrics=self._metrics,
            name=f"cluster.{pool.name}",
            labelled_sheds=True,
        )
        if self.control_plane is not None:
            # tenant-fair admission: the shard's intake drains in
            # weighted DRR order and preempts over-share tenants under
            # pressure; preempted items resolve to explicit outcomes
            # in their admission-order result positions
            intake = self.control_plane.intake(
                self.cluster.max_pending_per_shard,
                on_preempt=self._make_on_preempt(pool),
                **intake_kwargs,
            )
            if self._spec is not None:
                self.control_plane.attach_spec(batcher)
        else:
            intake = IntakeQueue(
                self.cluster.max_pending_per_shard, **intake_kwargs
            )
        batcher.intake = intake
        return _Shard(pool, batcher, intake)

    def _make_on_preempt(self, pool):
        """Preemption resolution for one shard's tenant-fair intake:
        release the submit-time page reservation, park an explicit
        :class:`~beholder_tpu.control.admission.Preempted` outcome in
        the request's admission-order result position, and emit the
        ``req.dropped`` lifecycle instant so the SLO layer classifies
        the loss (a preempted request must never read as attainment)."""

        def on_preempt(item, tenant):
            from beholder_tpu.control.admission import Preempted

            seq, request = item
            pool.release(self._need(request))
            self._pending_drops[seq] = Preempted(tenant)
            if self.flight_recorder is not None:
                # tenant rides the instant: a preempted request never
                # claimed, so the SLO fold has no open entry to read
                # the tenant from — without it the victim tenant's burn
                # would stay blind to exactly the loss the control
                # plane inflicted
                tenant_note = (
                    {"tenant": tenant} if tenant is not None else {}
                )
                self.flight_recorder.instant(
                    "req.dropped", gid=f"s{seq}",
                    reason="tenant_preempted", **tenant_note,
                )

        return on_preempt

    def scale_up(self) -> _Shard:
        """Spawn one decode shard (the autoscaler's scale-UP actuator;
        also callable directly for manual capacity adds): a fresh pool
        + batcher on the next mesh device in the cycle, routable
        immediately. The inverse is :meth:`drain` — PR 8's
        byte-identical migration — so capacity changes in either
        direction lose nothing."""
        from beholder_tpu.parallel.mesh import serving_shard_devices

        if self.cluster.group is not None:
            # the block cycle: a spawned group shard claims the next
            # CONTIGUOUS device block, same shape as boot-time groups
            device = serving_shard_devices(
                self._devices_used + 1,
                group_size=self.cluster.group.size,
            )[-1]
        else:
            device = serving_shard_devices(self._devices_used + 1)[-1]
        self._devices_used += 1
        shard = self._build_shard(len(self.shards), device)
        self.shards.append(shard)
        self.pool_view.shards.append(shard.pool)
        if self.failover is not None:
            from .failover import WORKER_UP

            self.failover._set_state(shard.pool.name, WORKER_UP)
        if self.fabric is not None:
            self.fabric.attach_shard(shard)
        if self.instruments is not None:
            self.instruments.shards.set(
                sum(
                    1 for s in self.shards
                    if self.failover is None
                    or self.failover.state(s.pool.name)
                    not in ("down", "drained")
                )
            )
        self.pool_view.refresh_gauges(self.instruments)
        return shard

    # -- introspection ---------------------------------------------------

    @property
    def total_pages(self) -> int:
        return self.pool_view.total_pages

    @property
    def disaggregated(self) -> bool:
        return bool(self.prefill_workers)

    def health_snapshot(self) -> dict:
        """Per-worker health for the ``/healthz`` ``cluster`` check:
        every decode shard's state (up/draining/down) + pool pressure,
        every prefill worker's state, and the down/draining rollups.
        Without failover every worker reports up (the fail-stop
        cluster has no other answer)."""
        fo = self.failover
        workers: dict[str, dict] = {}
        for shard in self.shards:
            workers[shard.pool.name] = {
                "state": fo.state(shard.pool.name) if fo else WORKER_UP,
                "free_pages": shard.pool.free,
                "committed_pages": shard.pool.committed,
            }
        for worker in self.prefill_workers:
            workers[worker.name] = {
                "state": fo.state(worker.name) if fo else WORKER_UP,
            }
        return {
            "workers": workers,
            # only FAILED workers roll up into "down" (the health
            # check's degradation trigger); a drained shard completed
            # a planned decommission — reported, never sick
            "down": sorted(
                n for n, w in workers.items() if w["state"] == "down"
            ),
            "draining": sorted(
                n for n, w in workers.items() if w["state"] == "draining"
            ),
            "drained": sorted(
                n for n, w in workers.items() if w["state"] == "drained"
            ),
        }

    def drain(self, shard_id: int) -> dict:
        """Gracefully decommission decode shard ``shard_id`` (requires
        failover): queued work migrates to surviving intakes, resident
        pool state — live slots and warm prefix-cache pages — moves
        byte-identically through the transfer engine, and the shard
        leaves the cluster with zero loss. See
        :meth:`~beholder_tpu.cluster.failover.FailoverEngine.drain`."""
        if self.failover is None:
            raise RuntimeError(
                "drain requires instance.cluster.failover — the "
                "fail-stop cluster has no migration machinery"
            )
        name = self.shards[shard_id].pool.name
        result = self.failover.drain(shard_id)
        if self.fabric is not None:
            # cross-shard pins against the drained pool repoint to the
            # migration target (the chains and their live_users marks
            # moved byte-identically); the drained shard leaves the
            # directory
            self.fabric.on_drain(name, result["target"])
        return result

    def shutdown(self, drain: bool = True) -> None:
        """Planned full-cluster shutdown (the SIGTERM path when
        ``failover.drain_on_sigterm``): every shard stops admitting
        FIRST (``draining`` — a submit racing the shutdown sheds
        ``shard_down`` instead of being silently lost at exit), then
        queued work is served to completion, so a decommission loses
        nothing. ``drain=False`` skips the final serve (fast
        shutdown)."""
        fo = self.failover
        if fo is not None:
            from .failover import WORKER_DRAINING

            for shard in self.shards:
                if fo.state(shard.pool.name) == WORKER_UP:
                    fo._set_state(shard.pool.name, WORKER_DRAINING)
        if drain and any(s.intake.depth for s in self.shards):
            if fo is not None:
                # draining shards still SERVE during the final drain —
                # they only stopped admitting
                fo._drain_serving = True
                try:
                    self.run_pending()
                finally:
                    fo._drain_serving = False
            else:
                self.run_pending()

    # -- routing ---------------------------------------------------------

    def _need(self, request) -> int:
        # shards share geometry, so any batcher's arithmetic serves
        return self.shards[0].batcher._need_pages(request)

    @staticmethod
    def _fits(shard: _Shard, need: int) -> bool:
        """Whether a worst-case ``need`` can EVER run on this shard
        (pool bound + per-seq table cap — the submit rule)."""
        return (
            need <= shard.batcher.num_pages
            and need <= shard.batcher.max_pages_per_seq
        )

    def _routable(self) -> list[_Shard]:
        """Shards admissions may route to: all of them fail-stop, the
        UP subset under failover (down/draining shards leave the set)."""
        if self.failover is None:
            return self.shards
        routable = self.failover.routable_shards()
        if not routable:
            raise NoHealthyShards(
                "every decode shard is down — nothing can serve"
            )
        return routable

    def _record_route(self, shard: _Shard, reason: str, need: int,
                      dur_s: float, ts_s: float) -> None:
        if self.instruments is not None:
            self.instruments.routes_total.inc(reason=reason)
        if self.flight_recorder is not None:
            self.flight_recorder.record(
                "route", ts_s, dur_s,
                worker=shard.pool.name, reason=reason, need=int(need),
            )

    def _route(self, need: int, request=None) -> _Shard:
        """Pick the shard for one request of worst-case ``need`` pages
        and record the decision (counter + recorder-only event). Under
        failover only UP shards are candidates — a down/draining shard
        is invisible to routing. With a control plane whose routing
        actuator is armed, placement consults the deadline-slack +
        tail-avoidance policy (:meth:`beholder_tpu.control.policy.
        ControlPlane.route_shard`) — decisions it overrides land on
        ``beholder_cluster_routes_total{reason}`` as
        ``control_tail_avoid``/``control_deadline``; without it (or
        when the policy agrees with plain pressure) routing is
        byte-identical to the pre-control cluster."""
        ts = time.time()
        t0 = time.perf_counter()
        candidates = self._routable()
        controlled = None
        if self.control_plane is not None and len(candidates) > 1:
            controlled = self.control_plane.route_shard(
                candidates, need, request
            )
            if (
                controlled is not None
                and controlled[1] == "pressure"
                and self.cluster.route_policy == ROUTE_ROUND_ROBIN
            ):
                # the control policy had nothing to override (no tail
                # inflation, no urgent deadline): a round-robin cluster
                # keeps round-robining — control must not silently
                # replace the configured default policy
                controlled = None
        if controlled is not None:
            shard, control_reason = controlled
            reason = (
                "pressure"
                if control_reason == "pressure"
                else f"control_{control_reason}"
            )
        elif len(candidates) == 1:
            shard, reason = candidates[0], "only_shard"
        elif self.cluster.route_policy == ROUTE_ROUND_ROBIN:
            shard = candidates[self._rr % len(candidates)]
            self._rr += 1
            reason = "round_robin"
        else:
            target = self.pool_view.least_pressure(
                [s.pool for s in candidates]
            )
            shard = self.shards[target.shard_id]
            reason = "pressure"
        self._record_route(
            shard, reason, need, time.perf_counter() - t0, ts
        )
        return shard

    def _next_prefill_worker(self) -> PrefillWorker:
        worker = self.prefill_workers[
            self._pf_rr % len(self.prefill_workers)
        ]
        self._pf_rr += 1
        return worker

    def _prefill_with_failover(self, shard: _Shard, feats_np, t: int):
        """One request's prefill on a healthy prefill worker, failing
        over: a typed worker death marks the worker down and the next
        survivor takes the request; with every dedicated worker down
        the shard prefills COLOCATED on its own device (a lazily built
        local fallback). Failover degrades PLACEMENT, never
        correctness — the chunks are bitwise the same wherever the
        forward ran. Returns ``(worker, (pred, ck, cv, n_pages))``."""
        from .failover import WorkerKilled

        fo = self.failover
        if fo is None:
            worker = self._next_prefill_worker()
            return worker, worker.prefill(feats_np, t)
        while True:
            candidates = fo.up_prefill_workers()
            if not candidates:
                break
            worker = candidates[self._pf_rr % len(candidates)]
            self._pf_rr += 1
            try:
                out = worker.prefill(feats_np, t)
            except WorkerKilled as err:
                fo.mark_down(worker.name, err.kind)
                continue
            fo.heartbeat(worker.name)
            return worker, out
        if shard.local_prefill is None:
            shard.local_prefill = PrefillWorker(
                self.model,
                shard.batcher.params,
                shard.batcher.page_size,
                device=shard.pool.device,
                name=shard.pool.name,
            )
        return shard.local_prefill, shard.local_prefill.prefill(
            feats_np, t
        )

    # -- the batcher-shaped API ------------------------------------------

    def run(self, requests: list) -> list[np.ndarray]:
        """Serve ``requests`` across the cluster; results are the same
        per-request forecast delta arrays the single-device engine
        returns, in the SAME order — routing is invisible to callers.
        Under exact greedy the streams are bitwise-identical to one
        :meth:`~beholder_tpu.models.serving.ContinuousBatcher.run` over
        the same stream (pinned by ``tests/test_cluster.py``) — and,
        with failover armed, that identity survives a shard dying
        mid-stream (pinned by ``tests/test_cluster_chaos.py``)."""
        out = self._serve_pairs(list(enumerate(requests)))
        return [out[gid] for gid in range(len(requests))]

    def _serve_pairs(self, pairs: list, waits: dict | None = None) -> dict:
        """Route + serve ``(key, request)`` pairs; returns
        ``{key: result}``. Fail-stop (no failover) this is one pass —
        route everything, serve shard by shard, exceptions propagate —
        byte-identical to the pre-failover router. With failover armed
        it is the RECOVERY loop: a typed worker failure
        (:data:`~beholder_tpu.cluster.failover.FailoverEngine.
        RECOVERABLE`) marks the shard down and its whole batch
        re-routes to surviving shards on the next pass, where the
        deterministic exact-greedy replay re-prefills from host-side
        request state (observed history; surviving shards' prefix
        caches serve warm hits) and :meth:`FailoverEngine.splice`
        joins it onto anything an incremental embedder already
        delivered (``record_emitted``) — no token index emitted twice
        or skipped; the synchronous whole-stream case splices an
        empty prefix. A
        request recovered more than ``max_recoveries_per_request``
        times, or one no surviving shard can ever hold, resolves to an
        explicit :class:`~beholder_tpu.cluster.failover.Dropped`
        outcome (``recovery_limit`` / ``shard_down``)."""
        from beholder_tpu.reliability.shed import SHED_SHARD_DOWN

        fo = self.failover
        out: dict = {}
        pending = list(pairs)
        attempts: dict = {}
        pass_index = 0
        self._gid_epoch += 1
        gid_of = (
            {key: f"g{self._gid_epoch}-{key}" for key, _ in pairs}
            if self.flight_recorder is not None
            else {}
        )
        while pending:
            if fo is not None:
                fo.sweep()
            t_pass = time.perf_counter()
            assignments: dict[int, list] = {
                s.pool.shard_id: [] for s in self.shards
            }
            for key, req in pending:
                need = self._need(req)
                if fo is not None:
                    routable = fo.routable_shards()
                    if (
                        not routable
                        or not any(self._fits(s, need) for s in routable)
                    ) and any(self._fits(s, need) for s in self.shards):
                        # servable on the full cluster, not on what's
                        # left (or nothing is left): explicit outcome.
                        # A request NO shard could ever hold falls
                        # through to the batcher's own oversized error
                        # — that is a caller bug, not a shard failure
                        out[key] = fo.drop(
                            SHED_SHARD_DOWN, key=gid_of.get(key)
                        )
                        continue
                shard = self._route(need, request=req)
                shard.pool.reserve(need)
                assignments[shard.pool.shard_id].append((key, req, need))
            pending = []
            self.pool_view.refresh_gauges(self.instruments)
            for shard in self.shards:
                # .get, not []: a standby promoted mid-pass (fabric
                # failover) appends to self.shards DURING this loop —
                # it has no assignment yet and serves next pass
                items = assignments.get(shard.pool.shard_id)
                if not items:
                    continue
                if fo is not None:
                    fo.begin_serve(shard.pool.name)
                if self.flight_recorder is not None:
                    # request-level timeline identity: the gid keys
                    # this request's claim/retire instants across
                    # shards AND recovery passes; the intake wait (when
                    # this drain came through run_pending) rides along
                    shard.batcher.annotate_requests({
                        rid: {
                            "gid": gid_of[key],
                            "worker": shard.pool.name,
                            **(
                                {"queue_wait_s": round(waits[key], 6)}
                                if waits and key in waits
                                else {}
                            ),
                        }
                        for rid, (key, _, _) in enumerate(items)
                    })
                try:
                    served = self._serve(
                        shard, [req for _, req, _ in items]
                    )
                except Exception as err:
                    if fo is None or not isinstance(
                        err, fo.RECOVERABLE
                    ):
                        raise
                    # the shard is gone: release its reservations, mark
                    # it down, and re-admit the batch on survivors
                    for _, _, need in items:
                        shard.pool.release(need)
                    kind = fo.on_shard_failure(shard, err)
                    if self.fabric is not None:
                        # release the dead worker's cross-shard pins,
                        # drop its directory facts, and — when a
                        # standby is mirroring — promote it in place
                        # of the replay path
                        self.fabric.on_worker_down(self, shard.pool.name)
                    retried = 0
                    for key, req, _ in items:
                        attempts[key] = attempts.get(key, 0) + 1
                        if (
                            attempts[key]
                            > fo.config.max_recoveries_per_request
                        ):
                            out[key] = fo.drop(
                                "recovery_limit", key=gid_of.get(key)
                            )
                        else:
                            pending.append((key, req))
                            retried += 1
                            if self.flight_recorder is not None:
                                # per-request recovery marker: the
                                # timeline layer attributes the
                                # recovery leg to the request that
                                # paid it (obs/timeline.py)
                                self.flight_recorder.instant(
                                    "req.recovered",
                                    gid=gid_of[key],
                                    worker=shard.pool.name,
                                    reason=kind,
                                )
                    fo.count_recovered(shard.pool.name, kind, retried)
                    continue
                finally:
                    if fo is not None:
                        fo.end_serve(shard.pool.name)
                # reservations come off FIRST: the serve is done, so
                # they are spent regardless of how splicing goes (a
                # splice refusal must not strand committed pages)
                for _, _, need in items:
                    shard.pool.release(need)
                if self.fabric is not None:
                    # the serve retired its slots: release this
                    # borrower's cross-shard pins, drop transient
                    # borrows that never reached the replication
                    # threshold
                    self.fabric.finish_serve(shard)
                for (key, _, _), res in zip(items, served):
                    if fo is not None and isinstance(res, np.ndarray):
                        res = fo.splice(key, res)
                    out[key] = res
                if self.instruments is not None:
                    self.instruments.requests_total.inc(
                        len(items), shard=str(shard.pool.shard_id)
                    )
            if fo is not None and pass_index > 0:
                fo.recovery_walls.append(time.perf_counter() - t_pass)
            pass_index += 1
        if fo is not None:
            # ledger hygiene: keys recur across run() calls, so
            # entries for terminal outcomes (splice already consumed
            # the rest) must not survive into the next call
            fo.discard_emitted(list(out))
        if self.fabric is not None:
            # fabric housekeeping between serves: spawn the standby on
            # first use and keep its mirror fresh against settled pools
            self.fabric.sync(self)
        self.pool_view.refresh_gauges(self.instruments)
        return out

    def submit(self, request):
        """Offer one request to the cluster: route, then the owning
        shard's bounded intake decides — an explicit
        :class:`~beholder_tpu.reliability.shed.Admission`, with sheds
        attributed to the shard's queue
        (``beholder_intake_shed_total{queue, reason}``). With failover
        armed, routing sees only UP shards; a request the full cluster
        could hold but the survivors cannot sheds ``shard_down``."""
        from beholder_tpu.reliability.shed import (
            SHED_OVERSIZED,
            SHED_SHARD_DOWN,
        )

        fo = self.failover
        need = self._need(request)
        if fo is not None:
            fo.sweep()
            if not any(self._fits(s, need) for s in fo.routable_shards()):
                reason = (
                    SHED_SHARD_DOWN
                    if any(self._fits(s, need) for s in self.shards)
                    else SHED_OVERSIZED
                )
                return fo.shed(reason)
        shard = self._route(need, request=request)
        batcher = shard.batcher
        if need > batcher.num_pages or need > batcher.max_pages_per_seq:
            # unservable at ANY load (the batcher's own submit rule)
            return shard.intake.shed(SHED_OVERSIZED)
        admission = shard.intake.offer((self._seq, request), cost=need)
        if admission.accepted:
            self._seq += 1
            shard.pool.reserve(need)
            self.pool_view.refresh_gauges(self.instruments)
        return admission

    def run_pending(self) -> list[np.ndarray]:
        """Rebalance queued work across shards (capacity freed by
        retirements since the last drain makes moves possible — the
        'rebalance on horizon' step), then drain and serve every
        shard. Results come back in ADMISSION order across the whole
        cluster — the single-engine ``run_pending`` contract; routing
        and rebalance stay invisible to callers.

        With failover armed the drain re-routes everything through the
        recovery-aware loop instead (a queued item's submit-time shard
        may have died since): queued work on a down shard migrates to
        survivors, failures mid-serve recover, and items nothing can
        hold (plus drain-time ``shard_down`` drops) resolve to
        explicit :class:`~beholder_tpu.cluster.failover.Dropped`
        outcomes in their admission-order positions. Preempted items
        (tenant-fair intakes under a control plane) resolve the same
        way — an explicit :class:`~beholder_tpu.control.admission.
        Preempted` in the request's position, either mode."""
        if self.control_plane is not None:
            # the autoscaler decision point: BETWEEN serves, never mid-
            # flight (scale-down is a drain — it must see settled pools)
            self.control_plane.evaluate_scaling(self)
        if self.failover is not None:
            return self._run_pending_failover()
        self._rebalance()
        drops, self._pending_drops = self._pending_drops, {}
        collected: list[tuple[int, np.ndarray]] = []
        for shard in self.shards:
            pending, drain_waits, _ = shard.intake.drain_all()
            if not pending:
                continue
            requests = [req for _, req in pending]
            if self.flight_recorder is not None:
                shard.batcher.annotate_requests({
                    rid: {
                        "gid": f"s{seq}",
                        "worker": shard.pool.name,
                        **(
                            {"queue_wait_s": round(drain_waits[rid], 6)}
                            if rid < len(drain_waits)
                            else {}
                        ),
                    }
                    for rid, (seq, _) in enumerate(pending)
                })
            served = self._serve(shard, requests)
            for req in requests:
                shard.pool.release(self._need(req))
            if self.fabric is not None:
                self.fabric.finish_serve(shard)
            collected.extend(
                zip((seq for seq, _ in pending), served)
            )
            if self.instruments is not None:
                self.instruments.requests_total.inc(
                    len(pending), shard=str(shard.pool.shard_id)
                )
        if self.fabric is not None:
            self.fabric.sync(self)
        self.pool_view.refresh_gauges(self.instruments)
        collected.extend(drops.items())
        collected.sort(key=lambda pair: pair[0])
        return [result for _, result in collected]

    def _run_pending_failover(self) -> list:
        """The failover drain: pull every shard's queue (down shards'
        included — their queued work must not die with them), release
        the submit-time reservations, and push everything through the
        recovery-aware ``_serve_pairs`` in admission order."""
        self.failover.sweep()
        pairs: list[tuple[int, object]] = []
        waits: dict[int, float] = {}
        for shard in self.shards:
            pending, drain_waits, _ = shard.intake.drain_all()
            for (seq, req), wait in zip(pending, drain_waits):
                shard.pool.release(self._need(req))
                pairs.append((seq, req))
                waits[seq] = wait
        drops, self._pending_drops = self._pending_drops, {}
        pairs.sort(key=lambda pair: pair[0])
        out = self._serve_pairs(pairs, waits=waits)
        out.update(drops)
        return [out[seq] for seq in sorted(out)]

    def _serve(self, shard: _Shard, requests: list) -> list[np.ndarray]:
        batcher = shard.batcher
        if (
            self.prefill_workers
            and batcher.prefix_cache is None
            and batcher.spec is None
        ):
            return self._run_disaggregated(shard, requests)
        if batcher.spec is not None:
            return batcher.run_spec(requests)
        return batcher.run(requests)

    # -- rebalance -------------------------------------------------------

    def _rebalance(self) -> None:
        """Re-pack queued requests across shards: a queued request
        whose shard can no longer hold its worst case (pages freed
        elsewhere, arrivals skewed) migrates to the least-pressure
        shard that fits it. Items move via
        :meth:`~beholder_tpu.reliability.shed.IntakeQueue.restock` —
        they were admitted once; rebalancing must not re-count (or
        re-shed) them."""
        if len(self.shards) < 2:
            return
        drained: dict[int, list] = {}
        stamps: dict[int, list[float]] = {}
        for s in self.shards:
            # a re-pack, not a claim: waits stay OFF the histogram;
            # the (items, stamps) pair is read atomically
            (
                drained[s.pool.shard_id],
                _,
                stamps[s.pool.shard_id],
            ) = s.intake.drain_all(record_waits=False)
        if not any(drained.values()):
            return
        # queued commitments come off while we re-pack (in-flight ones,
        # if any, stay reserved)
        needs: dict[int, list[int]] = {}
        for shard in self.shards:
            needs[shard.pool.shard_id] = [
                self._need(req) for _, req in drained[shard.pool.shard_id]
            ]
            shard.pool.release(sum(needs[shard.pool.shard_id]))
        # items re-pack with their ORIGINAL enqueue stamps riding along:
        # a rebalance must not zero the queue wait the SLO timeline
        # measures at claim
        final: dict[int, list] = {s.pool.shard_id: [] for s in self.shards}
        final_stamps: dict[int, list[float]] = {
            s.pool.shard_id: [] for s in self.shards
        }
        for shard in self.shards:
            sid = shard.pool.shard_id
            for (item, stamp), need in zip(
                zip(drained[sid], stamps[sid]), needs[sid]
            ):
                target = shard
                if shard.pool.free < need:
                    best = self.pool_view.least_pressure()
                    if best.shard_id != sid and best.free >= need:
                        target = self.shards[best.shard_id]
                        ts = time.time()
                        self._record_route(
                            target, "rebalance", need, 0.0, ts
                        )
                final[target.pool.shard_id].append(item)
                final_stamps[target.pool.shard_id].append(stamp)
                target.pool.reserve(need)
        for shard in self.shards:
            shard.intake.restock(
                final[shard.pool.shard_id],
                enqueued_at=final_stamps[shard.pool.shard_id],
            )
        self.pool_view.refresh_gauges(self.instruments)

    # -- the disaggregated serving loop ----------------------------------

    def _run_disaggregated(
        self, shard: _Shard, requests: list
    ) -> list[np.ndarray]:
        """Prefill-on-worker, decode-on-shard serving: the per-event
        scheduler's loop (claim under page headroom -> admit -> tick
        the event-free stretch -> retire -> one packed readback) with
        admission replaced by the handoff pipeline (prefill ->
        transfer -> adopt). Bitwise contract: a slot's stream depends
        only on its own pages and carry seed, and the handoff writes
        both exactly as a colocated admit would."""
        b = shard.batcher
        b._start_run(requests)
        t0 = time.perf_counter()
        try:
            with b._run_span(
                "serving.run_cluster",
                requests=len(requests),
                shard=shard.pool.name,
            ) as span:
                results = self._disagg_loop(shard, requests, span)
        except BaseException:
            b._poisoned = True
            raise
        if b._metrics:
            b._metrics.observe_run(
                "run_cluster",
                time.perf_counter() - t0,
                sum(max(r.horizon, 0) for r in requests),
                trace_id=b._span_trace_id(span),
            )
        return results

    def _disagg_loop(self, shard: _Shard, requests, span):
        import jax
        import jax.numpy as jnp

        from beholder_tpu.models.serving import (
            DeadlineExceededResult,
            _adopt_chunks_carry,
            _RunCarry,
        )
        from beholder_tpu.ops import NUM_STATUSES

        b = shard.batcher
        fr = self.flight_recorder
        queue = list(enumerate(requests))
        results: list = [None] * len(requests)
        cap = max(1, max((r.horizon for r in requests), default=1) - 1)
        carry = _RunCarry(
            jnp.zeros((b.slots,), jnp.float32),
            jnp.zeros((b.slots, NUM_STATUSES), jnp.float32),
            jnp.zeros((b.slots, cap), jnp.float32),
        )
        req_of = [None] * b.slots
        remaining = np.zeros(b.slots, np.int64)
        total_need = np.zeros(b.slots, np.int64)
        written = np.zeros(b.slots, np.int64)
        snap_batches: list = []
        served = [0, 0]

        def free_pages() -> int:
            return b.num_pages - int(total_need.sum())

        deadline_rids: list[int] = []
        has_deadlines = any(
            getattr(r, "deadline", None) is not None for r in requests
        )

        # retire_many and the packed readback below deliberately mirror
        # _run()'s — folding all three serving loops into one composable
        # step pipeline is ROADMAP open item 2; until then a change to
        # _run's snapshot/readback packing must be mirrored here (the
        # bitwise-identity test fails loudly if they drift)
        def retire_many(done: list[int], expired: bool = False):
            with b._round(span, "retire", slots=len(done)):
                idx = jnp.asarray(done, jnp.int32)
                rids = [req_of[s] for s in done]
                widths = [int(written[s]) for s in done]
                snap_batches.append((
                    rids,
                    carry.delta_buf[idx],
                    carry.last_pred[idx],
                    widths,
                ))
                b.state = b._release_many(b.state, idx)
                for s in done:
                    req_of[s] = None
                    total_need[s] = 0
                    written[s] = 0
                served[0] += len(done)
                if expired:
                    served[1] += sum(w + 1 for w in widths)
                    deadline_rids.extend(rids)
                    b._count_deadline_exceeded(len(done))
                    if fr is not None:
                        fr.instant(
                            "deadline_exceeded", stage="tick",
                            worker=shard.pool.name, slots=len(done),
                        )
                else:
                    served[1] += sum(requests[r].horizon for r in rids)
                outcome = "deadline_exceeded" if expired else "ok"
                for s, rid, w in zip(done, rids, widths):
                    b._emit_req_retire(
                        rid, s, w + 1, outcome, worker=shard.pool.name
                    )

        while queue or any(r is not None for r in req_of):
            if self.failover is not None:
                self.failover.heartbeat(shard.pool.name)
            if has_deadlines:
                # the deadline sweep at the scheduling-event boundary
                # (mirrors _run — an expired request must not wedge a
                # slot through a recovery storm)
                lapsed = [
                    s for s in range(b.slots)
                    if req_of[s] is not None
                    and b._deadline_expired(requests[req_of[s]])
                ]
                if lapsed:
                    retire_many(lapsed, expired=True)
            # claim round: ONE copy of the hardening invariants
            # (headroom arithmetic, pressure deferral + stall marker,
            # exhaustion fail-fast, recorder-only claim event) — the
            # batcher's own shared claim loop; its prefix-cache branch
            # is inert here (the disagg lane is guarded to
            # prefix_cache=None — warm traffic serves colocated)
            def commit(slot, rid, req, need):
                remaining[slot] = req.horizon
                total_need[slot] = need
                written[slot] = 0

            batch = b._claim_admissions(
                queue, results, req_of, free_pages, commit
            )

            for slot, rid, feats_np, t, _hit, _hashes in batch:
                # prefill on a dedicated worker (recorder-only event,
                # flash-family kernel tags — the prefill FLOPs moved
                # OFF this shard is exactly what the timeline shows);
                # under failover a dead worker's request fails over to
                # the next survivor (or the shard's colocated fallback)
                pf_ts = time.time() if fr is not None else 0.0
                pf_t0 = time.perf_counter()
                worker, (pred, chunks_k, chunks_v, n_pages) = (
                    self._prefill_with_failover(shard, feats_np, t)
                )
                if fr is not None:
                    fr.record(
                        "prefill", pf_ts,
                        time.perf_counter() - pf_t0,
                        worker=worker.name, slot=slot, tokens=int(t),
                        **b._kernel_tags(
                            "flash", t * b._flops_per_token(t / 2.0)
                        ),
                    )
                # page-granular handoff to the owning shard
                pred, chunks_k, chunks_v = self.transfer.handoff(
                    pred, chunks_k, chunks_v, n_pages,
                    shard.pool.device, src=worker.name,
                    dst=shard.pool.name,
                )
                # adopt into the shard pool + seed the decode carry
                # (the existing admit phase label — no new histogram
                # labels; the handoff-specific slices are above; the
                # slot tag lets the timeline layer pin THIS request's
                # first-token round instead of splitting it)
                with b._round(span, "admit", requests=1, slot=slot):
                    p_max = chunks_k[0].shape[0]
                    adopt = b._cached_jit(
                        ("cluster_adopt", p_max),
                        lambda: lambda s, c, sl, ck, cv, npg, ln, pr, st: (
                            _adopt_chunks_carry(
                                s, c, sl, ck, cv, npg, ln, pr, st
                            )
                        ),
                    )
                    b.state, carry = adopt(
                        b.state, carry, jnp.int32(slot),
                        chunks_k, chunks_v, jnp.int32(n_pages),
                        jnp.int32(t), pred,
                        jnp.int32(int(requests[rid].statuses[-1])),
                    )
            done = [x[0] for x in batch if remaining[x[0]] == 1]
            if done:
                retire_many(done)
            if b._metrics:
                b._metrics.slots_active.set(
                    sum(r is not None for r in req_of)
                )
                free_now = free_pages()
                b._metrics.pool_pages_free.set(free_now)
                b._metrics.pool_pressure_from(
                    free_now, req_of, requests, total_need,
                    b.max_pages_per_seq,
                )
            if not any(r is not None for r in req_of):
                continue

            active = [r is not None for r in req_of]
            n_chunk = max(
                1, int(min(remaining[s] for s in range(b.slots)
                           if active[s])) - 1
            )
            write_idx = np.where(active, written, cap).astype(np.int32)
            tick_tags = {"ticks": n_chunk, "worker": shard.pool.name}
            if fr is not None:
                lens = [
                    len(requests[req_of[s]].progress) - 1
                    + int(written[s])
                    for s in range(b.slots)
                    if active[s]
                ]
                tick_tags.update(b._kernel_tags(
                    "paged",
                    n_chunk * len(lens)
                    * b._flops_per_token(float(np.mean(lens))),
                ))
            with b._round(span, "tick", **tick_tags):
                b.state, carry = b._tick_chunk(
                    b.params, b.state, carry,
                    jnp.asarray(write_idx), jnp.int32(n_chunk),
                )
            done = []
            for slot in range(b.slots):
                if req_of[slot] is None:
                    continue
                written[slot] += n_chunk
                remaining[slot] -= n_chunk
                if remaining[slot] <= 1:
                    done.append(slot)
            if done:
                retire_many(done)
                if b._metrics:
                    b._metrics.slots_active.set(
                        sum(r is not None for r in req_of)
                    )
                    free_now = free_pages()
                    b._metrics.pool_pages_free.set(free_now)
                    b._metrics.pool_pressure_from(
                        free_now, req_of, requests, total_need,
                        b.max_pages_per_seq,
                    )

        # ONE packed readback, exactly the single-engine discipline
        if snap_batches:
            with b._round(span, "readback", batches=len(snap_batches)):
                rows = jnp.concatenate([x[1] for x in snap_batches])
                tails = jnp.concatenate([x[2] for x in snap_batches])
                packed = jnp.concatenate(
                    [
                        b.state.alloc_failed.astype(jnp.float32)[None],
                        tails.astype(jnp.float32),
                        rows.reshape(-1),
                    ]
                )
                got = np.asarray(jax.device_get(packed), np.float32)
            if got[0]:
                raise RuntimeError(b._ALLOCATOR_TRIPPED)
            rids = [rid for x in snap_batches for rid in x[0]]
            widths = [w for x in snap_batches for w in x[3]]
            r = len(rids)
            tails_v = got[1 : 1 + r]
            rows_v = got[1 + r :].reshape(r, cap)
            for i, (rid, w) in enumerate(zip(rids, widths)):
                results[rid] = np.append(rows_v[i, :w], tails_v[i])
            for rid in deadline_rids:
                results[rid] = DeadlineExceededResult(results[rid])
        elif bool(jax.device_get(b.state.alloc_failed)):
            raise RuntimeError(b._ALLOCATOR_TRIPPED)
        if b._metrics:
            b._metrics.served(*served)
        return results
