"""The cluster scheduler: the batcher promoted to an admission router.

One :class:`ClusterScheduler` owns N decode shards (each a full
:class:`~beholder_tpu.models.serving.ContinuousBatcher` over its own
per-shard paged pool on its own mesh device) and, optionally, M
dedicated prefill workers (:class:`~beholder_tpu.cluster.transfer.
PrefillWorker`). The caller-facing API is the batcher's own —
``run(requests)`` / ``submit(request)`` + ``run_pending()`` — so the
cluster layer is invisible to callers: same contract, same bitwise
outputs under exact greedy, N× the pool.

Scheduling structure:

- **Routing** (:meth:`_route`): by pool pressure per shard (most free
  worst-case pages; deterministic tie-break) or round-robin. Every
  decision lands on ``beholder_cluster_routes_total{reason}`` and as a
  recorder-only ``route`` phase event.
- **Claiming**: every lane claims (slot, request) pairs through the
  ONE shared ``ContinuousBatcher._claim_admissions`` loop — colocated
  shards via their untouched ``run()``/``run_spec()`` (so prefix-cache
  pins and spec rollback refcounts hold per shard exactly as the
  single-engine tests pin them), the disaggregated loop by calling it
  directly with its own headroom/commit closures before handoff
  admission.
- **Disaggregation** (:meth:`_run_disaggregated`): claimed requests
  prefill on a prefill worker, the KV hands off page-granularly to the
  owning shard (:class:`~beholder_tpu.cluster.transfer.
  PageTransferEngine`), and the decode loop ticks on the shard's own
  pool — long prefills occupy prefill-worker FLOPs, not the decode
  shard's tick cadence. Shards with a prefix cache or spec config
  serve colocated (their scheduler composes those subsystems; the
  handoff path is the plain exact-decode fast lane).
- **Rebalance on horizon** (:meth:`_rebalance`): at drain time —
  i.e. after retirements freed capacity — queued requests that no
  longer fit their shard migrate to the least-pressure shard
  (``reason="rebalance"``), so one hot shard cannot starve while
  another idles.

Instrumentation is host-side only (zero device reads, the serving
discipline): cluster series register only when a registry is wired,
``route``/``transfer``/``prefill`` are recorder-only events (the
round-histogram label set stays exactly the single-engine one), and
per-shard shed attribution rides each shard's uniquely named intake
queue (``beholder_intake_shed_total{queue, reason}``).
"""

from __future__ import annotations

import time

import numpy as np

from . import ROUTE_ROUND_ROBIN, ClusterConfig
from .pool import ShardedPoolView, ShardPool, place_paged_state
from .transfer import PageTransferEngine, PrefillWorker


class _Shard:
    """One decode shard: pool view + batcher + bounded intake."""

    def __init__(self, pool: ShardPool, batcher, intake):
        self.pool = pool
        self.batcher = batcher
        self.intake = intake


class ClusterScheduler:
    """Cluster-level serving over sharded paged pools.

    ``batcher_kwargs`` are the per-shard
    :class:`~beholder_tpu.models.serving.ContinuousBatcher` knobs
    (``num_pages`` — PER SHARD — ``page_size``, ``slots``,
    ``max_prefix``, ``max_pages_per_seq``, ``cache_dtype``).
    ``prefix_cache_factory`` builds one
    :class:`~beholder_tpu.cache.PrefixCache` PER SHARD (page ids are
    shard-local, so shards cannot share an index); ``spec`` is a
    shared :class:`~beholder_tpu.spec.SpecConfig` (per-shard drafters
    and controllers build lazily inside each batcher)."""

    def __init__(
        self,
        model,
        params,
        cluster: ClusterConfig,
        *,
        metrics=None,
        tracer=None,
        flight_recorder=None,
        prefix_cache_factory=None,
        spec=None,
        **batcher_kwargs,
    ):
        from beholder_tpu.models.serving import ContinuousBatcher
        from beholder_tpu.parallel.mesh import serving_shard_devices
        from beholder_tpu.reliability.shed import IntakeQueue

        self.cluster = cluster
        self.model = model
        self.flight_recorder = flight_recorder
        self._registry = (
            getattr(metrics, "registry", metrics)
            if metrics is not None
            else None
        )
        self.instruments = None
        if self._registry is not None:
            from .instruments import ClusterMetrics

            self.instruments = ClusterMetrics(self._registry)
            self.instruments.shards.set(cluster.n_decode_workers)

        n_workers = cluster.n_decode_workers + cluster.n_prefill_workers
        devices = serving_shard_devices(n_workers)

        self.shards: list[_Shard] = []
        for i in range(cluster.n_decode_workers):
            batcher = ContinuousBatcher(
                model,
                params,
                metrics=metrics,
                tracer=tracer,
                flight_recorder=flight_recorder,
                prefix_cache=(
                    prefix_cache_factory()
                    if prefix_cache_factory is not None
                    else None
                ),
                spec=spec,
                **batcher_kwargs,
            )
            # the pool partition IS the placement: this shard's pages,
            # page table and params live on their own mesh device, so
            # every dispatch the shard runs lands there
            batcher.state = place_paged_state(batcher.state, devices[i])
            batcher.params = place_paged_state(batcher.params, devices[i])
            pool = ShardPool(i, batcher.num_pages, device=devices[i])
            # the router owns the shard intakes: queued items are
            # (submit sequence, request) pairs so run_pending() can
            # hand results back in ADMISSION order across the whole
            # cluster (the batcher's own contract) no matter how
            # routing and rebalance interleaved the shards
            intake = IntakeQueue(
                cluster.max_pending_per_shard,
                max_cost=(
                    cluster.max_pending_pages_per_shard
                    if cluster.max_pending_pages_per_shard is not None
                    else batcher.num_pages
                ),
                cost_fn=lambda item, b=batcher: b._need_pages(item[1]),
                metrics=metrics,
                name=f"cluster.{pool.name}",
                labelled_sheds=True,
            )
            batcher.intake = intake
            self.shards.append(_Shard(pool, batcher, intake))
        self.pool_view = ShardedPoolView([s.pool for s in self.shards])

        self.prefill_workers: list[PrefillWorker] = [
            PrefillWorker(
                model,
                params,
                batcher_kwargs.get("page_size", 16),
                device=devices[cluster.n_decode_workers + j],
                name=f"prefill-{j}",
            )
            for j in range(cluster.n_prefill_workers)
        ]
        self.transfer = PageTransferEngine(
            instruments=self.instruments,
            flight_recorder=flight_recorder,
        )
        self._rr = 0
        self._pf_rr = 0
        #: monotone submit sequence — the admission-order key
        self._seq = 0

    # -- introspection ---------------------------------------------------

    @property
    def total_pages(self) -> int:
        return self.pool_view.total_pages

    @property
    def disaggregated(self) -> bool:
        return bool(self.prefill_workers)

    # -- routing ---------------------------------------------------------

    def _need(self, request) -> int:
        # shards share geometry, so any batcher's arithmetic serves
        return self.shards[0].batcher._need_pages(request)

    def _record_route(self, shard: _Shard, reason: str, need: int,
                      dur_s: float, ts_s: float) -> None:
        if self.instruments is not None:
            self.instruments.routes_total.inc(reason=reason)
        if self.flight_recorder is not None:
            self.flight_recorder.record(
                "route", ts_s, dur_s,
                worker=shard.pool.name, reason=reason, need=int(need),
            )

    def _route(self, need: int) -> _Shard:
        """Pick the shard for one request of worst-case ``need`` pages
        and record the decision (counter + recorder-only event)."""
        ts = time.time()
        t0 = time.perf_counter()
        if len(self.shards) == 1:
            shard, reason = self.shards[0], "only_shard"
        elif self.cluster.route_policy == ROUTE_ROUND_ROBIN:
            shard = self.shards[self._rr % len(self.shards)]
            self._rr += 1
            reason = "round_robin"
        else:
            target = self.pool_view.least_pressure()
            shard = self.shards[target.shard_id]
            reason = "pressure"
        self._record_route(
            shard, reason, need, time.perf_counter() - t0, ts
        )
        return shard

    def _next_prefill_worker(self) -> PrefillWorker:
        worker = self.prefill_workers[
            self._pf_rr % len(self.prefill_workers)
        ]
        self._pf_rr += 1
        return worker

    # -- the batcher-shaped API ------------------------------------------

    def run(self, requests: list) -> list[np.ndarray]:
        """Serve ``requests`` across the cluster; results are the same
        per-request forecast delta arrays the single-device engine
        returns, in the SAME order — routing is invisible to callers.
        Under exact greedy the streams are bitwise-identical to one
        :meth:`~beholder_tpu.models.serving.ContinuousBatcher.run` over
        the same stream (pinned by ``tests/test_cluster.py``)."""
        results: list = [None] * len(requests)
        assignments: dict[int, list[tuple[int, object, int]]] = {
            s.pool.shard_id: [] for s in self.shards
        }
        for gid, req in enumerate(requests):
            need = self._need(req)
            shard = self._route(need)
            shard.pool.reserve(need)
            assignments[shard.pool.shard_id].append((gid, req, need))
        self.pool_view.refresh_gauges(self.instruments)
        for shard in self.shards:
            items = assignments[shard.pool.shard_id]
            if not items:
                continue
            served = self._serve(shard, [req for _, req, _ in items])
            for (gid, _, need), res in zip(items, served):
                results[gid] = res
                shard.pool.release(need)
            if self.instruments is not None:
                self.instruments.requests_total.inc(
                    len(items), shard=str(shard.pool.shard_id)
                )
        self.pool_view.refresh_gauges(self.instruments)
        return results

    def submit(self, request):
        """Offer one request to the cluster: route, then the owning
        shard's bounded intake decides — an explicit
        :class:`~beholder_tpu.reliability.shed.Admission`, with sheds
        attributed to the shard's queue
        (``beholder_intake_shed_total{queue, reason}``)."""
        from beholder_tpu.reliability.shed import SHED_OVERSIZED

        need = self._need(request)
        shard = self._route(need)
        batcher = shard.batcher
        if need > batcher.num_pages or need > batcher.max_pages_per_seq:
            # unservable at ANY load (the batcher's own submit rule)
            return shard.intake.shed(SHED_OVERSIZED)
        admission = shard.intake.offer((self._seq, request), cost=need)
        if admission.accepted:
            self._seq += 1
            shard.pool.reserve(need)
            self.pool_view.refresh_gauges(self.instruments)
        return admission

    def run_pending(self) -> list[np.ndarray]:
        """Rebalance queued work across shards (capacity freed by
        retirements since the last drain makes moves possible — the
        'rebalance on horizon' step), then drain and serve every
        shard. Results come back in ADMISSION order across the whole
        cluster — the single-engine ``run_pending`` contract; routing
        and rebalance stay invisible to callers."""
        self._rebalance()
        collected: list[tuple[int, np.ndarray]] = []
        for shard in self.shards:
            pending = shard.intake.take_all()
            if not pending:
                continue
            requests = [req for _, req in pending]
            served = self._serve(shard, requests)
            for req in requests:
                shard.pool.release(self._need(req))
            collected.extend(
                zip((seq for seq, _ in pending), served)
            )
            if self.instruments is not None:
                self.instruments.requests_total.inc(
                    len(pending), shard=str(shard.pool.shard_id)
                )
        self.pool_view.refresh_gauges(self.instruments)
        collected.sort(key=lambda pair: pair[0])
        return [result for _, result in collected]

    def _serve(self, shard: _Shard, requests: list) -> list[np.ndarray]:
        batcher = shard.batcher
        if (
            self.prefill_workers
            and batcher.prefix_cache is None
            and batcher.spec is None
        ):
            return self._run_disaggregated(shard, requests)
        if batcher.spec is not None:
            return batcher.run_spec(requests)
        return batcher.run(requests)

    # -- rebalance -------------------------------------------------------

    def _rebalance(self) -> None:
        """Re-pack queued requests across shards: a queued request
        whose shard can no longer hold its worst case (pages freed
        elsewhere, arrivals skewed) migrates to the least-pressure
        shard that fits it. Items move via
        :meth:`~beholder_tpu.reliability.shed.IntakeQueue.restock` —
        they were admitted once; rebalancing must not re-count (or
        re-shed) them."""
        if len(self.shards) < 2:
            return
        drained = {
            s.pool.shard_id: s.intake.take_all() for s in self.shards
        }
        if not any(drained.values()):
            return
        # queued commitments come off while we re-pack (in-flight ones,
        # if any, stay reserved)
        needs: dict[int, list[int]] = {}
        for shard in self.shards:
            needs[shard.pool.shard_id] = [
                self._need(req) for _, req in drained[shard.pool.shard_id]
            ]
            shard.pool.release(sum(needs[shard.pool.shard_id]))
        final: dict[int, list] = {s.pool.shard_id: [] for s in self.shards}
        for shard in self.shards:
            sid = shard.pool.shard_id
            for item, need in zip(drained[sid], needs[sid]):
                target = shard
                if shard.pool.free < need:
                    best = self.pool_view.least_pressure()
                    if best.shard_id != sid and best.free >= need:
                        target = self.shards[best.shard_id]
                        ts = time.time()
                        self._record_route(
                            target, "rebalance", need, 0.0, ts
                        )
                final[target.pool.shard_id].append(item)
                target.pool.reserve(need)
        for shard in self.shards:
            shard.intake.restock(final[shard.pool.shard_id])
        self.pool_view.refresh_gauges(self.instruments)

    # -- the disaggregated serving loop ----------------------------------

    def _run_disaggregated(
        self, shard: _Shard, requests: list
    ) -> list[np.ndarray]:
        """Prefill-on-worker, decode-on-shard serving: the per-event
        scheduler's loop (claim under page headroom -> admit -> tick
        the event-free stretch -> retire -> one packed readback) with
        admission replaced by the handoff pipeline (prefill ->
        transfer -> adopt). Bitwise contract: a slot's stream depends
        only on its own pages and carry seed, and the handoff writes
        both exactly as a colocated admit would."""
        b = shard.batcher
        b._start_run(requests)
        t0 = time.perf_counter()
        try:
            with b._run_span(
                "serving.run_cluster",
                requests=len(requests),
                shard=shard.pool.name,
            ) as span:
                results = self._disagg_loop(shard, requests, span)
        except BaseException:
            b._poisoned = True
            raise
        if b._metrics:
            b._metrics.observe_run(
                "run_cluster",
                time.perf_counter() - t0,
                sum(max(r.horizon, 0) for r in requests),
                trace_id=b._span_trace_id(span),
            )
        return results

    def _disagg_loop(self, shard: _Shard, requests, span):
        import jax
        import jax.numpy as jnp

        from beholder_tpu.models.serving import (
            _adopt_chunks_carry,
            _RunCarry,
        )
        from beholder_tpu.ops import NUM_STATUSES

        b = shard.batcher
        fr = self.flight_recorder
        queue = list(enumerate(requests))
        results: list = [None] * len(requests)
        cap = max(1, max((r.horizon for r in requests), default=1) - 1)
        carry = _RunCarry(
            jnp.zeros((b.slots,), jnp.float32),
            jnp.zeros((b.slots, NUM_STATUSES), jnp.float32),
            jnp.zeros((b.slots, cap), jnp.float32),
        )
        req_of = [None] * b.slots
        remaining = np.zeros(b.slots, np.int64)
        total_need = np.zeros(b.slots, np.int64)
        written = np.zeros(b.slots, np.int64)
        snap_batches: list = []
        served = [0, 0]

        def free_pages() -> int:
            return b.num_pages - int(total_need.sum())

        # retire_many and the packed readback below deliberately mirror
        # _run()'s — folding all three serving loops into one composable
        # step pipeline is ROADMAP open item 2; until then a change to
        # _run's snapshot/readback packing must be mirrored here (the
        # bitwise-identity test fails loudly if they drift)
        def retire_many(done: list[int]):
            with b._round(span, "retire", slots=len(done)):
                idx = jnp.asarray(done, jnp.int32)
                rids = [req_of[s] for s in done]
                snap_batches.append((
                    rids,
                    carry.delta_buf[idx],
                    carry.last_pred[idx],
                    [int(written[s]) for s in done],
                ))
                b.state = b._release_many(b.state, idx)
                for s in done:
                    req_of[s] = None
                    total_need[s] = 0
                    written[s] = 0
                served[0] += len(done)
                served[1] += sum(requests[r].horizon for r in rids)

        while queue or any(r is not None for r in req_of):
            # claim round: ONE copy of the hardening invariants
            # (headroom arithmetic, pressure deferral + stall marker,
            # exhaustion fail-fast, recorder-only claim event) — the
            # batcher's own shared claim loop; its prefix-cache branch
            # is inert here (the disagg lane is guarded to
            # prefix_cache=None — warm traffic serves colocated)
            def commit(slot, rid, req, need):
                remaining[slot] = req.horizon
                total_need[slot] = need
                written[slot] = 0

            batch = b._claim_admissions(
                queue, results, req_of, free_pages, commit
            )

            for slot, rid, feats_np, t, _hit, _hashes in batch:
                # prefill on a dedicated worker (recorder-only event,
                # flash-family kernel tags — the prefill FLOPs moved
                # OFF this shard is exactly what the timeline shows)
                worker = self._next_prefill_worker()
                pf_ts = time.time() if fr is not None else 0.0
                pf_t0 = time.perf_counter()
                pred, chunks_k, chunks_v, n_pages = worker.prefill(
                    feats_np, t
                )
                if fr is not None:
                    fr.record(
                        "prefill", pf_ts,
                        time.perf_counter() - pf_t0,
                        worker=worker.name, slot=slot, tokens=int(t),
                        **b._kernel_tags(
                            "flash", t * b._flops_per_token(t / 2.0)
                        ),
                    )
                # page-granular handoff to the owning shard
                pred, chunks_k, chunks_v = self.transfer.handoff(
                    pred, chunks_k, chunks_v, n_pages,
                    shard.pool.device, src=worker.name,
                    dst=shard.pool.name,
                )
                # adopt into the shard pool + seed the decode carry
                # (the existing admit phase label — no new histogram
                # labels; the handoff-specific slices are above)
                with b._round(span, "admit", requests=1):
                    p_max = chunks_k[0].shape[0]
                    adopt = b._cached_jit(
                        ("cluster_adopt", p_max),
                        lambda: lambda s, c, sl, ck, cv, npg, ln, pr, st: (
                            _adopt_chunks_carry(
                                s, c, sl, ck, cv, npg, ln, pr, st
                            )
                        ),
                    )
                    b.state, carry = adopt(
                        b.state, carry, jnp.int32(slot),
                        chunks_k, chunks_v, jnp.int32(n_pages),
                        jnp.int32(t), pred,
                        jnp.int32(int(requests[rid].statuses[-1])),
                    )
            done = [x[0] for x in batch if remaining[x[0]] == 1]
            if done:
                retire_many(done)
            if b._metrics:
                b._metrics.slots_active.set(
                    sum(r is not None for r in req_of)
                )
                b._metrics.pool_pages_free.set(free_pages())
            if not any(r is not None for r in req_of):
                continue

            active = [r is not None for r in req_of]
            n_chunk = max(
                1, int(min(remaining[s] for s in range(b.slots)
                           if active[s])) - 1
            )
            write_idx = np.where(active, written, cap).astype(np.int32)
            tick_tags = {"ticks": n_chunk, "worker": shard.pool.name}
            if fr is not None:
                lens = [
                    len(requests[req_of[s]].progress) - 1
                    + int(written[s])
                    for s in range(b.slots)
                    if active[s]
                ]
                tick_tags.update(b._kernel_tags(
                    "paged",
                    n_chunk * len(lens)
                    * b._flops_per_token(float(np.mean(lens))),
                ))
            with b._round(span, "tick", **tick_tags):
                b.state, carry = b._tick_chunk(
                    b.params, b.state, carry,
                    jnp.asarray(write_idx), jnp.int32(n_chunk),
                )
            done = []
            for slot in range(b.slots):
                if req_of[slot] is None:
                    continue
                written[slot] += n_chunk
                remaining[slot] -= n_chunk
                if remaining[slot] <= 1:
                    done.append(slot)
            if done:
                retire_many(done)
                if b._metrics:
                    b._metrics.slots_active.set(
                        sum(r is not None for r in req_of)
                    )
                    b._metrics.pool_pages_free.set(free_pages())

        # ONE packed readback, exactly the single-engine discipline
        if snap_batches:
            with b._round(span, "readback", batches=len(snap_batches)):
                rows = jnp.concatenate([x[1] for x in snap_batches])
                tails = jnp.concatenate([x[2] for x in snap_batches])
                packed = jnp.concatenate(
                    [
                        b.state.alloc_failed.astype(jnp.float32)[None],
                        tails.astype(jnp.float32),
                        rows.reshape(-1),
                    ]
                )
                got = np.asarray(jax.device_get(packed), np.float32)
            if got[0]:
                raise RuntimeError(b._ALLOCATOR_TRIPPED)
            rids = [rid for x in snap_batches for rid in x[0]]
            widths = [w for x in snap_batches for w in x[3]]
            r = len(rids)
            tails_v = got[1 : 1 + r]
            rows_v = got[1 + r :].reshape(r, cap)
            for i, (rid, w) in enumerate(zip(rids, widths)):
                results[rid] = np.append(rows_v[i, :w], tails_v[i])
        elif bool(jax.device_get(b.state.alloc_failed)):
            raise RuntimeError(b._ALLOCATOR_TRIPPED)
        if b._metrics:
            b._metrics.served(*served)
        return results
