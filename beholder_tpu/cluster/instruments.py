"""The cluster subsystem's metric catalog.

Extension surface like ``cache/instruments.py`` / ``spec/
instruments.py``: nothing is registered unless a cluster scheduler is
handed a registry, so the reference exposition stays byte-identical by
default (pinned by ``tests/test_cluster.py``). Every series uses
:func:`~beholder_tpu.metrics.get_or_create`, so a replacement
scheduler re-attaches instead of tripping the duplicate guard.

Catalog (all appear only when a cluster scheduler gets a registry):

- ``beholder_cluster_shards`` — gauge: decode shards in this cluster
- ``beholder_cluster_pool_pages_free{shard}`` — gauge: each shard's
  free KV pages by the router's host arithmetic (the per-shard twin of
  the unlabelled ``beholder_serving_pool_pages_free``, which N shard
  batchers would otherwise overwrite)
- ``beholder_cluster_pool_pages_committed{shard}`` — gauge: worst-case
  pages committed to each shard's queued + in-flight requests
- ``beholder_cluster_transfers_total`` — counter: prefill->decode KV
  handoffs completed
- ``beholder_cluster_transferred_pages_total`` — counter: live KV
  pages moved by those handoffs
- ``beholder_cluster_transferred_bytes_total`` — counter: live KV
  bytes moved (page bytes x layers x k+v, at the transfer dtype)
- ``beholder_cluster_transfer_failed_total`` — counter: transfers
  that failed terminally (bounded retry exhausted)
- ``beholder_cluster_routes_total{reason}`` — counter: routing
  decisions by reason (``pressure`` / ``round_robin`` / ``only_shard``
  / ``rebalance``)
- ``beholder_cluster_requests_total{shard}`` — counter: requests fully
  served, attributed to the shard that decoded them

Shed attribution lives on the intake side:
``beholder_intake_shed_total{queue, reason}`` (see
:class:`~beholder_tpu.reliability.shed.IntakeQueue` — the router names
each shard's queue uniquely, so sheds chart per shard).
"""

from __future__ import annotations

from beholder_tpu.metrics import get_or_create


class ClusterMetrics:
    """The series above, find-or-registered on a shared registry (a
    :class:`~beholder_tpu.metrics.Registry`, or a
    :class:`~beholder_tpu.metrics.Metrics` whose registry is used)."""

    def __init__(self, registry):
        registry = getattr(registry, "registry", registry)
        self.registry = registry
        self.shards = get_or_create(
            registry, "gauge",
            "beholder_cluster_shards",
            "Decode shards (per-shard paged KV pools) in this cluster",
        )
        self.pool_pages_free = get_or_create(
            registry, "gauge",
            "beholder_cluster_pool_pages_free",
            "Free KV pages per decode shard (router host arithmetic)",
            labelnames=["shard"],
        )
        self.pool_pages_committed = get_or_create(
            registry, "gauge",
            "beholder_cluster_pool_pages_committed",
            "Worst-case KV pages committed to queued + in-flight "
            "requests per decode shard",
            labelnames=["shard"],
        )
        self.transfers_total = get_or_create(
            registry, "counter",
            "beholder_cluster_transfers_total",
            "Prefill->decode page-granular KV handoffs completed",
        )
        self.transferred_pages_total = get_or_create(
            registry, "counter",
            "beholder_cluster_transferred_pages_total",
            "Live KV pages moved by prefill->decode handoffs",
        )
        self.transferred_bytes_total = get_or_create(
            registry, "counter",
            "beholder_cluster_transferred_bytes_total",
            "Live KV bytes moved by prefill->decode handoffs",
        )
        self.transfer_failed_total = get_or_create(
            registry, "counter",
            "beholder_cluster_transfer_failed_total",
            "Page transfers that failed terminally (bounded retry "
            "exhausted; surfaced to the router as TransferFailed)",
        )
        self.routes_total = get_or_create(
            registry, "counter",
            "beholder_cluster_routes_total",
            "Cluster routing decisions by reason",
            labelnames=["reason"],
        )
        self.requests_total = get_or_create(
            registry, "counter",
            "beholder_cluster_requests_total",
            "Requests fully served, by the decode shard that served them",
            labelnames=["shard"],
        )

    def observe_transfer(self, pages: int, nbytes: int) -> None:
        """Record one completed prefill->decode handoff."""
        self.transfers_total.inc()
        self.transferred_pages_total.inc(pages)
        self.transferred_bytes_total.inc(nbytes)

    def set_shard_pool(self, shard: str, free: int, committed: int) -> None:
        self.pool_pages_free.set(free, shard=shard)
        self.pool_pages_committed.set(committed, shard=shard)


class FailoverMetrics:
    """The ``beholder_failover_*`` catalog, registered only when a
    failover-armed cluster scheduler gets a registry (same on-demand
    contract as every other subsystem catalog — default exposition
    stays byte-identical):

    - ``beholder_failover_worker_up{worker}`` — gauge: 1 while a
      decode shard / prefill worker routes traffic, 0 once down or
      drained
    - ``beholder_failover_worker_failures_total{worker, kind}`` —
      counter: detected worker failures (``kill`` / ``hang`` /
      ``transfer_failed``)
    - ``beholder_failover_recoveries_total{reason}`` — counter:
      in-flight requests re-admitted on surviving shards
    - ``beholder_failover_dropped_total{reason}`` — counter: requests
      resolved to an explicit Dropped outcome (``shard_down`` /
      ``recovery_limit``)
    - ``beholder_failover_drains_total`` — counter: graceful shard
      decommissions completed
    - ``beholder_failover_migrated_pages_total`` — counter: resident
      KV pages moved byte-identically by drains
    - ``beholder_failover_deadline_exceeded_total`` — counter:
      requests retired with an expired deadline (the serving layer
      registers the same series lazily on first expiry)
    """

    def __init__(self, registry):
        registry = getattr(registry, "registry", registry)
        self.registry = registry
        self.worker_up = get_or_create(
            registry, "gauge",
            "beholder_failover_worker_up",
            "1 while the worker routes traffic, 0 once down or drained",
            labelnames=["worker"],
        )
        self.worker_failures_total = get_or_create(
            registry, "counter",
            "beholder_failover_worker_failures_total",
            "Detected worker failures by worker and kind",
            labelnames=["worker", "kind"],
        )
        self.recoveries_total = get_or_create(
            registry, "counter",
            "beholder_failover_recoveries_total",
            "In-flight requests recovered onto surviving shards, by "
            "failure reason",
            labelnames=["reason"],
        )
        self.dropped_total = get_or_create(
            registry, "counter",
            "beholder_failover_dropped_total",
            "Requests resolved to an explicit Dropped outcome, by reason",
            labelnames=["reason"],
        )
        self.drains_total = get_or_create(
            registry, "counter",
            "beholder_failover_drains_total",
            "Graceful shard decommissions completed",
        )
        self.migrated_pages_total = get_or_create(
            registry, "counter",
            "beholder_failover_migrated_pages_total",
            "Resident KV pages migrated byte-identically by drains",
        )
        self.deadline_exceeded_total = get_or_create(
            registry, "counter",
            "beholder_failover_deadline_exceeded_total",
            "Requests retired with an expired deadline (explicit "
            "deadline_exceeded outcome instead of a wedged slot)",
        )
