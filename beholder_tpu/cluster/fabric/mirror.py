"""Standby-replica page mirroring.

The standby shard's own radix cache IS the mirror state: each
:meth:`StandbyMirror.sync` diffs every primary's prefix-cache index
against what the standby already caches, moves only the FRESH pages
(pool representation, verbatim — the same
:func:`~beholder_tpu.models.serving.paged_export_pages` /
:func:`~beholder_tpu.models.serving.paged_import_pages` pair every
other fabric hop rides), and drops entries no primary caches anymore
(staleness — a mirror must track evictions or it slowly becomes a
museum of dead prefixes holding real pages hostage).

The standby stays DARK: it owns no slots, serves no requests, holds
every mirrored page at the cache's refcount 1 with ``live_users=0``,
and its cache is a plain :class:`~beholder_tpu.cache.prefix.
PrefixCache` (never published into the global directory) so it can
never be picked as a fetch owner or a mirror source. Promotion
(:meth:`~.engine.FabricEngine.promote`) is what turns the mirror into
serving state: the recovered requests re-admit against the warm cache
— a page-table row written from already-resident pages plus pin
adoption, not a re-prefill.

Mirroring runs BETWEEN serves (the router's sync point), where the
primaries' pools are settled — live-slot transients never mirror,
which is exactly right: a mid-serve slot's pages are re-derivable
from the request (the splice ledger guarantees no token is lost), but
the prefix cache is the expensive-to-rebuild state.
"""

from __future__ import annotations


class StandbyMirror:
    """Asynchronous page mirroring onto the dark standby shard."""

    def __init__(self, engine):
        self.engine = engine
        self.mirrored_pages = 0
        self.stale_dropped = 0
        #: pages a sync could not place for standby headroom (counted,
        #: never silently capped)
        self.skipped_pages = 0
        self.syncs = 0

    def sync(self, standby, primaries: list) -> None:
        """One mirror pass: per primary, move pages the standby does
        not cache yet (parent-first — any prefix of an export is
        parent-closed, so a headroom cut still adopts valid chains),
        then drop standby entries no primary indexes anymore."""
        import jax

        cache = standby.batcher.prefix_cache
        if cache is None:  # pragma: no cover - factory-less cluster
            return
        batcher = standby.batcher
        union: set[bytes] = set()
        for shard in primaries:
            src_cache = shard.batcher.prefix_cache
            if src_cache is None:
                continue
            entries = src_cache.export_entries()
            union.update(key for key, _, _, _ in entries)
            fresh = [
                (key, parent, page_id)
                for key, parent, page_id, _ in entries
                if key not in cache._entries
            ]
            if not fresh:
                continue
            free = int(jax.device_get(batcher.state.free_top))
            if len(fresh) > free:
                self.skipped_pages += len(fresh) - free
                fresh = fresh[:free]
            if not fresh:
                continue
            dest = self.engine._move_pages(
                shard, standby, [pid for _, _, pid in fresh],
                plane="mirror",
            )
            duplicates: list[int] = []
            for (key, parent, _), new_id in zip(fresh, dest):
                if not cache.adopt_entry(key, parent, new_id, live_users=0):
                    duplicates.append(new_id)
            if duplicates:  # pragma: no cover - keys were diffed above
                ids, alive = batcher._page_id_batch(duplicates)
                batcher.state = batcher._cache_unref(
                    batcher.state, ids, alive
                )
            self.mirrored_pages += len(fresh)
        stale = [key for key in list(cache._entries) if key not in union]
        if stale:
            dropped = cache.drop_entries(stale)
            if dropped:
                ids, alive = batcher._page_id_batch(dropped)
                batcher.state = batcher._cache_unref(
                    batcher.state, ids, alive
                )
                self.stale_dropped += len(dropped)
        self.syncs += 1
