"""The global prefix index: one cluster-wide directory over every
shard's prefix cache.

Host-side only (no jax) — the same division of labor as
:mod:`beholder_tpu.cache.prefix`: this module is pure bookkeeping; the
device half (the actual page movement) lives in :mod:`.engine`. The
directory maps chained prefix-page hashes (the radix cache's
content-derived key space, identical on every shard by construction —
``H(parent, page_bytes)`` does not mention the shard) to the shards
currently caching that page and the pool page id each holds.

Two pieces:

- :class:`GlobalPrefixIndex` — the directory itself, plus the
  cross-shard pin ledger (a borrower fetching pages from an owner
  pins the owner's chain so eviction cannot reclaim it mid-move; pins
  release on serve completion, drop, drain, and failover) and the
  per-chain remote hit counter driving the replicate-vs-borrow
  decision.
- :class:`IndexedPrefixCache` — a transparent proxy wrapped around a
  shard's :class:`~beholder_tpu.cache.prefix.PrefixCache` that keeps
  the directory coherent as a side effect of the cache's own
  mutations (insert/adopt publish, evict/drop retract). The serving
  layer sees the exact PrefixCache surface it already speaks; with
  the fabric off nothing wraps and behavior is byte-identical.
"""

from __future__ import annotations


class GlobalPrefixIndex:
    """Cluster-wide directory: prefix hash -> {owner shard: page id}.

    The index never holds device references itself — each owning
    shard's cache keeps its usual ONE reference per cached page, and
    the directory only records WHO holds what. Directory staleness is
    therefore safe the same way the radix cache's host index is: a
    fetch re-resolves pages against the owner's live cache before
    moving anything, and the device refcounts own reclamation truth.
    """

    def __init__(self):
        #: key -> {shard name: pool page id on that shard}
        self._owners: dict[bytes, dict[str, int]] = {}
        #: key -> parent key (same chain structure as the radix cache)
        self._parents: dict[bytes, bytes | None] = {}
        #: chain tip key -> cross-shard hits served from it
        self._hits: dict[bytes, int] = {}
        #: outstanding cross-shard pins:
        #: {"owner": shard, "borrower": shard, "keys": [chain keys]}
        self._pins: list[dict] = []

    # -- directory maintenance (driven by IndexedPrefixCache) ------------

    def publish(
        self, shard: str, key: bytes, parent: bytes | None, page_id: int
    ) -> None:
        self._owners.setdefault(key, {})[shard] = int(page_id)
        self._parents[key] = parent

    def retract(self, shard: str, key: bytes) -> None:
        owners = self._owners.get(key)
        if owners is None:
            return
        owners.pop(shard, None)
        if not owners:
            del self._owners[key]
            self._parents.pop(key, None)
            self._hits.pop(key, None)

    def forget_shard(self, shard: str) -> None:
        """Drop every directory fact about one shard (worker death,
        drain) in one sweep."""
        for key in list(self._owners):
            self.retract(shard, key)

    # -- lookup -----------------------------------------------------------

    def best_owner(
        self, chain: list[bytes], exclude: str, beyond: int
    ) -> tuple[str, int] | None:
        """The shard (other than ``exclude``) caching the DEEPEST
        consecutive-from-root run of ``chain``, provided that depth
        exceeds ``beyond`` (the borrower's own local hit depth — a
        fetch that cannot extend the local hit is pure waste).
        Deterministic: candidate shards walk in sorted-name order and
        the first deepest wins."""
        candidates: set[str] = set()
        for key in chain:
            candidates.update(self._owners.get(key, ()))
        candidates.discard(exclude)
        best: tuple[str, int] | None = None
        for shard in sorted(candidates):
            depth = 0
            for key in chain:
                if self._owners.get(key, {}).get(shard) is None:
                    break
                depth += 1
            if depth > beyond and (best is None or depth > best[1]):
                best = (shard, depth)
        return best

    def page_ids(self, shard: str, keys: list[bytes]) -> list[int]:
        """The ``shard``-local pool page ids for ``keys`` (raises
        KeyError on a key the shard does not own — callers resolve
        against the owner's live cache, so this is a directory-vs-
        cache coherence assertion, not a fallible probe)."""
        return [self._owners[key][shard] for key in keys]

    # -- hot-prefix accounting --------------------------------------------

    def record_remote_hit(self, tip: bytes) -> int:
        """Count one cross-shard hit against a chain tip; returns the
        running total (the replicate-vs-borrow input)."""
        self._hits[tip] = self._hits.get(tip, 0) + 1
        return self._hits[tip]

    # -- cross-shard pin ledger --------------------------------------------

    def register_pin(
        self, owner: str, borrower: str, keys: list[bytes]
    ) -> dict:
        record = {
            "owner": owner, "borrower": borrower, "keys": list(keys)
        }
        self._pins.append(record)
        return record

    def release_pin(self, record: dict) -> None:
        try:
            self._pins.remove(record)
        except ValueError:
            pass

    def take_pins(
        self, owner: str | None = None, borrower: str | None = None
    ) -> list[dict]:
        """Remove and return every pin matching the given owner and/or
        borrower — the release sweep for retire/drop/drain/failover."""
        taken, kept = [], []
        for record in self._pins:
            if (owner is not None and record["owner"] != owner) or (
                borrower is not None and record["borrower"] != borrower
            ):
                kept.append(record)
            else:
                taken.append(record)
        self._pins = kept
        return taken

    def rewrite_pin_owner(self, old: str, new: str) -> int:
        """Repoint pins after a drain migrated the owner's pool: the
        chains (and their ``live_users`` marks) moved byte-identically
        to ``new``, so outstanding borrows release against it."""
        n = 0
        for record in self._pins:
            if record["owner"] == old:
                record["owner"] = new
                n += 1
        return n

    @property
    def outstanding_pins(self) -> int:
        return len(self._pins)

    @property
    def indexed_keys(self) -> int:
        return len(self._owners)


class IndexedPrefixCache:
    """A shard's :class:`~beholder_tpu.cache.prefix.PrefixCache`,
    published. Pure delegation proxy — NOT a subclass: every read and
    every method the serving layer uses passes straight through to the
    wrapped cache, so pin semantics, eviction order, and counters are
    the inner cache's own. Only the four index-mutating operations are
    intercepted, to mirror the mutation into the global directory."""

    def __init__(self, inner, index: GlobalPrefixIndex, shard: str):
        self._inner = inner
        self._index = index
        self._shard = str(shard)
        # a cache wrapped mid-life (standby promotion) publishes what
        # it already holds
        for key, parent, page_id, _ in inner.export_entries():
            index.publish(self._shard, key, parent, page_id)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def insert(self, hashes, page_ids):
        new_pages, new_keys = self._inner.insert(hashes, page_ids)
        for key in new_keys:
            entry = self._inner._entries[key]
            self._index.publish(
                self._shard, key, entry.parent, entry.page_id
            )
        return new_pages, new_keys

    def adopt_entry(self, key, parent, page_id, live_users=0):
        adopted = self._inner.adopt_entry(key, parent, page_id, live_users)
        if adopted:
            self._index.publish(self._shard, key, parent, page_id)
        return adopted

    def evict(self, n_pages):
        before = set(self._inner._entries)
        out = self._inner.evict(n_pages)
        for key in before - set(self._inner._entries):
            self._index.retract(self._shard, key)
        return out

    def drop_entries(self, keys):
        keys = list(keys)
        out = self._inner.drop_entries(keys)
        for key in keys:
            if key not in self._inner._entries:
                self._index.retract(self._shard, key)
        return out
