"""The fabric engine: cross-shard page movement with one owner.

The router consults this engine at four points, all behind
``cluster.fabric is not None`` (the default-OFF contract — with no
engine the cluster is byte-identical to pre-fabric main):

- **Admission** — every attached shard's batcher gets a
  ``prefix_fetcher`` hook: when the local radix cache cannot cover a
  request's prefix, the engine asks the :class:`~.index.
  GlobalPrefixIndex` who can, pins the owner's chain, moves the
  missing pages verbatim over the transfer engine
  (:func:`~beholder_tpu.models.serving.paged_export_pages` /
  :func:`~beholder_tpu.models.serving.paged_import_pages` — the same
  byte-identical path drain migration rides, so fp8 pools move their
  int8 values + scales with zero fabric-specific transport code), and
  adopts them into the borrower's cache so the ordinary local lookup
  one line later HITS. Bitwise identity falls out: after the fetch the
  admission is a plain warm hit — same pins, same eviction rules,
  same page bytes.
- **Serve completion** (:meth:`finish_serve`) — the borrower's
  cross-shard pins release against their owners, and borrowed chains
  whose cross-shard hit count never reached
  ``FabricConfig.replicate_after`` are dropped (transient borrows;
  hot prefixes stay as durable replicas).
- **Worker death** (:meth:`on_worker_down`) / **drain**
  (:meth:`on_drain`) — the pin ledger and the directory forget the
  worker (drain repoints pins at the migration target instead —
  the chains moved there byte-identically, ``live_users`` intact),
  and a mirroring standby is promoted in place of the replay path.
- **Between serves** (:meth:`sync`) — the standby mirror refreshes
  (:class:`~.mirror.StandbyMirror`), spawning a dark standby shard on
  first use.

Every fabric/mirror hop is tagged with a flight-plane edge id when a
recorder is armed — ``fabric.send``/``fabric`` and ``mirror.send``/
``mirror`` pair into Perfetto flow arrows through the same generic
``*.send`` matching the transfer/drain planes use.
"""

from __future__ import annotations

import time

import numpy as np

from .index import GlobalPrefixIndex, IndexedPrefixCache
from .mirror import StandbyMirror


class FabricEngine:
    """One cluster's memory fabric: directory + pins + standby."""

    def __init__(self, config, transfer, flight_recorder=None):
        self.config = config
        self.transfer = transfer
        self.flight_recorder = flight_recorder
        self.index = GlobalPrefixIndex()
        self.mirror = StandbyMirror(self)
        #: attached serving shards by pool name (the standby stays
        #: OUT until promotion — a dark shard must never be a fetch
        #: owner or a mirror source)
        self._shards: dict[str, object] = {}
        #: transient borrows per borrower: chains adopted below the
        #: replication threshold, dropped at finish_serve
        self._borrows: dict[str, list[list[bytes]]] = {}
        #: the dark standby (a router ``_Shard``), or None
        self.standby = None
        # host-side counters (bench/tests read these directly; none
        # registers a metric series — the exposition stays pinned)
        self.cross_shard_lookups = 0
        self.cross_shard_hits = 0
        self.pages_fetched = 0
        self.fetch_failures = 0
        self.pins_released = 0
        self.borrows_dropped = 0
        self.replicas = 0
        self.promotions = 0
        self.standbys_spawned = 0
        self.standby_failures = 0

    # -- attachment -------------------------------------------------------

    def attach_shard(self, shard) -> None:
        """Join one serving shard to the fabric: wrap its prefix cache
        so the directory tracks every index mutation (publishing
        whatever the cache already holds), and arm the batcher's
        admission hook. A shard with no prefix cache has nothing to
        share and stays un-attached."""
        batcher = shard.batcher
        if batcher.prefix_cache is None:
            return
        name = shard.pool.name
        batcher.prefix_cache = IndexedPrefixCache(
            batcher.prefix_cache, self.index, name
        )
        batcher.prefix_fetcher = self._make_fetcher(shard)
        self._shards[name] = shard
        import jax.numpy as jnp

        # failover re-groups retire rounds on the SURVIVORS (the dead
        # worker's requests re-admit wherever routing lands them), and
        # the retire program jits per round width — pre-build every
        # width now, while the shard is quiet, so no width is first
        # seen inside a recovery wall. Releasing zero-length slots is
        # the documented no-op and the result is discarded.
        for width in range(1, int(batcher.slots) + 1):
            batcher._release_many(
                batcher.state, jnp.arange(width, dtype=jnp.int32)
            )

    # -- admission: the cross-shard fetch ---------------------------------

    def _make_fetcher(self, shard):
        def fetch(hashes, max_pages, free_fn):
            try:
                self._fetch(shard, hashes, max_pages, free_fn)
            except Exception:  # noqa: BLE001 - degrade, never poison
                # a fabric fetch must degrade to a cold prefill, never
                # surface into the borrower's claim loop (a
                # TransferFailed escaping here would mark the BORROWER
                # down for the OWNER's link fault)
                self.fetch_failures += 1

        return fetch

    def _fetch(self, shard, hashes, max_pages, free_fn) -> None:
        batcher = shard.batcher
        name = shard.pool.name
        cache = batcher.prefix_cache
        chain = hashes[:max_pages]
        if not chain:
            return
        local = cache.lookup(chain, len(chain), record=False)
        if len(local) >= len(chain):
            return
        self.cross_shard_lookups += 1
        found = self.index.best_owner(chain, exclude=name, beyond=len(local))
        if found is None:
            return
        owner_name, depth = found
        owner = self._shards.get(owner_name)
        if owner is None:
            return
        owner_cache = owner.batcher.prefix_cache
        # re-resolve against the owner's LIVE cache — the directory is
        # kept coherent, but the cache's own index is the page truth
        owner_pages = owner_cache.lookup(chain, depth, record=False)
        if len(owner_pages) <= len(local):
            return
        fetch_keys = chain[len(local):len(owner_pages)]
        n = len(fetch_keys)
        if n > max(0, int(free_fn())):
            # no headroom for the fetched pages on top of the
            # request's own worst case: cold prefill beats thrashing
            return
        # pin BEFORE moving: the owner's eviction must not reclaim the
        # chain mid-move; the pin outlives the move (released at the
        # borrower's finish_serve — the retire/drop/drain rule)
        pin_keys = chain[: len(owner_pages)]
        owner_cache.acquire(pin_keys)
        pin = self.index.register_pin(owner_name, name, pin_keys)
        src_ids = owner_pages[len(local):]
        try:
            dest = self._move_pages(owner, shard, src_ids, plane="fabric")
        except Exception:
            owner_cache.release(pin_keys)
            self.index.release_pin(pin)
            raise
        # adopt into the borrower's cache (each imported page arrived
        # with refcount 1 — the cache's ONE reference; a collision
        # keeps the resident entry and unrefs the duplicate, the same
        # rule insert/migration apply)
        parent = chain[len(local) - 1] if local else None
        adopted: list[bytes] = []
        duplicates: list[int] = []
        for key, page_id in zip(fetch_keys, dest):
            if cache.adopt_entry(key, parent, page_id, live_users=0):
                adopted.append(key)
            else:
                duplicates.append(page_id)
            parent = key
        if duplicates:
            ids, alive = batcher._page_id_batch(duplicates)
            batcher.state = batcher._cache_unref(batcher.state, ids, alive)
        self.cross_shard_hits += 1
        self.pages_fetched += n
        hits = self.index.record_remote_hit(chain[len(owner_pages) - 1])
        if hits < self.config.replicate_after:
            # cold cross-shard traffic BORROWS (dropped after the
            # serve); a chain hit this often REPLICATES — it stays
            # cached here, so the hot prefix stops paying the wire
            self._borrows.setdefault(name, []).append(adopted)
        else:
            self.replicas += 1

    # -- the raw page hop --------------------------------------------------

    #: moves pad their page list to the next multiple of this, so the
    #: export/import programs are FIXED-SHAPE: one compile per (bucket,
    #: pool dtype, device pair) instead of one per chain length. The
    #: import masks rows past the real count (its standard static-width
    #: chunk rule), so padding costs a few wire bytes, never a page.
    MOVE_BUCKET = 8

    def _move_pages(self, src, dst, page_ids, *, plane: str) -> list[int]:
        """Move ``page_ids`` from ``src``'s pool into ``dst``'s pool
        verbatim (pool representation — quantized layers move values +
        scales raw) with refcount 1 installed per page (the receiving
        cache's ONE reference). Returns the destination page ids.
        ``plane`` ("fabric" | "mirror") names the op for per-plane
        transfer accounting and the edge-paired flight events."""
        import jax
        import jax.numpy as jnp

        src_name, dst_name = src.pool.name, dst.pool.name
        n = len(page_ids)
        fr = self.flight_recorder
        ts = time.time() if fr is not None else 0.0
        edge = fr.next_edge() if fr is not None else None
        if edge is not None:
            fr.instant(
                f"{plane}.send", worker=src_name, dst=dst_name,
                pages=n, edge=edge,
            )
        t0 = time.perf_counter()
        padded = list(page_ids)
        padded += [padded[-1]] * (-n % self.MOVE_BUCKET)
        # export/import through the batcher's wire methods: a group
        # shard merges member head-slices on export and re-slices on
        # import, so fabric peers speak ONE full-head dialect whether
        # either endpoint is grouped or not
        chunks_k, chunks_v = src.batcher.export_pages(
            jnp.asarray(padded, jnp.int32)
        )
        chunks_k, chunks_v = self.transfer.raw_move(
            (chunks_k, chunks_v), dst.batcher.transfer_device,
            src=src_name, dst=dst_name,
            op=f"{plane}.{src_name}->{dst_name}",
        )
        new_state, dest = dst.batcher.import_pages(
            chunks_k, chunks_v,
            jnp.int32(n), jnp.ones(len(padded), jnp.int32),
        )
        dst.batcher.state = new_state
        dest = np.asarray(jax.device_get(dest))[:n]
        if fr is not None:
            edge_note = {"edge": edge} if edge is not None else {}
            fr.record(
                plane, ts, time.perf_counter() - t0,
                worker=dst_name, src=src_name, pages=n, **edge_note,
            )
        return [int(d) for d in dest]

    # -- pin lifecycle -----------------------------------------------------

    def _release_borrower_pins(self, name: str) -> None:
        for pin in self.index.take_pins(borrower=name):
            owner = self._shards.get(pin["owner"])
            if owner is not None:
                owner.batcher.prefix_cache.release(pin["keys"])
            self.pins_released += 1

    def finish_serve(self, shard) -> None:
        """The borrower's serve retired its slots: release its
        cross-shard pins against their owners and drop transient
        borrows (their device reference comes off in one vectorized
        unref; a borrowed page a live slot still shares survives at
        refcount >= 1 — ``drop_entries``'s own safety rule)."""
        name = shard.pool.name
        self._release_borrower_pins(name)
        chains = self._borrows.pop(name, None)
        if not chains:
            return
        batcher = shard.batcher
        dropped: list[int] = []
        for keys in chains:
            dropped.extend(batcher.prefix_cache.drop_entries(keys))
        if dropped:
            ids, alive = batcher._page_id_batch(dropped)
            batcher.state = batcher._cache_unref(batcher.state, ids, alive)
            self.borrows_dropped += len(dropped)

    # -- failure / drain ----------------------------------------------------

    def on_worker_down(self, scheduler, name: str):
        """A worker failed: its borrower pins release against the
        surviving owners, pins against its own (dead) pool just leave
        the ledger, the directory forgets it — and, when a standby is
        mirroring, the standby is promoted so recovery re-admits onto
        warm pages instead of replaying prefill."""
        self._release_borrower_pins(name)
        # the dead worker's pool died with its pins — nothing to
        # release on a device that no longer serves
        self.pins_released += len(self.index.take_pins(owner=name))
        self._borrows.pop(name, None)
        self.index.forget_shard(name)
        self._shards.pop(name, None)
        if self.standby is not None and name == self.standby.pool.name:
            # defensive: the standby itself died — discard, re-spawn
            # at the next sync
            self.standby = None
            self.standby_failures += 1
            return None
        if self.standby is not None:
            return self.promote(scheduler)
        return None

    def promote(self, scheduler):
        """Failover's page-table swap: the mirrored standby joins the
        routing set as a full shard. Recovery then re-admits the dead
        worker's requests against a pool already holding their warm
        prefix pages — admission is a prefix HIT plus pin adoption,
        not a re-prefill; that is the near-zero-recovery claim the
        bench measures."""
        shard = self.standby
        self.standby = None
        if shard is None:  # pragma: no cover - guarded by callers
            return None
        shard.pool.shard_id = len(scheduler.shards)
        scheduler.shards.append(shard)
        scheduler.pool_view.shards.append(shard.pool)
        if scheduler.failover is not None:
            scheduler.failover.adopt_worker(shard.pool.name)
        if scheduler.instruments is not None:
            scheduler.instruments.shards.set(
                sum(
                    1 for s in scheduler.shards
                    if scheduler.failover is None
                    or scheduler.failover.state(s.pool.name)
                    not in ("down", "drained")
                )
            )
        scheduler.pool_view.refresh_gauges(scheduler.instruments)
        self.promotions += 1
        if self.flight_recorder is not None:
            self.flight_recorder.instant(
                "promote", worker=shard.pool.name,
                pages=int(shard.batcher.prefix_cache.page_count),
            )
        # wrapping the (plain, dark) mirror cache publishes every
        # mirrored chain — the promoted shard becomes a fetch owner
        self.attach_shard(shard)
        return shard

    def on_drain(self, name: str, target: str) -> None:
        """A planned drain migrated ``name``'s pool to ``target``:
        outstanding pins against the drained owner repoint there (the
        chains and their ``live_users`` marks moved byte-identically),
        its own borrows release, and the directory forgets it — the
        migration itself re-published the chains under ``target``
        through its wrapped cache's ``adopt_entry``."""
        self._release_borrower_pins(name)
        self.index.rewrite_pin_owner(name, target)
        self._borrows.pop(name, None)
        self.index.forget_shard(name)
        self._shards.pop(name, None)

    # -- the standby mirror --------------------------------------------------

    def sync(self, scheduler) -> None:
        """Between-serves housekeeping: with ``standby`` configured,
        spawn the dark standby on first use and refresh its mirror
        from every attached primary. A standby that dies mid-mirror
        (chaos: a scripted transfer fault on its link) is DISCARDED —
        the primaries were only ever read, so they keep serving — and
        a fresh standby re-syncs from live pages at the next call."""
        if not self.config.standby:
            return
        from beholder_tpu.cluster.failover import WorkerKilled
        from beholder_tpu.cluster.transfer import TransferFailed

        try:
            if self.standby is None:
                self._spawn_standby(scheduler)
            self.mirror.sync(self.standby, self._mirror_sources(scheduler))
        except (TransferFailed, WorkerKilled):
            self.standby = None
            self.standby_failures += 1

    def _mirror_sources(self, scheduler) -> list:
        up = self._shards
        if scheduler.failover is not None:
            from beholder_tpu.cluster.failover import WORKER_UP

            state = scheduler.failover.state
            return [
                up[n] for n in sorted(up)
                if state(up[n].pool.name) == WORKER_UP
            ]
        return [up[n] for n in sorted(up)]

    def _spawn_standby(self, scheduler) -> None:
        from beholder_tpu.parallel.mesh import serving_shard_devices

        gcfg = scheduler.cluster.group
        if gcfg is not None:
            # standbys stay SINGLE-DEVICE even when primaries are
            # grouped: the mirror's wire format is the full-head
            # dialect either way, and promotion is bitwise because
            # group == single is pinned. Place it on the first device
            # after the used group blocks (one block is consumed from
            # the cycle — the accepted co-location rule covers the
            # remainder).
            device = serving_shard_devices(
                scheduler._devices_used * gcfg.size + 1
            )[-1]
        else:
            device = serving_shard_devices(
                scheduler._devices_used + 1
            )[-1]
        scheduler._devices_used += 1
        n = self.standbys_spawned
        self.standbys_spawned += 1
        # id space disjoint from decode-<n> until promotion re-ids it;
        # the name marks its provenance in health/trace output
        shard = scheduler._build_shard(
            1000 + n, device, name=f"standby-{n}"
        )
        self._warm_standby(shard)
        self._probe_links(shard)
        self.standby = shard
        if self.flight_recorder is not None:
            self.flight_recorder.instant(
                "standby", worker=shard.pool.name, action="spawn"
            )

    #: shape-replay budget for :meth:`_warm_standby` — real serving
    #: workloads bucket into a handful of geometries; past this, warming
    #: the tail costs more housekeeping time than the promotion saves
    MAX_WARM_SHAPES = 8

    def _warm_standby(self, shard) -> None:
        """Compile the dark standby's serving programs at spawn time.

        Promotion must be near-zero: the recovery pass after a worker
        death re-admits the dead worker's requests onto the standby's
        mirrored pages, and on a freshly-built batcher that first serve
        would pay every XLA compile (admission prefill, warm-hit
        adoption, tick chunk/carry, release, readback) INSIDE the
        recovery wall — tens of compile-seconds against a
        page-adoption path that is otherwise milliseconds. Programs jit
        per request geometry, so a generic warmup misses the shapes
        that matter; instead the standby replays the PRIMARIES'
        observed serve shapes (each batcher's ``seen_request_shapes``
        working set, at its observed concurrency) — the standard
        compile-ahead-with-representative-shapes serving warmup. Each
        shape runs twice — cold, then again as a warm prefix hit — so
        both admission paths' executables plus the tick/retire
        programs exist for the standby's device before it is ever
        promoted. The throwaway chains are then dropped and their
        device references unref'd: the mirror still starts from a
        pristine cache on a pristine pool, and the whole cost lands in
        between-serves housekeeping while the primaries keep serving."""
        from beholder_tpu.models.serving import Request

        batcher = shard.batcher
        shapes: dict[tuple[int, int], int] = {}
        for primary in self._shards.values():
            for key, n in primary.batcher.seen_request_shapes.items():
                shapes[key] = max(shapes.get(key, 0), n)
        if not shapes:
            # nothing observed yet: a minimal request still builds the
            # shape-independent programs (release/unref/readback)
            shapes = {(int(batcher.page_size) + 1, 2): 1}
        replay = sorted(shapes.items())[-self.MAX_WARM_SHAPES:]
        cache = batcher.prefix_cache
        for (width, horizon), n in replay:
            reqs = [
                Request(
                    np.cumsum(np.full(width, 1.0 + 0.25 * i)),
                    np.full(width, 2),
                    horizon,
                )
                for i in range(n)
            ]
            batcher.run(reqs)  # cold: batched prefill + tick + retire
            if cache is not None:
                batcher.run(reqs)  # warm: the prefix-hit admission twin
        import jax.numpy as jnp

        # the retire program jits per round width, and recovery retire
        # rounds group however the re-routed requests happen to land —
        # releasing zero-length slots is the documented no-op, so every
        # width is one discarded call on the pristine state
        for width in range(1, int(batcher.slots) + 1):
            batcher._release_many(
                batcher.state, jnp.arange(width, dtype=jnp.int32)
            )
        if cache is None:  # pragma: no cover - fabric implies caches
            return
        keys = [key for key, _, _, _ in cache.export_entries()]
        dropped = cache.drop_entries(keys)
        if dropped:
            ids, alive = batcher._page_id_batch(dropped)
            batcher.state = batcher._cache_unref(batcher.state, ids, alive)

    def _probe_links(self, standby) -> None:
        """Pre-compile the promoted-standby FETCH programs: one
        bucket-width probe move standby -> each primary builds the
        export-on-standby / import-on-primary executables. The mirror's
        own syncs compile only the opposite direction (primary export,
        standby import), so without the probe a survivor's first
        cross-shard fetch after promotion — the page pull that replaces
        its re-prefill — would pay those compiles inside the recovery
        wall. The probe page is unref'd on arrival (refcount 1 -> 0,
        back on the free stack), so every pool stays pristine; a link
        fault here propagates to :meth:`sync`'s discard-and-respawn
        handling like any other standby housekeeping failure."""
        for name in sorted(self._shards):
            primary = self._shards[name]
            dest = self._move_pages(standby, primary, [0], plane="mirror")
            batcher = primary.batcher
            ids, alive = batcher._page_id_batch(dest)
            batcher.state = batcher._cache_unref(batcher.state, ids, alive)
