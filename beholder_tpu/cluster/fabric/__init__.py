"""Cluster memory fabric: KV pages as a cluster-wide resource.

Two halves behind ``instance.cluster.fabric.*`` (default OFF ⇒
serving output, wire bytes, and the /metrics exposition stay
byte-identical to the fabric-less cluster), sharing one page-movement
plane built from primitives the repo already pins byte-identical:

- **Global prefix index** (:mod:`.index`): a cluster-wide directory
  over every shard's radix prefix cache. The chained content hashes
  are shard-agnostic, so "warm anywhere" is a directory lookup; a
  prefix warm on shard A admits with a prefix hit on shard B via a
  verbatim cross-shard page fetch over the transfer engine, with
  refcount/pin rules extended to cross-shard pins (released on
  retire/drop/drain/failover) and a borrow-vs-replicate policy for
  hot prefixes (``replicate_after``).
- **Standby-replica recovery** (:mod:`.mirror`): a dark standby shard
  asynchronously mirrors the primaries' cached pages; failover
  promotes it (:meth:`~.engine.FabricEngine.promote`) so recovery
  re-admits onto already-resident pages — pin adoption instead of
  re-prefill.

:mod:`.engine` owns both and is the router's single integration
surface. This package's host-side half (:mod:`.index`) is
import-light (no jax), matching the cluster package convention.
"""

from __future__ import annotations

from .index import GlobalPrefixIndex, IndexedPrefixCache

__all__ = [
    "GlobalPrefixIndex",
    "IndexedPrefixCache",
]
