"""The group-parallel decode engine: one logical shard, N devices.

:class:`GroupBatcher` subclasses the single-device
:class:`~beholder_tpu.models.serving.ContinuousBatcher` and keeps its
ENTIRE host half — claim loop, page-headroom arithmetic, prefix-cache
bookkeeping, deadline sweeps, packed readback — untouched. What changes
is purely where programs run: every device program the scheduler
dispatches (admit, warm admit, handoff adopt, tick chunk, release,
cache ref/unref, page export/import) is rebuilt as ONE ``shard_map``
over the group's ``(1, N)`` dp×tp mesh.

The layout contract that makes the host half reusable verbatim:

- **Pools split by KV head, everything else replicated.** Member ``m``
  holds heads ``[m*Hkv/N, (m+1)*Hkv/N)`` of every page (stacked along a
  leading member axis, sharded ``P(axis)``); page tables, free stacks,
  refcounts, lengths and the sticky error flag are ``P()``. Allocator
  arithmetic never reads a head, so each member's replicated copy
  evolves in BITWISE LOCKSTEP — every pinned allocator invariant holds
  member-locally by construction, and page ids stay group-global (the
  prefix cache, fabric directory and host free-page mirror are none the
  wiser).
- **Params at rest in megatron column→row TP**
  (:func:`~beholder_tpu.parallel.mesh.seq_state_shardings` — the same
  specs training uses). Inside a member program, tp-sharded leaves are
  reassembled with one tiled ``all_gather`` per leaf before the
  forward: pure data movement, bitwise — the model then computes
  full-width everywhere except attention.
- **Attention is the only head-aware stage.** The group-threaded model
  (``group=`` on :class:`~beholder_tpu.models.sequence.Block`) slices
  q/k/v to the member's heads, attends member-local pages, and one
  tiled ``all_gather`` reassembles the head axis. No psum touches the
  numbers anywhere in the tick — which is WHY exact-greedy group
  streams are ``np.array_equal`` to the single-device engine (a psum's
  reduction order would not be).

Dispatch plumbing: the scheduler's device calls all flow through
``self._tick_chunk`` / ``self._release_many`` / ``self._cache_ref`` /
``self._cache_unref`` attributes and the :meth:`_cached_jit` program
cache, so this class overrides exactly those — ``_run`` itself is
inherited line for line. ``run_waves`` / ``run_spec`` / ``run_what_if``
raise: wave fleets and speculative decoding are single-device paths
(route them to non-group shards).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from beholder_tpu.models.serving import (
    ContinuousBatcher,
    PagedKVState,
    _admit_cached_carry,
    _admit_many_carry,
    _adopt_chunks_carry,
    _tick_chunk,
    _tick_with_carry,
    cache_ref_pages,
    cache_unref_pages,
    paged_export_pages,
    paged_import_pages,
    paged_release_many,
)
from beholder_tpu.ops.paged_attention import GroupSpec
from beholder_tpu.parallel.mesh import (
    _seq_spec_for,
    group_mesh,
    seq_state_shardings,
)
from beholder_tpu.parallel.sharding import path_specs


def _local(state: PagedKVState) -> PagedKVState:
    """Inside a member program: drop the (length-1 per member) leading
    stack axis off the pools — the member sees a plain single-device
    PagedKVState over its OWN head slice."""
    squeeze = lambda x: x[0]
    return state._replace(
        k_pools=jax.tree.map(squeeze, state.k_pools),
        v_pools=jax.tree.map(squeeze, state.v_pools),
    )


def _restack(state: PagedKVState) -> PagedKVState:
    """Inverse of :func:`_local` on the way out of a member program."""
    expand = lambda x: x[None]
    return state._replace(
        k_pools=jax.tree.map(expand, state.k_pools),
        v_pools=jax.tree.map(expand, state.v_pools),
    )


class GroupBatcher(ContinuousBatcher):
    """A :class:`ContinuousBatcher` whose device programs run as ONE
    ``shard_map`` over a group of ``len(devices)`` mesh devices.

    Drop-in for the cluster: the router treats a group as a single
    routable shard (its :attr:`transfer_device` — member 0 — receives
    handoffs and migrations; the wire format stays the single-device
    full-head dialect, byte for byte). Composes with the prefix cache,
    deadlines, intake shedding, metrics, tracing and the flight
    recorder exactly like the base class; rejects ``spec`` and
    ``fused_verify`` (single-device lanes) at construction.
    """

    def __init__(
        self,
        model,
        params,
        *,
        devices,
        axis: str = "tp",
        name: str = "decode-g0",
        **kwargs,
    ):
        devices = tuple(devices)
        if len(devices) < 2:
            raise ValueError(
                f"a decode group needs >= 2 devices, got {len(devices)} "
                "(group_size=1 is the plain ContinuousBatcher)"
            )
        hkv = model.kv_heads or model.heads
        if hkv % len(devices):
            raise ValueError(
                f"group size {len(devices)} does not divide the model's "
                f"{hkv} KV heads (head-partition policy is kv_head)"
            )
        if kwargs.get("spec") is not None:
            raise ValueError(
                "group-parallel decode does not compose with speculative "
                "decoding (spec verify is a single-device lane) — route "
                "spec traffic to a non-group shard"
            )
        if kwargs.get("fused_verify"):
            raise ValueError(
                "fused_verify is a per-batcher single-device knob; the "
                "group engine always runs warm admissions fused (drop "
                "the knob — it is implied)"
            )
        self.group = GroupSpec(axis, len(devices))
        self.devices = devices
        self.name = name
        self.mesh = group_mesh(devices, axis)
        super().__init__(model, params, **kwargs)

        repl = NamedSharding(self.mesh, P())
        pool_sh = NamedSharding(self.mesh, P(axis))
        self._repl_sharding = repl
        n = self.group.size

        # -- state: stack member head-slices on a leading axis, shard it
        def stack(leaf):
            hloc = leaf.shape[1] // n  # head axis is 1 for values AND scales
            return jnp.stack(
                [leaf[:, m * hloc : (m + 1) * hloc] for m in range(n)]
            )

        full = self.state
        stacked = full._replace(
            k_pools=jax.tree.map(stack, full.k_pools),
            v_pools=jax.tree.map(stack, full.v_pools),
        )
        self.state = jax.device_put(
            stacked,
            PagedKVState(
                k_pools=jax.tree.map(lambda _: pool_sh, stacked.k_pools),
                v_pools=jax.tree.map(lambda _: pool_sh, stacked.v_pools),
                page_table=repl,
                seq_lens=repl,
                active=repl,
                free_stack=repl,
                free_top=repl,
                page_ref=repl,
                alloc_failed=repl,
            ),
        )
        #: shard_map spec prefix for the stacked state (pools along the
        #: member axis, allocator leaves replicated)
        self._sspec = PagedKVState(
            k_pools=P(axis),
            v_pools=P(axis),
            page_table=P(),
            seq_lens=P(),
            active=P(),
            free_stack=P(),
            free_top=P(),
            page_ref=P(),
            alloc_failed=P(),
        )

        # -- params: megatron TP at rest; remember which axis (if any)
        # each leaf shards on so member programs can all_gather it back
        self.params = jax.device_put(
            params, seq_state_shardings(params, self.mesh)
        )
        self._param_specs = path_specs(params, _seq_spec_for)

        def axis_of(spec):
            for i, names in enumerate(spec):
                if names is None:
                    continue
                if axis in (names if isinstance(names, tuple) else (names,)):
                    return i
            return -1

        self._param_axes = jax.tree_util.tree_map_with_path(
            lambda path, leaf: axis_of(_seq_spec_for(path, leaf)), params
        )

        # -- rebuild the fixed-shape program attributes the scheduler
        # dispatches through (the keyed programs go via _cached_jit)
        self._tick_chunk = self._instrumented_tick(
            self._smap(
                self._member_state_carry(
                    lambda p, s, c, w, nn: _tick_chunk(
                        self.model, p, s, c, w, nn, group=self.group
                    )
                ),
                (self._param_specs, self._sspec, P(), P(), P()),
                (self._sspec, P()),
            )
        )
        self._tick_carry = self._smap(
            self._member_state_carry(
                lambda p, s, c, w: _tick_with_carry(
                    self.model, p, s, c, w, group=self.group
                )
            ),
            (self._param_specs, self._sspec, P(), P()),
            (self._sspec, P()),
        )
        self._release_many = self._smap(
            lambda s, idx: _restack(paged_release_many(_local(s), idx)),
            (self._sspec, P()),
            self._sspec,
        )
        if self.prefix_cache is not None:
            self._cache_ref = self._smap(
                lambda s, ids, alive: _restack(
                    cache_ref_pages(_local(s), ids, alive)
                ),
                (self._sspec, P(), P()),
                self._sspec,
            )
            self._cache_unref = self._smap(
                lambda s, ids, alive: _restack(
                    cache_unref_pages(_local(s), ids, alive)
                ),
                (self._sspec, P(), P()),
                self._sspec,
            )
        # page export/import (migration + fabric wire): jit retraces per
        # chunk shape, so one program object each serves every width
        self._export_prog = self._smap(
            self._member_export,
            (self._sspec, P()),
            P(),
        )
        self._import_prog = self._smap(
            lambda s, ck, cv, npg, refs: (
                lambda out: (_restack(out[0]), out[1])
            )(
                paged_import_pages(
                    _local(s), ck, cv, npg, refs, group=self.group
                )
            ),
            (self._sspec, P(), P(), P(), P()),
            (self._sspec, P()),
        )

    # -- program construction helpers -----------------------------------

    def _smap(self, fn, in_specs, out_specs):
        """jit(shard_map(...)) over the group mesh. ``check_rep=False``:
        the replicated-output invariant here comes from the layout
        contract (lockstep allocator + tiled all_gathers), which the
        checker cannot see through ``lax.while_loop``."""
        return jax.jit(
            shard_map(
                fn,
                mesh=self.mesh,
                in_specs=in_specs,
                out_specs=out_specs,
                check_rep=False,
            )
        )

    def _gather_params(self, p):
        """Reassemble tp-sharded param leaves inside a member program —
        one tiled all_gather per sharded leaf (bitwise: concatenation,
        not reduction). Replicated leaves pass through untouched."""
        ax = self.group.axis
        return jax.tree.map(
            lambda leaf, a: (
                leaf
                if a < 0
                else jax.lax.all_gather(leaf, ax, axis=a, tiled=True)
            ),
            p,
            self._param_axes,
        )

    def _member_state_carry(self, fn):
        """Wrap a ``(params, state, carry, *rest) -> (state, carry)``
        serving function as a member program: gather params, unstack the
        member's pool slice, restack on the way out."""

        def member(p, s, c, *rest):
            s, c = fn(self._gather_params(p), _local(s), c, *rest)
            return _restack(s), c

        return member

    def _member_export(self, s, page_ids):
        """Member half of :meth:`export_pages`: export the local head
        slice, then all_gather every chunk leaf (values AND scales —
        both carry heads on axis 1) back to the full-head wire format."""
        chunks = paged_export_pages(_local(s), page_ids)
        merge = lambda a: jax.lax.all_gather(
            a, self.group.axis, axis=1, tiled=True
        )
        return jax.tree.map(merge, chunks)

    def _instrumented_tick(self, prog):
        """Flight-plane member identities: each tick-chunk dispatch
        drops one instant PER MEMBER (``worker=decode-g0.m1`` style)
        tagged with the reassembly collective, so a merged cluster
        timeline shows which chips the tick spanned. Recorder off, the
        wrapper is a passthrough call — zero cost, byte-identical."""

        def tick(p, s, c, w, nn):
            fr = self.flight_recorder
            if fr is not None:
                for m in range(self.group.size):
                    fr.instant(
                        "group.tick",
                        worker=f"{self.name}.m{m}",
                        collective="all_gather",
                        members=self.group.size,
                    )
            return prog(p, s, c, w, nn)

        return tick

    # -- keyed program cache ---------------------------------------------

    def _cached_jit(self, key: tuple, build):
        """The scheduler's keyed dispatch point. The single-device
        builders close over full-head state, so the ``build`` thunk is
        IGNORED here and the group twin of the keyed program is built
        from the key itself — same cache, same keys, same call
        signatures (``_run`` and the router's disagg loop run
        unchanged)."""
        fn = self._serve_cache.get(key)
        if fn is not None:
            return fn
        kind = key[0] if key and isinstance(key[0], str) else None
        if kind == "admit":
            fn = self._smap(
                self._member_state_carry(
                    lambda p, s, c, ids, f, ln, st: _admit_many_carry(
                        self.model, p, s, c, ids, f, ln, st,
                        group=self.group,
                    )
                ),
                (self._param_specs, self._sspec, P(), P(), P(), P(), P()),
                (self._sspec, P()),
            )
        elif kind == "admit_cached":
            # warm admissions ALWAYS run fused in a group — the dense
            # oracle's context gather cannot run on a head slice, and
            # fused == dense is bitwise-pinned repo-wide
            fn = self._smap(
                self._member_state_carry(
                    lambda p, s, c, sl, f, ln, pg, st: _admit_cached_carry(
                        self.model, p, s, c, sl, f, ln, pg, st,
                        fused=True, group=self.group,
                    )
                ),
                (
                    self._param_specs, self._sspec,
                    P(), P(), P(), P(), P(), P(),
                ),
                (self._sspec, P()),
            )
        elif kind == "cluster_adopt":
            inner = self._smap(
                lambda s, c, sl, ck, cv, npg, ln, pr, st: (
                    lambda out: (_restack(out[0]), out[1])
                )(
                    _adopt_chunks_carry(
                        _local(s), c, sl, ck, cv, npg, ln, pr, st,
                        group=self.group,
                    )
                ),
                (self._sspec, P(), P(), P(), P(), P(), P(), P(), P()),
                (self._sspec, P()),
            )
            fn = self._adopt_host(inner)
        else:
            raise NotImplementedError(
                f"GroupBatcher has no group twin for program key {key!r} "
                "(wave/spec/what-if lanes are single-device)"
            )
        self._serve_cache[key] = fn
        return fn

    def _adopt_host(self, inner):
        """Handoff chunks arrive COMMITTED to the transfer device
        (member 0); replicate them across the mesh before the shard_map
        program (committed single-device inputs would otherwise clash
        with the mesh-committed state)."""

        def adopt(s, c, sl, ck, cv, npg, ln, pr, st):
            put = lambda t: jax.device_put(
                t, jax.tree.map(lambda _: self._repl_sharding, t)
            )
            return inner(s, c, sl, put(ck), put(cv), npg, ln, put(pr), st)

        return adopt

    # -- page-granular wire (migration + fabric) -------------------------

    @property
    def transfer_device(self):
        """Where handoffs and migrations land: member 0. (The base
        class reads it off the state, which here is mesh-committed.)"""
        return self.devices[0]

    def export_pages(self, page_ids):
        """Gather pages for the wire in the FULL-HEAD single-device
        dialect — the export side merges member slices, so migration
        and fabric peers (grouped or not) speak one format, byte for
        byte."""
        return self._export_prog(
            self.state, jnp.asarray(page_ids, jnp.int32)
        )

    def import_pages(self, chunks_k, chunks_v, n_pages, refs):
        """Adopt full-head wire chunks: replicate them over the mesh,
        then each member slices and writes only its own heads. Returns
        (state, dest_ids) like the base — caller assigns state."""
        put = lambda t: jax.device_put(
            t, jax.tree.map(lambda _: self._repl_sharding, t)
        )
        return self._import_prog(
            self.state,
            put(chunks_k),
            put(chunks_v),
            jnp.int32(n_pages),
            put(jnp.asarray(refs, jnp.int32)),
        )

    # -- single-device-only lanes ----------------------------------------

    def run_waves(self, *a, **kw):
        raise NotImplementedError(
            "run_waves is a single-device lane (fused per-wave programs "
            "do not shard by KV head) — use run()/run_pending on a "
            "group shard"
        )

    def run_what_if(self, *a, **kw):
        raise NotImplementedError(
            "run_what_if forks are a single-device lane — replay "
            "what-ifs on a non-group shard"
        )

    def run_spec(self, *a, **kw):
        raise NotImplementedError(
            "speculative decoding is a single-device lane (spec is "
            "rejected at GroupBatcher construction)"
        )
