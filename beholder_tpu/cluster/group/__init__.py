"""Group-parallel decode: serve the decode tick itself across chips.

EXTENSION BEYOND THE REFERENCE. The cluster partitions REQUESTS — every
shard's model and KV pool must fit one device, and the memory fabric
(PR 18) can move pages between shards but cannot make a bigger model or
a faster token. This subsystem partitions the TICK: a group of N mesh
devices serves ONE logical shard (``instance.cluster.group.*`` —
default OFF, under which serving output, wire bytes, and the /metrics
exposition stay byte-identical):

- **Params** lie at rest in the existing :mod:`beholder_tpu.parallel`
  megatron column→row tensor-parallel shardings over the group's
  ``(1, N)`` dp×tp mesh — the machinery trained models already use,
  now wired into serving.
- **The paged KV pool partitions by KV HEAD**: member ``m`` holds every
  page's heads ``[m*Hkv/N, (m+1)*Hkv/N)``. Page tables, free stacks,
  refcounts and lengths are REPLICATED — allocator arithmetic is
  head-free, so every member evolves in bitwise lockstep and every
  pinned allocator invariant holds member-locally by construction.
  Page ids are group-global: the prefix cache, fabric directory and
  host arithmetic never learn the pool was split.
- **One program per tick**: claim → admit → tick → retire → packed
  readback dispatch ``shard_map`` programs over the group; attention
  runs on member-local heads and one tiled ``all_gather`` reassembles
  the head dim (pure data movement — bitwise, unlike a psum).
- **The scheduler sees ONE shard**: a group routes, drains, fails over
  and mirrors as a single ``decode-g<id>`` worker; flight-plane events
  carry ``worker=decode-g0.m1`` member identities.

Exact-greedy group streams are ``np.array_equal`` to the single-device
engine for bf16/int8/fp8 pools (pinned by ``tests/test_group.py``).
The device half lives in :mod:`.engine` and loads on first use — this
module stays import-light.
"""

from __future__ import annotations

__all__ = ["GroupBatcher"]


def __getattr__(name):
    if name == "GroupBatcher":
        from .engine import GroupBatcher

        return GroupBatcher
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
