"""Disaggregated multi-chip serving: shard the paged KV pool across
the mesh and split prefill from decode.

EXTENSION BEYOND THE REFERENCE (which has no inference of any kind —
SURVEY.md §0). The single-device serving engine caps concurrent-user
capacity at one chip's HBM: one paged pool, one
:class:`~beholder_tpu.models.serving.ContinuousBatcher`. This
subsystem turns that engine into an N-worker cluster in the spirit of
GPUOS's transparent scheduling primitives (PAPERS.md) — same
submit/run API, same bitwise outputs, N× the pool:

- **Sharded KV pool** (:mod:`.pool`): each decode shard owns its own
  paged pool + page table on its own mesh device, with per-shard free
  lists and refcounts (a shard's pool IS a
  :class:`~beholder_tpu.models.serving.PagedKVState`, so every
  allocator invariant already pinned — refcounted prefix sharing,
  prefix-cache pins, spec rollback — holds PER SHARD for free). Total
  KV capacity (= concurrent users) scales with shard count.
- **Prefill/decode disaggregation** (:mod:`.transfer`): dedicated
  prefill workers run the prefill forward OFF-POOL
  (:func:`~beholder_tpu.models.serving.kv_prefill_chunks`) and hand
  the KV to the owning decode shard page-granularly
  (:func:`~beholder_tpu.models.serving.paged_adopt_chunks`), so a
  long prefill occupies a prefill worker's FLOPs, not the decode
  shard's tick cadence. The destination pool ends up bitwise what a
  colocated prefill would have written.
- **Cluster scheduler** (:mod:`.router`): the batcher promoted to a
  cluster-level admission router — route by pool pressure per shard
  (or round-robin), per-shard bounded intakes with labelled shed
  attribution, rebalance queued work across shards at drain time,
  and a per-shard serving loop that claims via the same invariants as
  ``ContinuousBatcher._claim_admissions``.
- **Fault tolerance** (:mod:`.failover`, ``instance.cluster.
  failover.*`` — default OFF, under which the cluster stays
  fail-stop): worker heartbeats + chaos-injectable failure detection,
  in-flight request recovery onto surviving shards (exact-greedy
  recovered streams bitwise-identical to an uninterrupted run),
  graceful drain with byte-identical live-page + prefix-pin
  migration, and deadline-aware retirement.
- **Cluster memory fabric** (:mod:`.fabric`, ``instance.cluster.
  fabric.*`` — default OFF, under which every shard's prefix cache
  stays private and failover replays prefill): a cluster-wide prefix
  index so a prefix warm on one shard is a byte-identical cross-shard
  page fetch away on every other shard, plus an optional dark standby
  shard mirroring live pages so failover becomes promotion + pin
  adoption instead of re-prefill.
- **Group-parallel decode** (:mod:`.group`, ``instance.cluster.
  group.*`` — default OFF, under which every decode shard stays
  single-device): a group of N mesh devices serves ONE logical shard —
  params at rest in the megatron tp shardings, the paged pool
  partitioned by KV head, one shard_map program per tick, the head
  axis reassembled by a tiled all_gather (never a psum). The
  scheduler, fabric and failover see one routable shard.

**Exactness.** Under exact greedy the cluster emits token streams
bitwise-identical to the single-device engine on the same request
stream: a slot's decode reads only its own pages, the handoff writes
pool content byte-for-byte (same ``_write_chunks`` cast path), and
the carry seeds apply the same casts — routing and disaggregation
change WHERE work runs, never what it computes (pinned by
``tests/test_cluster.py``).

Everything is opt-in: the service parses ``instance.cluster.*`` into
a :class:`ClusterConfig` (None when disabled — the default, under
which serving behavior and the /metrics exposition stay
byte-identical); whatever embeds the serving layer builds a
:class:`~beholder_tpu.cluster.router.ClusterScheduler` from it. This
module stays import-light (no jax) — the device half lives in
:mod:`.pool` / :mod:`.transfer` / :mod:`.router` and loads on first
use.
"""

from __future__ import annotations

from dataclasses import dataclass

#: routing policies
ROUTE_PRESSURE = "pressure"
ROUTE_ROUND_ROBIN = "round_robin"


@dataclass
class FailoverConfig:
    """Fault-tolerance knobs (``instance.cluster.failover.*``).

    None on :class:`ClusterConfig` (the default) means fail-stop: a
    worker failure raises, exactly the pre-failover cluster. Set, the
    router arms a :class:`~beholder_tpu.cluster.failover.
    FailoverEngine`: per-worker heartbeats + failure detection,
    in-flight request recovery onto surviving shards, graceful drain,
    and deadline-aware retirement — all invisible to callers (recovered
    exact-greedy streams stay bitwise-identical to an uninterrupted
    run; pinned by ``tests/test_cluster_chaos.py``)."""

    #: heartbeat staleness unit: a watched worker whose last beat is
    #: older than ``heartbeat_interval_s * miss_threshold`` is marked
    #: down (hang detection)
    heartbeat_interval_s: float = 5.0
    miss_threshold: int = 3
    #: recovery cap per request: a request re-admitted more times than
    #: this (pathological cascades) resolves to an explicit ``Dropped``
    #: outcome instead of looping forever
    max_recoveries_per_request: int = 2
    #: service shutdown (SIGTERM routes to close()): drain every shard
    #: — stop admitting, serve what's queued — before exiting
    drain_on_sigterm: bool = True

    def __post_init__(self):
        if self.heartbeat_interval_s <= 0:
            raise ValueError(
                f"heartbeat_interval_s must be > 0, "
                f"got {self.heartbeat_interval_s}"
            )
        if self.miss_threshold < 1:
            raise ValueError(
                f"miss_threshold must be >= 1, got {self.miss_threshold}"
            )
        if self.max_recoveries_per_request < 0:
            raise ValueError(
                f"max_recoveries_per_request must be >= 0, "
                f"got {self.max_recoveries_per_request}"
            )


@dataclass
class FabricConfig:
    """Cluster-memory-fabric knobs (``instance.cluster.fabric.*``).

    None on :class:`ClusterConfig` (the default) keeps each shard's
    prefix cache private and failover on the replay path. Set, the
    router arms a :class:`~beholder_tpu.cluster.fabric.engine.
    FabricEngine`: a cluster-wide prefix index (a prefix warm on shard
    A is admitted with a prefix hit on shard B via a byte-identical
    cross-shard page fetch) and, with ``standby``, a dark standby
    shard that mirrors live pages so failover becomes promotion + pin
    adoption instead of re-prefill (pinned by ``tests/test_fabric.py``).
    """

    #: cross-shard hit count at/past which a fetched chain stays
    #: cached on the borrowing shard as a durable replica; below it
    #: the borrow is transient and dropped after the serve (hot
    #: prefixes replicate, cold ones never accumulate copies)
    replicate_after: int = 2
    #: keep one dark standby shard mirroring live pages; on a worker
    #: death the standby is promoted in place of the replay path
    standby: bool = False

    def __post_init__(self):
        if self.replicate_after < 1:
            raise ValueError(
                f"replicate_after must be >= 1, got {self.replicate_after}"
            )


@dataclass
class GroupConfig:
    """Group-parallel-decode knobs (``instance.cluster.group.*``).

    None on :class:`ClusterConfig` (the default) keeps every decode
    shard single-device: serving output, handoff wire bytes, and the
    /metrics exposition byte-identical to the pre-group cluster. Set,
    every decode shard becomes a GROUP of ``size`` mesh devices serving
    ONE logical shard (:class:`~beholder_tpu.cluster.group.
    GroupBatcher`): params at rest in the megatron tp shardings, the
    paged pool partitioned by KV head, one shard_map program per tick.
    Exact-greedy group streams are bitwise-identical to the
    single-device engine (pinned by ``tests/test_group.py``)."""

    #: devices per decode group (>= 2 — a group of 1 IS the plain
    #: single-device shard, so asking for it is a config error, not a
    #: silent no-op); must divide the model's KV-head count and the
    #: mesh's device count
    size: int = 2
    #: mesh-axis name the group's collectives run over — the params'
    #: tp axis (``seq_state_shardings`` specs name it), so trained
    #: sharded params drop in without a respec
    axis: str = "tp"
    #: pool-partition policy. Only ``"kv_head"`` exists: member m owns
    #: heads [m*Hkv/size, (m+1)*Hkv/size) of every page, which is what
    #: keeps every allocator invariant member-local by construction.
    #: The field is explicit (not implied) so a future page-partition
    #: policy is a VALUE, not a schema change.
    head_partition: str = "kv_head"

    def __post_init__(self):
        if self.size < 2:
            raise ValueError(
                f"group size must be >= 2, got {self.size} (size 1 is "
                "the plain single-device shard — disable the group "
                "block instead)"
            )
        if not str(self.axis).isidentifier():
            raise ValueError(
                f"group axis must be a mesh-axis identifier, "
                f"got {self.axis!r}"
            )
        if self.head_partition != "kv_head":
            raise ValueError(
                f"head_partition must be 'kv_head', "
                f"got {self.head_partition!r}"
            )


@dataclass
class ClusterConfig:
    """Cluster-serving knobs (``instance.cluster.*``).

    ``n_prefill_workers == 0`` is the COLOCATED cluster: requests
    route to decode shards that prefill and decode on their own pool
    (capacity scaling without disaggregation). ``>= 1`` arms the
    disaggregated path: prefill runs on dedicated workers and the KV
    hands off page-granularly to the owning decode shard."""

    n_decode_workers: int = 2
    n_prefill_workers: int = 0
    route_policy: str = ROUTE_PRESSURE   # pressure | round_robin
    #: per-shard intake bounds (the admission-control front door; the
    #: page-cost bound defaults to the shard's own pool size so a
    #: shard sheds when its queued worst-case pages exceed what it
    #: can ever hold)
    max_pending_per_shard: int = 16
    max_pending_pages_per_shard: int | None = None
    #: fault tolerance: None (the default) keeps the fail-stop cluster
    failover: FailoverConfig | None = None
    #: cluster memory fabric: None (the default) keeps per-shard
    #: prefix caches private and failover on the replay path
    fabric: FabricConfig | None = None
    #: group-parallel decode: None (the default) keeps decode shards
    #: single-device
    group: GroupConfig | None = None

    def __post_init__(self):
        if self.n_decode_workers < 1:
            raise ValueError(
                f"n_decode_workers must be >= 1, got {self.n_decode_workers}"
            )
        if self.n_prefill_workers < 0:
            raise ValueError(
                f"n_prefill_workers must be >= 0, "
                f"got {self.n_prefill_workers}"
            )
        if self.route_policy not in (ROUTE_PRESSURE, ROUTE_ROUND_ROBIN):
            raise ValueError(
                f"route_policy must be {ROUTE_PRESSURE!r}|"
                f"{ROUTE_ROUND_ROBIN!r}, got {self.route_policy!r}"
            )
        if self.max_pending_per_shard < 1:
            raise ValueError(
                f"max_pending_per_shard must be >= 1, "
                f"got {self.max_pending_per_shard}"
            )


def cluster_from_config(config) -> ClusterConfig | None:
    """Parse ``instance.cluster.*`` into a :class:`ClusterConfig`;
    None unless ``instance.cluster.enabled`` — the same off-by-default
    contract as the cache/spec/flight-recorder subsystems (disabled
    means byte-identical behavior and exposition)."""
    if not bool(config.get("instance.cluster.enabled")):
        return None
    max_pages = config.get("instance.cluster.max_pending_pages_per_shard")
    failover = None
    if bool(config.get("instance.cluster.failover.enabled")):
        fo = "instance.cluster.failover"
        failover = FailoverConfig(
            heartbeat_interval_s=float(
                config.get(f"{fo}.heartbeat_interval_s", 5.0)
            ),
            miss_threshold=int(config.get(f"{fo}.miss_threshold", 3)),
            max_recoveries_per_request=int(
                config.get(f"{fo}.max_recoveries_per_request", 2)
            ),
            drain_on_sigterm=bool(
                config.get(f"{fo}.drain_on_sigterm", True)
            ),
        )
    fabric = None
    if bool(config.get("instance.cluster.fabric.enabled")):
        fb = "instance.cluster.fabric"
        fabric = FabricConfig(
            replicate_after=int(config.get(f"{fb}.replicate_after", 2)),
            standby=bool(config.get(f"{fb}.standby", False)),
        )
    group = None
    if bool(config.get("instance.cluster.group.enabled")):
        gp = "instance.cluster.group"
        group = GroupConfig(
            size=int(config.get(f"{gp}.size", 2)),
            axis=str(config.get(f"{gp}.axis", "tp")),
            head_partition=str(
                config.get(f"{gp}.head_partition", "kv_head")
            ),
        )
    return ClusterConfig(
        n_decode_workers=int(
            config.get("instance.cluster.n_decode_workers", 2)
        ),
        n_prefill_workers=int(
            config.get("instance.cluster.n_prefill_workers", 0)
        ),
        route_policy=str(
            config.get("instance.cluster.route_policy", ROUTE_PRESSURE)
        ),
        max_pending_per_shard=int(
            config.get("instance.cluster.max_pending_per_shard", 16)
        ),
        max_pending_pages_per_shard=(
            int(max_pages) if max_pages is not None else None
        ),
        failover=failover,
        fabric=fabric,
        group=group,
    )


__all__ = [
    "ClusterConfig",
    "FabricConfig",
    "FailoverConfig",
    "GroupConfig",
    "ROUTE_PRESSURE",
    "ROUTE_ROUND_ROBIN",
    "cluster_from_config",
]
