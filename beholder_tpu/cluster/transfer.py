"""Prefill workers + the page-granular KV handoff between workers.

Disaggregation splits one admission into three device-side steps:

1. **Prefill** on a dedicated prefill worker:
   :func:`~beholder_tpu.models.serving.kv_prefill_chunks` runs the
   same prefill forward a colocated admit runs, but returns the KV as
   page-layout chunks instead of scattering it into a local pool —
   prefill workers own FLOPs, not pages.
2. **Transfer**: the chunks (plus the admit prediction riding along)
   move to the owning decode shard's device in one
   ``jax.device_put`` — page-granular, so the wire unit is the same
   unit the pool allocates. On TPU this is the ICI/DMA hop; on a CPU
   test mesh it is a host copy; either way the content is
   bit-preserved (pinned by ``tests/test_cluster.py``).
3. **Adopt** on the decode shard:
   :func:`~beholder_tpu.models.serving.paged_adopt_chunks` pops pages
   off THAT shard's free stack and writes the chunks through the same
   cast/quantize path a local prefill would have used — the
   destination pool ends up byte-identical to a colocated admit.

The handoff is instrumented twice, both host-side: the
``beholder_cluster_transfer*`` counters (:mod:`.instruments`) and a
recorder-only ``transfer`` phase event carrying the worker pair (the
flight-recorder satellite — it must NOT appear as a new
round-histogram label, so it records straight to the ring like the
``claim`` phase).
"""

from __future__ import annotations

import time


class TransferFailed(RuntimeError):
    """A page transfer failed TERMINALLY (the bounded retry inside
    :class:`PageTransferEngine` was exhausted). Typed so the cluster
    router can treat it as a worker-level fault — mark the destination
    unreachable and recover the request on a surviving shard — instead
    of an anonymous exception raising through the tick loop."""

    def __init__(self, src: str, dst: str, cause: BaseException):
        super().__init__(
            f"page transfer {src} -> {dst} failed after retries: "
            f"{cause!r}"
        )
        self.src = src
        self.dst = dst
        self.kind = "transfer_failed"


class PrefillWorker:
    """A dedicated prefill worker: the model forward on its own mesh
    device, producing handoff chunks instead of pool writes.

    Stateless by design — pure prefill holds no pages (nothing decodes
    here), so the worker is just committed params + a jit cache keyed
    on the padded prefix width."""

    def __init__(self, model, params, page_size: int, device=None,
                 name: str = "prefill-0"):
        from beholder_tpu.cluster.pool import place_paged_state

        self.model = model
        self.page_size = int(page_size)
        self.device = device
        self.name = name
        self.params = place_paged_state(params, device)
        self._jits: dict[int, object] = {}

    def _fn(self, t_pad: int):
        fn = self._jits.get(t_pad)
        if fn is None:
            import jax

            from beholder_tpu.models.serving import kv_prefill_chunks

            fn = jax.jit(
                lambda p, f, ln: kv_prefill_chunks(
                    self.model, p, f, ln, self.page_size
                )
            )
            self._jits[t_pad] = fn
        return fn

    def prefill(self, feats_np, t: int):
        """Prefill one request's (t, F) features; returns
        ((,) admit prediction, per-layer k chunks, per-layer v chunks,
        live page count) — device arrays on THIS worker's device,
        ready for :meth:`PageTransferEngine.handoff`."""
        import jax.numpy as jnp
        import numpy as np

        t_pad = -(-t // self.page_size) * self.page_size
        n_pages = -(-t // self.page_size)
        padded = np.pad(feats_np, ((0, t_pad - feats_np.shape[0]), (0, 0)))
        pred, chunks_k, chunks_v = self._fn(t_pad)(
            self.params, jnp.asarray(padded)[None], jnp.int32(t)
        )
        return pred, chunks_k, chunks_v, n_pages


class PageTransferEngine:
    """Moves prefilled KV chunks to the owning decode shard.

    Counts every handoff host-side (``transfers`` / ``pages`` /
    ``bytes`` mirror the ``beholder_cluster_transfer*`` counters when
    a registry is wired, and exist without one so tests and the bench
    can read them directly), and records a recorder-only ``transfer``
    phase event per handoff with the (src, dst) worker pair — the
    timeline shows WHICH workers the pages crossed between, one track
    per worker in the Chrome trace export.

    ``retry`` (a :class:`~beholder_tpu.reliability.policy.RetryPolicy`)
    bounds the ``device_put`` hop: a transient fabric fault retries
    with jittered backoff, a persistent one surfaces as a typed
    :class:`TransferFailed` (counted on ``failed`` and
    ``beholder_cluster_transfer_failed_total``) for the router to act
    on — never an anonymous exception out of the tick loop.
    ``fail_next`` is the deterministic chaos hook (the
    ``transfer_corruption`` leg of
    :class:`~beholder_tpu.reliability.chaos.WorkerFault`)."""

    def __init__(self, instruments=None, flight_recorder=None, retry=None):
        self.instruments = instruments
        self.flight_recorder = flight_recorder
        self.retry = retry
        self.transfers = 0
        self.pages = 0
        self.bytes = 0
        #: terminal transfer failures (retries exhausted)
        self.failed = 0
        #: successful hops by plane (the ``op`` prefix before the first
        #: ``.`` — "transfer", "drain", "fabric", "mirror"), so the
        #: bench and tests can attribute wire traffic to the subsystem
        #: that moved it without parsing the flight ring
        self.ops_by_plane: dict[str, int] = {}
        #: chaos injections observed
        self.faults_injected = 0
        self._fail_next = 0
        self._fail_exc: Exception | None = None
        self._fail_worker: str | None = None

    # -- fault injection + the retried device hop ------------------------

    def fail_next(
        self, n: int, exc: Exception | None = None,
        worker: str | None = None,
    ) -> None:
        """Script the next ``n`` device hops to fail (chaos: a
        corrupted/failed fabric transfer). ``worker`` scopes the fault
        to hops whose DESTINATION is that worker (a broken link to one
        shard, the realistic fabric fault); None faults any hop.
        Default exception is ``ConnectionError`` — retryable, so ``n``
        below the retry budget exercises recovery-by-retry and ``n``
        at/above it the terminal :class:`TransferFailed` path."""
        self._fail_next = int(n)
        self._fail_exc = exc
        self._fail_worker = worker

    def _device_put(self, tree, device, dst: str | None = None):
        """The fault-gated hop; ``device=None`` is the no-hop local
        path (same gate, so chaos behaves identically on one device)."""
        if self._fail_next > 0 and (
            self._fail_worker is None or self._fail_worker == dst
        ):
            self._fail_next -= 1
            self.faults_injected += 1
            raise (
                self._fail_exc
                if self._fail_exc is not None
                else ConnectionError("chaos: injected page-transfer fault")
            )
        if device is None:
            return tree
        import jax

        return jax.device_put(tree, device)

    def raw_move(self, tree, device, *, src: str, dst: str, op: str):
        """One retried device hop. ``device=None`` is the single-device
        fallback (no hop, but the chaos/fault surface still applies so
        tests behave identically on one device). Terminal failure
        raises :class:`TransferFailed` and counts it."""
        plane = op.split(".", 1)[0]
        try:
            if self.retry is not None:
                out = self.retry.call(
                    lambda: self._device_put(tree, device, dst=dst),
                    op=op,
                )
            else:
                out = self._device_put(tree, device, dst=dst)
            self.ops_by_plane[plane] = self.ops_by_plane.get(plane, 0) + 1
            return out
        except Exception as err:  # noqa: BLE001 - typed terminal surface
            self.failed += 1
            if self.instruments is not None:
                self.instruments.transfer_failed_total.inc()
            raise TransferFailed(src, dst, err) from err

    @staticmethod
    def _live_bytes(chunks_k, chunks_v, n_pages: int) -> int:
        """Bytes of LIVE pages moved (the dead static-width tail is
        masked off at adopt, but device_put moves it too — the counter
        reports the page-granular payload, the honest fabric figure)."""
        per_page = 0
        for c in (*chunks_k, *chunks_v):
            # (p_max, Hkv, Dh, page) -> bytes of one page row
            per_page += int(c.size // c.shape[0]) * c.dtype.itemsize
        return per_page * int(n_pages)

    def handoff(self, pred, chunks_k, chunks_v, n_pages: int, dst_device,
                src: str, dst: str):
        """Move (pred, chunks) to ``dst_device``; returns the moved
        pytree. ``dst_device=None`` keeps the arrays where they are
        (single-device fallback) but still counts — the handoff
        happened, the fabric hop was just free. The hop rides
        :meth:`raw_move`'s bounded retry; a persistent fault surfaces
        as :class:`TransferFailed`."""
        fr = self.flight_recorder
        ts = time.time() if fr is not None else 0.0
        # edge id: None unless a flight plane is bound (the armed write
        # side) — with one, the send instant lands on the SOURCE
        # worker's track and the transfer record on the destination's,
        # and the shared id becomes a cross-worker flow arrow + a skew
        # constraint in flightplane.merge()
        edge = fr.next_edge() if fr is not None else None
        if edge is not None:
            fr.instant(
                "transfer.send", worker=src, dst=dst,
                pages=int(n_pages), edge=edge,
            )
        t0 = time.perf_counter()
        pred, chunks_k, chunks_v = self.raw_move(
            (pred, chunks_k, chunks_v), dst_device,
            src=src, dst=dst, op=f"transfer.{src}->{dst}",
        )
        nbytes = self._live_bytes(chunks_k, chunks_v, n_pages)
        self.transfers += 1
        self.pages += int(n_pages)
        self.bytes += nbytes
        if self.instruments is not None:
            self.instruments.observe_transfer(int(n_pages), nbytes)
        if fr is not None:
            edge_note = {"edge": edge} if edge is not None else {}
            fr.record(
                "transfer", ts, time.perf_counter() - t0,
                worker=dst, src=src, pages=int(n_pages), bytes=nbytes,
                **edge_note,
            )
        return pred, chunks_k, chunks_v
