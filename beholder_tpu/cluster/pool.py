"""The sharded KV pool: per-shard page accounting + mesh placement.

A shard's device-side pool IS a
:class:`~beholder_tpu.models.serving.PagedKVState` — per-shard free
stack, per-shard refcounts, every allocator invariant already pinned
by the serving tests holds per shard by construction. What this
module adds is the HOST half the cluster router schedules on:

- :class:`ShardPool` — one shard's worst-case page arithmetic
  (``committed`` mirrors what the shard batcher's own ``free_pages``
  closure would compute: queued + in-flight requests' worst-case page
  needs; the device allocator stays the safety net, exactly the
  single-engine discipline), plus the shard's mesh device and name.
- :class:`ShardedPoolView` — the aggregate the router routes over:
  total capacity scales with shard count, ``least_pressure`` picks
  the shard with the most free pages (ties to the lowest id, so
  routing is deterministic on a replayed stream).

Placement rides :func:`beholder_tpu.parallel.mesh.
serving_shard_devices` — one device per shard, cycling over the mesh
(on a CPU test mesh the forced host-platform devices; on TPU the
chips), so each shard's pages and page table live on their own chip
and the only cross-device traffic is the page-granular handoff
(:mod:`.transfer`).
"""

from __future__ import annotations


class ShardPool:
    """Host-side view of one decode shard's paged pool."""

    def __init__(self, shard_id: int, num_pages: int, device=None):
        self.shard_id = shard_id
        self.name = f"decode-{shard_id}"
        self.num_pages = int(num_pages)
        self.device = device
        #: worst-case pages reserved by queued + in-flight requests
        #: (host arithmetic — never a device read)
        self.committed = 0

    @property
    def free(self) -> int:
        return self.num_pages - self.committed

    def reserve(self, pages: int) -> None:
        self.committed += int(pages)

    def release(self, pages: int) -> None:
        self.committed -= int(pages)
        if self.committed < 0:  # defensive: accounting must never wedge
            self.committed = 0

    def fits(self, pages: int) -> bool:
        """Whether a request of worst-case ``pages`` can EVER run on
        this shard (the per-shard twin of ``_check_servable``'s pool
        bound; the per-seq table cap stays the batcher's check)."""
        return pages <= self.num_pages

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"ShardPool({self.name}, free={self.free}/{self.num_pages})"
        )


class ShardedPoolView:
    """The router's aggregate over every shard's page arithmetic."""

    def __init__(self, shards: list[ShardPool]):
        if not shards:
            raise ValueError("a cluster needs at least one shard pool")
        self.shards = shards

    @property
    def total_pages(self) -> int:
        return sum(s.num_pages for s in self.shards)

    @property
    def total_free(self) -> int:
        return sum(s.free for s in self.shards)

    def least_pressure(self, pools: list[ShardPool] | None = None) -> ShardPool:
        """The shard with the most free pages — over every shard, or
        the ``pools`` subset (the failover router routes over UP
        shards only; drain targets survivors). Ties break to the
        lowest shard id so a replayed stream routes identically —
        every caller MUST come through here so the tie-break can never
        silently diverge between routing and drain."""
        return max(
            self.shards if pools is None else pools,
            key=lambda s: (s.free, -s.shard_id),
        )

    def refresh_gauges(self, instruments) -> None:
        """Export every shard's free/committed pages on the labelled
        cluster gauges (no-op without instruments)."""
        if instruments is None:
            return
        for shard in self.shards:
            instruments.set_shard_pool(
                str(shard.shard_id), shard.free, shard.committed
            )


def place_paged_state(state, device):
    """Commit one shard's :class:`~beholder_tpu.models.serving.
    PagedKVState` (and anything else pytree-shaped, e.g. params) onto
    its mesh device. Committed state pins every jit the shard batcher
    dispatches to that device — the pool partition IS the placement."""
    import jax

    if device is None:
        return state
    return jax.device_put(state, device)
