"""Fault-tolerant cluster serving: failure detection, in-flight
request recovery, live-slot migration, and graceful drain.

PR 7 made serving multi-chip but fail-stop: a dead decode shard took
its pool — and every in-flight request on it — with it. This module is
the serving-plane twin of the I/O-plane reliability layer (breakers,
DLQ, chaos): failures are absorbed by the runtime, invisibly to the
caller, in the spirit of GPUOS's transparent-fallback primitives
(PAPERS.md). Everything is default-OFF behind
``instance.cluster.failover.*`` (``ClusterConfig.failover is None`` ⇒
byte-identical serving + exposition, the same contract as
cache/spec/recorder/cluster).

Four mechanisms, one engine:

- **Worker health + failure detection.** Every decode shard and
  prefill worker carries a heartbeat the router stamps at each
  scheduling event; :meth:`FailoverEngine.sweep` marks a WATCHED
  worker (one with a serve in progress) down once its beat goes stale
  past ``heartbeat_interval_s * miss_threshold``. Deterministic
  chaos (:class:`~beholder_tpu.reliability.chaos.WorkerFault`) injects
  the three failure kinds — ``kill`` (a typed :class:`WorkerKilled`
  raised mid-dispatch), ``hang`` (frozen beats), and
  ``transfer_corruption`` (scripted device-hop faults absorbed by the
  transfer engine's bounded retry or surfaced as
  :class:`~beholder_tpu.cluster.transfer.TransferFailed`). A down
  shard leaves the routing set (``_route``/``submit``/rebalance skip
  it) and degrades ``/healthz``.

- **In-flight request recovery.** Requests living on a failed shard
  are re-admitted on surviving shards by re-prefilling from host-side
  request state — the observed history plus any tokens already
  delivered — reusing the surviving shard's prefix cache where warm.
  Under exact greedy the replay is the SAME deterministic computation
  the dead shard was running, so recovered streams are
  bitwise-identical to an uninterrupted run. The synchronous
  schedulers deliver whole streams (nothing is emitted before a run
  completes, so a failed batch has zero delivered tokens by
  construction); an embedder that DOES deliver incrementally records
  delivered tokens on the :meth:`FailoverEngine.record_emitted`
  ledger, and :meth:`FailoverEngine.splice` — on the recovery path
  for every result — then guarantees no token index is ever emitted
  twice or skipped (the recomputed prefix is cross-checked, a
  divergent replay refused loudly).

- **Graceful drain** (:meth:`drain`). Planned decommission: queued
  work migrates to surviving intakes (FIFO and admission counters
  preserved), and the shard's RESIDENT pool state — live slots and
  warm prefix-cache pages — moves page-granularly through the
  transfer engine's retried device hop using the raw
  :func:`~beholder_tpu.models.serving.paged_export_pages` /
  :func:`~beholder_tpu.models.serving.paged_import_pages` pair: no
  dequantize/requantize round trip, so destination pages are
  byte-identical (bf16 AND int8), refcounts move wholesale (prefix
  sharing and fork structure survive), and the prefix-cache index is
  re-rooted onto the destination pool with its pins intact. Capacity
  can be removed with zero loss.

- **Deadline-aware degraded mode.** :class:`~beholder_tpu.models.
  serving.Request.deadline` threads :class:`~beholder_tpu.reliability.
  policy.Deadline` into the engine claim/tick loop — an expired
  request retires with an explicit
  :class:`~beholder_tpu.models.serving.DeadlineExceededResult`
  (partial tokens attached) instead of wedging a slot through a
  recovery storm — and the router sheds with ``reason=shard_down``
  when surviving capacity is insufficient, resolving affected
  requests to an explicit :class:`Dropped` outcome.

Observability: the ``beholder_failover_*`` catalog
(:class:`~beholder_tpu.cluster.instruments.FailoverMetrics`,
registered on demand) plus recorder-only ``failover`` / ``drain`` /
``heartbeat`` events on the owning worker's track
(``tools/trace_export.py`` renders them in the ``failover``
category). Artifact schema v7 carries
``failover: {recoveries, migrated_pages, deadline_exceeded}``.
"""

from __future__ import annotations

import time

import numpy as np

from .transfer import TransferFailed

#: worker lifecycle states. DOWN is a FAILURE (degrades /healthz);
#: DRAINED is a completed planned decommission — capacity is gone but
#: nothing was lost, and planned is not sick (the health check treats
#: only DOWN as degradation)
WORKER_UP = "up"
WORKER_DRAINING = "draining"
WORKER_DOWN = "down"
WORKER_DRAINED = "drained"


class WorkerKilled(RuntimeError):
    """A worker died mid-dispatch (chaos ``kill`` or a wrapped device
    fault). Typed so the router's recovery loop can distinguish a
    worker-level failure from a numerics/logic bug — only typed
    failures are recovered; anything else still raises."""

    def __init__(self, worker: str, kind: str = "kill"):
        super().__init__(f"worker {worker} {kind}ed mid-dispatch")
        self.worker = worker
        self.kind = kind


class NoHealthyShards(RuntimeError):
    """Every decode shard is down — nothing can serve."""


class DrainError(RuntimeError):
    """A graceful drain could not complete (capacity shortfall on the
    surviving shards, or the shard is not in a drainable state)."""


class Dropped:
    """Explicit terminal outcome for a request the failover layer could
    not serve: ``shard_down`` (surviving capacity insufficient) or
    ``recovery_limit`` (re-admitted more than
    ``max_recoveries_per_request`` times). Callers in failover mode
    receive this in the request's result position instead of an
    exception tearing down every other in-flight request."""

    __slots__ = ("reason",)
    outcome = "dropped"

    def __init__(self, reason: str):
        self.reason = reason

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Dropped({self.reason!r})"


class FailoverEngine:
    """The cluster's fault-tolerance brain: worker states, heartbeats,
    fault injection, recovery bookkeeping, and drain migration. Owned
    by a :class:`~beholder_tpu.cluster.router.ClusterScheduler` when
    ``ClusterConfig.failover`` is set; the router consults it at every
    scheduling decision and hands it failed shards' batches to
    recover. ``clock`` is injectable for deterministic heartbeat
    tests."""

    #: typed failures the router recovers from (anything else raises —
    #: a logic bug must never be silently absorbed as a worker fault)
    RECOVERABLE: tuple[type[BaseException], ...] = (
        WorkerKilled, TransferFailed,
    )

    def __init__(self, router, config, registry=None,
                 flight_recorder=None, clock=time.monotonic):
        self.router = router
        self.config = config
        self.flight_recorder = flight_recorder
        self._clock = clock
        self.instruments = None
        if registry is not None:
            from .instruments import FailoverMetrics

            self.instruments = FailoverMetrics(registry)
        self.states: dict[str, str] = {}
        for shard in router.shards:
            self._set_state(shard.pool.name, WORKER_UP)
        for worker in router.prefill_workers:
            self._set_state(worker.name, WORKER_UP)
        self.last_beat: dict[str, float] = {}
        #: workers with a serve in progress — the only ones a stale
        #: heartbeat can condemn (an idle worker is not a dead worker)
        self._watched: set[str] = set()
        #: chaos-hung workers: their beats freeze
        self._hung: set[str] = set()
        #: host-side tokens already DELIVERED per request key — the
        #: splice ledger that pins "no token emitted twice or skipped"
        self._emitted: dict = {}
        self.recovered_total = 0
        self.dropped_total = 0
        self.drains = 0
        self.migrated_pages = 0
        #: wall seconds of each recovery re-serve pass (bench evidence)
        self.recovery_walls: list[float] = []
        #: set by shutdown()'s final drain: draining shards stay
        #: servable (they only stopped admitting)
        self._drain_serving = False

    # -- worker state ----------------------------------------------------

    def _set_state(self, worker: str, state: str) -> None:
        self.states[worker] = state
        if self.instruments is not None:
            self.instruments.worker_up.set(
                1 if state == WORKER_UP else 0, worker=worker
            )

    def state(self, worker: str) -> str:
        return self.states.get(worker, WORKER_UP)

    def routable_shards(self) -> list:
        """Shards admissions may use: UP shards — plus DRAINING ones
        during a shutdown's final drain (they stopped ADMITTING, not
        serving; see :meth:`~beholder_tpu.cluster.router.
        ClusterScheduler.shutdown`)."""
        states = (
            (WORKER_UP, WORKER_DRAINING)
            if self._drain_serving
            else (WORKER_UP,)
        )
        return [
            s for s in self.router.shards
            if self.state(s.pool.name) in states
        ]

    def up_prefill_workers(self) -> list:
        return [
            w for w in self.router.prefill_workers
            if self.state(w.name) == WORKER_UP
        ]

    def adopt_worker(self, worker: str) -> None:
        """Register a worker that joined OUTSIDE :meth:`~beholder_tpu.
        cluster.router.ClusterScheduler.scale_up` — the fabric's
        standby promotion — as UP and beating, so routing and the
        sweep treat it exactly like a boot-time shard."""
        self._set_state(worker, WORKER_UP)
        self.heartbeat(worker)

    def mark_down(self, worker: str, kind: str) -> None:
        """Record a detected failure: the worker leaves the routing
        set, the failure counts by kind, and the timeline gets a
        ``failover`` instant on the worker's track."""
        if self.state(worker) == WORKER_DOWN:
            return
        self._set_state(worker, WORKER_DOWN)
        self._watched.discard(worker)
        if self.instruments is not None:
            self.instruments.worker_failures_total.inc(
                worker=worker, kind=kind
            )
        if self.flight_recorder is not None:
            self.flight_recorder.instant(
                "failover", worker=worker, reason=kind
            )

    # -- heartbeats ------------------------------------------------------

    def heartbeat(self, worker: str) -> None:
        if worker not in self._hung:
            self.last_beat[worker] = self._clock()

    def begin_serve(self, worker: str) -> None:
        self._watched.add(worker)
        self.heartbeat(worker)

    def end_serve(self, worker: str) -> None:
        self._watched.discard(worker)

    def sweep(self) -> None:
        """Failure-detection pass, run at every router entry point: a
        WATCHED worker whose heartbeat is stale past
        ``heartbeat_interval_s * miss_threshold`` is marked down
        (``kind="hang"``), with a recorder-only ``heartbeat`` instant
        carrying the observed staleness."""
        limit = (
            self.config.heartbeat_interval_s * self.config.miss_threshold
        )
        now = self._clock()
        for worker in list(self._watched):
            if self.state(worker) != WORKER_UP:
                continue
            age = now - self.last_beat.get(worker, now)
            if age > limit:
                if self.flight_recorder is not None:
                    self.flight_recorder.instant(
                        "heartbeat", worker=worker,
                        age_s=round(age, 3), limit_s=limit,
                    )
                self.mark_down(worker, "hang")

    # -- chaos injection -------------------------------------------------

    def inject_fault(self, fault) -> None:
        """Arm one deterministic :class:`~beholder_tpu.reliability.
        chaos.WorkerFault`. ``kill`` wraps the worker's dispatch entry
        point (the decode shard's tick program / the prefill worker's
        forward) to raise :class:`WorkerKilled` after
        ``after_dispatches`` successful calls — a genuine mid-stream
        death. ``hang`` freezes the worker's heartbeats (and watches
        it) so the next sweep condemns it. ``transfer_corruption``
        scripts the transfer engine's next hops to fail."""
        from beholder_tpu.reliability.chaos import (
            WORKER_HANG,
            WORKER_KILL,
            WORKER_TRANSFER_CORRUPTION,
        )

        if fault.kind == WORKER_TRANSFER_CORRUPTION:
            # scoped to hops whose DESTINATION is the faulted worker —
            # one broken link, not a cluster-wide fabric outage
            self.router.transfer.fail_next(
                fault.transfer_failures, worker=fault.worker
            )
            return
        if fault.kind == WORKER_HANG:
            self._hung.add(fault.worker)
            self._watched.add(fault.worker)
            limit = (
                self.config.heartbeat_interval_s
                * self.config.miss_threshold
            )
            self.last_beat[fault.worker] = self._clock() - limit - 1.0
            return
        if fault.kind != WORKER_KILL:
            raise ValueError(f"unknown worker-fault kind {fault.kind!r}")
        shard = next(
            (s for s in self.router.shards
             if s.pool.name == fault.worker), None
        )
        if shard is not None:
            self._wrap_kill(
                shard.batcher, "_tick_chunk", fault.worker,
                fault.after_dispatches,
            )
            return
        worker = next(
            (w for w in self.router.prefill_workers
             if w.name == fault.worker), None
        )
        if worker is None:
            raise ValueError(f"unknown worker {fault.worker!r}")
        self._wrap_kill(worker, "prefill", fault.worker,
                        fault.after_dispatches)

    @staticmethod
    def _wrap_kill(owner, attr: str, worker: str, after: int) -> None:
        orig = getattr(owner, attr)
        calls = [0]

        def killer(*args, **kwargs):
            calls[0] += 1
            if calls[0] > after:
                raise WorkerKilled(worker)
            return orig(*args, **kwargs)

        setattr(owner, attr, killer)

    # -- recovery bookkeeping --------------------------------------------

    def on_shard_failure(self, shard, err) -> str:
        """A typed worker failure escaped a shard's serve: mark it
        down; returns the failure kind. Recovery accounting happens
        separately (:meth:`count_recovered`) — only requests actually
        RE-ADMITTED count, not ones the recovery cap drops."""
        kind = getattr(err, "kind", "kill")
        self.mark_down(shard.pool.name, kind)
        return kind

    def count_recovered(self, worker: str, reason: str, n: int) -> None:
        """Account ``n`` requests genuinely re-admitted on surviving
        shards after ``worker`` failed with ``reason``."""
        if n <= 0:
            return
        self.recovered_total += n
        if self.instruments is not None:
            self.instruments.recoveries_total.inc(n, reason=reason)
        if self.flight_recorder is not None:
            self.flight_recorder.instant(
                "failover", worker=worker, reason=reason, recovered=n
            )

    def drop(self, reason: str, key=None) -> Dropped:
        """Resolve one request to an explicit :class:`Dropped` outcome.
        ``key`` (the request's timeline gid, when the caller has one)
        also emits a recorder-only ``req.dropped`` lifecycle instant —
        a lost request must be VISIBLE to the SLO layer (a recovery
        storm that drops requests while attainment reads 1.0 would be
        exactly the blind spot the burn-rate page exists to close)."""
        self.dropped_total += 1
        if self.instruments is not None:
            self.instruments.dropped_total.inc(reason=reason)
        if self.flight_recorder is not None and key is not None:
            self.flight_recorder.instant(
                "req.dropped", gid=key, reason=reason
            )
        return Dropped(reason)

    def shed(self, reason: str):
        """Shed one SUBMISSION on the counters of the queue that said
        no — a down shard's when one exists (it is the missing
        capacity), the first shard's otherwise. Deliberately not
        counted on ``dropped_total``: that series is reserved for
        in-flight requests resolved to a :class:`Dropped` outcome; a
        submit-time rejection already lands on the intake shed
        counters, and double-counting the same rejection across both
        families would inflate either read."""
        intake = next(
            (s.intake for s in self.router.shards
             if self.state(s.pool.name) != WORKER_UP),
            self.router.shards[0].intake,
        )
        return intake.shed(reason)

    # -- emitted-token ledger (the no-duplicate/no-skip pin) -------------

    def record_emitted(self, key, tokens) -> None:
        """Record tokens already DELIVERED for ``key`` (host-side
        request state). Recovery replays the full deterministic stream
        and splices past these — they are never re-emitted. The
        embedder-facing half of the ledger: the synchronous schedulers
        deliver whole streams only (their recoveries always splice an
        empty prefix); a caller streaming tokens out incrementally
        records each delivery here so a later recovery cannot
        re-emit or skip an index."""
        self._emitted[key] = np.asarray(tokens, np.float32)

    def splice(self, key, replayed):
        """Join a recovered request's replayed stream onto what was
        already delivered: the recomputed prefix must MATCH the
        delivered tokens bitwise (exact greedy is deterministic —
        a mismatch means corrupted recovery, raised loudly, never
        silently emitted), and only the suffix past the delivered
        count is new. With nothing delivered (the common batch case)
        the replay passes through untouched.

        The ledger entry is CONSUMED here — producing the request's
        final stream completes it, and run()'s keys (0..n-1) recur on
        every call, so a surviving entry would splice one run's stale
        tokens into the next run's same-keyed request (and leak
        unboundedly on a long-lived scheduler)."""
        emitted = self._emitted.pop(key, None)
        if emitted is None or len(emitted) == 0:
            return replayed
        replayed = np.asarray(replayed)
        if not np.array_equal(replayed[: len(emitted)], emitted):
            raise RuntimeError(
                f"recovered stream diverged from {len(emitted)} "
                f"already-emitted token(s) for request {key!r} — "
                "refusing to emit a token index twice with a "
                "different value"
            )
        return np.concatenate([emitted, replayed[len(emitted):]])

    def discard_emitted(self, keys) -> None:
        """Drop ledger entries for keys whose requests reached a
        TERMINAL outcome without a splice (Dropped, deadline) — the
        serve loop calls this once per batch so run()'s recurring key
        space can never inherit a dead run's tokens."""
        for key in keys:
            self._emitted.pop(key, None)

    # -- graceful drain --------------------------------------------------

    def drain(self, shard_id: int):
        """Planned decommission of one decode shard with zero loss:

        1. the shard leaves the routing set (``draining``);
        2. its queued intake migrates to surviving shards'
           queues (restocked — admission counters untouched, FIFO
           preserved via the cluster-wide submit sequence); items no
           surviving shard can ever hold shed ``shard_down``;
        3. its RESIDENT pool — live slots and prefix-cache pages —
           migrates byte-identically to the least-pressure surviving
           shard (:func:`migrate_pool`), refcounts and cache pins
           intact;
        4. the shard is marked down (``drained`` capacity is gone, but
           nothing on it was lost).

        Returns ``{"requeued": n, "migrated_pages": n, "target": name}``.
        """
        from beholder_tpu.reliability.shed import SHED_SHARD_DOWN

        router = self.router
        shard = router.shards[shard_id]
        name = shard.pool.name
        if self.state(name) != WORKER_UP:
            raise DrainError(f"{name} is {self.state(name)}, not up")
        self._set_state(name, WORKER_DRAINING)
        survivors = self.routable_shards()
        if not survivors:
            self._set_state(name, WORKER_UP)
            raise DrainError(
                f"cannot drain {name}: it is the last healthy shard"
            )
        ts = time.time()
        t0 = time.perf_counter()

        # 2. queued work moves first (it holds no device state). The
        # original enqueue stamps migrate WITH the items, so the
        # eventual claim still measures the full queue wait
        # a re-pack onto survivors, not a claim: waits stay OFF the
        # histogram (the claiming drain observes the one true wait);
        # the (items, stamps) pair is read atomically — a second-step
        # attribute read could be clobbered by a concurrent drain and
        # zip-drop every pending item
        pending, _, pending_stamps = shard.intake.drain_all(
            record_waits=False
        )
        requeued = 0
        moves: dict[int, list] = {s.pool.shard_id: [] for s in survivors}
        move_stamps: dict[int, list[float]] = {
            s.pool.shard_id: [] for s in survivors
        }
        for item, stamp in zip(pending, pending_stamps):
            request = item[1]
            need = router._need(request)
            shard.pool.release(need)
            fits = [s for s in survivors if router._fits(s, need)]
            if not fits:
                # ONE family records the loss: the request resolves to
                # a Dropped outcome (dropped_total) — it was already
                # counted admitted at submit, so re-shedding it on the
                # intake counters would double-report one request. The
                # submit-seq gid keeps the loss on the SLO books too
                router._pending_drops[item[0]] = self.drop(
                    SHED_SHARD_DOWN, key=f"s{item[0]}"
                )
                continue
            target = router.shards[
                router.pool_view.least_pressure(
                    [s.pool for s in fits]
                ).shard_id
            ]
            target.pool.reserve(need)
            moves[target.pool.shard_id].append(item)
            move_stamps[target.pool.shard_id].append(stamp)
            router._record_route(target, "drain", need, 0.0, time.time())
            requeued += 1
        for target in survivors:
            items = moves[target.pool.shard_id]
            if items:
                # flight-plane edge pair (armed only): the drained
                # worker's send instant + the survivor's restock mark
                # share one edge id — a cross-worker flow arrow and a
                # skew constraint in flightplane.merge()
                fr = self.flight_recorder
                edge = fr.next_edge() if fr is not None else None
                if edge is not None:
                    fr.instant(
                        "drain.send", worker=name,
                        dst=target.pool.name, requeued=len(items),
                        edge=edge,
                    )
                target.intake.restock(
                    items,
                    enqueued_at=move_stamps[target.pool.shard_id],
                )
                if edge is not None:
                    fr.instant(
                        "restock", worker=target.pool.name, src=name,
                        requeued=len(items), edge=edge,
                    )

        # 3. resident pool state moves byte-identically. A migration
        # failure (destination capacity, fabric) rolls the shard back
        # to UP — its pool is untouched (capacity checks precede any
        # destination write), its queued work already lives safely on
        # survivors, and the operator can retry after adding capacity;
        # a shard stranded in "draining" would be unroutable forever
        target = router.shards[
            router.pool_view.least_pressure(
                [s.pool for s in survivors]
            ).shard_id
        ]
        try:
            migrated = migrate_pool(
                shard.batcher, target.batcher, router.transfer,
                src=name, dst=target.pool.name,
            )
        except Exception:
            self._set_state(name, WORKER_UP)
            raise
        self.migrated_pages += migrated

        # 4. capacity is gone; nothing on it was lost. DRAINED, not
        # DOWN: a planned decommission must not degrade /healthz
        self._set_state(name, WORKER_DRAINED)
        self.drains += 1
        if self.instruments is not None:
            self.instruments.drains_total.inc()
            if migrated:
                self.instruments.migrated_pages_total.inc(migrated)
        if self.flight_recorder is not None:
            self.flight_recorder.record(
                "drain", ts, time.perf_counter() - t0,
                worker=name, dst=target.pool.name,
                pages=int(migrated), requeued=int(requeued),
            )
        router.pool_view.refresh_gauges(router.instruments)
        return {
            "requeued": requeued,
            "migrated_pages": int(migrated),
            "target": target.pool.name,
        }


# -- live migration: the raw page/slot move -------------------------------


def migrate_pool(src_batcher, dst_batcher, transfer=None, *,
                 src: str = "src", dst: str = "dst") -> int:
    """Move EVERYTHING resident in ``src_batcher``'s pool — live
    slots' pages, prefix-cache pages, their refcounts, and the cache
    index — into ``dst_batcher``'s pool, byte-identically.

    The unit is the page, the path is the transfer engine's retried
    device hop, and the representation is RAW
    (:func:`~beholder_tpu.models.serving.paged_export_pages` /
    :func:`~beholder_tpu.models.serving.paged_import_pages`): int8
    pools move their quantized values and scales verbatim — no
    dequantize/requantize round trip — so destination page content is
    bitwise what the source held (bf16 and int8, pinned by
    ``tests/test_cluster_chaos.py``). Refcounts move wholesale, so
    prefix sharing, fork structure, and the cache's own references
    survive; live slots land in free destination slots with their page
    tables rewritten through the old→new page mapping, and the prefix
    cache index is re-rooted with its pins (``live_users``) intact.

    Capacity pressure degrades gracefully: when the destination's
    free stack cannot hold every live source page, COLD prefix-cache
    pages are surrendered on the source first (the cache is a
    best-effort tenant; live-slot state always moves losslessly or
    the drain fails loudly with :class:`DrainError`).

    This is an ADMIN operation — the one place host readbacks are
    fine. A destination batcher that receives live slots is under
    external scheduling (the migrated slots are driven by ops-level
    ticks, as the chaos tests do); the cluster drain path only ever
    migrates between runs, where live state is cache pages.

    Returns the number of pages migrated."""
    import jax
    import jax.numpy as jnp

    def snapshot():
        state = src_batcher.state
        table, lens, active, refs = (
            np.asarray(x) for x in jax.device_get(
                (state.page_table, state.seq_lens, state.active,
                 state.page_ref)
            )
        )
        return table, lens, active, refs

    table, lens, active, refs = snapshot()
    live = np.nonzero(refs > 0)[0]
    if live.size == 0:
        return 0

    dst_free = int(jax.device_get(dst_batcher.state.free_top))
    if live.size > dst_free and src_batcher.prefix_cache is not None:
        # surrender cold cache pages on the source — live slots must
        # move losslessly, cache warmth is best-effort
        src_batcher._evict_cached(int(live.size) - dst_free)
        table, lens, active, refs = snapshot()
        live = np.nonzero(refs > 0)[0]
    if live.size > dst_free:
        raise DrainError(
            f"destination pool cannot hold {live.size} live pages "
            f"({dst_free} free) — add capacity before draining"
        )

    src_slots = np.nonzero(active)[0]
    free_slots: np.ndarray = np.zeros(0, np.int64)
    if src_slots.size:
        dst_active = np.asarray(
            jax.device_get(dst_batcher.state.active)
        )
        free_slots = np.nonzero(~dst_active)[0]
        if src_slots.size > free_slots.size:
            raise DrainError(
                f"destination has {free_slots.size} free slots for "
                f"{src_slots.size} live source slots"
            )

    # the raw move: export in pool representation (a group shard's
    # export merges member head-slices back to the single-device
    # full-head wire dialect), one retried device hop to the
    # destination batcher's wire endpoint (member 0 for a group),
    # import verbatim with the SOURCE refcounts
    ids = jnp.asarray(live, jnp.int32)
    chunks_k, chunks_v = src_batcher.export_pages(ids)
    dst_device = dst_batcher.transfer_device
    if transfer is not None:
        chunks_k, chunks_v = transfer.raw_move(
            (chunks_k, chunks_v), dst_device,
            src=src, dst=dst, op=f"drain.{src}->{dst}",
        )
    elif dst_device is not None:
        chunks_k, chunks_v = jax.device_put(
            (chunks_k, chunks_v), dst_device
        )
    ref_vals = jnp.asarray(refs[live], jnp.int32)
    new_state, dest = dst_batcher.import_pages(
        chunks_k, chunks_v, jnp.int32(int(live.size)), ref_vals,
    )
    dest = np.asarray(jax.device_get(dest))[: live.size]
    mapping = {int(o): int(d) for o, d in zip(live, dest)}

    # live slots: free destination slots, page tables rewritten
    # through the mapping (seq_lens/active carried over)
    page = src_batcher.page_size
    max_pages = int(new_state.page_table.shape[1])
    for i, s in enumerate(src_slots):
        d = int(free_slots[i])
        row = np.zeros(max_pages, np.int32)
        count = -(-int(lens[s]) // page)
        row[:count] = [mapping[int(p)] for p in table[s][:count]]
        new_state = new_state._replace(
            page_table=new_state.page_table.at[d].set(jnp.asarray(row)),
            seq_lens=new_state.seq_lens.at[d].set(
                jnp.int32(int(lens[s]))
            ),
            active=new_state.active.at[d].set(True),
        )
    dst_batcher.state = new_state

    # prefix-cache index: re-root chains onto the destination pool.
    # A chain already cached on the destination (same content, both
    # shards served it) keeps the destination's entry; the duplicate
    # migrated page drops the cache's one reference (and frees if
    # nobody else holds it) — the same collision rule insert() applies.
    src_cache = src_batcher.prefix_cache
    dst_cache = dst_batcher.prefix_cache
    if src_cache is not None and dst_cache is not None:
        duplicates: list[int] = []
        for key, parent, page_id, live_users in src_cache.export_entries():
            new_id = mapping[int(page_id)]
            if not dst_cache.adopt_entry(key, parent, new_id, live_users):
                duplicates.append(new_id)
        if duplicates:
            dup_ids, dup_alive = dst_batcher._page_id_batch(duplicates)
            dst_batcher.state = dst_batcher._cache_unref(
                dst_batcher.state, dup_ids, dup_alive
            )

    # the source is decommissioned: poison it so accidental reuse
    # fails loudly instead of serving from a migrated-away pool
    src_batcher._poisoned = True
    return int(live.size)
