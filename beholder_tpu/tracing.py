"""Distributed tracing: spans, samplers, reporters, and AMQP propagation.

The reference's shared library ships jaeger-client + opentracing
(/root/reference/yarn.lock:2000,2004 via triton-core), but index.js never
opens a span — tracing exists one layer down, inside the library
(SURVEY.md §5 "Tracing / profiling"). This module is that layer's
from-scratch equivalent: a jaeger-flavored tracer the service wires into
its consumers behind ``instance.tracing.enabled``.

Design (no opentracing/jaeger package exists in this image):

- ``SpanContext`` is the (trace_id, span_id, parent_id, flags) tuple;
  ``inject``/``extract`` speak the jaeger text-map format — one
  ``uber-trace-id: {trace:032x}:{span:016x}:{parent:016x}:{flags:x}``
  entry — carried in the AMQP basic-properties headers table
  (``Delivery.headers``), so producer→consumer traces stitch across
  processes exactly like jaeger's AMQP instrumentation.
- ``Span`` records operation, service, start/duration (epoch µs, jaeger's
  unit), tags, and logs; finished spans go to a pluggable reporter.
- Reporters: ``InMemoryReporter`` (tests/introspection), ``LogReporter``
  (one structured line per span through the pino-style logger),
  ``JsonlReporter`` (one jaeger-shaped JSON object per line, for offline
  ingestion).
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from secrets import randbits
from typing import Any, Callable

TRACE_HEADER = "uber-trace-id"
#: W3C Trace Context header (the flight plane's wire format): spans the
#: AMQP headers table, HTTP client requests, and Request metadata, so a
#: trace started at message receive survives every cross-process hop.
W3C_HEADER = "traceparent"
FLAG_SAMPLED = 0x01

#: The span context active in this task/thread — set by ``with span:``
#: blocks. Metric observations read it (metrics.py's observation log) to
#: stamp raw latency samples with the trace that produced them.
_ACTIVE: contextvars.ContextVar["SpanContext | None"] = contextvars.ContextVar(
    "beholder_active_span", default=None
)


def active_context() -> "SpanContext | None":
    """The :class:`SpanContext` of the innermost ``with span:`` block."""
    return _ACTIVE.get()


def current_trace_id() -> str | None:
    """The active trace id as a 32-hex string, or None outside any span —
    the cross-link key between jsonl span reports (``traceID``) and the
    metrics observation log."""
    ctx = _ACTIVE.get()
    return f"{ctx.trace_id:032x}" if ctx is not None else None


class SpanContext:
    """Immutable identity of one span in one trace."""

    __slots__ = ("trace_id", "span_id", "parent_id", "flags")

    def __init__(
        self, trace_id: int, span_id: int, parent_id: int = 0, flags: int = FLAG_SAMPLED
    ):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.flags = flags

    @property
    def sampled(self) -> bool:
        return bool(self.flags & FLAG_SAMPLED)

    def encode(self) -> str:
        return (
            f"{self.trace_id:032x}:{self.span_id:016x}"
            f":{self.parent_id:016x}:{self.flags:x}"
        )

    @classmethod
    def decode(cls, value: str) -> "SpanContext":
        trace_id, span_id, parent_id, flags = value.split(":")
        return cls(int(trace_id, 16), int(span_id, 16), int(parent_id, 16), int(flags, 16))

    def __repr__(self) -> str:
        return f"SpanContext({self.encode()})"


def inject(ctx: SpanContext, carrier: dict) -> dict:
    """Write ``ctx`` into a headers carrier (AMQP headers table / dict)."""
    carrier[TRACE_HEADER] = ctx.encode()
    return carrier


def extract(carrier: dict | None) -> SpanContext | None:
    """Read a :class:`SpanContext` out of a headers carrier; None if absent
    or malformed (a broken upstream header must never kill a consumer).
    Falls back to the W3C ``traceparent`` entry when the jaeger header is
    absent — read-side W3C support is always on (reading an extra header
    changes no bytes), only the WRITE side sits behind the flight-plane
    knob."""
    if not carrier:
        return None
    value = carrier.get(TRACE_HEADER)
    if value:
        try:
            return SpanContext.decode(str(value))
        except (ValueError, AttributeError):
            return None
    w3c = carrier.get(W3C_HEADER)
    if w3c:
        return from_traceparent(str(w3c))
    return None


def to_traceparent(ctx: SpanContext) -> str:
    """Render ``ctx`` as a W3C ``traceparent`` value
    (``00-{trace:032x}-{span:016x}-{flags:02x}``). The parent id does
    not travel — W3C carries only the direct ancestor, which is exactly
    what a child span needs."""
    return f"00-{ctx.trace_id:032x}-{ctx.span_id:016x}-{ctx.flags & 0xFF:02x}"


def from_traceparent(value: str) -> SpanContext | None:
    """Parse a W3C ``traceparent`` value; None on malformed input or the
    all-zero trace/span ids the spec marks invalid."""
    try:
        version, trace_hex, span_hex, flags_hex = value.strip().split("-")
        if len(trace_hex) != 32 or len(span_hex) != 16:
            return None
        int(version, 16)
        trace_id = int(trace_hex, 16)
        span_id = int(span_hex, 16)
        flags = int(flags_hex, 16)
    except (ValueError, AttributeError):
        return None
    if trace_id == 0 or span_id == 0:
        return None
    return SpanContext(trace_id, span_id, 0, flags)


def inject_traceparent(ctx: SpanContext, carrier: dict) -> dict:
    """Write the W3C form of ``ctx`` into a headers carrier (the flight
    plane's armed write side)."""
    carrier[W3C_HEADER] = to_traceparent(ctx)
    return carrier


class Span:
    """One timed operation. Finish exactly once; use as a context manager
    to get error tagging + finish on the way out."""

    __slots__ = (
        "context",
        "operation",
        "service",
        "start_us",
        "duration_us",
        "tags",
        "logs",
        "_tracer",
        "_t0_ns",
        "_activation",
    )

    def __init__(
        self,
        tracer: "Tracer",
        operation: str,
        context: SpanContext,
        tags: dict[str, Any] | None = None,
    ):
        self._tracer = tracer
        self.operation = operation
        self.service = tracer.service
        self.context = context
        self.start_us = int(time.time() * 1e6)  # epoch, for jaeger startTime
        self._t0_ns = time.perf_counter_ns()  # monotonic, for duration
        self.duration_us: int | None = None
        self.tags: dict[str, Any] = dict(tags or {})
        self.logs: list[dict[str, Any]] = []

    def set_tag(self, key: str, value: Any) -> "Span":
        self.tags[key] = value
        return self

    def log(self, event: str, **fields: Any) -> "Span":
        self.logs.append(
            {"timestamp_us": int(time.time() * 1e6), "event": event, **fields}
        )
        return self

    def finish(self) -> None:
        if self.duration_us is not None:
            return  # finish is idempotent, like opentracing's
        # monotonic delta: an NTP step between start and finish must not
        # corrupt (or negate) the one number tracing exists to measure
        self.duration_us = (time.perf_counter_ns() - self._t0_ns) // 1000
        self._tracer._forget(self)
        self._tracer._report(self)

    @property
    def finished(self) -> bool:
        return self.duration_us is not None

    # -- context manager ----------------------------------------------------
    def __enter__(self) -> "Span":
        # entering makes this the ACTIVE span: nested start_span calls
        # default to it as parent, and histogram observations inside the
        # block carry its trace id (metrics.py observation log)
        self._activation = _ACTIVE.set(self.context)
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        _ACTIVE.reset(self._activation)
        if exc is not None:
            self.set_tag("error", True)
            self.log("error", message=repr(exc))
        self.finish()

    def to_dict(self) -> dict[str, Any]:
        return {
            "traceID": f"{self.context.trace_id:032x}",
            "spanID": f"{self.context.span_id:016x}",
            "parentSpanID": f"{self.context.parent_id:016x}",
            "operationName": self.operation,
            "serviceName": self.service,
            "startTime": self.start_us,
            "duration": self.duration_us,
            "tags": self.tags,
            "logs": self.logs,
        }


class _NoopSpan:
    """Returned for unsampled traces: absorbs the Span API at near-zero
    cost and never reaches a reporter."""

    __slots__ = ("context", "_activation")

    def __init__(self, context: SpanContext):
        self.context = context

    def set_tag(self, key: str, value: Any) -> "_NoopSpan":
        return self

    def log(self, event: str, **fields: Any) -> "_NoopSpan":
        return self

    def finish(self) -> None:
        pass

    finished = True

    def __enter__(self) -> "_NoopSpan":
        # an UNSAMPLED span must still become the active context: spans
        # started inside it via the _ACTIVE fallback then inherit its
        # cleared sample flag instead of minting (and independently
        # re-sampling) a fresh root trace — a trace is never half-reported
        self._activation = _ACTIVE.set(self.context)
        return self

    def __exit__(self, *exc_info) -> None:
        _ACTIVE.reset(self._activation)


# -- reporters ---------------------------------------------------------------


class InMemoryReporter:
    """Collects finished spans; the test/introspection sink."""

    def __init__(self):
        self.spans: list[Span] = []
        self._lock = threading.Lock()

    def report(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def by_operation(self, operation: str) -> list[Span]:
        with self._lock:
            return [s for s in self.spans if s.operation == operation]


class LogReporter:
    """One structured log line per finished span."""

    def __init__(self, logger):
        self._logger = logger

    def report(self, span: Span) -> None:
        self._logger.info(
            "span %s %s trace=%032x span=%016x duration_us=%d tags=%s",
            span.service,
            span.operation,
            span.context.trace_id,
            span.context.span_id,
            span.duration_us,
            span.tags,
        )


class JsonlReporter:
    """One jaeger-shaped JSON object per line, append-only."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def report(self, span: Span) -> None:
        line = json.dumps(span.to_dict(), default=str)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")


# -- tracer ------------------------------------------------------------------


class Tracer:
    """Makes spans, samples, reports. One per service process.

    ``sample_rate`` is probabilistic head sampling (jaeger's
    ``probabilistic`` sampler): the root span decides, children inherit the
    decision through the flags bit so a trace is never half-reported.
    """

    def __init__(
        self,
        service: str,
        reporter=None,
        sample_rate: float = 1.0,
        _rand: Callable[[], float] | None = None,
    ):
        self.service = service
        self.reporter = reporter if reporter is not None else InMemoryReporter()
        self.sample_rate = sample_rate
        self._rand = _rand or __import__("random").random
        #: unfinished sampled spans, for the shutdown flush: a span open
        #: when the process exits would otherwise never reach a reporter
        #: (short-lived runs drop their tail)
        self._live: set[Span] = set()
        self._live_lock = threading.Lock()

    def start_span(
        self,
        operation: str,
        child_of: SpanContext | Span | None = None,
        tags: dict[str, Any] | None = None,
    ) -> Span | _NoopSpan:
        # accept Span AND _NoopSpan (an unsampled parent still carries
        # the context whose flags suppress the whole trace)
        parent = getattr(child_of, "context", child_of)
        if parent is None:
            # default to the active ``with span:`` block, so layers that
            # know nothing of each other (consumer -> serving scheduler)
            # still stitch into one trace
            parent = _ACTIVE.get()
        if parent is not None:
            ctx = SpanContext(
                trace_id=parent.trace_id,
                span_id=randbits(64) or 1,
                parent_id=parent.span_id,
                flags=parent.flags,  # inherit the head-sampling decision
            )
        else:
            sampled = self.sample_rate >= 1.0 or self._rand() < self.sample_rate
            ctx = SpanContext(
                trace_id=randbits(128) or 1,
                span_id=randbits(64) or 1,
                parent_id=0,
                flags=FLAG_SAMPLED if sampled else 0,
            )
        if not ctx.sampled:
            return _NoopSpan(ctx)
        span = Span(self, operation, ctx, tags)
        with self._live_lock:
            self._live.add(span)
        return span

    def _forget(self, span: Span) -> None:
        with self._live_lock:
            self._live.discard(span)

    def flush(self) -> int:
        """Finish (and report) every span still open — the shutdown /
        SIGTERM path: a consumer mid-message or a scheduler call cut off
        by process exit reports a truncated-but-present span (tagged
        ``flushed_at_shutdown``) instead of vanishing. Returns how many
        spans were flushed; safe to call repeatedly."""
        with self._live_lock:
            open_spans = list(self._live)
        for span in open_spans:
            span.set_tag("flushed_at_shutdown", True)
            span.finish()
        return len(open_spans)

    def _report(self, span: Span) -> None:
        try:
            self.reporter.report(span)
        except Exception:  # noqa: BLE001 - a broken sink must not kill work
            pass


def tracer_from_config(config, logger=None) -> Tracer | None:
    """Build the service tracer from ``instance.tracing.*`` config, or None
    when disabled (the default — the reference never opens spans either).

    Keys: ``enabled`` (bool), ``sample_rate`` (float, default 1.0),
    ``jsonl_path`` (str; also via $TRACE_JSONL — when set, spans append
    there instead of the log).
    """
    if not config.get("instance.tracing.enabled"):
        return None
    path = os.environ.get("TRACE_JSONL") or config.get("instance.tracing.jsonl_path")
    if path:
        reporter = JsonlReporter(str(path))
    elif logger is not None:
        reporter = LogReporter(logger)
    else:
        reporter = InMemoryReporter()
    rate = float(config.get("instance.tracing.sample_rate", 1.0))
    return Tracer("beholder", reporter=reporter, sample_rate=rate)
