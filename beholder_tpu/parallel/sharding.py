"""Shared pytree → PartitionSpec/NamedSharding utilities.

Every sharding scheme in this package follows the same two-step shape:
derive a PartitionSpec per leaf (from its path or its leading dim), then
wrap each spec in ``NamedSharding(mesh, spec)``. The wrap step lives here
once so schemes (tp/ep/pp/…) only define their spec rule.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def leading_axis_spec(leaf: Any, axis: str) -> P:
    """P(axis, None, ...) over the leaf's leading dim; replicated for
    scalars (a 0-d leaf has no dim to shard)."""
    ndim = getattr(leaf, "ndim", 0)
    if ndim < 1:
        return P()
    return P(axis, *([None] * (ndim - 1)))


def path_specs(
    tree: Any, rule: Callable[[tuple, Any], P]
) -> Any:
    """PartitionSpec pytree from a ``rule(path, leaf) -> P`` mapping."""
    return jax.tree_util.tree_map_with_path(rule, tree)


def shardings_from_specs(specs: Any, mesh: Mesh) -> Any:
    """Wrap every PartitionSpec leaf in ``NamedSharding(mesh, spec)``."""
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def path_key_names(path: tuple) -> set[str]:
    """The string key/name of every entry on a pytree path."""
    return {str(getattr(p, "key", getattr(p, "name", ""))) for p in path}
