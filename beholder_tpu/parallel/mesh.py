"""Device mesh and GSPMD shardings for the anomaly model.

Layout (megatron-style column->row tensor parallel over a 2-D dp×tp mesh):

- batch arrays:     P("dp")             — data parallel over the batch dim
- in_proj kernel:   P(None, "tp")       — column parallel (hidden sharded)
- in_proj bias:     P("tp")
- mid_proj kernel:  P("tp", None)       — row parallel (contracting dim
                                          sharded; GSPMD inserts the psum)
- everything else:  replicated

The same path-based rule shards the optimizer moments, because optax's
adam state mirrors the param tree (its leaf paths contain the layer
names). On TPU hardware the dp/tp collectives ride ICI; on CPU test
meshes (xla_force_host_platform_device_count) the same program runs
unchanged.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import path_key_names, path_specs, shardings_from_specs


def make_mesh(n_devices: int | None = None, tp: int | None = None) -> Mesh:
    """A 2-D ("dp", "tp") mesh over the first ``n_devices`` devices.

    ``tp`` defaults to 2 when the device count is even, else 1 (pure dp).
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    if n > len(devices):
        raise ValueError(f"requested {n} devices, have {len(devices)}")
    if tp is None:
        tp = 2 if n % 2 == 0 and n >= 2 else 1
    if n % tp:
        raise ValueError(f"n_devices={n} not divisible by tp={tp}")
    grid = np.array(devices[:n]).reshape(n // tp, tp)
    return Mesh(grid, ("dp", "tp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp"))


def _spec_for(path: tuple, leaf: Any) -> P:
    names = path_key_names(path)
    ndim = getattr(leaf, "ndim", 0)
    if "in_proj" in names and ndim == 2:
        return P(None, "tp")
    if "in_proj" in names and ndim == 1:
        return P("tp")
    if "mid_proj" in names and ndim == 2:
        return P("tp", None)
    return P()


def state_shardings(state: Any, mesh: Mesh) -> Any:
    """Sharding pytree for a whole TrainState (params + optimizer moments +
    step), derived from leaf paths."""
    return shardings_from_specs(path_specs(state, _spec_for), mesh)


def param_shardings(params: Any, mesh: Mesh) -> Any:
    return state_shardings(params, mesh)


def place_state(state: Any, mesh: Mesh) -> Any:
    """device_put the train state onto the mesh with its shardings."""
    return jax.device_put(state, state_shardings(state, mesh))


# -- serving-shard placement for the paged KV pool ---------------------------
#
# The cluster subsystem (beholder_tpu.cluster) partitions the paged
# serving state by WORKER, not by array axis: each decode shard's whole
# PagedKVState (pools + page table + free stack + refcounts) commits to
# one device, and the only cross-device traffic is the page-granular
# prefill->decode handoff. That is deliberately NOT a GSPMD sharding —
# the pool's free-stack pop/push is a sequential stack discipline that
# partitions cleanly per shard (per-shard free lists) but not across a
# named mesh axis.


def serving_shard_devices(n_workers: int, group_size: int = 1) -> list:
    """One device — or one device GROUP — per serving worker (decode
    shards first, then prefill workers), cycling over the available
    devices — on a forced host-platform CPU mesh the virtual devices,
    on TPU the chips. More workers than devices co-locate round-robin
    (capacity arithmetic still shards; the fabric hop degrades to a
    local copy).

    ``group_size=1`` (the default) keeps the existing shape: a flat
    list of single devices. ``group_size=N`` returns a list of
    N-tuples — worker ``i`` owns the contiguous device block
    ``[i*N, (i+1)*N)`` (mod the device count), so a group's members
    are ICI neighbours on real hardware and its per-tick collectives
    never cross another group's block. The device count must divide by
    ``group_size`` — a group straddling the wrap-around would alias
    its own members."""
    if n_workers < 1:
        raise ValueError(f"n_workers must be >= 1, got {n_workers}")
    if group_size < 1:
        raise ValueError(f"group_size must be >= 1, got {group_size}")
    devices = jax.devices()
    if group_size == 1:
        return [devices[i % len(devices)] for i in range(n_workers)]
    if len(devices) % group_size:
        raise ValueError(
            f"group_size {group_size} does not divide the device "
            f"count {len(devices)}"
        )
    if group_size > len(devices):
        raise ValueError(
            f"group_size {group_size} exceeds the device count "
            f"{len(devices)}"
        )
    return [
        tuple(
            devices[(i * group_size + m) % len(devices)]
            for m in range(group_size)
        )
        for i in range(n_workers)
    ]


def group_mesh(devices: tuple, axis: str = "tp") -> Mesh:
    """The ONE-group mesh a group-parallel decode engine shard_maps
    over: ``(1, N)`` — a degenerate ``dp`` axis of 1 (so the existing
    dp×tp param specs from :func:`seq_state_shardings` apply verbatim)
    and the group's members along ``axis``. Each group gets its OWN
    mesh over its own device tuple; groups never share a collective
    scope."""
    devices = tuple(devices)
    if not devices:
        raise ValueError("group_mesh needs at least one device")
    grid = np.array(devices, dtype=object).reshape(1, len(devices))
    return Mesh(grid, ("dp", axis))


# -- megatron tensor parallelism for the transformer ------------------------
#
# Column-parallel (output dim sharded over tp): q/k/v projections and the
# FFN up-projection — each tp shard holds whole heads / a slice of the
# hidden. Row-parallel (contracting dim sharded): the attention out-proj
# and FFN down-projection — GSPMD inserts one psum per row layer, exactly
# megatron's two all-reduces per block. Embedding, head, LayerNorms, and
# biases of row layers stay replicated.

_COLUMN = ("q_proj", "k_proj", "v_proj", "up")
_ROW = ("proj", "down")


def _seq_spec_for(path: tuple, leaf: Any) -> P:
    names = path_key_names(path)
    ndim = getattr(leaf, "ndim", 0)
    if any(n in names for n in _COLUMN):
        if ndim == 2:
            return P(None, "tp")  # (d_in, d_out/tp)
        if ndim == 1:
            return P("tp")  # bias lives with the sharded output dim
    if any(n in names for n in _ROW) and ndim == 2:
        return P("tp", None)  # (d_in/tp, d_out); psum after
    return P()


def seq_state_shardings(state: Any, mesh: Mesh) -> Any:
    """Sharding pytree for a sequence-model TrainState (params + adam
    moments + step), megatron column/row TP over the ``tp`` axis."""
    return shardings_from_specs(path_specs(state, _seq_spec_for), mesh)


def place_seq_state(state: Any, mesh: Mesh) -> Any:
    return jax.device_put(state, seq_state_shardings(state, mesh))


def sharded_seq_train_step(model, tx, mesh: Mesh, state_template: Any):
    """Jit the sequence-model train step over a ("dp", "tp"[, "sp"]) mesh:
    batch dp-sharded, every Block's q/k/v/up column-parallel and
    proj/down row-parallel; on a 3-D mesh with an "sp" axis the sequence
    dim of the data is context-parallel too (ring/Ulysses attention inside
    megatron TP inside dp). Returns fn(state, feats, targets)."""
    from beholder_tpu.models.sequence import seq_train_step

    shardings = seq_state_shardings(state_template, mesh)
    seq = "sp" if "sp" in mesh.axis_names else None
    data = NamedSharding(mesh, P("dp", seq, None))
    tgt = NamedSharding(mesh, P("dp", seq))
    return jax.jit(
        lambda state, f, t: seq_train_step(model, tx, state, f, t),
        in_shardings=(shardings, data, tgt),
        out_shardings=(shardings, replicated(mesh)),
    )


def sharded_train_step(tx, mesh: Mesh, state_template: Any):
    """Jit the pure train step with explicit in/out shardings on ``mesh``.

    Returns ``fn(state, windows, targets) -> (state, loss)``: batch
    dp-sharded, first two layers tp-sharded, GSPMD inserting the
    collectives. Callers place the state once with :func:`place_state`.
    """
    from beholder_tpu.models.anomaly import train_step

    shardings = state_shardings(state_template, mesh)
    data = batch_sharding(mesh)
    return jax.jit(
        lambda state, w, t: train_step(state, tx, w, t),
        in_shardings=(shardings, data, data),
        out_shardings=(shardings, replicated(mesh)),
    )


def tp_all_reduce(x: jax.Array, axis: str = "tp") -> jax.Array:
    """Megatron's ``g`` operator for hand-rolled tensor parallelism inside
    ``shard_map``: all-reduce forward, IDENTITY backward.

    ``jax.lax.psum``'s transpose is ``psum`` again, so a loss computed on
    tp-replicated activations hands every tp member an identical
    cotangent and the plain-psum backward multiplies gradients by the tp
    size. After a row-parallel matmul use THIS instead: the cotangent is
    already replicated, so the correct per-shard backward is the
    identity (Megatron-LM's conjugate-operator rule)."""

    @jax.custom_vjp
    def g(v):
        return jax.lax.psum(v, axis)

    g.defvjp(lambda v: (jax.lax.psum(v, axis), None), lambda _, dy: (dy,))
    return g(x)


def tp_replicate(x: jax.Array, axis: str = "tp") -> jax.Array:
    """Megatron's ``f`` operator: IDENTITY forward, all-reduce backward.

    Apply to a replicated activation entering column-parallel matmuls:
    each tp member computes only its shard's contribution to the input
    gradient, so the backward must sum them (the conjugate of
    :func:`tp_all_reduce`)."""

    @jax.custom_vjp
    def f(v):
        return v

    f.defvjp(lambda v: (v, None), lambda _, dy: (jax.lax.psum(dy, axis),))
    return f(x)
