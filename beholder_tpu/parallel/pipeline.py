"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

EXTENSION BEYOND THE REFERENCE (tritonmedia/beholder has no parallelism of
any kind — SURVEY.md §2 lists every strategy as absent; the reference is a
single-threaded Node event loop, /root/reference/index.js:1-160).

The TPU-idiomatic shape of pipeline parallelism:

- Per-stage parameters are stacked along a new leading "stage" axis and
  sharded ``P("pp", ...)`` — each device materializes only its own stage's
  weights, so an S-stage model needs 1/S of the parameter memory per chip.
- Activations flow around the ring with ``ppermute`` (riding ICI on real
  hardware). The schedule is the classic GPipe fill-and-drain: with M
  microbatches and S stages, M + S - 1 ticks run, every device executing
  the *same* program (its stage fn on its resident weights) each tick —
  no data-dependent control flow, one ``lax.scan``, fully jittable and
  differentiable (grads flow back through the ``ppermute`` transposes).
- Bubble fraction is (S-1)/(M+S-1); callers pick M >> S to amortize.

The same program runs on the virtual CPU test mesh and a TPU pod slice.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from beholder_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from .sharding import leading_axis_spec, shardings_from_specs


def stack_stage_params(stage_params: list[Any]) -> Any:
    """Stack S per-stage param pytrees along a new leading stage axis.

    All stages must be homotypic (same tree structure and leaf shapes) —
    the uniform-block transformer case pipeline parallelism is built for.
    """
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *stage_params)


def stage_specs(stacked: Any, axis: str = "pp") -> Any:
    """PartitionSpec pytree placing each leaf's leading (stage) dim on
    ``axis`` and leaving the rest replicated."""
    return jax.tree.map(lambda leaf: leading_axis_spec(leaf, axis), stacked)


def stage_shardings(stacked: Any, mesh: Mesh, axis: str = "pp") -> Any:
    """NamedSharding pytree for :func:`stage_specs` on ``mesh``."""
    return shardings_from_specs(stage_specs(stacked, axis), mesh)


def split_microbatches(x: jax.Array, num_microbatches: int) -> jax.Array:
    """(B, ...) -> (M, B/M, ...) microbatch stack for :func:`pipeline_forward`."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches={num_microbatches}"
        )
    return x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])


def merge_microbatches(x: jax.Array) -> jax.Array:
    """Inverse of :func:`split_microbatches`: (M, Bm, ...) -> (M*Bm, ...)."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def pipeline_forward(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
) -> jax.Array:
    """Run microbatches through S = ``mesh.shape[axis]`` pipeline stages.

    ``stage_fn(params, x) -> y`` must preserve shape and dtype (uniform
    stages). ``stacked_params`` leaves carry a leading stage dim of size S
    (see :func:`stack_stage_params`); ``x`` is an (M, Bm, ...) microbatch
    stack. Returns the (M, Bm, ...) outputs of the final stage, replicated,
    equal to applying the S stages in sequence.
    """
    s = mesh.shape[axis]
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != s:
            raise ValueError(
                f"stage leaf has leading dim {leaf.shape[0]}, mesh {axis}={s}"
            )

    def local(params_l: Any, xs: jax.Array) -> jax.Array:
        # each device sees a single stage's slice (leading dim 1)
        params = jax.tree.map(lambda leaf: leaf[0], params_l)
        idx = jax.lax.axis_index(axis)
        if s > 1:
            pad = jnp.zeros((s - 1, *xs.shape[1:]), xs.dtype)
            feed = jnp.concatenate([xs, pad])
        else:
            feed = xs
        ring = [(j, (j + 1) % s) for j in range(s)]

        def tick(state: jax.Array, inp: jax.Array):
            # stage 0 ingests the next microbatch; later stages keep the
            # activation ppermute delivered last tick
            state = jnp.where(idx == 0, inp, state)
            out = stage_fn(params, state)
            nxt = jax.lax.ppermute(out, axis, ring) if s > 1 else out
            return nxt, out

        _, ys = jax.lax.scan(tick, jnp.zeros_like(xs[0]), feed)
        # tick t on the last stage completes microbatch t-(S-1)
        done = ys[s - 1 :]
        keep = jnp.where(idx == s - 1, jnp.ones((), done.dtype), 0)
        return jax.lax.psum(done * keep, axis)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(stage_specs(stacked_params, axis), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, x)


def bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """Idle fraction of the 1F1B schedule in :func:`pipeline_train_step`.

    Each device runs ``M`` forward and ``M`` backward units over
    ``M + 2(S-1)`` ticks, each tick holding one F and one B slot: of the
    ``2(M + 2(S-1))`` slots, ``4(S-1)`` are idle (2(S-1) empty F slots plus
    2(S-1) empty B slots), so the bubble is ``2(S-1) / (M + 2(S-1))`` —
    equivalently, per-device utilization is ``M / (M + 2(S-1))``.
    """
    s, m = num_stages, num_microbatches
    return (2 * (s - 1)) / (m + 2 * (s - 1)) if s > 1 else 0.0


def pipeline_train_step(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    y: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
    dp_axis: str | None = None,
    param_specs: Any = None,
) -> tuple[jax.Array, Any]:
    """One 1F1B training step over ``S = mesh.shape[axis]`` pipeline stages.

    Unlike running :func:`pipeline_forward` under ``jax.grad`` (GPipe: all
    M microbatch activations live until the backward drain), this schedules
    forward AND backward units in the same ``lax.scan``: tick ``t`` runs
    stage ``i``'s forward for microbatch ``t - i`` and its backward for
    microbatch ``t - 2(S-1) + i``, with activations hopping the ``ppermute``
    ring forward and cotangents hopping it backward, one hop per tick. A
    device therefore holds at most ``2(S-1)+1`` in-flight residuals —
    activation memory is O(S), independent of M — and per-microbatch
    gradients accumulate into the device's own stage shard.

    Residuals store only the stage INPUT; the backward re-runs the stage
    through ``jax.vjp`` (rematerialized 1F1B, the TPU-idiomatic trade: one
    extra forward of FLOPs for an M-independent memory footprint).

    ``loss_fn(out_mb, y_mb) -> scalar`` is applied on the last stage;
    the step returns ``(loss, grads)`` where ``loss`` is the mean over
    microbatches (replicated scalar) and ``grads`` matches
    ``stacked_params`` — stacked on the stage axis and sharded
    ``P(axis)``, so NO activation-sized collective runs at the end (the
    masked-psum broadcast of :func:`pipeline_forward` is inference-only).

    Grads equal running the S stages sequentially under ``jax.grad`` with
    the same mean-over-microbatches loss (pinned by
    ``tests/test_pipeline.py``).

    With ``dp_axis`` (a second mesh axis), the per-microbatch batch dim is
    additionally data-parallel: each dp replica pipelines its own batch
    shard through the same 1F1B schedule, and gradients are averaged over
    dp with one psum at the end — the Megatron dp×pp composition. Stage
    params stay sharded over ``axis`` only (replicated across dp).
    NOTE: under ``dp_axis`` the per-shard losses are AVERAGED over dp, so
    ``loss_fn`` must be a mean over its batch dim (the usual convention);
    a sum-type loss would come out a factor of dp small.

    ``param_specs`` overrides the stage-parameter PartitionSpecs (default
    :func:`stage_specs`: leading dim on ``axis``, rest replicated) —
    THE hook for tensor parallelism INSIDE pipeline stages: pass specs
    that additionally shard weight dims over a ``tp`` mesh axis and have
    ``stage_fn`` run megatron's conjugate collective pair —
    :func:`~beholder_tpu.parallel.mesh.tp_replicate` before its
    column-parallel matmul and
    :func:`~beholder_tpu.parallel.mesh.tp_all_reduce` after its
    row-parallel matmul (a plain ``jax.lax.psum`` would double-count the
    replicated cotangent in the backward: psum's transpose is psum).
    Gradients come back with the same tp sharding and need no extra
    collective. Pinned by
    ``tests/test_pipeline.py::test_1f1b_composes_with_tp_inside_stages``.
    """
    s = mesh.shape[axis]
    m = x.shape[0]
    if y.shape[0] != m:
        raise ValueError(f"x has {m} microbatches, y has {y.shape[0]}")
    dp = 1
    if dp_axis is not None:
        if dp_axis not in mesh.axis_names:
            raise ValueError(
                f"dp_axis {dp_axis!r} not in mesh axes {mesh.axis_names}"
            )
        dp = mesh.shape[dp_axis]
        for name, arr in (("x", x), ("y", y)):
            if arr.ndim < 2 or arr.shape[1] % dp:
                raise ValueError(
                    f"{name} microbatch dim {arr.shape[1:2]} not divisible "
                    f"by {dp_axis}={dp}"
                )
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != s:
            raise ValueError(
                f"stage leaf has leading dim {leaf.shape[0]}, mesh {axis}={s}"
            )
    n_ticks = m + 2 * (s - 1)
    r = min(2 * (s - 1) + 1, m)  # residual ring slots actually reachable

    def local(params_l: Any, xs: jax.Array, ys: jax.Array):
        params = jax.tree.map(lambda leaf: leaf[0], params_l)
        idx = jax.lax.axis_index(axis)
        fwd_ring = [(j, (j + 1) % s) for j in range(s)]
        bwd_ring = [(j, (j - 1) % s) for j in range(s)]
        is_last = idx == s - 1

        zero_mb = jnp.zeros_like(xs[0])
        resid0 = jnp.zeros((r, *xs.shape[1:]), xs.dtype)
        gacc0 = jax.tree.map(jnp.zeros_like, params)

        def tick(carry, t):
            fwd_in, bwd_in, resid, gacc, lacc = carry

            # ---- forward unit: stage idx works on microbatch jf = t - idx
            jf = t - idx
            f_live = (jf >= 0) & (jf < m)
            jf_c = jnp.clip(jf, 0, m - 1)
            x_own = jax.lax.dynamic_index_in_dim(xs, jf_c, keepdims=False)
            x_in = jnp.where(idx == 0, x_own, fwd_in)
            out = stage_fn(params, x_in)

            # park the stage input for this microbatch's backward
            slot = jf_c % r
            resid = jnp.where(
                f_live,
                jax.lax.dynamic_update_index_in_dim(resid, x_in, slot, 0),
                resid,
            )

            # last stage seeds the cotangent from the loss in the SAME tick
            # (its backward microbatch jb == jf)
            y_own = jax.lax.dynamic_index_in_dim(ys, jf_c, keepdims=False)
            loss_mb, seed = jax.value_and_grad(loss_fn)(out, y_own)
            lacc = lacc + jnp.where(is_last & f_live, loss_mb, 0.0)

            # ---- backward unit: microbatch jb = t - 2(S-1) + idx
            jb = t - 2 * (s - 1) + idx
            b_live = (jb >= 0) & (jb < m)
            jb_c = jnp.clip(jb, 0, m - 1)
            x_res = jax.lax.dynamic_index_in_dim(
                resid, jb_c % r, keepdims=False
            )
            cot = jnp.where(is_last, seed, bwd_in)
            _, vjp_fn = jax.vjp(stage_fn, params, x_res)
            dparams, dx = vjp_fn(cot)
            gacc = jax.tree.map(
                lambda acc, g: acc + jnp.where(b_live, g, 0), gacc, dparams
            )

            fwd_out = jax.lax.ppermute(out, axis, fwd_ring) if s > 1 else out
            bwd_out = jax.lax.ppermute(dx, axis, bwd_ring) if s > 1 else dx
            return (fwd_out, bwd_out, resid, gacc, lacc), None

        (_, _, _, gacc, lacc), _ = jax.lax.scan(
            tick,
            (zero_mb, zero_mb, resid0, gacc0, jnp.zeros(())),
            jnp.arange(n_ticks),
        )
        # the only collectives: one scalar psum for the loss, and (under
        # dp) one grad-sized psum averaging the dp replicas' accumulators
        if dp_axis is None:
            loss = jax.lax.psum(lacc, axis) / m
            grads = jax.tree.map(lambda g: (g / m)[None], gacc)
        else:
            loss = jax.lax.psum(lacc, (axis, dp_axis)) / (m * dp)
            grads = jax.tree.map(
                lambda g: (jax.lax.psum(g, dp_axis) / (m * dp))[None], gacc
            )
        return loss, grads

    p_specs = (
        param_specs if param_specs is not None
        else stage_specs(stacked_params, axis)
    )
    data_spec = P(None, dp_axis) if dp_axis is not None else P()
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(p_specs, data_spec, data_spec),
        out_specs=(P(), p_specs),
        check_vma=False,
    )(stacked_params, x, y)
