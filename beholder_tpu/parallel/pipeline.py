"""GPipe-style pipeline parallelism over a ``pp`` mesh axis.

EXTENSION BEYOND THE REFERENCE (tritonmedia/beholder has no parallelism of
any kind — SURVEY.md §2 lists every strategy as absent; the reference is a
single-threaded Node event loop, /root/reference/index.js:1-160).

The TPU-idiomatic shape of pipeline parallelism:

- Per-stage parameters are stacked along a new leading "stage" axis and
  sharded ``P("pp", ...)`` — each device materializes only its own stage's
  weights, so an S-stage model needs 1/S of the parameter memory per chip.
- Activations flow around the ring with ``ppermute`` (riding ICI on real
  hardware). The schedule is the classic GPipe fill-and-drain: with M
  microbatches and S stages, M + S - 1 ticks run, every device executing
  the *same* program (its stage fn on its resident weights) each tick —
  no data-dependent control flow, one ``lax.scan``, fully jittable and
  differentiable (grads flow back through the ``ppermute`` transposes).
- Bubble fraction is (S-1)/(M+S-1); callers pick M >> S to amortize.

The same program runs on the virtual CPU test mesh and a TPU pod slice.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .sharding import leading_axis_spec, shardings_from_specs


def stack_stage_params(stage_params: list[Any]) -> Any:
    """Stack S per-stage param pytrees along a new leading stage axis.

    All stages must be homotypic (same tree structure and leaf shapes) —
    the uniform-block transformer case pipeline parallelism is built for.
    """
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *stage_params)


def stage_specs(stacked: Any, axis: str = "pp") -> Any:
    """PartitionSpec pytree placing each leaf's leading (stage) dim on
    ``axis`` and leaving the rest replicated."""
    return jax.tree.map(lambda leaf: leading_axis_spec(leaf, axis), stacked)


def stage_shardings(stacked: Any, mesh: Mesh, axis: str = "pp") -> Any:
    """NamedSharding pytree for :func:`stage_specs` on ``mesh``."""
    return shardings_from_specs(stage_specs(stacked, axis), mesh)


def split_microbatches(x: jax.Array, num_microbatches: int) -> jax.Array:
    """(B, ...) -> (M, B/M, ...) microbatch stack for :func:`pipeline_forward`."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches={num_microbatches}"
        )
    return x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])


def merge_microbatches(x: jax.Array) -> jax.Array:
    """Inverse of :func:`split_microbatches`: (M, Bm, ...) -> (M*Bm, ...)."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def pipeline_forward(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
) -> jax.Array:
    """Run microbatches through S = ``mesh.shape[axis]`` pipeline stages.

    ``stage_fn(params, x) -> y`` must preserve shape and dtype (uniform
    stages). ``stacked_params`` leaves carry a leading stage dim of size S
    (see :func:`stack_stage_params`); ``x`` is an (M, Bm, ...) microbatch
    stack. Returns the (M, Bm, ...) outputs of the final stage, replicated,
    equal to applying the S stages in sequence.
    """
    s = mesh.shape[axis]
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != s:
            raise ValueError(
                f"stage leaf has leading dim {leaf.shape[0]}, mesh {axis}={s}"
            )

    def local(params_l: Any, xs: jax.Array) -> jax.Array:
        # each device sees a single stage's slice (leading dim 1)
        params = jax.tree.map(lambda leaf: leaf[0], params_l)
        idx = jax.lax.axis_index(axis)
        if s > 1:
            pad = jnp.zeros((s - 1, *xs.shape[1:]), xs.dtype)
            feed = jnp.concatenate([xs, pad])
        else:
            feed = xs
        ring = [(j, (j + 1) % s) for j in range(s)]

        def tick(state: jax.Array, inp: jax.Array):
            # stage 0 ingests the next microbatch; later stages keep the
            # activation ppermute delivered last tick
            state = jnp.where(idx == 0, inp, state)
            out = stage_fn(params, state)
            nxt = jax.lax.ppermute(out, axis, ring) if s > 1 else out
            return nxt, out

        _, ys = jax.lax.scan(tick, jnp.zeros_like(xs[0]), feed)
        # tick t on the last stage completes microbatch t-(S-1)
        done = ys[s - 1 :]
        keep = jnp.where(idx == s - 1, jnp.ones((), done.dtype), 0)
        return jax.lax.psum(done * keep, axis)

    return jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(stage_specs(stacked_params, axis), P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, x)
