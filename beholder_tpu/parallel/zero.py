"""ZeRO-style optimizer-state sharding over the data-parallel axis.

EXTENSION BEYOND THE REFERENCE (no optimizers, tensors, or parallelism of
any kind exist there — SURVEY.md §0/§2). TPU-first take on ZeRO: instead
of hand-written reduce-scatter/all-gather schedules (the DeepSpeed/NCCL
formulation), we ANNOTATE — optimizer moments (and optionally the
params) get ``P("dp", ...)`` shardings and GSPMD lowers the training step
to the same collective schedule (grads reduce-scattered into the shard
each device owns, updated shards all-gathered for the next forward),
riding ICI on hardware.

- stage 2 (default): adam moments sharded over ``dp``; params replicated.
  Cuts optimizer memory by the dp degree; the update math is local to
  each shard.
- stage 3 (``shard_params=True``): parameters sharded too; XLA inserts
  the all-gather in the forward pass. Cheapest memory, one extra
  collective per step.

Leaves are sharded along their LARGEST dim divisible by the dp size
(P() when none divides; tiny leaves aren't worth a collective). Works for
any model here because the rule is shape-based, not name-based.
"""

from __future__ import annotations

from typing import Any, Callable

from typing import TYPE_CHECKING

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .sharding import shardings_from_specs

if TYPE_CHECKING:  # runtime import is lazy: models -> ops -> parallel would
    from beholder_tpu.models.train import TrainState  # cycle at import time

#: leaves smaller than this stay replicated: a collective per step costs
#: more than the bytes it would save
MIN_SHARD_ELEMENTS = 1024


def zero_leaf_spec(leaf: Any, dp: int, axis: str = "dp") -> P:
    """Shard the largest dim divisible by ``dp``; replicate if none."""
    shape = getattr(leaf, "shape", ())
    size = getattr(leaf, "size", 0)
    if not shape or size < MIN_SHARD_ELEMENTS:
        return P()
    divisible = [i for i, d in enumerate(shape) if d % dp == 0 and d >= dp]
    if not divisible:
        return P()
    best = max(divisible, key=lambda i: shape[i])
    spec = [None] * len(shape)
    spec[best] = axis
    return P(*spec)


def zero_state_specs(
    state: "TrainState", mesh: Mesh, axis: str = "dp", shard_params: bool = False
) -> "TrainState":
    """PartitionSpec pytree for a TrainState under ZeRO stage 2/3."""
    from beholder_tpu.models.train import TrainState

    dp = mesh.shape[axis]
    rule = lambda leaf: zero_leaf_spec(leaf, dp, axis)  # noqa: E731
    params = (
        jax.tree.map(rule, state.params)
        if shard_params
        else jax.tree.map(lambda _: P(), state.params)
    )
    opt_state = jax.tree.map(rule, state.opt_state)
    return TrainState(params, opt_state, P())


def zero_state_shardings(
    state: "TrainState", mesh: Mesh, axis: str = "dp", shard_params: bool = False
) -> "TrainState":
    return shardings_from_specs(
        zero_state_specs(state, mesh, axis, shard_params), mesh
    )


def zero_train_step(
    tx: optax.GradientTransformation,
    mesh: Mesh,
    state_template: "TrainState",
    loss_fn: Callable[[Any, Any, Any], jax.Array],
    axis: str = "dp",
    shard_params: bool = False,
):
    """Jit a dp-batch training step with ZeRO shardings.

    ``loss_fn(params, batch, targets) -> scalar``. Returns
    ``fn(state, batch, targets) -> (state, loss)`` with the input state
    donated (the sharded moments are updated in place, not copied).
    """
    from beholder_tpu.models.train import apply_gradients

    shardings = zero_state_shardings(state_template, mesh, axis, shard_params)
    data = NamedSharding(mesh, P(axis))

    def step(state, batch, targets):
        return apply_gradients(state, tx, lambda p: loss_fn(p, batch, targets))

    return jax.jit(
        step,
        in_shardings=(shardings, data, data),
        out_shardings=(shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def place_zero_state(
    state: "TrainState", mesh: Mesh, axis: str = "dp", shard_params: bool = False
) -> "TrainState":
    """device_put the train state with its ZeRO shardings."""
    return jax.device_put(
        state, zero_state_shardings(state, mesh, axis, shard_params)
    )
