"""Mesh + sharding helpers for the analytics extension.

EXTENSION BEYOND THE REFERENCE (which has no parallelism of any kind —
SURVEY.md §2 lists every strategy as absent). Scaling here follows the
idiomatic JAX recipe: build a ``jax.sharding.Mesh``, annotate array
shardings with ``NamedSharding``/``PartitionSpec``, jit the pure train
step, and let GSPMD insert the collectives.
"""

from .distributed import initialize, make_hybrid_mesh
from .mesh import (
    batch_sharding,
    make_mesh,
    param_shardings,
    place_seq_state,
    replicated,
    seq_state_shardings,
    sharded_seq_train_step,
    sharded_train_step,
    tp_all_reduce,
    tp_replicate,
)
from .pipeline import (
    bubble_fraction,
    merge_microbatches,
    pipeline_forward,
    pipeline_train_step,
    split_microbatches,
    stack_stage_params,
    stage_shardings,
)
from .zero import (
    place_zero_state,
    zero_state_shardings,
    zero_train_step,
)

__all__ = [
    "make_mesh",
    "batch_sharding",
    "param_shardings",
    "replicated",
    "seq_state_shardings",
    "place_seq_state",
    "sharded_seq_train_step",
    "sharded_train_step",
    "tp_all_reduce",
    "tp_replicate",
    "initialize",
    "make_hybrid_mesh",
    "pipeline_forward",
    "pipeline_train_step",
    "bubble_fraction",
    "stack_stage_params",
    "stage_shardings",
    "split_microbatches",
    "merge_microbatches",
    "place_zero_state",
    "zero_state_shardings",
    "zero_train_step",
]
