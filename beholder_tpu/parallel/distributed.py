"""Multi-host initialization and hybrid ICI/DCN meshes.

EXTENSION BEYOND THE REFERENCE (single Node process, SURVEY.md §2). The
scaling recipe for multi-host TPU pods:

1. every host calls :func:`initialize` (JAX's distributed runtime; no-op
   for single-process runs),
2. build a hybrid mesh with :func:`make_hybrid_mesh` — inner axes map to
   ICI (fast intra-slice links), the outer ``dp`` axis maps to DCN
   (between slices/hosts),
3. annotate shardings exactly as on one host; GSPMD routes collectives
   over the right fabric because the mesh encodes the topology.

Model code is identical single-host and multi-host — only mesh
construction differs, which is the point of doing it this way.
"""

from __future__ import annotations

import os

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize JAX's distributed runtime when running multi-process.

    Reads ``JAX_COORDINATOR``/``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID`` when
    arguments are omitted; silently a no-op for single-process runs (so the
    same entrypoint serves laptops and pods).
    """
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR")
    if coordinator_address is None:
        return  # single-process
    # NB: `x or env` would silently override an explicit process_id=0 with
    # a stale env var, corrupting cluster membership — test for None
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_hybrid_mesh(ici_tp: int = 2, axis_names=("dp", "tp")) -> Mesh:
    """A 2-D mesh whose ``tp`` axis stays inside a slice (ICI) and whose
    ``dp`` axis spans slices/hosts (DCN).

    Single-slice (or CPU test) runs degrade to a plain mesh with the same
    axis names, so calling code never branches.
    """
    devices = jax.devices()
    n = len(devices)
    if ici_tp > n or n % ici_tp:
        raise ValueError(f"ici_tp={ici_tp} does not divide device count {n}")
    procs = jax.process_count()
    if procs > 1:
        # assumes one slice per process (the common v5e/v5p pod-slice
        # deployment); per-slice dp must be a whole number
        per_slice = n // procs
        if per_slice * procs != n or per_slice % ici_tp:
            raise ValueError(
                f"{n} devices over {procs} processes with ici_tp={ici_tp}: "
                "need devices evenly split per process and divisible by "
                "ici_tp; for multi-host-per-slice topologies build the "
                "hybrid mesh explicitly with mesh_utils"
            )
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(per_slice // ici_tp, ici_tp),
            dcn_mesh_shape=(procs, 1),
        )
    else:
        grid = mesh_utils.create_device_mesh((n // ici_tp, ici_tp))
    return Mesh(grid, axis_names)
