"""Export flight-recorder events as Chrome trace-event JSON.

The :class:`~beholder_tpu.obs.FlightRecorder` ring (or its
:meth:`~beholder_tpu.obs.FlightRecorder.dump` JSONL) becomes one
``{"traceEvents": [...]}`` document loadable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing`` — a whole serving run
inspectable as a timeline: per-round phase slices (claim / admit /
draft / tick / wave / verify / readback / rollback / retire), instant
markers for prefix-cache lookups, pressure-deferral stalls and spec
accept/rollback outcomes, and each dispatch's kernel family + achieved
fraction of the host's measured matmul ceiling in its args.

Rows: each distinct trace id (one scheduler call / consumer message)
gets its own named track, so concurrent runs and the spans they cross-
link to (``$TRACE_JSONL`` / the metrics observation log, keyed on the
same trace id) line up visually. Untraced events share track 0.

CLI::

    python -m beholder_tpu.tools.trace_export events.jsonl -o trace.json
"""

from __future__ import annotations

import json
from typing import Any

PROCESS_NAME = "beholder-serving"


def load_events(path: str) -> list[dict[str, Any]]:
    """Read a :meth:`FlightRecorder.dump` JSONL file (one event per
    line; blank/corrupt lines are skipped, not fatal — a ring dumped
    mid-crash must still export)."""
    events: list[dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "name" in obj:
                events.append(obj)
    return events


#: cluster-worker tracks start here — far above any plausible count of
#: distinct trace ids in one ring, so the two tid namespaces never
#: collide
WORKER_TID_BASE = 100_000

#: failover-subsystem events: worker failures, drain migrations,
#: missed heartbeats, deadline retirements, and the fabric's standby
#: lifecycle (``standby`` spawn / ``promote``) render in their own
#: category (Perfetto can filter/color them apart from serving
#: phases), as instants — or, for ``drain``, a duration slice — on
#: the OWNING worker's track (they all carry a ``worker`` arg)
FAILOVER_EVENTS = frozenset(
    {"failover", "drain", "heartbeat", "deadline_exceeded",
     "promote", "standby"}
)


def chrome_trace(events: list[dict[str, Any]]) -> dict[str, Any]:
    """Convert recorder events to the Chrome trace-event format (JSON
    Array Format with metadata, the Perfetto-compatible subset).

    Events whose args carry a ``worker`` tag (the cluster subsystem's
    route/transfer/prefill/claim/tick events, and the failover
    subsystem's failover/drain/heartbeat instants) get ONE TRACK PER
    WORKER instead of one per trace id — a disaggregated serving run
    reads as parallel worker lanes (``worker decode-0``, ``worker
    prefill-0``, ...), with the page handoffs visible as slices on the
    destination worker's lane, worker deaths/missed beats as
    ``failover``-category instants on the dying worker's lane, and a
    graceful drain as a duration slice spanning the migration.
    Worker-less events keep the per-trace tracks."""
    tid_of: dict[str, int] = {}
    worker_tid_of: dict[str, int] = {}

    def tid(trace_id: str | None) -> int:
        if not trace_id:
            return 0
        if trace_id not in tid_of:
            tid_of[trace_id] = len(tid_of) + 1
        return tid_of[trace_id]

    def worker_tid(worker: str) -> int:
        if worker not in worker_tid_of:
            worker_tid_of[worker] = WORKER_TID_BASE + len(worker_tid_of)
        return worker_tid_of[worker]

    trace_events: list[dict[str, Any]] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": 1,
            "tid": 0,
            "args": {"name": PROCESS_NAME},
        },
        {
            "name": "thread_name",
            "ph": "M",
            "pid": 1,
            "tid": 0,
            "args": {"name": "untraced"},
        },
    ]
    for event in events:
        if event.get("ph") == "M":
            # flight-plane header lines (flight.meta / flight.plane)
            # carry ring identity, not timeline content
            continue
        trace_id = event.get("trace_id")
        worker = (event.get("args") or {}).get("worker")
        row = worker_tid(str(worker)) if worker else tid(trace_id)
        out: dict[str, Any] = {
            "name": event["name"],
            "ph": event.get("ph", "X"),
            "ts": int(event.get("ts_us", 0)),
            "pid": 1,
            "tid": row,
            "cat": (
                "failover"
                if event["name"] in FAILOVER_EVENTS
                else "serving"
            ),
            "args": {**event.get("args", {}), "trace_id": trace_id},
        }
        if out["ph"] == "X":
            out["dur"] = int(event.get("dur_us", 0))
        elif out["ph"] == "i":
            out["s"] = "t"  # thread-scoped instant marker
        trace_events.append(out)
    trace_events.extend(_flow_events(events, worker_tid))
    # one named track per trace: the trace id prefix is enough to join
    # against span reports without 32 hex chars of track label
    for trace_id, row in tid_of.items():
        trace_events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": row,
                "args": {"name": f"trace {trace_id[:12]}"},
            }
        )
    # ...and one named track per cluster worker
    for worker, row in worker_tid_of.items():
        trace_events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": 1,
                "tid": row,
                "args": {"name": f"worker {worker}"},
            }
        )
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def _flow_events(
    events: list[dict[str, Any]], worker_tid
) -> list[dict[str, Any]]:
    """Cross-worker flow arrows (``ph="s"`` start / ``ph="f"`` finish
    pairs sharing an ``id``) for a flight-plane merged timeline:

    - **edge pairs** — a ``<base>.send`` instant and the receiving
      event tagged with the same ``args["edge"]`` (transfer handoffs,
      drain restocks) become one arrow from the sender's track to the
      receiver's;
    - **recovery legs** — a ``req.recovered`` instant chains to the
      SAME gid's next ``req.claim`` on the surviving worker, so a
      failover re-admission reads as an arrow from the dying worker to
      wherever the request landed.

    Events without edges/gids produce nothing — a plane-less ring
    exports byte-identically to before."""
    flows: list[dict[str, Any]] = []

    def arrow(flow_id: str, name: str, src_ev, dst_ev) -> None:
        src_worker = (src_ev.get("args") or {}).get("worker")
        dst_worker = (dst_ev.get("args") or {}).get("worker")
        if not src_worker or not dst_worker:
            return
        flows.append({
            "name": name, "ph": "s", "id": flow_id, "pid": 1,
            "tid": worker_tid(str(src_worker)),
            "ts": int(src_ev.get("ts_us", 0)), "cat": "flow",
        })
        flows.append({
            "name": name, "ph": "f", "bp": "e", "id": flow_id, "pid": 1,
            "tid": worker_tid(str(dst_worker)),
            "ts": int(dst_ev.get("ts_us", 0)), "cat": "flow",
        })

    sends: dict[str, dict[str, Any]] = {}
    recvs: dict[str, dict[str, Any]] = {}
    recovered: list[dict[str, Any]] = []
    claims: dict[str, list[dict[str, Any]]] = {}
    for event in events:
        args = event.get("args") or {}
        edge = args.get("edge")
        name = str(event.get("name", ""))
        if edge:
            (sends if name.endswith(".send") else recvs)[str(edge)] = event
        if name == "req.recovered" and args.get("gid"):
            recovered.append(event)
        elif name == "req.claim" and args.get("gid"):
            claims.setdefault(str(args["gid"]), []).append(event)
    for edge in sorted(sends.keys() & recvs.keys()):
        send, recv = sends[edge], recvs[edge]
        base = str(send["name"]).removesuffix(".send")
        arrow(str(edge), base, send, recv)
    for k, rec in enumerate(recovered):
        gid = str((rec.get("args") or {})["gid"])
        rec_ts = int(rec.get("ts_us", 0))
        after = [
            c for c in claims.get(gid, ())
            if int(c.get("ts_us", 0)) >= rec_ts
        ]
        if after:
            nxt = min(after, key=lambda c: int(c.get("ts_us", 0)))
            arrow(f"rec-{gid}-{k}", "recovery", rec, nxt)
    return flows


def export(events_or_path, out_path: str) -> str:
    """Write the Chrome trace for ``events_or_path`` (a recorder-event
    list, a :class:`FlightRecorder`, or a dump JSONL path) to
    ``out_path``; returns the path."""
    if isinstance(events_or_path, str):
        events = load_events(events_or_path)
    elif hasattr(events_or_path, "events"):
        events = events_or_path.events()
    else:
        events = list(events_or_path)
    with open(out_path, "w") as f:
        json.dump(chrome_trace(events), f, indent=1)
        f.write("\n")
    return out_path


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description=(
            "Convert a flight-recorder JSONL dump to Chrome trace-event "
            "JSON (load the output in https://ui.perfetto.dev)"
        )
    )
    parser.add_argument("events", help="FlightRecorder.dump() JSONL path")
    parser.add_argument(
        "-o",
        "--out",
        default=None,
        help="output path (default: <events>.trace.json)",
    )
    args = parser.parse_args(argv)
    out = args.out or f"{args.events.removesuffix('.jsonl')}.trace.json"
    events = load_events(args.events)
    export(events, out)
    slices = sum(1 for e in events if e.get("ph", "X") == "X")
    instants = len(events) - slices
    print(
        f"wrote {out}: {slices} phase slices, {instants} instant markers "
        f"from {args.events}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
