"""Telemetry producer CLI.

The reference ecosystem's producers (the triton converter/downloader
services) publish ``api.TelemetryStatus`` / ``api.TelemetryProgress``
protos to RabbitMQ; beholder only consumes them. This tool is the
operator-side counterpart for smoke tests and backfills:

    beholder-publish status   --media-id m1 --status DEPLOYED
    beholder-publish progress --media-id m1 --status CONVERTING \
        --progress 55 --host enc-1
    beholder-publish status ... --url amqp://user:pw@host:5672/

``--url`` defaults to ``dyn('rabbitmq')`` resolution, same as the service.
"""

from __future__ import annotations

import argparse
import sys

from beholder_tpu import proto
from beholder_tpu.config import dyn
from beholder_tpu.mq.amqp import AmqpBroker
from beholder_tpu.service import PROGRESS_TOPIC, STATUS_TOPIC

STATUS_NAMES = list(proto.TelemetryStatusEntry.keys())


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="beholder-publish", description=__doc__.split("\n\n")[0]
    )
    parser.add_argument("--url", default=None, help="amqp:// broker URL")
    parser.add_argument(
        "--trace",
        action="store_true",
        help="start a trace: send an uber-trace-id header so the consumer's "
        "span joins this publish's trace",
    )
    sub = parser.add_subparsers(dest="kind", required=True)

    status = sub.add_parser("status", help="publish a status transition")
    progress = sub.add_parser("progress", help="publish a progress update")
    for p in (status, progress):
        p.add_argument("--media-id", required=True)
        p.add_argument("--status", required=True, choices=STATUS_NAMES)
        # accepted after the subcommand too; SUPPRESS keeps a post-subcommand
        # default from clobbering a pre-subcommand value
        p.add_argument("--url", default=argparse.SUPPRESS, help=argparse.SUPPRESS)
    progress.add_argument("--progress", type=int, required=True, metavar="PCT")
    progress.add_argument("--host", default="")
    return parser


def encode_message(args: argparse.Namespace) -> tuple[str, bytes]:
    status = proto.TelemetryStatusEntry.Value(args.status)
    if args.kind == "status":
        return STATUS_TOPIC, proto.encode(
            proto.TelemetryStatus(mediaId=args.media_id, status=status)
        )
    if not 0 <= args.progress <= 100:
        raise SystemExit(f"--progress must be 0..100, got {args.progress}")
    return PROGRESS_TOPIC, proto.encode(
        proto.TelemetryProgress(
            mediaId=args.media_id,
            status=status,
            progress=args.progress,
            host=args.host,
        )
    )


def main(argv: list[str] | None = None, broker: AmqpBroker | None = None) -> int:
    args = build_parser().parse_args(argv)
    topic, body = encode_message(args)

    headers = None
    span = None
    if getattr(args, "trace", False):
        from beholder_tpu.log import get_logger
        from beholder_tpu.tracing import LogReporter, Tracer, inject

        tracer = Tracer("beholder-publish", reporter=LogReporter(get_logger("trace")))
        span = tracer.start_span(
            "publish", tags={"topic": topic, "mediaId": args.media_id}
        )
        headers = inject(span.context, {})

    own_broker = broker is None
    if own_broker:
        broker = AmqpBroker(args.url or dyn("rabbitmq"))
        broker.connect(timeout=10)
    try:
        broker.publish(topic, body, headers=headers)
    finally:
        if span is not None:
            span.finish()
        if own_broker:
            broker.close()
    print(f"published {args.kind} for {args.media_id} to {topic}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
