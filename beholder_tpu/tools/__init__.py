"""Operator tools: the telemetry producer CLI (`beholder-publish`)."""
