"""Drift-proof perf gate: ratio metrics only, explicit noise bands.

BENCH_NOTES.md documents ±30% absolute swings on this shared host with
zero code changes — an absolute msg/s or TFLOP/s gate would have failed
the r01→r02 "regression" that was actually the machine. This gate
therefore compares a current bench artifact against the committed
baseline ONLY on environment-normalized ratios, each with an explicit
noise band:

==========================  ========================================  ======
metric                      why it survives host drift                fails
==========================  ========================================  ======
``mfu_vs_measured_matmul``  kernel vs a matmul ceiling measured in    lower
                            the same session, same harness
``native_speedup``          native wire loop vs python wire loop,     lower
                            same process, same host
``warm_cold_prefill_ratio`` warm prefill tokens / cold prefill        higher
                            tokens — pure token accounting
``mean_accept_len``         emitted tokens per verify slot-step —     lower
                            pure step accounting
``phase_pct:*``             % of recorded wall per engine phase       either
                            (schema-v5 attribution) — shape of the
                            step, not its speed
``stall_pct``               % of recorded wall spent waiting          higher
``ttft_tail_ratio``         p95/p50 TTFT from the same run's SLO      higher
                            digests — distribution shape, host
                            speed divides out
``slo_attainment``          fraction of requests inside every         lower
                            latency objective — request accounting
``fused_verify_ratio``      fused verify-round wall / dense-gather    higher
                            verify-round wall, slope-timed
                            interleaved in the same session — host
                            speed divides out
``wire_ingest_ratio``       native-batched / python-framed wire       lower
                            throughput, interleaved passes in the
                            same session — host speed divides out
``control_victim_ttft_
ratio``                     controlled / uncontrolled victim p95 on   higher
                            the SAME deterministic tenant-skew
                            replay, interleaved — host divides out
``control_tail_fairness_
ratio``                     victim p95 / flood p95 under control —    higher
                            both tenants ride the same rounds
``retention_overhead_
ratio``                     vault-armed / plain serving wall,         higher
                            slope-timed interleaved in the same
                            session — host speed divides out
``capacity_admitted_
ratio``                     fp8 admitted / int8 admitted on pools     lower
                            holding the SAME HBM byte budget — pure
                            admission accounting, host-independent
``fused_wave_ratio``        fused-wave / dense-wave run_waves wall,   higher
                            interleaved in the same session after a
                            bitwise stream assert — host divides out
``fabric_cross_shard_hit_
ratio``                     cross-shard prefix-index hits / lookups   lower
                            on a workload warm ONLY on another shard
                            — pure admission accounting
``replica_recovery_ratio``  replayed-recovery wall / standby-         lower
                            promotion recovery wall, both measured
                            interleaved in the same session after
                            bitwise stream asserts
``group_decode_latency_
ratio``                     group-of-N per-token decode wall /        higher
                            single-device wall on the SAME trace,
                            interleaved in the same session after a
                            bitwise stream assert — host divides out
==========================  ========================================  ======

Absolute figures (telemetry msg/s, flash TFLOP/s, tok/s) are REPORTED
in the verdict for the reader but never gated. A metric missing on
either side (e.g. accelerator sections skipped on a CPU runner) is
SKIPPED with a reason, never failed — degradation must be provable,
not inferred from absence.

The verdict is machine-readable JSON (schema ``beholder-perf-gate``)
printed to stdout (and ``--out``); the exit code is the gate.

CLI::

    python -m beholder_tpu.tools.perf_gate \\
        --baseline artifacts/bench_e2e.json \\
        --current  artifacts/bench_e2e.json

CI stashes the committed artifact before the bench run and compares the
fresh artifact against it; ``make perf-gate`` runs the self-compare on
the committed artifacts (a wiring check: every extractor must resolve
and every band must hold at ratio 1.0).
"""

from __future__ import annotations

import json
from typing import Any, Callable

SCHEMA = "beholder-perf-gate"

#: relative noise bands per gated ratio (the shared-host experiment in
#: BENCH_NOTES.md puts ABSOLUTE swings at ±30%; ratios are the stable
#: signal, so their bands can be tighter — but not zero: jit ordering,
#: allocator state and sampling keep a few percent of jitter even in
#: ratio space)
NOISE_BANDS: dict[str, float] = {
    "mfu_vs_measured_matmul": 0.25,
    "native_speedup": 0.30,
    "warm_cold_prefill_ratio": 0.30,
    "mean_accept_len": 0.15,
    # per-family achieved-fraction-of-measured-ceiling (attribution):
    # noisier than the offline mfu figure — host walls measured around
    # async dispatches — so the band is wider, but it is the ONLY
    # kernel-efficiency ratio available on runners where the accel
    # section is skipped, so it must be gated, not just carried
    "kernel_ceiling_frac": 0.40,
    # disaggregated-vs-colocated decode wall ratio (the cluster bench
    # runs both modes back to back on the SAME host, so the ratio is
    # environment-normalized by construction); compile caches, transfer
    # scheduling and CPU fan-out keep it the noisiest ratio here, hence
    # the widest band — what it must catch is the handoff path turning
    # from "a few percent around 1x" into a multiple
    "cluster_decode_latency_ratio": 0.50,
    # recovered-vs-uninterrupted decode wall ratio (the failover bench
    # kills a live shard mid-trace and re-serves its requests on the
    # survivor, back to back with an uninterrupted run on the same
    # host). The ratio structurally exceeds 1 — recovery REPLAYS the
    # dead shard's work — so the gate bands drift, not the overhead
    # itself: a regression is the recovery path getting materially
    # slower relative to its own committed baseline
    "failover_recovery_overhead_ratio": 0.50,
    # p95/p50 TTFT from the SLO digests (schema v8): both quantiles
    # come from the SAME run, so host speed divides out — the ratio is
    # the SHAPE of the latency distribution. A tail regression (one
    # request class stalling while the median holds) moves it where no
    # throughput ratio looks. Tails are the noisiest structural signal
    # here (a single straggler moves p95 on a 10-60-request bench), so
    # the band is the widest in the table — what it must catch is the
    # tail DETACHING from the median, not jitter around it
    "ttft_tail_ratio": 0.75,
    # fraction of requests inside every latency objective — pure
    # request accounting against objectives evaluated in-run; the
    # committed baseline's objectives are sized so healthy CI runs sit
    # at/near 1.0, making any material drop a real scheduling change
    "slo_attainment": 0.10,
    # fused/dense verify-round wall (schema v9): both sides slope-timed
    # INTERLEAVED in the same session, so host drift divides out — what
    # the band must catch is the fused path losing its edge (the ratio
    # rising back toward/past the dense oracle), not scheduler jitter
    # around the committed value
    "fused_verify_ratio": 0.40,
    # native-batched / python-framed wire throughput (schema v10): both
    # sides interleaved over the same sockets on the same host, so host
    # drift divides out — what the band must catch is the batched front
    # door losing its edge (the ratio falling back toward the
    # per-message loop), not scheduler jitter. Thread-scheduling
    # weather moves this more than the kernel ratios (four live threads
    # per pass), hence the kernel-width band
    "wire_ingest_ratio": 0.40,
    # controlled / uncontrolled victim p95 claim-relative latency on
    # the tenant-skew replay (schema v11): both replays run interleaved
    # on the same host over the SAME deterministic trace, so host speed
    # divides out — the ratio is the fair-admission plane's protection
    # factor. Degradation = the ratio RISING back toward 1.0 (the
    # victim re-buried behind the flood). Tails on a small replay are
    # noisy, hence the tail-width band
    "control_victim_ttft_ratio": 0.75,
    # controlled victim p95 / flooding-tenant p95 (same replay): the
    # per-tenant tail-fairness figure — under DRR the minority tenant's
    # tail must sit well under the flood's; degradation = the victim's
    # tail inflating toward the flood's. Same tail-width band
    "control_tail_fairness_ratio": 0.75,
    # vault-armed / plain serving wall (schema v13): both passes
    # slope-timed interleaved in the same session, so host drift
    # divides out — what the band must catch is always-on retention
    # stopping being cheap enough to leave on (the listener fold or
    # the keep-path assembly leaking into the serving wall), not
    # scheduler jitter around ~1x. Same interleaved-ratio width as
    # fused_verify_ratio
    "retention_overhead_ratio": 0.40,
    # fp8-admitted / int8-admitted on pools holding the same HBM byte
    # budget (schema v14): pure admission accounting — no walls at all,
    # so host speed is irrelevant and the figure is near-deterministic
    # (page geometry + the replayed request mix). The band only absorbs
    # request-mix tweaks between rounds; degradation = the ratio
    # FALLING toward 1.0 (fp8's scale side-channel no longer buying
    # pages over int8's f32 scales)
    "capacity_admitted_ratio": 0.10,
    # fused-wave / dense-wave run_waves wall (schema v14): both engines
    # interleaved in the same session on the same request replay, after
    # asserting their streams bitwise-equal — host drift divides out.
    # Same interleaved-ratio width as fused_verify_ratio; what it must
    # catch is the fused wave lane losing its edge, not jitter
    "fused_wave_ratio": 0.40,
    # cross-shard hits / lookups on the fabric bench's workload, whose
    # prefixes are warm ONLY on another shard (schema v15): pure
    # admission accounting — no walls, host-independent, and
    # near-deterministic (directory contents + the replayed request
    # mix). The band only absorbs request-mix tweaks between rounds;
    # degradation = the ratio FALLING (warm-anywhere admission
    # silently turning back into cold prefill)
    "fabric_cross_shard_hit_ratio": 0.30,
    # replayed-recovery wall / standby-promotion recovery wall, both
    # killed-shard passes measured interleaved in the same session
    # after bitwise stream asserts (schema v15) — host drift divides
    # out. Recovery walls on a small bench are tail-noisy (one
    # straggler pass moves the mean; observed run-to-run swing spans
    # ~0.4-0.7 on the CPU tunnel), hence the widest band here;
    # degradation = the ratio FALLING (the standby no longer buying
    # recovery time over replay)
    "replica_recovery_ratio": 0.60,
    # group-of-N / single-device per-token decode wall, both engines
    # interleaved in the same session on the same trace after bitwise
    # stream asserts (schema v16) — host drift divides out. On the
    # CPU host-platform mesh the ratio is structurally ABOVE 1: every
    # group tick pays tiled all_gather reassembly (params + attention
    # rows) through the XLA CPU collective emulation, a pure tax with
    # no ICI to hide it, and run-to-run collective scheduling moves it
    # like the cluster handoff ratio does. The gate bands drift, not
    # the tax itself: a regression is the group tick's collective
    # cost becoming a MULTIPLE of its committed baseline (e.g. an
    # accidental psum or a per-tick re-gather of frozen params), so
    # the band matches cluster_decode_latency_ratio's width
    "group_decode_latency_ratio": 0.50,
}

#: phase-time percentages compare in absolute percentage POINTS (a
#: 2% phase doubling to 4% is structure noise; a 30% phase becoming
#: 55% is a real shape change), and only phases carrying at least
#: PHASE_FLOOR_PCT of the baseline wall are gated
PHASE_BAND_POINTS = 20.0
PHASE_FLOOR_PCT = 5.0
STALL_BAND_POINTS = 20.0


def _get(obj: Any, *path: str) -> Any:
    for part in path:
        if not isinstance(obj, dict) or part not in obj:
            return None
        obj = obj[part]
    return obj


def _mfu(artifact: dict) -> float | None:
    return _get(
        artifact, "sections", "accel", "result", "flash",
        "mfu_vs_measured_matmul",
    )


def _native_speedup(artifact: dict) -> float | None:
    native = _get(artifact, "sections", "wire_native", "result", "rate")
    python = _get(artifact, "sections", "wire_python", "result", "rate")
    if not isinstance(native, (int, float)) or not isinstance(
        python, (int, float)
    ):
        return None
    if python <= 0:
        return None
    return float(native) / float(python)


def _warm_cold(artifact: dict) -> float | None:
    value = _get(artifact, "sections", "prefix_cache", "result", "value")
    return float(value) if isinstance(value, (int, float)) else None


def _mean_accept_len(artifact: dict) -> float | None:
    value = _get(artifact, "spec", "mean_accept_len")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # zero means no spec section ran, not "accepted nothing"
    return float(value)


def _cluster_decode_ratio(artifact: dict) -> float | None:
    value = _get(artifact, "sections", "cluster", "result", "value")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v6 artifact / cluster scenario not run
    return float(value)


def _failover_recovery_ratio(artifact: dict) -> float | None:
    value = _get(artifact, "sections", "failover", "result", "value")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v7 artifact / failover scenario not run
    return float(value)


def _ttft_tail_ratio(artifact: dict) -> float | None:
    p50 = _get(artifact, "slo", "ttft_p50_ms")
    p95 = _get(artifact, "slo", "ttft_p95_ms")
    if (
        not isinstance(p50, (int, float))
        or not isinstance(p95, (int, float))
        or p50 <= 0
        or p95 <= 0
    ):
        return None  # pre-v8 artifact / slo scenario not run
    return float(p95) / float(p50)


def _slo_attainment(artifact: dict) -> float | None:
    value = _get(artifact, "slo", "attainment")
    if not isinstance(value, (int, float)):
        return None
    # "scenario not run" (the empty v8 block) is distinguished by the
    # digest, not by attainment itself — a genuine 0% attainment (every
    # request bad) must still hit the gate, not silently skip it
    ttft = _get(artifact, "slo", "ttft_p50_ms")
    if not isinstance(ttft, (int, float)) or ttft <= 0:
        return None  # no request was ever digested: slo scenario absent
    return float(value)


def _wire_ingest_ratio(artifact: dict) -> float | None:
    value = _get(artifact, "ingest", "wire_ingest_ratio")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v10 artifact / ingest scenario not run
    return float(value)


def _fused_verify_ratio(artifact: dict) -> float | None:
    value = _get(artifact, "kernel", "fused_verify_ratio")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v9 artifact / kernel scenario not run
    return float(value)


def _control_victim_ratio(artifact: dict) -> float | None:
    value = _get(artifact, "control", "victim_ttft_ratio")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v11 artifact / control scenario not run
    return float(value)


def _control_tail_fairness(artifact: dict) -> float | None:
    value = _get(artifact, "control", "tail_fairness_ratio")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v11 artifact / control scenario not run
    return float(value)


def _retention_overhead(artifact: dict) -> float | None:
    value = _get(artifact, "retention", "overhead_ratio")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v13 artifact / retention scenario not run
    return float(value)


def _capacity_admitted_ratio(artifact: dict) -> float | None:
    value = _get(artifact, "capacity", "capacity_admitted_ratio")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v14 artifact / capacity scenario not run
    return float(value)


def _fused_wave_ratio(artifact: dict) -> float | None:
    value = _get(artifact, "capacity", "fused_wave_ratio")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v14 artifact / capacity scenario not run
    return float(value)


def _fabric_hit_ratio(artifact: dict) -> float | None:
    value = _get(artifact, "fabric", "cross_shard_prefix_hit_ratio")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v15 artifact / fabric scenario not run
    return float(value)


def _replica_recovery_ratio(artifact: dict) -> float | None:
    value = _get(artifact, "fabric", "replica_recovery_ratio")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v15 artifact / fabric scenario not run
    return float(value)


def _group_decode_ratio(artifact: dict) -> float | None:
    value = _get(artifact, "group", "group_decode_latency_ratio")
    if not isinstance(value, (int, float)) or value <= 0:
        return None  # pre-v16 artifact / group scenario not run
    return float(value)


#: (metric, extractor, fail direction): "lower" = degradation is the
#: current value falling below baseline * (1 - band); "higher" = rising
#: above baseline * (1 + band)
RATIO_CHECKS: list[tuple[str, Callable[[dict], float | None], str]] = [
    ("mfu_vs_measured_matmul", _mfu, "lower"),
    ("native_speedup", _native_speedup, "lower"),
    ("warm_cold_prefill_ratio", _warm_cold, "higher"),
    ("mean_accept_len", _mean_accept_len, "lower"),
    # disaggregated/colocated wall ratio: a handoff-path regression
    # shows as the ratio RISING (degradation direction "higher")
    ("cluster_decode_latency_ratio", _cluster_decode_ratio, "higher"),
    # recovered/uninterrupted wall ratio: a recovery-path regression
    # shows as the ratio RISING
    ("failover_recovery_overhead_ratio", _failover_recovery_ratio,
     "higher"),
    # p95/p50 TTFT: a latency-tail regression shows as the ratio RISING
    ("ttft_tail_ratio", _ttft_tail_ratio, "higher"),
    # objective attainment: degradation is the fraction FALLING
    ("slo_attainment", _slo_attainment, "lower"),
    # fused/dense verify wall: a fused-kernel regression shows as the
    # ratio RISING back toward the dense-gather cost
    ("fused_verify_ratio", _fused_verify_ratio, "higher"),
    # native-batched/python-framed wire throughput: an ingest-path
    # regression shows as the ratio FALLING toward the per-message loop
    ("wire_ingest_ratio", _wire_ingest_ratio, "lower"),
    # controlled/uncontrolled victim tail on the tenant-skew replay: a
    # fair-admission regression shows as the ratio RISING toward 1.0
    ("control_victim_ttft_ratio", _control_victim_ratio, "higher"),
    # victim/flood tail under control: fairness eroding shows as the
    # victim's tail RISING toward the flood's
    ("control_tail_fairness_ratio", _control_tail_fairness, "higher"),
    # vault-armed/plain serving wall: a retention-cost regression shows
    # as the ratio RISING away from "cheap enough to leave on"
    ("retention_overhead_ratio", _retention_overhead, "higher"),
    # fp8/int8 admitted on a matched byte budget: the capacity win
    # eroding shows as the ratio FALLING toward 1.0
    ("capacity_admitted_ratio", _capacity_admitted_ratio, "lower"),
    # fused-wave/dense-wave serving wall: the fused lane losing its
    # edge shows as the ratio RISING back toward the dense program
    ("fused_wave_ratio", _fused_wave_ratio, "higher"),
    # cross-shard hits/lookups on the warm-on-another-shard workload:
    # the warm-anywhere admission eroding shows as the ratio FALLING
    ("fabric_cross_shard_hit_ratio", _fabric_hit_ratio, "lower"),
    # replayed/standby-promotion recovery wall: the standby losing its
    # edge over replay shows as the ratio FALLING toward 1.0
    ("replica_recovery_ratio", _replica_recovery_ratio, "lower"),
    # group/single per-token decode wall: a group-tick regression (the
    # collective tax becoming a multiple) shows as the ratio RISING
    ("group_decode_latency_ratio", _group_decode_ratio, "higher"),
]

#: absolute figures carried in the verdict for the reader — NEVER gated
REPORTED_ABSOLUTES: list[tuple[str, Callable[[dict], Any]]] = [
    (
        "telemetry_msgs_per_sec",
        lambda a: _get(a, "sections", "service", "result", "value"),
    ),
    (
        "flash_tflops",
        lambda a: _get(a, "sections", "accel", "result", "flash", "value"),
    ),
    (
        "spec_on_tokens_per_sec",
        lambda a: _get(
            a, "sections", "spec", "result", "spec_on_tokens_per_sec"
        ),
    ),
    (
        "cluster_transferred_pages",
        lambda a: _get(a, "cluster", "transferred_pages"),
    ),
    (
        "failover_recoveries",
        lambda a: _get(a, "failover", "recoveries"),
    ),
    (
        "failover_recovery_latency_ms",
        lambda a: _get(
            a, "sections", "failover", "result", "recovery_latency_ms"
        ),
    ),
    # absolute SLO milliseconds: host-speed-dependent, reported only
    # (the gated figures are the tail ratio and attainment above)
    ("slo_ttft_p50_ms", lambda a: _get(a, "slo", "ttft_p50_ms")),
    ("slo_tpot_p50_ms", lambda a: _get(a, "slo", "tpot_p50_ms")),
    # absolute kernel walls behind fused_verify_ratio: host-speed-
    # dependent, reported only
    (
        "kernel_fused_verify_wall_s",
        lambda a: _get(a, "kernel", "fused_verify_wall_s"),
    ),
    (
        "kernel_dense_verify_wall_s",
        lambda a: _get(a, "kernel", "dense_verify_wall_s"),
    ),
    # absolute wire throughput behind wire_ingest_ratio: host-speed-
    # dependent (a 14x cross-host swing is on record), reported only
    (
        "wire_msgs_per_sec",
        lambda a: _get(a, "sections", "wire_native", "result", "rate"),
    ),
    (
        "ingest_native_msgs_per_sec",
        lambda a: _get(a, "ingest", "native_msgs_per_sec"),
    ),
    (
        "ingest_python_msgs_per_sec",
        lambda a: _get(a, "ingest", "python_msgs_per_sec"),
    ),
    # control-plane actuation evidence behind the fairness ratios:
    # workload-count-dependent, reported only
    (
        "control_uncontrolled_fairness_ratio",
        lambda a: _get(a, "control", "uncontrolled_fairness_ratio"),
    ),
    (
        "control_k_shed_events",
        lambda a: _get(a, "control", "k_shed_events"),
    ),
    (
        "control_scale_events",
        lambda a: _get(a, "control", "scale_events"),
    ),
    # retention evidence behind retention_overhead_ratio: keep rate and
    # kept-trace counts are policy/workload-dependent, reported only
    (
        "retention_kept_traces",
        lambda a: _get(a, "retention", "kept"),
    ),
    (
        "retention_keep_rate",
        lambda a: _get(a, "retention", "keep_rate"),
    ),
    (
        "retention_incidents",
        lambda a: _get(a, "retention", "incidents"),
    ),
    # capacity evidence behind capacity_admitted_ratio: raw admission
    # counts are pool-geometry/workload-dependent, reported only
    (
        "capacity_admitted_fp8",
        lambda a: _get(a, "capacity", "admitted_fp8"),
    ),
    (
        "capacity_admitted_int8",
        lambda a: _get(a, "capacity", "admitted_int8"),
    ),
    (
        "capacity_admitted_bf16",
        lambda a: _get(a, "capacity", "admitted_bf16"),
    ),
    # fabric evidence behind the v15 ratios: page counts and absolute
    # recovery milliseconds are workload/host-dependent, reported only
    (
        "fabric_pages_fetched",
        lambda a: _get(a, "fabric", "pages_fetched"),
    ),
    (
        "fabric_mirrored_pages",
        lambda a: _get(a, "fabric", "mirrored_pages"),
    ),
    (
        "fabric_replayed_recovery_ms",
        lambda a: _get(a, "fabric", "replayed_recovery_ms"),
    ),
    (
        "fabric_replica_recovery_ms",
        lambda a: _get(a, "fabric", "replica_recovery_ms"),
    ),
    # group-decode evidence behind the v16 ratio: absolute per-token
    # walls are host-dependent, reported only
    (
        "group_single_decode_ms_per_tok",
        lambda a: _get(a, "group", "single_decode_ms_per_tok"),
    ),
    (
        "group_decode_ms_per_tok",
        lambda a: _get(a, "group", "group_decode_ms_per_tok"),
    ),
]


def run_gate(baseline: dict, current: dict) -> dict[str, Any]:
    """Compare two bench artifacts; returns the machine-readable
    verdict dict (``verdict`` is ``"pass"`` or ``"fail"``)."""
    checks: list[dict[str, Any]] = []
    skipped: list[dict[str, str]] = []

    def check(
        metric: str,
        base: float | None,
        cur: float | None,
        band: float,
        direction: str,
        unit: str = "ratio",
    ) -> None:
        if base is None or cur is None:
            skipped.append(
                {
                    "metric": metric,
                    "reason": (
                        "missing in "
                        + ("baseline" if base is None else "current")
                    ),
                }
            )
            return
        if unit == "points":
            delta = cur - base
            if direction == "lower":
                ok = delta >= -band
            elif direction == "higher":
                ok = delta <= band
            else:  # either direction beyond the band fails
                ok = abs(delta) <= band
            detail = f"delta {delta:+.2f} points vs band ±{band:g}"
        else:
            floor = base * (1.0 - band)
            ceil = base * (1.0 + band)
            if direction == "lower":
                ok = cur >= floor
                detail = f"current {cur:.4g} vs floor {floor:.4g}"
            else:
                ok = cur <= ceil
                detail = f"current {cur:.4g} vs ceiling {ceil:.4g}"
        checks.append(
            {
                "metric": metric,
                "baseline": round(float(base), 6),
                "current": round(float(cur), 6),
                "band": band,
                "unit": unit,
                "fails_when": direction,
                "ok": ok,
                "detail": detail,
            }
        )

    for metric, extract, direction in RATIO_CHECKS:
        check(
            metric,
            extract(baseline),
            extract(current),
            NOISE_BANDS[metric],
            direction,
        )

    # schema-v5 attribution: the STEP SHAPE must not drift — a phase
    # silently eating the round (or stalls exploding) is a regression
    # even when every throughput ratio still clears its band. The UNION
    # of both sides' phases is gated: a phase absent from one summary
    # means 0% of that run's recorded wall (the summaries are total
    # decompositions), so a small-or-new phase GROWING to dominate is
    # exactly what the band must catch — only phases tiny on BOTH sides
    # are structure noise.
    base_phases = _get(baseline, "attribution", "phase_ms_pcts") or {}
    cur_phases = _get(current, "attribution", "phase_ms_pcts") or {}
    if base_phases or cur_phases:
        for phase in sorted(set(base_phases) | set(cur_phases)):
            base_pct = float(base_phases.get(phase, 0.0))
            cur_pct = float(cur_phases.get(phase, 0.0))
            if max(base_pct, cur_pct) < PHASE_FLOOR_PCT:
                continue
            check(
                f"phase_pct:{phase}",
                base_pct,
                cur_pct,
                PHASE_BAND_POINTS,
                "either",
                unit="points",
            )
    check(
        "stall_pct",
        _get(baseline, "attribution", "stall_pct"),
        _get(current, "attribution", "stall_pct"),
        STALL_BAND_POINTS,
        "higher",
        unit="points",
    )
    # per-family kernel efficiency vs the same-session measured ceiling
    # — gated per family present on both sides (a family absent from
    # one run's workload is a scenario change, not a regression)
    base_fracs = _get(baseline, "attribution", "kernel_ceiling_fracs") or {}
    cur_fracs = _get(current, "attribution", "kernel_ceiling_fracs") or {}
    for family in sorted(set(base_fracs) & set(cur_fracs)):
        check(
            f"kernel_ceiling_frac:{family}",
            base_fracs.get(family),
            cur_fracs.get(family),
            NOISE_BANDS["kernel_ceiling_frac"],
            "lower",
        )

    reported = {
        name: {"baseline": extract(baseline), "current": extract(current)}
        for name, extract in REPORTED_ABSOLUTES
    }
    failed = [c["metric"] for c in checks if not c["ok"]]
    verdict = {
        "schema": SCHEMA,
        "verdict": "fail" if failed else "pass",
        "failed": failed,
        "checks": checks,
        "skipped": skipped,
        "reported_not_gated": reported,
        "note": (
            "gated on environment-normalized ratios only; absolute "
            "msg/s and TFLOP/s are reported, never gated "
            "(BENCH_NOTES.md: ±30% host swings)"
        ),
    }
    if failed:
        # every band failure arrives pre-attributed: the ranked
        # phase/worker/family explanation rides the verdict so CI
        # says WHAT moved, not just that something did. Best-effort —
        # an explain error must never change the gate's answer.
        try:
            from beholder_tpu.tools.perf_explain import explain_artifacts

            verdict["explanation"] = explain_artifacts(baseline, current)
        except Exception as err:  # noqa: BLE001 - the gate is the product
            verdict["explanation_error"] = repr(err)
    return verdict


def main(argv: list[str] | None = None) -> int:
    import argparse

    from beholder_tpu.artifact import validate_file

    parser = argparse.ArgumentParser(
        description=(
            "Ratio-only perf gate between two bench artifacts "
            "(machine-readable verdict on stdout; exit 1 on fail)"
        )
    )
    parser.add_argument(
        "--baseline",
        default="artifacts/bench_e2e.json",
        help="committed baseline artifact (default: artifacts/bench_e2e.json)",
    )
    parser.add_argument(
        "--current",
        default="artifacts/bench_e2e.json",
        help="freshly produced artifact (default: self-compare)",
    )
    parser.add_argument(
        "--out", default=None, help="also write the verdict JSON here"
    )
    parser.add_argument(
        "--explain-out", default=None,
        help=(
            "also write the phase-level explanation JSON here "
            "(perf_explain over the same two artifacts, regardless of "
            "the gate's verdict — CI uploads it next to the verdict)"
        ),
    )
    args = parser.parse_args(argv)

    baseline = validate_file(args.baseline)
    current = validate_file(args.current)
    if current.get("schema_version", 0) < 5:
        raise SystemExit(
            f"current artifact {args.current} is schema "
            f"v{current.get('schema_version')}: the perf gate needs the "
            "v5 attribution section — regenerate with bench.py"
        )

    verdict = run_gate(baseline, current)
    verdict["baseline_path"] = args.baseline
    verdict["current_path"] = args.current
    rendered = json.dumps(verdict, indent=1)
    print(rendered)
    if args.out:
        with open(args.out, "w") as f:
            f.write(rendered + "\n")
    if args.explain_out:
        from beholder_tpu.tools.perf_explain import explain_artifacts

        with open(args.explain_out, "w") as f:
            f.write(
                json.dumps(
                    explain_artifacts(baseline, current), indent=1
                ) + "\n"
            )
    return 0 if verdict["verdict"] == "pass" else 1


if __name__ == "__main__":
    raise SystemExit(main())
