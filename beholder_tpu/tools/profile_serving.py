"""Step-level serving profile (round 5, VERDICT task 1).

Attributes paged-serving wall time on the real accelerator with two
tunnel-robust methods:

- SLOPE timing: run k chained device calls then ONE host readback; the
  per-call cost is the slope between k=2 and k=10, which cancels both
  the readback constant and dispatch latency. ``block_until_ready`` is
  NOT trusted here — on the axon tunnel it returns early for some
  programs (measured: a 127-tick scan "completed" in 0.3 ms against a
  3.4 ms HBM roofline).
- Latency probes: one-off costs of a jit dispatch, an eager op, an h2d
  copy, and a d2h readback (the ~65 ms constant that produced round 4's
  100x serving regression — see BENCH_NOTES.md).

Run: ``python -m beholder_tpu.tools.profile_serving``
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def _slope(fn, n1: int = 2, n2: int = 10, label: str | None = None) -> float:
    """Marginal per-call seconds of ``fn(k)`` (k chained calls + one
    readback): (T(n2) - T(n1)) / (n2 - n1), best of two rounds each.
    With ``label``, all four raw round times land in the artifact."""
    from beholder_tpu import artifact

    fn(2)  # warm/compile
    t1s = [fn(n1) for _ in range(2)]
    t2s = [fn(n2) for _ in range(2)]
    if label is not None:
        artifact.record_raw(label, "slope_timeit", t1s + t2s, k1=n1, k2=n2)
    return (min(t2s) - min(t1s)) / (n2 - n1)


def probe_latencies() -> dict[str, float]:
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((1024,))
    jax.block_until_ready(f(x))

    def best(fn, n=10):
        out = float("inf")
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            out = min(out, time.perf_counter() - t0)
        return out

    return {
        "jit_dispatch_ms": best(lambda: f(x)) * 1e3,
        "eager_op_ms": best(
            lambda: jax.block_until_ready(jnp.zeros((8,)) + 1)
        ) * 1e3,
        "h2d_8kb_ms": best(
            lambda: jax.block_until_ready(jnp.asarray(np.zeros(1024)))
        ) * 1e3,
        "d2h_readback_ms": best(lambda: float(np.asarray(f(x)[:1])[0]))
        * 1e3,
    }


def profile_serving() -> dict[str, float]:
    from beholder_tpu.models import (
        TelemetrySequenceModel,
        forecast_deltas,
        init_seq_state,
    )
    from beholder_tpu.models.serving import (
        ContinuousBatcher,
        Request,
        init_paged,
        paged_admit_batch,
        paged_wave,
        serve_wave,
    )
    from beholder_tpu.proto import TelemetryStatusEntry

    model = TelemetrySequenceModel(dim=512, heads=8, kv_heads=2, layers=4)
    t, horizon, slots = 256, 128, 8
    state, _, _ = init_seq_state(jax.random.PRNGKey(0), t, model=model)
    params = jax.tree.map(
        lambda p: p.astype(jnp.bfloat16)
        if p.dtype == jnp.float32 and p.ndim >= 2
        else p,
        state.params,
    )
    rng = np.random.default_rng(0)
    out: dict[str, float] = {}

    # fused serve_wave program (admit + 127-tick scan + release)
    serve = jax.jit(
        lambda p, s, f, ln, st: serve_wave(
            model, p, s, f, ln, st, horizon - 1
        )
    )
    pstate0 = init_paged(model, 32, 128, slots, 4)
    feats = jnp.asarray(rng.normal(size=(slots, t, 7)), jnp.float32)
    lens = jnp.full((slots,), t, jnp.int32)
    stats = jnp.full(
        (slots,), int(TelemetryStatusEntry.CONVERTING), jnp.int32
    )

    def run_serve(k):
        s = pstate0
        t0 = time.perf_counter()
        d = None
        for _ in range(k):
            d, s = serve(params, s, feats, lens, stats)
        float(np.asarray(d)[0, 0])
        return time.perf_counter() - t0

    out["serve_wave_program_ms"] = _slope(
        run_serve, label="profile.serve_wave"
    ) * 1e3

    # wave scan alone (admitted state held fixed)
    admit = jax.jit(
        lambda p, s, si, f, n: paged_admit_batch(model, p, s, si, f, n)
    )
    pred0, pstate1 = admit(
        params, pstate0, jnp.arange(slots, dtype=jnp.int32), feats, lens
    )
    oh = jnp.zeros((slots, 6))
    wave = jax.jit(
        lambda p, s, pr, o: paged_wave(model, p, s, pr, o, horizon - 1)
    )

    def run_wave(k):
        t0 = time.perf_counter()
        d = None
        for _ in range(k):
            d, _ = wave(params, pstate1, pred0, oh)
        float(np.asarray(d)[0, 0])
        return time.perf_counter() - t0

    out["wave_scan_program_ms"] = _slope(
        run_wave, label="profile.wave_scan"
    ) * 1e3
    out["us_per_tick"] = out["wave_scan_program_ms"] / (horizon - 1) * 1e3

    # full host path (what bench_serving times)
    reqs = [
        Request(
            np.cumsum(1.0 + rng.normal(0, 0.05, t + 1)),
            np.full(t + 1, int(TelemetryStatusEntry.CONVERTING)),
            horizon,
        )
        for _ in range(slots)
    ]
    b = ContinuousBatcher(
        model, params, num_pages=32, page_size=128, slots=slots,
        max_prefix=t, max_pages_per_seq=4,
    )
    b.run_waves(reqs)

    def run_rw(k):
        t0 = time.perf_counter()
        o = None
        for _ in range(k):
            o = b.run_waves(reqs, device_results=True)
        float(np.asarray(o[-1])[0])
        return time.perf_counter() - t0

    out["run_waves_host_path_ms"] = _slope(
        run_rw, label="profile.run_waves_host"
    ) * 1e3

    # the dense rollout it is compared against
    prog = jnp.asarray(
        np.cumsum(1.0 + rng.normal(0, 0.05, (slots, t + 1)), axis=-1)
    )
    sts = jnp.full((slots, t + 1), TelemetryStatusEntry.CONVERTING)
    roll = jax.jit(
        lambda p, pr, st: forecast_deltas(model, p, pr, st, horizon)
    )

    def run_roll(k):
        t0 = time.perf_counter()
        d = None
        for _ in range(k):
            d = roll(params, prog, sts)
        float(np.asarray(d)[0, 0])
        return time.perf_counter() - t0

    out["dense_rollout_program_ms"] = _slope(
        run_roll, label="profile.dense_rollout"
    ) * 1e3
    return out


def main() -> None:
    import sys

    from beholder_tpu import artifact

    # same contract as bench.py: every profiling run leaves a
    # schema-versioned raw artifact behind, even on error
    rec = artifact.ArtifactRecorder("profile_serving")
    artifact.set_current(rec)
    try:
        probes = rec.section("latency_probes", probe_latencies())
        print("latency probes:", {
            k: round(v, 3) for k, v in probes.items()
        })
        profile = rec.section("serving_profile", profile_serving())
        for k, v in profile.items():
            print(f"{k}: {v:.2f}")
    except BaseException as err:
        rec.error = repr(err)
        raise
    finally:
        artifact.set_current(None)
        print(f"profile artifact: {rec.write()}", file=sys.stderr)


if __name__ == "__main__":
    main()
