"""Explain a perf regression phase-by-phase, per worker and kernel family.

The perf gate (:mod:`beholder_tpu.tools.perf_gate`) says a ratio
drifted; an operator's next question is WHICH phase on WHICH worker
moved it. This tool diffs two runs — either two flight-plane merged
timelines (``MergedTimeline.jsonl`` / ``FlightRecorder.dump`` files)
or two committed bench artifacts' attribution blocks — and emits a
ranked machine-readable verdict::

    {"schema": "beholder-perf-explain",
     "regressed": true,
     "totals": {"baseline": ..., "current": ..., "delta": ...},
     "ranked": [{"kind": "phase", "phase": "readback",
                 "worker": "decode-1", "baseline": ..., "current": ...,
                 "delta": ..., "share_of_regression": 0.38}, ...],
     "families": [... same shape, kind="family" ...],
     "verdict": "readback on decode-1 +38% of the regression"}

``share_of_regression`` normalizes each positive phase delta by the
SUM of positive deltas — robust to both absolute walls (merged
timelines, seconds) and the artifact's ``phase_ms_pcts`` (percentage
points, whose total is ~invariant), and to runs where some phases got
faster while others regressed. The perf gate embeds this explanation
in every band-failure verdict, so CI regressions arrive pre-attributed.

CLI::

    python -m beholder_tpu.tools.perf_explain baseline current -o out.json
"""

from __future__ import annotations

import json
from typing import Any

SCHEMA = "beholder-perf-explain"


def walls_from_events(events: list[dict[str, Any]]) -> dict[str, Any]:
    """Phase/family walls for one merged (or plain) recorder event
    stream — :func:`beholder_tpu.obs.timeline.phase_walls`."""
    from beholder_tpu.obs.timeline import phase_walls

    return phase_walls(events)


def walls_from_artifact(artifact: dict[str, Any]) -> dict[str, Any]:
    """Phase/family walls out of a bench artifact's committed
    attribution block (``phase_ms_pcts`` + ``kernel_ceiling_fracs``,
    schema >= 5). Worker identity does not survive into the artifact's
    aggregate block, so everything keys under ``all``."""
    attribution = artifact.get("attribution", {}) or {}
    phases = {
        f"{phase}@all": float(pct)
        for phase, pct in (attribution.get("phase_ms_pcts") or {}).items()
    }
    # ceiling fracs INVERT for diffing: a family that achieves LESS of
    # the measured ceiling got slower, so its "wall" figure here is the
    # lost fraction (1 - frac) — a drop in achieved fraction shows as a
    # positive delta, the same sign convention as a phase that grew
    families = {
        f"{family}@all": 1.0 - float(frac)
        for family, frac in (
            attribution.get("kernel_ceiling_fracs") or {}
        ).items()
    }
    return {"phases": phases, "families": families}


def load_walls(path: str) -> dict[str, Any]:
    """Auto-detecting loader: a JSON object with a ``schema_version``
    (bench artifact) goes through :func:`walls_from_artifact`; anything
    else is read as recorder/merged JSONL (``flight.*`` header lines
    skipped) through :func:`walls_from_events`."""
    with open(path) as f:
        head = f.read(1)
        f.seek(0)
        if head == "{":
            try:
                obj = json.load(f)
                if isinstance(obj, dict) and "schema_version" in obj:
                    return walls_from_artifact(obj)
            except json.JSONDecodeError:
                f.seek(0)
        events = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(obj, dict) or obj.get("ph") == "M":
                continue
            if "name" in obj:
                events.append(obj)
    return walls_from_events(events)


def _rank(
    baseline: dict[str, float],
    current: dict[str, float],
    kind: str,
) -> list[dict[str, Any]]:
    deltas = {
        key: float(current.get(key, 0.0)) - float(baseline.get(key, 0.0))
        for key in sorted(baseline.keys() | current.keys())
    }
    pos_sum = sum(d for d in deltas.values() if d > 0)
    ranked = []
    for key, delta in deltas.items():
        name, _, worker = key.partition("@")
        ranked.append({
            "kind": kind,
            "key": key,
            kind: name,
            "worker": worker or "all",
            "baseline": float(baseline.get(key, 0.0)),
            "current": float(current.get(key, 0.0)),
            "delta": delta,
            "share_of_regression": (
                delta / pos_sum if pos_sum > 0 and delta > 0 else 0.0
            ),
        })
    ranked.sort(key=lambda r: (-r["delta"], r["key"]))
    return ranked


def explain(
    baseline: dict[str, Any], current: dict[str, Any]
) -> dict[str, Any]:
    """Diff two phase-wall aggregates (``walls_from_*`` output) into
    the ranked verdict. Deterministic: ties break on key order."""
    ranked = _rank(
        baseline.get("phases", {}), current.get("phases", {}), "phase"
    )
    families = _rank(
        baseline.get("families", {}), current.get("families", {}), "family"
    )
    base_total = sum(baseline.get("phases", {}).values())
    cur_total = sum(current.get("phases", {}).values())
    regressed = any(r["delta"] > 0 for r in ranked)
    if regressed:
        top = ranked[0]
        verdict = (
            f"{top['phase']} on {top['worker']} "
            f"+{top['share_of_regression'] * 100:.0f}% of the regression"
        )
    else:
        verdict = "no phase regressed"
    return {
        "schema": SCHEMA,
        "regressed": regressed,
        "totals": {
            "baseline": base_total,
            "current": cur_total,
            "delta": cur_total - base_total,
        },
        "ranked": ranked,
        "families": families,
        "verdict": verdict,
    }


def explain_artifacts(
    baseline: dict[str, Any], current: dict[str, Any]
) -> dict[str, Any]:
    """Explain between two loaded bench artifacts (the perf gate's
    embed path — it already holds both JSON objects)."""
    return explain(
        walls_from_artifact(baseline), walls_from_artifact(current)
    )


def main(argv: list[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        description=(
            "Diff two runs (merged flight-plane timelines or bench "
            "artifacts) phase-by-phase and rank what moved"
        )
    )
    parser.add_argument("baseline", help="baseline timeline JSONL or artifact JSON")
    parser.add_argument("current", help="current timeline JSONL or artifact JSON")
    parser.add_argument(
        "-o", "--out", default=None,
        help="write the explanation JSON here (default: stdout only)",
    )
    args = parser.parse_args(argv)
    result = explain(load_walls(args.baseline), load_walls(args.current))
    rendered = json.dumps(result, indent=2, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(rendered + "\n")
    print(result["verdict"])
    if not args.out:
        print(rendered)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
