"""Trello REST v1 client.

Covers the two operations the reference performs through the ``trello`` npm
package: moving a card to a list (index.js:83-86) and commenting on a card
(index.js:53-55). Auth is key+token query parameters, as the npm client does.
"""

from __future__ import annotations

import os
from typing import Any

from .http import HttpResponse, HttpTransport, RequestsTransport

BASE_URL = "https://api.trello.com"


class TrelloClient:
    def __init__(
        self,
        key: str,
        token: str,
        transport: HttpTransport | None = None,
        base_url: str | None = None,
        deadline_s: float = 10.0,
    ):
        self._key = key
        self._token = token
        self._transport = transport or RequestsTransport()
        # TRELLO_API_URL lets tests/self-hosted setups redirect traffic
        base_url = base_url or os.environ.get("TRELLO_API_URL", BASE_URL)
        self._base_url = base_url.rstrip("/")
        #: per-request time budget handed to the transport (the service
        #: threads ``instance.http.deadline_s`` here)
        self._deadline_s = float(deadline_s)

    def make_request(
        self, method: str, path: str, params: dict[str, Any] | None = None
    ) -> HttpResponse:
        """Generic call mirroring ``trello.makeRequest`` (index.js:53,83)."""
        merged = {"key": self._key, "token": self._token}
        merged.update(params or {})
        resp = self._transport.request(
            method, f"{self._base_url}{path}", params=merged,
            timeout=self._deadline_s,
        )
        resp.raise_for_status()
        return resp

    def move_card(self, card_id: str, list_id: str, pos: int = 2) -> HttpResponse:
        """PUT /1/cards/<id> with idList + pos, exactly as index.js:83-86."""
        return self.make_request(
            "put", f"/1/cards/{card_id}", {"idList": list_id, "pos": pos}
        )

    def get_board(self, board_id: str) -> HttpResponse:
        """GET /1/boards/<id> — a read-only lookup (board metadata, list
        layout). Hot when resolving flow lists for many cards; the
        service's :class:`~beholder_tpu.clients.http.CachingTransport`
        TTL-caches it (``instance.cache.http``)."""
        return self.make_request("get", f"/1/boards/{board_id}")

    def get_card(self, card_id: str) -> HttpResponse:
        """GET /1/cards/<id> — read-only card lookup (same cache tier
        as :meth:`get_board`)."""
        return self.make_request("get", f"/1/cards/{card_id}")

    def comment_card(self, card_id: str, text: str) -> HttpResponse:
        """POST a comment action; empty text falls back like index.js:54."""
        return self.make_request(
            "post",
            f"/1/cards/{card_id}/actions/comments",
            {"text": text or "Failed to retrieve comment text."},
        )
