"""Emby media-server client.

One operation: trigger a library refresh after a deployment
(index.js:110-118).
"""

from __future__ import annotations

from .http import HttpResponse, HttpTransport, RequestsTransport


class EmbyClient:
    def __init__(
        self,
        host: str,
        token: str,
        transport: HttpTransport | None = None,
        deadline_s: float = 10.0,
    ):
        self._host = host.rstrip("/")
        self._token = token
        self._transport = transport or RequestsTransport()
        #: per-request time budget handed to the transport (the service
        #: threads ``instance.http.deadline_s`` here)
        self._deadline_s = float(deadline_s)

    def refresh_library(self) -> HttpResponse:
        resp = self._transport.request(
            "get",  # request-promise-native defaults to GET (index.js:112)
            f"{self._host}/emby/library/refresh",
            params={"api_key": self._token},
            timeout=self._deadline_s,
        )
        resp.raise_for_status()
        return resp

    def library_folders(self) -> HttpResponse:
        """GET /emby/Library/VirtualFolders — the read-only library
        listing. Unlike :meth:`refresh_library` (a GET with a side
        effect, never cacheable) this is a pure lookup, TTL-cached by
        the service's :class:`~beholder_tpu.clients.http
        .CachingTransport` (``instance.cache.http``)."""
        resp = self._transport.request(
            "get",
            f"{self._host}/emby/Library/VirtualFolders",
            params={"api_key": self._token},
            timeout=self._deadline_s,
        )
        resp.raise_for_status()
        return resp
