"""Side-effect clients: Trello, Telegram, Emby.

Each mirrors one network boundary in the reference (SURVEY.md §3):
Trello card moves/comments (index.js:50-58,79-90), Telegram deployment
notifications (index.js:94-107), Emby library refresh (index.js:110-118).
All share a pluggable HTTP transport so tests can intercept traffic.
"""

from .emby import EmbyClient
from .http import (
    CachingTransport,
    HttpResponse,
    HttpTransport,
    RecordingTransport,
    RequestsTransport,
    TimedTransport,
    read_only_get,
)
from .telegram import TelegramClient
from .trello import TrelloClient

__all__ = [
    "HttpTransport",
    "HttpResponse",
    "RequestsTransport",
    "RecordingTransport",
    "TimedTransport",
    "CachingTransport",
    "read_only_get",
    "TrelloClient",
    "TelegramClient",
    "EmbyClient",
]
