"""Telegram Bot API client.

One operation: the "new media deployed" notification (index.js:94-107).
The reference sends a markdown message linking the Kitsu metadata page.
"""

from __future__ import annotations

import os

from .http import HttpResponse, HttpTransport, RequestsTransport

BASE_URL = "https://api.telegram.org"


class TelegramClient:
    def __init__(
        self,
        token: str,
        transport: HttpTransport | None = None,
        base_url: str | None = None,
        deadline_s: float = 10.0,
    ):
        self._token = token
        self._transport = transport or RequestsTransport()
        # TELEGRAM_API_URL lets tests/self-hosted setups redirect traffic
        base_url = base_url or os.environ.get("TELEGRAM_API_URL", BASE_URL)
        self._base_url = base_url.rstrip("/")
        #: per-request time budget handed to the transport (the service
        #: threads ``instance.http.deadline_s`` here)
        self._deadline_s = float(deadline_s)

    def send_message(
        self, chat_id: str, text: str, parse_mode: str = "markdown"
    ) -> HttpResponse:
        resp = self._transport.request(
            "get",  # request-promise-native defaults to GET (index.js:99)
            f"{self._base_url}/bot{self._token}/sendMessage",
            params={"chat_id": chat_id, "text": text, "parse_mode": parse_mode},
            timeout=self._deadline_s,
        )
        resp.raise_for_status()
        return resp

    def notify_deployed(self, chat_id: str, name: str, metadata_id: str) -> HttpResponse:
        """The exact message shape from index.js:103."""
        text = f"*New Anime:* {name}\nKitsu: https://kitsu.io/anime/{metadata_id}"
        return self.send_message(chat_id, text)
