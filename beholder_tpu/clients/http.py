"""Pluggable HTTP transport.

The reference talks to Trello through the ``trello`` npm package and to
Telegram/Emby through raw ``request-promise-native`` calls (index.js:14,
99-118). This rebuild routes all three through one transport interface so
tests can assert on exact requests without network access.
"""

from __future__ import annotations

import abc
import time
from dataclasses import dataclass
from typing import Any


@dataclass
class HttpResponse:
    status: int
    body: Any = None

    def raise_for_status(self) -> None:
        if self.status >= 400:
            raise HttpError(self.status, self.body)


class HttpError(RuntimeError):
    def __init__(self, status: int, body: Any = None):
        super().__init__(f"HTTP {status}")
        self.status = status
        self.body = body


class HttpTransport(abc.ABC):
    @abc.abstractmethod
    def request(
        self,
        method: str,
        url: str,
        *,
        params: dict[str, Any] | None = None,
        json: dict[str, Any] | None = None,
        timeout: float = 10.0,
    ) -> HttpResponse:
        """Perform one HTTP request and return the (possibly JSON) response."""


class RequestsTransport(HttpTransport):
    """Production transport backed by ``requests``."""

    def request(self, method, url, *, params=None, json=None, timeout=10.0):
        import requests

        resp = requests.request(
            method.upper(), url, params=params, json=json, timeout=timeout
        )
        try:
            body = resp.json()
        except ValueError:
            body = resp.text
        return HttpResponse(status=resp.status_code, body=body)


def is_timeout_error(exc: BaseException) -> bool:
    """Transport-agnostic timeout detection: stdlib ``TimeoutError``
    (``socket.timeout`` is its alias since 3.10) plus duck-typing for
    requests' ``Timeout``/``ConnectTimeout``/``ReadTimeout`` — checked
    by class NAME so this module never imports requests."""
    if isinstance(exc, TimeoutError):
        return True
    return any("Timeout" in klass.__name__ for klass in type(exc).__mro__)


class TimedTransport(HttpTransport):
    """Wraps any transport with a request-latency histogram
    (``beholder_http_request_seconds{method,outcome}``). Extension
    surface: nothing is registered unless one is constructed (the
    service wires it behind ``instance.observability.enabled``), so the
    reference exposition stays byte-identical by default. ``outcome``
    is the status class (``2xx``/``4xx``/...), ``timeout`` when the
    transport raised a timeout, or ``error`` for any other raise —
    deadline misses and dependency errors are different failure modes
    and alert differently (a timeout spike says "slow dependency or
    deadline too tight", not "dependency down")."""

    def __init__(self, inner: HttpTransport, registry):
        from beholder_tpu.metrics import get_or_create

        self.inner = inner
        self._hist = get_or_create(
            getattr(registry, "registry", registry),
            "histogram",
            "beholder_http_request_seconds",
            "Outbound HTTP request latency by method and outcome",
            labelnames=["method", "outcome"],
        )

    def request(self, method, url, *, params=None, json=None, timeout=10.0):
        t0 = time.perf_counter()
        try:
            resp = self.inner.request(
                method, url, params=params, json=json, timeout=timeout
            )
        except Exception as err:
            self._hist.observe(
                time.perf_counter() - t0, method=method.upper(),
                outcome="timeout" if is_timeout_error(err) else "error",
            )
            raise
        self._hist.observe(
            time.perf_counter() - t0, method=method.upper(),
            outcome=f"{resp.status // 100}xx",
        )
        return resp


@dataclass
class _Recorded:
    method: str
    url: str
    params: dict[str, Any] | None
    json: dict[str, Any] | None


class RecordingTransport(HttpTransport):
    """Test transport: records every request, replies from a scripted queue."""

    def __init__(self):
        self.requests: list[_Recorded] = []
        self.responses: list[HttpResponse] = []
        self.fail_with: Exception | None = None

    def request(self, method, url, *, params=None, json=None, timeout=10.0):
        self.requests.append(_Recorded(method.upper(), url, params, json))
        if self.fail_with is not None:
            raise self.fail_with
        if self.responses:
            return self.responses.pop(0)
        return HttpResponse(status=200, body={})
