"""Pluggable HTTP transport.

The reference talks to Trello through the ``trello`` npm package and to
Telegram/Emby through raw ``request-promise-native`` calls (index.js:14,
99-118). This rebuild routes all three through one transport interface so
tests can assert on exact requests without network access.
"""

from __future__ import annotations

import abc
import copy
import time
from dataclasses import dataclass
from typing import Any


@dataclass
class HttpResponse:
    status: int
    body: Any = None

    def raise_for_status(self) -> None:
        if self.status >= 400:
            raise HttpError(self.status, self.body)


class HttpError(RuntimeError):
    def __init__(self, status: int, body: Any = None):
        super().__init__(f"HTTP {status}")
        self.status = status
        self.body = body


class HttpTransport(abc.ABC):
    @abc.abstractmethod
    def request(
        self,
        method: str,
        url: str,
        *,
        params: dict[str, Any] | None = None,
        json: dict[str, Any] | None = None,
        timeout: float = 10.0,
        headers: dict[str, str] | None = None,
    ) -> HttpResponse:
        """Perform one HTTP request and return the (possibly JSON) response."""


class RequestsTransport(HttpTransport):
    """Production transport backed by ``requests``."""

    def request(self, method, url, *, params=None, json=None, timeout=10.0,
                headers=None):
        import requests

        resp = requests.request(
            method.upper(), url, params=params, json=json, timeout=timeout,
            headers=headers,
        )
        try:
            body = resp.json()
        except ValueError:
            body = resp.text
        return HttpResponse(status=resp.status_code, body=body)


def is_timeout_error(exc: BaseException) -> bool:
    """Transport-agnostic timeout detection: stdlib ``TimeoutError``
    (``socket.timeout`` is its alias since 3.10) plus duck-typing for
    requests' ``Timeout``/``ConnectTimeout``/``ReadTimeout`` — checked
    by class NAME so this module never imports requests."""
    if isinstance(exc, TimeoutError):
        return True
    return any("Timeout" in klass.__name__ for klass in type(exc).__mro__)


class TimedTransport(HttpTransport):
    """Wraps any transport with a request-latency histogram
    (``beholder_http_request_seconds{method,outcome}``). Extension
    surface: nothing is registered unless one is constructed (the
    service wires it behind ``instance.observability.enabled``), so the
    reference exposition stays byte-identical by default. ``outcome``
    is the status class (``2xx``/``4xx``/...), ``timeout`` when the
    transport raised a timeout, or ``error`` for any other raise —
    deadline misses and dependency errors are different failure modes
    and alert differently (a timeout spike says "slow dependency or
    deadline too tight", not "dependency down")."""

    def __init__(self, inner: HttpTransport, registry):
        from beholder_tpu.metrics import get_or_create

        self.inner = inner
        self._hist = get_or_create(
            getattr(registry, "registry", registry),
            "histogram",
            "beholder_http_request_seconds",
            "Outbound HTTP request latency by method and outcome",
            labelnames=["method", "outcome"],
        )

    def request(self, method, url, *, params=None, json=None, timeout=10.0,
                headers=None):
        # headers forwarded only when set: duck-typed transports
        # predating the headers kwarg keep working headerless
        extra = {"headers": headers} if headers is not None else {}
        t0 = time.perf_counter()
        try:
            resp = self.inner.request(
                method, url, params=params, json=json, timeout=timeout,
                **extra,
            )
        except Exception as err:
            self._hist.observe(
                time.perf_counter() - t0, method=method.upper(),
                outcome="timeout" if is_timeout_error(err) else "error",
            )
            raise
        self._hist.observe(
            time.perf_counter() - t0, method=method.upper(),
            outcome=f"{resp.status // 100}xx",
        )
        return resp


class TracingTransport(HttpTransport):
    """Injects the active span's W3C ``traceparent`` header into every
    outbound request — the flight plane's HTTP propagation leg, so an
    egress call (Trello/Telegram/Emby) carries the trace the triggering
    message opened across the process boundary. The service wires this
    OUTERMOST, and only when ``instance.observability.flight_plane.*``
    is armed: with the knob off no wrapper exists and outbound wire
    bytes are byte-identical. Caller-provided headers win on conflict
    (an explicit traceparent is an explicit parent)."""

    def __init__(self, inner: HttpTransport):
        self.inner = inner

    def request(self, method, url, *, params=None, json=None, timeout=10.0,
                headers=None):
        from beholder_tpu.tracing import active_context, to_traceparent

        ctx = active_context()
        if ctx is not None:
            merged = {"traceparent": to_traceparent(ctx)}
            if headers:
                merged.update(headers)
            headers = merged
        extra = {"headers": headers} if headers is not None else {}
        return self.inner.request(
            method, url, params=params, json=json, timeout=timeout,
            **extra,
        )


def read_only_get(method: str, url: str) -> bool:
    """The service's default cacheability predicate: ONLY known
    read-only lookups. It must be an allowlist — this stack's
    "request-promise-native defaults to GET" heritage means GETs with
    side effects exist (Telegram ``sendMessage``, Emby
    ``library/refresh``), and caching one would silently swallow the
    side effect on every hit."""
    if method.upper() != "GET":
        return False
    return (
        "/1/boards/" in url          # Trello board lookups
        or "/1/cards/" in url        # Trello card lookups
        or "VirtualFolders" in url   # Emby library listing
    )


class CachingTransport(HttpTransport):
    """TTL response cache for read-only outbound lookups.

    Wraps any transport (the service puts it OUTSIDE
    :class:`~beholder_tpu.reliability.breaker.ResilientTransport`, so a
    hit skips the breaker/retry machinery entirely — cached traffic
    costs the dependency nothing) and serves repeat lookups from a
    :class:`beholder_tpu.cache.KeyedCache` keyed by (method, url,
    params). Singleflight collapses concurrent identical lookups into
    one wire call. Only responses passing ``cacheable`` (default:
    :func:`read_only_get`) with status < 300 are stored; everything
    else — writes, side-effectful GETs, errors — passes straight
    through. Extension surface: nothing registers on the exposition
    unless a registry is handed in."""

    def __init__(
        self,
        inner: HttpTransport,
        ttl_s: float = 5.0,
        max_entries: int = 256,
        cacheable=read_only_get,
        metrics=None,
        clock=None,
    ):
        from beholder_tpu.cache import KeyedCache

        self.inner = inner
        self._cacheable = cacheable
        kwargs = {"clock": clock} if clock is not None else {}
        self._cache = KeyedCache(
            "http.get",
            max_entries=max_entries,
            policy="ttl",
            ttl_s=ttl_s,
            metrics=metrics,
            **kwargs,
        )

    @property
    def cache(self):
        return self._cache

    def request(self, method, url, *, params=None, json=None, timeout=10.0,
                headers=None):
        # headers forwarded only when set: duck-typed transports
        # predating the headers kwarg keep working headerless
        extra = {"headers": headers} if headers is not None else {}
        if json is not None or not self._cacheable(method, url):
            return self.inner.request(
                method, url, params=params, json=json, timeout=timeout,
                **extra,
            )
        # headers are deliberately NOT part of the cache key: trace
        # context varies per request and must not shatter the cache
        key = (method.upper(), url, _freeze(params or {}))

        def load():
            resp = self.inner.request(
                method, url, params=params, json=None, timeout=timeout,
                **extra,
            )
            if resp.status >= 300:
                # an error/redirect must not be replayed for ttl_s; the
                # private raise carries it out of the cache uncached
                raise _Uncached(resp)
            return resp

        # a defensive copy per caller on EVERY exit (hit, fresh load, or
        # error bypass — singleflight can hand one object to several
        # collapsed callers): the body is a mutable parsed-JSON object
        # and one caller's mutation must not poison another's view (same
        # contract as CachingStorage's row clones)
        try:
            resp = self._cache.get_or_load(key, load)
        except _Uncached as bypass:
            resp = bypass.response
        return HttpResponse(resp.status, copy.deepcopy(resp.body))


def _freeze(value):
    """Recursively hashable view of a params structure — list-valued
    query params are legal for the uncached transport, so they must not
    crash the cache-key build."""
    if isinstance(value, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in value.items()))
    if isinstance(value, (list, tuple)):
        return tuple(_freeze(v) for v in value)
    if isinstance(value, (set, frozenset)):
        return frozenset(_freeze(v) for v in value)
    return value


class _Uncached(Exception):
    """Internal: carries a non-cacheable response out of a loader."""

    def __init__(self, response: HttpResponse):
        super().__init__(response.status)
        self.response = response


@dataclass
class _Recorded:
    method: str
    url: str
    params: dict[str, Any] | None
    json: dict[str, Any] | None
    headers: dict[str, str] | None = None


class RecordingTransport(HttpTransport):
    """Test transport: records every request, replies from a scripted queue."""

    def __init__(self):
        self.requests: list[_Recorded] = []
        self.responses: list[HttpResponse] = []
        self.fail_with: Exception | None = None

    def request(self, method, url, *, params=None, json=None, timeout=10.0,
                headers=None):
        self.requests.append(
            _Recorded(method.upper(), url, params, json, headers)
        )
        if self.fail_with is not None:
            raise self.fail_with
        if self.responses:
            return self.responses.pop(0)
        return HttpResponse(status=200, body={})
