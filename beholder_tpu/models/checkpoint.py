"""Checkpoint/resume for the anomaly model (orbax-backed).

EXTENSION: the reference is stateless (its state lives in Postgres —
SURVEY.md §5 "Checkpoint / resume: absent"), but the analytics extension
trains a model, and a trained model is state worth persisting. Orbax is
the idiomatic JAX checkpointer: async-capable, sharding-aware, and it
restores arrays onto whatever mesh the template pytree prescribes, so a
checkpoint written on one topology restores onto another.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

from .anomaly import TrainState


def save_state(path: str | Path, state: TrainState) -> None:
    """Write ``state`` (params + optimizer moments + step) to ``path``.

    Overwrites an existing checkpoint at ``path`` (``force=True``) so the
    periodic save-to-fixed-"latest"-path workflow works."""
    path = Path(path).resolve()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
        ckptr.wait_until_finished()


def restore_state(path: str | Path, template: TrainState) -> TrainState:
    """Restore a TrainState; ``template`` supplies structure, dtypes, and
    (optionally) target shardings — pass a mesh-placed template to restore
    directly onto a device mesh."""
    path = Path(path).resolve()
    abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, template)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, abstract)
