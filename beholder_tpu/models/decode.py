"""Autoregressive inference for the sequence model: KV cache + forecast.

EXTENSION BEYOND THE REFERENCE (no inference paths exist there —
SURVEY.md §0). Completes the sequence model's lifecycle: train with
:func:`~beholder_tpu.models.sequence.seq_train_step`, then roll the model
forward to forecast an encode job's progress trajectory and ETA.

TPU-first design:

- The KV cache is a static-shape pytree ((B, H, max_len, Dh) per layer
  plus a scalar write index); every decode step is the same compiled
  program — ``dynamic_update_slice`` into the cache, one masked attention
  over the full cache width, no shape change, no recompilation.
- Prefill is ONE batched forward over the whole prefix (MXU-sized
  matmuls), not T sequential steps; only generation runs step-by-step,
  inside a single ``lax.scan`` so the whole rollout is one XLA program.
- Decode attends q(1) against the cache with a position mask — the
  flash/ring machinery is a training concern; a 1-row query is pure
  bandwidth and XLA's fused masked softmax is already optimal for it.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from beholder_tpu.ops import NUM_STATUSES

from .sequence import FEATURES, TelemetrySequenceModel


class DecodeCache(NamedTuple):
    """Per-layer key/value tensors (B, Hkv, max_len, Dh) + write index.

    Hkv is ``model.kv_heads or model.heads`` — under grouped-query
    attention the cache holds only the kv heads."""

    keys: tuple
    values: tuple
    index: jax.Array  # scalar int32: number of positions already written


def init_cache(
    model: TelemetrySequenceModel, batch: int, max_len: int
) -> DecodeCache:
    """With grouped-query attention (``model.kv_heads < heads``) the cache
    holds only the kv heads — the (B, Hkv, max_len, Dh) tensors shrink by
    the group factor, which is THE serving-memory lever."""
    dh = model.dim // model.heads
    hkv = model.kv_heads or model.heads
    shape = (batch, hkv, max_len, dh)
    zeros = tuple(jnp.zeros(shape, jnp.bfloat16) for _ in range(model.layers))
    return DecodeCache(zeros, tuple(jnp.zeros_like(z) for z in zeros), jnp.int32(0))


def prefill(
    model: TelemetrySequenceModel, params, feats: jax.Array, max_len: int
) -> tuple[jax.Array, DecodeCache]:
    """Run the whole (B, T, F) prefix in one forward; return the last
    position's prediction and a cache holding the prefix k/v."""
    b, t, _ = feats.shape
    preds, kvs = model.apply(params, feats, return_kv=True)
    cache = init_cache(model, b, max_len)
    keys, values = [], []
    for (k, v), ck, cv in zip(kvs, cache.keys, cache.values):
        keys.append(jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, 0, 0, 0)))
        values.append(
            jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, 0, 0, 0))
        )
    return preds[:, -1], DecodeCache(tuple(keys), tuple(values), jnp.int32(t))


def decode_step(
    model: TelemetrySequenceModel, params, cache: DecodeCache, feats_t: jax.Array
) -> tuple[jax.Array, DecodeCache]:
    """One autoregressive step. ``feats_t`` is (B, F); returns ((B,) next
    prediction, updated cache). Same compiled program every step."""
    pred, new_kvs = model.apply(
        params, feats_t[:, None, :], cache=(cache.keys, cache.values, cache.index)
    )
    keys = tuple(k for k, _ in new_kvs)
    values = tuple(v for _, v in new_kvs)
    return pred[:, 0], DecodeCache(keys, values, cache.index + 1)


def forecast_deltas(
    model: TelemetrySequenceModel,
    params,
    progress: jax.Array,
    statuses: jax.Array,
    horizon: int,
) -> jax.Array:
    """Roll the model ``horizon`` steps past the observed stream.

    ``progress``/``statuses`` are the observed (B, T+1) history (same
    shapes as :func:`~beholder_tpu.models.sequence.stream_features`).
    Returns (B, horizon) predicted per-step progress deltas: the model's
    own predictions are fed back as inputs, status held at its last
    observed value.
    """
    from .sequence import stream_features

    feats, _ = stream_features(progress, statuses)
    b, t, _ = feats.shape
    max_len = t + horizon
    last_pred, cache = prefill(model, params, feats, max_len)
    last_status = statuses[:, -1]
    status_oh = jax.nn.one_hot(last_status, NUM_STATUSES)  # (B, S)

    def step(carry, _):
        delta, cache = carry
        feats_t = jnp.concatenate([delta[:, None], status_oh], axis=-1)
        pred, cache = decode_step(model, params, cache, feats_t)
        return (pred, cache), delta

    (_, _), deltas = jax.lax.scan(
        step, (last_pred, cache), None, length=horizon
    )
    return deltas.T  # (B, horizon)


def cache_shardings(
    model: TelemetrySequenceModel, mesh, axis: str = "dp",
    head_axis: str | None = None,
) -> DecodeCache:
    """NamedSharding pytree for a :class:`DecodeCache`: the (B, Hkv,
    max_len, Dh) key/value tensors sharded over ``axis`` on their batch
    dim — and, when ``head_axis`` is given (tensor-parallel serving), over
    it on the HEAD dim (matching megatron column-parallel q/k/v, whose
    shards each produce whole kv heads). The write index is replicated.
    With B streams on a dp=P (×tp=T) mesh each device holds
    (B/P, Hkv/T, max_len, Dh) — the cache, the serving-memory wall, scales
    out with the mesh instead of replicating, and shrinks by heads/kv_heads
    under GQA on top. ``head_axis`` follows the PARAMS placement, not the
    mesh shape: head-sharding the cache of replicated params would insert
    a k/v reshard into every decode step."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if head_axis is not None:
        hkv = model.kv_heads or model.heads
        if hkv % mesh.shape[head_axis]:
            raise ValueError(
                f"kv heads ({hkv}) must divide by mesh axis "
                f"'{head_axis}'={mesh.shape[head_axis]} for head-sharded "
                f"serving — with GQA pick kv_heads as a multiple of tp"
            )
    kv = NamedSharding(mesh, P(axis, head_axis, None, None))
    return DecodeCache(
        tuple(kv for _ in range(model.layers)),
        tuple(kv for _ in range(model.layers)),
        NamedSharding(mesh, P()),
    )


def _serving_head_axis(mesh, params_shardings, batch_axis) -> str | None:
    """Head-shard the cache over tp only when the provided params
    shardings ACTUALLY use the tp axis (replicated params + a head-sharded
    cache would reshard k/v every step)."""
    if (
        params_shardings is None
        or "tp" not in mesh.axis_names
        or batch_axis == "tp"
    ):
        return None
    uses_tp = any(
        "tp" in str(getattr(leaf, "spec", ""))
        for leaf in jax.tree.leaves(params_shardings)
    )
    return "tp" if uses_tp else None


def sharded_prefill(
    model: TelemetrySequenceModel,
    mesh,
    max_len: int,
    axis: str = "dp",
    params_shardings=None,
):
    """Jit :func:`prefill` over ``mesh``: feats batch-sharded on ``axis``,
    the returned cache sharded per :func:`cache_shardings`. For a 2-D
    (dp, tp) serving mesh pass megatron ``params_shardings`` (from
    :func:`beholder_tpu.parallel.seq_state_shardings` on the params tree)
    so the model weights are tensor-parallel while the cache heads follow.
    Returns ``fn(params, feats) -> (last_pred, cache)``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    p_sh = params_shardings or NamedSharding(mesh, P())
    head_axis = _serving_head_axis(mesh, params_shardings, axis)
    return jax.jit(
        lambda params, feats: prefill(model, params, feats, max_len),
        in_shardings=(p_sh, NamedSharding(mesh, P(axis, None, None))),
        out_shardings=(
            NamedSharding(mesh, P(axis)),
            cache_shardings(model, mesh, axis, head_axis),
        ),
    )


def sharded_decode_step(
    model: TelemetrySequenceModel,
    mesh,
    axis: str = "dp",
    params_shardings=None,
):
    """Jit :func:`decode_step` over ``mesh`` with the cache staying
    sharded in AND out — every step reads/writes only the local
    (B/dp, H/tp, max_len, Dh) shard. Returns ``fn(params, cache, feats_t)``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    p_sh = params_shardings or NamedSharding(mesh, P())
    c_sh = cache_shardings(
        model, mesh, axis, _serving_head_axis(mesh, params_shardings, axis)
    )
    return jax.jit(
        lambda params, cache, feats_t: decode_step(model, params, cache, feats_t),
        in_shardings=(p_sh, c_sh, NamedSharding(mesh, P(axis, None))),
        out_shardings=(NamedSharding(mesh, P(axis)), c_sh),
    )


def sharded_forecast_eta(
    model: TelemetrySequenceModel,
    mesh,
    horizon: int,
    target: float = 100.0,
    axis: str = "dp",
    params_shardings=None,
):
    """Jit :func:`forecast_eta` over ``mesh`` with the observed streams
    batch-sharded on ``axis``; GSPMD propagates the dp sharding through
    prefill, the KV cache, and the whole rollout scan. Pass megatron
    ``params_shardings`` for tensor-parallel serving (otherwise params
    are replicated). Returns
    ``fn(params, progress, statuses) -> (eta, reached)``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    p_sh = params_shardings or NamedSharding(mesh, P())
    data = NamedSharding(mesh, P(axis, None))
    out = NamedSharding(mesh, P(axis))
    return jax.jit(
        lambda params, prog, stats: forecast_eta(
            model, params, prog, stats, horizon, target
        ),
        in_shardings=(p_sh, data, data),
        out_shardings=(out, out),
    )


def forecast_eta(
    model: TelemetrySequenceModel,
    params,
    progress: jax.Array,
    statuses: jax.Array,
    horizon: int,
    target: float = 100.0,
) -> tuple[jax.Array, jax.Array]:
    """Steps until each stream's forecast reaches ``target`` progress.

    Returns (eta_steps (B,), reached (B,) bool). ``eta_steps`` is the
    number of future steps until the cumulative forecast crosses the
    target (= ``horizon`` where the forecast never gets there — check
    ``reached``).
    """
    deltas = forecast_deltas(model, params, progress, statuses, horizon)
    future = progress[:, -1:] + jnp.cumsum(deltas, axis=-1)  # (B, horizon)
    hit = future >= target
    reached = jnp.any(hit, axis=-1)
    eta = jnp.where(reached, jnp.argmax(hit, axis=-1) + 1, horizon)
    return eta, reached
