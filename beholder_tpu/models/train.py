"""Shared training-state machinery for all models in this package."""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import optax


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def apply_gradients(
    state: TrainState,
    tx: optax.GradientTransformation,
    loss_fn: Callable[[Any], jax.Array],
) -> tuple[TrainState, jax.Array]:
    """One optimizer step of ``loss_fn(params)``; pure, jit/pjit-friendly."""
    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return TrainState(params, opt_state, state.step + 1), loss
