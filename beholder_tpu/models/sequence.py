"""Long-context telemetry sequence model.

EXTENSION BEYOND THE REFERENCE. A small causal transformer over telemetry
streams — per-step features are (progress delta, one-hot status), targets
are next-step deltas (same self-supervision as the MLP flagship, but over
arbitrarily long streams). The attention backend is pluggable:

- ``attention="full"``  — O(T^2) on one device (short streams)
- ``attention="flash"`` — single-device flash attention: Pallas forward
  kernel + blocked XLA backward, O(T * block) memory
  (:mod:`beholder_tpu.ops.flash_attention`)
- ``attention="ring"``  — context-parallel ring attention over an ``sp``
  mesh axis (:func:`beholder_tpu.ops.attention.ring_attention`): each
  device holds T/P of the stream, k/v blocks rotate over ICI, memory per
  device stays O(T/P * d). This is how week-long telemetry streams score
  without a single-chip memory wall.
- ``attention="ulysses"`` — Ulysses sequence parallelism over ``sp``:
  one all-to-all trades sequence shards for head shards, flash attention
  runs on whole-sequence heads, one all-to-all trades back
  (:func:`beholder_tpu.ops.attention.ulysses_attention`). Needs
  heads % sp == 0; cheaper collectives than ring for moderate T.

TPU-first notes: static shapes throughout; bfloat16 matmuls with float32
accumulation; heads/features sized for MXU tiles.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from jax.sharding import Mesh

from beholder_tpu.ops import NUM_STATUSES
from beholder_tpu.ops.attention import (
    full_attention,
    ring_attention,
    ulysses_attention,
)
from beholder_tpu.ops.flash_attention import flash_attention
from beholder_tpu.ops.moe import SwitchFFN
from beholder_tpu.ops.paged_attention import (
    ChunkPagedInfo,
    GroupSpec,
    PagedInfo,
    QuantizedPool,
    paged_chunk_attention,
    paged_decode_attention,
)

from .train import TrainState, apply_gradients

FEATURES = 1 + NUM_STATUSES


def _pool_write_column(pool, info: PagedInfo, col: jax.Array):
    """Scatter each slot's new (Hkv, Dh) kv column into its write page
    at its write offset — (tokens-on-lanes pool layout, so the column
    lands on one lane). Out-of-bounds page ids (inactive slots) drop.
    Quantized pools (int8 or fp8) quantize the column per (head, token)
    on the way in, through the pool's own scheme."""
    if isinstance(pool, QuantizedPool):
        from beholder_tpu.ops.quant import pool_quantize

        # scale (S, Hkv)
        q, scale = pool_quantize(col, axis=-1, values_dtype=pool.values.dtype)
        return QuantizedPool(
            pool.values.at[info.write_pages, :, :, info.write_offsets].set(
                q, mode="drop"
            ),
            pool.scales.at[info.write_pages, :, info.write_offsets].set(
                scale, mode="drop"
            ),
        )
    return pool.at[info.write_pages, :, :, info.write_offsets].set(
        col.astype(pool.dtype), mode="drop"
    )


def _group_slice(x: jax.Array, group: GroupSpec, width: int) -> jax.Array:
    """This group member's head slice of ``x`` (head axis 1): member
    ``m`` of the ``group.axis`` mesh axis owns heads
    ``[m*width, (m+1)*width)``. Contiguous by construction — GQA groups
    q heads contiguously per kv head (the ``bhgqd`` reshape in the
    dense branch), so slicing ``width = hkv_loc`` kv heads and
    ``width = hkv_loc * g`` q heads at the matching offset keeps every
    q head next to its kv head. Only meaningful inside a ``shard_map``
    over ``group.axis``."""
    m = jax.lax.axis_index(group.axis)
    return jax.lax.dynamic_slice_in_dim(x, m * width, width, axis=1)


def _seq_shard_constraint(mesh: Mesh | None, x: jax.Array) -> jax.Array:
    """Megatron sequence parallelism for the non-matmul residue of TP:
    constrain the residual stream / LayerNorm activations to be sharded
    along the SEQUENCE dim over the tp axis (plus sp when ring/Ulysses
    context parallelism is also active). GSPMD then lowers the row-parallel
    layers' all-reduce into reduce-scatter + all-gather around the sharded
    LayerNorms, so the replicated (B, T, D) activations between megatron's
    two all-reduces never materialize — activation memory between blocks
    drops by the tp factor (pinned by tests/test_parallel.py)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        return x
    seq_axes = tuple(a for a in ("sp", "tp") if a in mesh.axis_names)
    if not seq_axes:
        return x
    # drop any axis the array can't divide over (e.g. model.init traces
    # with a batch of 1) — an unconstrained dim just stays replicated
    batch = "dp" if "dp" in mesh.axis_names else None
    if batch is not None and x.shape[0] % mesh.shape[batch]:
        batch = None
    seq_size = 1
    for a in seq_axes:
        seq_size *= mesh.shape[a]
    if x.shape[1] % seq_size:
        return x
    seq = seq_axes if len(seq_axes) > 1 else seq_axes[0]
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(batch, seq))
    )


class Block(nn.Module):
    dim: int
    heads: int
    attention: str = "full"  # "full" | "flash" | "ring" | "ulysses"
    mesh: Mesh | None = None
    ffn: str = "dense"  # "dense" | "moe"
    num_experts: int = 4
    moe_topk: int = 1  # 1 = Switch, 2 = GShard top-2
    #: "tokens" (Switch/GShard) or "experts" (expert-choice: perfect load
    #: balance, no aux loss; see ops.moe — scoring workloads only)
    moe_router: str = "tokens"
    #: shard LayerNorm/residual activations along T over tp (megatron
    #: sequence parallelism); needs ``mesh``
    seq_shard: bool = False
    #: grouped-query attention: k/v carry this many heads (< heads; 1 =
    #: MQA). Shrinks the KV cache by heads/kv_heads — the serving-memory
    #: lever. With megatron tp, kv_heads % tp must be 0 so each shard
    #: holds whole kv heads.
    kv_heads: int | None = None
    #: sliding-window attention: each position attends only the previous
    #: ``window`` positions (all backends; the packed banded kernel grid —
    #: and ring's bounded rotations — make cost scale with T * window)
    window: int | None = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        cache=None,
        return_kv: bool = False,
        group: GroupSpec | None = None,
    ):
        """Training/scoring forward, or — with ``cache=(k, v, index)`` —
        one KV-cached decode step on a (B, 1, D) input (see
        :mod:`beholder_tpu.models.decode`).

        With ``group`` (inside a ``shard_map`` over ``group.axis`` —
        group-parallel decode, :mod:`beholder_tpu.cluster.group`) the
        paged branches run MEMBER-LOCAL: the pools in ``cache`` carry
        this member's ``hkv/group.size`` kv-head slice, q/k/v
        projections are head-sliced to match, attention runs on local
        heads only, and a tiled ``all_gather`` reassembles the full
        head dim before the (replicated) output projection — bitwise
        the single-device forward, because head-sliced attention
        touches exactly the same values per head and the gather is
        pure data movement. Paged branches only; anything else raises."""
        b, t, d = x.shape
        h = self.heads
        hkv = self.kv_heads or h
        dh = d // h
        if h % hkv:
            raise ValueError(f"heads {h} not a multiple of kv_heads {hkv}")
        if group is not None and hkv % group.size:
            raise ValueError(
                f"group size {group.size} must divide kv_heads {hkv} "
                "(each member holds whole kv heads)"
            )
        if self.seq_shard:
            x = _seq_shard_constraint(self.mesh, x)
        y = nn.LayerNorm()(x)
        # separate q/k/v projections (not one packed 3d Dense): with
        # megatron column sharding P(None, "tp") each tp shard then holds
        # whole heads of each of q, k, v — a packed kernel's thirds would
        # straddle shard boundaries and force resharding before attention
        q = nn.Dense(d, name="q_proj", dtype=jnp.bfloat16)(y)
        k = nn.Dense(hkv * dh, name="k_proj", dtype=jnp.bfloat16)(y)
        v = nn.Dense(hkv * dh, name="v_proj", dtype=jnp.bfloat16)(y)
        # (B, T, D) -> (B, H, T, Dh): leading dims pass through attention
        q = q.reshape(b, t, h, dh).transpose(0, 2, 1, 3)
        k, v = (
            a.reshape(b, t, hkv, dh).transpose(0, 2, 1, 3) for a in (k, v)
        )
        if cache is not None:
            k_cache, v_cache, index = cache
            if group is not None and not isinstance(
                index, (PagedInfo, ChunkPagedInfo)
            ):
                raise ValueError(
                    "group-parallel forwards are paged-only (PagedInfo "
                    f"or ChunkPagedInfo cache index), got {type(index)}"
                )
            if isinstance(index, PagedInfo):
                # paged serving: scatter the new kv column into this
                # slot's page (OOB page ids drop — inactive slots), then
                # attend the slot's pages IN PLACE via the page table
                # inside the Pallas decode kernel. t must be 1 here;
                # execution falls through to the shared proj/FFN tail.
                q_col, k_col, v_col = q[:, :, 0, :], k[:, :, 0, :], v[:, :, 0, :]
                if group is not None:
                    # member-local tick: slice this member's kv heads
                    # out of the full projections BEFORE the pool write
                    # (quantize/slice commute — per-(head, token)
                    # scales), attend local heads, gather back to full
                    hloc = hkv // group.size
                    k_col = _group_slice(k_col, group, hloc)
                    v_col = _group_slice(v_col, group, hloc)
                    q_col = _group_slice(q_col, group, hloc * (h // hkv))
                k_cache = _pool_write_column(k_cache, index, k_col)
                v_cache = _pool_write_column(v_cache, index, v_col)
                quant = isinstance(k_cache, QuantizedPool)
                att = paged_decode_attention(
                    q_col,
                    k_cache.values if quant else k_cache,
                    v_cache.values if quant else v_cache,
                    index.page_table,
                    index.lens,
                    window=self.window,
                    k_scale=k_cache.scales if quant else None,
                    v_scale=v_cache.scales if quant else None,
                )
                if group is not None:
                    att = jax.lax.all_gather(
                        att, group.axis, axis=1, tiled=True
                    )
                att = att[:, :, None, :]                 # (S, H, 1, Dh)
                kv_out = (k_cache, v_cache)
            elif isinstance(index, ChunkPagedInfo):
                # fused chunk attention (spec verify / prefix-suffix
                # prefill): the t>=1 chunk attends its slot's pool
                # pages IN PLACE via the fused Pallas kernel — no
                # dense gather, no tentative cache writes; the chunk's
                # own kv rides into the kernel as an overlay and comes
                # back to the caller, which scatters exactly the
                # columns it keeps (accepted prefix / suffix pages).
                # Bitwise-identical to the dense-gather branch below
                # (pinned by tests/test_paged_chunk_kernel.py).
                if group is not None:
                    # member-local chunk: head-slice q and the chunk's
                    # own kv overlay; the pools are already this
                    # member's slice. kv_out is the LOCAL columns, so
                    # the caller's scatter lands in the local pool.
                    hloc = hkv // group.size
                    k = _group_slice(k, group, hloc)
                    v = _group_slice(v, group, hloc)
                    q = _group_slice(q, group, hloc * (h // hkv))
                quant = isinstance(k_cache, QuantizedPool)
                att = paged_chunk_attention(
                    q, k, v,
                    k_cache.values if quant else k_cache,
                    v_cache.values if quant else v_cache,
                    index.page_table,
                    index.lens,
                    ctx_len=index.ctx_len,
                    live_pages=index.live_pages,
                    window=self.window,
                    k_scale=k_cache.scales if quant else None,
                    v_scale=v_cache.scales if quant else None,
                    group=1 if group is None else group.size,
                )                                        # (S, H, t, Dh)
                if group is not None:
                    att = jax.lax.all_gather(
                        att, group.axis, axis=1, tiled=True
                    )
                kv_out = (k, v)      # the chunk's OWN hkv-head columns
            else:
                if getattr(index, "ndim", 0) == 1:
                    # per-sequence positions (continuous batching: each
                    # slot sits at its own length). t == 1 is the classic
                    # decode tick; t > 1 is a PER-ROW chunked
                    # continuation (speculative verify: every slot scores
                    # a draft chunk at its own offset) — row b's columns
                    # land at index[b]..index[b]+t-1
                    rows = jnp.arange(b)
                    if t == 1:
                        k_cache = k_cache.at[rows, :, index, :].set(
                            k[:, :, 0, :].astype(k_cache.dtype)
                        )
                        v_cache = v_cache.at[rows, :, index, :].set(
                            v[:, :, 0, :].astype(v_cache.dtype)
                        )
                    else:
                        pos_w = index[:, None] + jnp.arange(t)  # (B, t)
                        k_cache = k_cache.at[rows[:, None], :, pos_w, :].set(
                            k.transpose(0, 2, 1, 3).astype(k_cache.dtype),
                            mode="drop",
                        )
                        v_cache = v_cache.at[rows[:, None], :, pos_w, :].set(
                            v.transpose(0, 2, 1, 3).astype(v_cache.dtype),
                            mode="drop",
                        )
                else:
                    k_cache = jax.lax.dynamic_update_slice(
                        k_cache, k.astype(k_cache.dtype), (0, 0, index, 0)
                    )
                    v_cache = jax.lax.dynamic_update_slice(
                        v_cache, v.astype(v_cache.dtype), (0, 0, index, 0)
                    )
                # Same dtype mix as ops.attention.full_attention (the
                # training forward): score matmul in the cache dtype
                # (bf16 on the MXU), f32 softmax, weights cast back
                # before the PV matmul — so incremental decode reproduces
                # the full causal forward bit-for-bit up to accumulation
                # order. The group dim g = H/Hkv makes every q head in a
                # group read its shared kv-cache head (g=1 degenerates to
                # plain MHA).
                g = h // hkv
                qg = q.astype(k_cache.dtype).reshape(b, hkv, g, t, dh)
                scores = jnp.einsum(
                    "bhgqd,bhkd->bhgqk", qg, k_cache
                ) / jnp.sqrt(jnp.float32(dh))
                positions = jnp.arange(k_cache.shape[2])
                if getattr(index, "ndim", 0) == 1 and t == 1:
                    live = positions[None, :] <= index[:, None]  # (B, L)
                    if self.window is not None:
                        live = live & (
                            positions[None, :] > index[:, None] - self.window
                        )
                    live = live[:, None, None, None, :]
                elif getattr(index, "ndim", 0) == 1:
                    # per-row chunked continuation: query j of row b is
                    # position index[b] + j and sees cache positions
                    # <= itself — the t>1 causal-offset mask, per row
                    pos_q = index[:, None] + jnp.arange(t)       # (B, t)
                    live = (
                        positions[None, None, :] <= pos_q[:, :, None]
                    )                                            # (B, t, L)
                    if self.window is not None:
                        live = live & (
                            positions[None, None, :]
                            > pos_q[:, :, None] - self.window
                        )
                    live = live[:, None, None, :, :]
                else:
                    # scalar index: positions index..index+t-1 are being
                    # decoded this call. t == 1 is the classic decode
                    # step; t > 1 is a CHUNKED continuation — e.g. the
                    # prefix cache's suffix prefill on top of cached
                    # context — causal WITHIN the chunk (query j sees
                    # cache positions <= index + j)
                    pos_q = index + jnp.arange(t)
                    live = positions[None, :] <= pos_q[:, None]  # (t, L)
                    if self.window is not None:
                        # each decoded position sees the previous
                        # ``window`` cache slots, matching the training
                        # band
                        live = live & (
                            positions[None, :] > pos_q[:, None] - self.window
                        )
                    live = live[None, None, None, :, :]
                scores = jnp.where(live, scores, -1e30)
                weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
                att = jnp.einsum(
                    "bhgqk,bhkd->bhgqd", weights.astype(q.dtype), v_cache
                ).reshape(b, h, t, dh)
                kv_out = (k_cache, v_cache)
        else:
            if group is not None:
                raise ValueError(
                    "group-parallel forwards need a paged cache; the "
                    "training/prefill paths stay single-device full-head"
                )
            if self.attention in ("ring", "ulysses") and self.mesh is None:
                raise ValueError(f"{self.attention} attention needs a mesh")
            kv_out = (k, v)  # cache k/v keep their hkv heads
            if self.attention == "ring":
                att = ring_attention(
                    q, k, v, self.mesh, causal=True, window=self.window
                )
            elif self.attention == "ulysses":
                # GQA-native: the kv all-to-all runs at kv-head width when
                # sp divides the per-tp-shard kv head count
                # (ulysses_attention broadcasts groups itself otherwise);
                # window rides the local banded grid
                att = ulysses_attention(
                    q, k, v, self.mesh, causal=True, window=self.window
                )
            elif self.attention == "flash":
                att = flash_attention(q, k, v, causal=True, window=self.window)
            else:
                att = full_attention(q, k, v, causal=True, window=self.window)
        att = att.transpose(0, 2, 1, 3).reshape(b, t, d)
        x = x + nn.Dense(d, name="proj", dtype=jnp.bfloat16)(att).astype(x.dtype)

        if self.seq_shard:
            # row-parallel output lands sequence-sharded: GSPMD emits a
            # reduce-scatter here instead of megatron's first all-reduce
            x = _seq_shard_constraint(self.mesh, x)
        y = nn.LayerNorm()(x)
        if self.ffn == "moe":
            x = x + SwitchFFN(
                d, 4 * d, self.num_experts, name="moe",
                router_topk=self.moe_topk, router_type=self.moe_router,
                mesh=self.mesh,
            )(y)
        else:
            y = nn.Dense(4 * d, name="up", dtype=jnp.bfloat16)(y)
            y = nn.gelu(y)
            x = x + nn.Dense(d, name="down", dtype=jnp.bfloat16)(y).astype(x.dtype)
        if self.seq_shard:
            x = _seq_shard_constraint(self.mesh, x)
        if cache is not None or return_kv:
            return x, kv_out
        return x


class TelemetrySequenceModel(nn.Module):
    """Causal next-delta predictor over telemetry streams."""

    dim: int = 128
    heads: int = 4
    layers: int = 2
    attention: str = "full"
    mesh: Mesh | None = None
    ffn: str = "dense"  # "dense" | "moe" (Switch/GShard, ep-shardable)
    num_experts: int = 4
    moe_topk: int = 1  # 1 = Switch, 2 = GShard top-2
    #: MoE router direction: "tokens" (Switch/GShard) or "experts"
    moe_router: str = "tokens"
    #: rematerialize each block's activations in the backward pass
    #: (jax.checkpoint): trades one extra forward per block for O(layers)
    #: less activation memory — the standard long-context lever on TPU,
    #: where HBM, not FLOPs, is the wall
    remat: bool = False
    #: megatron sequence parallelism: LayerNorm/residual activations
    #: sharded along T over the tp axis (reduce-scatter/all-gather instead
    #: of the two per-block all-reduces); needs ``mesh``
    seq_shard: bool = False
    #: grouped-query attention (GQA; 1 = MQA): k/v heads per block. The
    #: KV cache shrinks by heads/kv_heads (see models/decode.py)
    kv_heads: int | None = None
    #: sliding-window attention span (any attention backend)
    window: int | None = None

    @nn.compact
    def __call__(
        self,
        feats: jax.Array,
        cache=None,
        return_kv: bool = False,
        group: GroupSpec | None = None,
    ):
        """(B, T, FEATURES) -> (B, T) predicted next delta per position.

        With ``cache=(keys, values, index)`` (per-layer tuples) this is a
        KV-cached decode step; with ``return_kv=True`` the per-layer
        (k, v) tensors come back alongside the predictions (prefill).
        ``group`` (paged cache paths only) runs each block member-local
        over its KV-head slice inside a ``shard_map`` — see
        :class:`~beholder_tpu.ops.paged_attention.GroupSpec`.
        """
        x = nn.Dense(self.dim, name="embed")(feats.astype(jnp.float32))
        # remat only pays off in the training backward; the decode/prefill
        # paths route a cache pytree and a Python-bool return_kv through
        # the block, which jax.checkpoint would trace (breaking the
        # `cache is not None or return_kv` branch) — use the plain class
        decoding = cache is not None or return_kv
        block_cls = nn.remat(Block) if (self.remat and not decoding) else Block
        kvs = []
        for i in range(self.layers):
            block = block_cls(
                self.dim,
                self.heads,
                attention=self.attention,
                mesh=self.mesh,
                ffn=self.ffn,
                num_experts=self.num_experts,
                moe_topk=self.moe_topk,
                moe_router=self.moe_router,
                seq_shard=self.seq_shard,
                kv_heads=self.kv_heads,
                window=self.window,
                name=f"block_{i}",
            )
            if cache is not None:
                x, kv = block(
                    x, cache=(cache[0][i], cache[1][i], cache[2]),
                    group=group,
                )
                kvs.append(kv)
            elif return_kv:
                if group is not None:
                    raise ValueError(
                        "group-parallel forwards need a paged cache "
                        "(prefill stays single-device full-head)"
                    )
                x, kv = block(x, return_kv=True)
                kvs.append(kv)
            else:
                x = block(x)
        if self.seq_shard:
            x = _seq_shard_constraint(self.mesh, x)
        x = nn.LayerNorm()(x)
        preds = nn.Dense(1, name="head", dtype=jnp.float32)(x)[..., 0]
        if cache is not None or return_kv:
            return preds, kvs
        return preds


def stream_features(progress: jax.Array, statuses: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(B, T+1) progress / (B, T+1) statuses -> (B, T, F) feats, (B, T) targets.

    Feature t is (delta_t, one-hot status_t); target t is delta_{t+1}
    (last position's target is a zero pad, masked out by the loss).
    """
    deltas = jnp.diff(progress.astype(jnp.float32), axis=-1)  # (B, T)
    oh = jax.nn.one_hot(statuses[:, 1:], NUM_STATUSES)
    feats = jnp.concatenate([deltas[..., None], oh], axis=-1)
    targets = jnp.concatenate(
        [deltas[:, 1:], jnp.zeros_like(deltas[:, :1])], axis=-1
    )
    return feats, targets


AUX_LOSS_WEIGHT = 0.01  # standard Switch load-balance coefficient
Z_LOSS_WEIGHT = 1e-3  # ST-MoE router z-loss coefficient


def seq_loss(model: TelemetrySequenceModel, params, feats, targets) -> jax.Array:
    pred, sown = model.apply(params, feats, mutable="intermediates")
    err = (pred - targets) ** 2
    mask = jnp.ones_like(err).at[:, -1].set(0.0)  # last target is padding
    loss = (err * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    # MoE blocks sow per-layer router terms by name; dense models sow
    # nothing. drop_fraction is a health METRIC, never a loss term.
    from jax.tree_util import tree_flatten_with_path

    from beholder_tpu.parallel.sharding import path_key_names

    for path, leaf in tree_flatten_with_path(sown)[0]:
        names = path_key_names(path)
        if "aux_loss" in names:
            loss = loss + AUX_LOSS_WEIGHT * leaf
        elif "router_z_loss" in names:
            loss = loss + Z_LOSS_WEIGHT * leaf
    return loss


def init_seq_state(
    rng: jax.Array,
    seq_len: int,
    model: TelemetrySequenceModel | None = None,
    learning_rate: float = 1e-3,
) -> tuple[TrainState, optax.GradientTransformation, TelemetrySequenceModel]:
    model = model or TelemetrySequenceModel()
    variables = model.init(rng, jnp.zeros((1, seq_len, FEATURES)))
    # MoE blocks sow an "intermediates" collection during init; only the
    # trainable params belong in the train state
    params = {"params": variables["params"]}
    tx = optax.adam(learning_rate)
    return TrainState(params, tx.init(params), jnp.int32(0)), tx, model


def seq_train_step(model, tx, state: TrainState, feats, targets):
    return apply_gradients(state, tx, lambda p: seq_loss(model, p, feats, targets))
