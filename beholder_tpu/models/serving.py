"""Serving v2: paged KV cache + continuous batching.

EXTENSION BEYOND THE REFERENCE (which has no inference of any kind —
SURVEY.md §0). :mod:`beholder_tpu.models.decode` serves a FIXED batch
with one dense (B, Hkv, max_len, Dh) cache per layer; this module serves
a CHANGING population of requests the way modern LLM servers do
(vLLM-style), re-thought for XLA's static-shape compilation model:

- **Paged pool.** Each layer's cache is a (num_pages, Hkv, Dh, page)
  pool — tokens on the minor (lane) dim, the TPU-native page layout (see
  :mod:`beholder_tpu.ops.paged_attention`); a sequence owns a list of
  pages (``page_table`` row). Memory scales with TOKENS IN FLIGHT, not
  slots x max_len; a retiring request returns its pages to a free stack.
- **Paged at COMPUTE time too.** The decode tick scatters each slot's
  new kv column into its page and then attends the pages IN PLACE via
  the scalar-prefetched page table inside a Pallas kernel
  (:func:`~beholder_tpu.ops.paged_attention.paged_decode_attention`) —
  no dense (slots, max_pages*page) view of the cache ever materializes
  (round 3 gathered one per layer per tick; pinned gone by
  ``tests/test_serving.py::test_tick_never_materializes_dense_views``).
- **Static shapes everywhere.** The tick is ONE compiled program for all
  slots; admission and retirement are fixed shape too: page allocation
  is a masked vectorized stack pop, freeing a masked push — no
  data-dependent Python in jit.
- **Int8 KV cache** (``cache_dtype="int8"``): pages are stored int8 with
  per-(token, head) scales, dequantized inside the decode kernel — the
  cache's HBM FOOTPRINT halves vs bf16, composing with GQA's kv-head
  shrink (same capacity lever stack as vLLM + the weight-only quant in
  :mod:`beholder_tpu.ops.quant`). Throughput is shape-dependent and
  measured per round in BENCH_NOTES.md (~1.2x at the headline shape,
  ~0.8x at long context where the kernel is issue-bound, not
  bandwidth-bound) — int8's contract here is capacity, not speed.
- **fp8 KV cache** (``cache_dtype="fp8"``): pages are ``float8_e4m3fn``
  values + uint8 E8M0 per-(token, head) scales (``2**(e - 127)``, the
  MX block-format scale encoding — see :mod:`beholder_tpu.ops.quant`).
  Values stay 8-bit; the capacity win over int8 is the SCALE
  side-channel (4 bytes -> 1 byte per (head, token) block): page bytes
  go from ``Hkv*page*(Dh + 4)`` to ``Hkv*page*(Dh + 1)``, so the same
  HBM budget holds more pages — large at telemetry head dims (~15% more
  at Dh=16), modest at LLM dims (~2% at Dh=128); the honest numbers are
  pinned per round in BENCH_NOTES.md. Same values+scales container as
  int8, so export/import, drain migration, and prefix pins move fp8
  pages byte-identically with ZERO new structural code paths.
- **Prefix sharing** (:func:`paged_fork` / :meth:`ContinuousBatcher.
  run_what_if`): one sequence forked into k branches shares its FULL
  prefix pages by refcount (``page_ref``) — a slot only writes at its
  own length, past every full prefix page, so shared pages are
  read-only without any copy-on-write machinery; only a partial tail
  page is copied per fork. Prefill runs once instead of k times and
  the pool holds the prefix once — the vLLM parallel-sampling lever,
  used here for what-if forecasting (same telemetry history, k
  hypothetical status branches). Release returns a page to the free
  stack only when its last owner retires.
- **Continuous batching, two ways.** :meth:`ContinuousBatcher.run` is
  the flexible scheduler: admit queued requests into free slots
  mid-flight, tick all active slots together, retire finished ones. For
  fixed-horizon fleets :meth:`ContinuousBatcher.run_waves` fuses
  admit -> scan(ticks) -> retire into ONE compiled program per wave
  (:func:`serve_wave`) — the prediction feedback loop stays ON DEVICE
  inside one ``lax.scan`` (no per-token host round-trip, the round-3
  latency wall).
- **Zero mid-flight host readbacks** (round 5). On a tunneled
  accelerator a single device->host read costs ~65 ms (measured; jit
  dispatch is ~20 us) — round 4's "100x slower than dense" serving
  number was ~11 such syncs per wave plus ~100 eager dispatches, not
  kernel time. Both schedulers now keep every decision input on the
  host (page headroom and retirement are host-arithmetic over request
  lengths), build features in NumPy, and read results (plus the sticky
  ``alloc_failed`` flag) back in ONE ``jax.device_get`` at the end.

The paged decode is numerically equivalent to the dense per-request
rollout (pinned by ``tests/test_serving.py``).
"""

from __future__ import annotations

import time
from contextlib import contextmanager, nullcontext
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from beholder_tpu.ops import NUM_STATUSES
from beholder_tpu.ops.paged_attention import (
    GroupSpec,
    PagedInfo,
    QuantizedPool,
    pool_dtype_family,
)
from beholder_tpu.tracing import current_trace_id, from_traceparent

from .sequence import TelemetrySequenceModel


class PagedKVState(NamedTuple):
    """Paged serving state (a pytree; every leaf has a static shape).

    - ``k_pools``/``v_pools``: per-layer (num_pages, Hkv, Dh, page)
      arrays, or :class:`~beholder_tpu.ops.paged_attention.QuantizedPool`
      (int8 values + (num_pages, Hkv, page) f32 scales) under int8
      caching
    - ``page_table``: (slots, max_pages) pool indices per slot
    - ``seq_lens``: (slots,) tokens written per slot
    - ``active``: (slots,) bool
    - ``free_stack``: (num_pages,) pool indices; ``free_stack[:free_top]``
      are free
    - ``page_ref``: (num_pages,) reference counts — 1 for a page owned by
      one slot, >1 for a prefix page SHARED between forks
      (:func:`paged_fork`); release only returns a page to the free stack
      when its count reaches zero
    - ``alloc_failed``: sticky error flag (pool exhausted / table
      overflow) — checked host-side by the batcher
    """

    k_pools: tuple
    v_pools: tuple
    page_table: jax.Array
    seq_lens: jax.Array
    active: jax.Array
    free_stack: jax.Array
    free_top: jax.Array
    page_ref: jax.Array
    alloc_failed: jax.Array


def init_paged(
    model: TelemetrySequenceModel,
    num_pages: int,
    page_size: int,
    slots: int,
    max_pages_per_seq: int,
    cache_dtype=jnp.bfloat16,
) -> PagedKVState:
    dh = model.dim // model.heads
    hkv = model.kv_heads or model.heads
    shape = (num_pages, hkv, dh, page_size)
    if cache_dtype in ("bf16", "bfloat16"):
        cache_dtype = jnp.bfloat16  # config-file spelling
    if cache_dtype in (jnp.int8, "int8"):
        def pool():
            return QuantizedPool(
                jnp.zeros(shape, jnp.int8),
                jnp.ones((num_pages, hkv, page_size), jnp.float32),
            )
    elif cache_dtype in (jnp.float8_e4m3fn, "fp8"):
        # fp8 shared-exponent pages: float8_e4m3fn values + uint8 E8M0
        # per-(head, token) scales (127 = biased exponent of 2**0, the
        # identity scale — the fp8 twin of int8's f32 ones). Same
        # values+scales container as int8, so every structural pool op
        # (export/import, migration, forks, prefix pins) is already
        # generic over it.
        from beholder_tpu.ops.quant import E8M0_BIAS

        def pool():
            return QuantizedPool(
                jnp.zeros(shape, jnp.float8_e4m3fn),
                jnp.full(
                    (num_pages, hkv, page_size), E8M0_BIAS, jnp.uint8
                ),
            )
    else:
        def pool():
            return jnp.zeros(shape, cache_dtype)
    return PagedKVState(
        tuple(pool() for _ in range(model.layers)),
        tuple(pool() for _ in range(model.layers)),
        jnp.zeros((slots, max_pages_per_seq), jnp.int32),
        jnp.zeros((slots,), jnp.int32),
        jnp.zeros((slots,), bool),
        jnp.arange(num_pages, dtype=jnp.int32),
        jnp.int32(num_pages),
        jnp.zeros((num_pages,), jnp.int32),
        jnp.zeros((), bool),
    )


def _pool_geometry(state: PagedKVState) -> tuple[int, int]:
    """(num_pages, page_size) of the state's pools (quantized or not)."""
    p0 = state.k_pools[0]
    vals = p0.values if isinstance(p0, QuantizedPool) else p0
    return vals.shape[0], vals.shape[3]


def _pop_pages(state: PagedKVState, need: jax.Array):
    """Vectorized masked stack pop: needer i (with ``need[i]``) gets page
    ``free_stack[free_top - 1 - rank_i]`` where rank_i numbers the
    needers; popped pages start at refcount 1. Returns
    (pages (len(need),), new_top, new_ref, failed)."""
    num_pages = state.free_stack.shape[0]
    rank = jnp.cumsum(need.astype(jnp.int32)) - 1
    n = need.sum().astype(jnp.int32)
    idx = state.free_top - 1 - rank
    failed = state.alloc_failed | (n > state.free_top)
    pages = state.free_stack[jnp.clip(idx, 0, num_pages - 1)]
    ref = state.page_ref.at[
        jnp.where(need, pages, num_pages)
    ].set(1, mode="drop")
    return pages, state.free_top - n, ref, failed


def _unref_pages(
    state: PagedKVState, held_flat: jax.Array, alive_flat: jax.Array
) -> PagedKVState:
    """Drop one reference from each held page (``held_flat`` page ids
    where ``alive_flat``); pages whose count reaches zero go back on the
    free stack in ONE vectorized pass over the pool (a compaction scan —
    no dedup needed even when several released slots shared a page)."""
    num_pages, _ = _pool_geometry(state)
    ids = jnp.where(alive_flat, held_flat, num_pages)
    ref = state.page_ref.at[ids].add(-1, mode="drop")
    newly_free = (ref <= 0) & (state.page_ref > 0)
    rank = jnp.cumsum(newly_free.astype(jnp.int32)) - 1
    dest = jnp.where(newly_free, state.free_top + rank, num_pages)
    stack = state.free_stack.at[dest].set(
        jnp.arange(num_pages, dtype=jnp.int32), mode="drop"
    )
    return state._replace(
        free_stack=stack,
        free_top=state.free_top + newly_free.sum().astype(jnp.int32),
        page_ref=jnp.maximum(ref, 0),
    )


def _alloc_for_tick(state: PagedKVState) -> PagedKVState:
    """Give every active slot whose next write position opens a fresh
    page (len % page == 0) a page off the free stack."""
    _, page = _pool_geometry(state)
    slots, max_pages = state.page_table.shape
    need = state.active & (state.seq_lens % page == 0)
    pages, new_top, ref, failed = _pop_pages(state, need)
    pidx = state.seq_lens // page
    failed = failed | jnp.any(need & (pidx >= max_pages))
    rows = jnp.where(need, jnp.arange(slots), slots)  # OOB row -> dropped
    table = state.page_table.at[
        rows, jnp.clip(pidx, 0, max_pages - 1)
    ].set(pages, mode="drop")
    return state._replace(
        page_table=table, free_top=new_top, page_ref=ref,
        alloc_failed=failed,
    )


def slot_cache(state: PagedKVState, slot: int, layer: int):
    """DEBUG/TEST helper: gather ``slot``'s written cache for ``layer``
    as dense (Hkv, Dh, seq_len) arrays (dequantized). Never called by
    the serving path — the tick attends pages in place."""
    num_pages, page = _pool_geometry(state)

    def dense(pool):
        if isinstance(pool, QuantizedPool):
            from beholder_tpu.ops.quant import pool_scales_f32

            vals = (
                pool.values.astype(jnp.float32)
                * pool_scales_f32(pool.scales)[:, :, None, :]
            )
        else:
            vals = pool.astype(jnp.float32)
        g = vals[state.page_table[slot]]          # (P, Hkv, Dh, page)
        g = g.transpose(1, 2, 0, 3).reshape(
            vals.shape[1], vals.shape[2], -1
        )
        return g[:, :, : int(state.seq_lens[slot])]

    return dense(state.k_pools[layer]), dense(state.v_pools[layer])


def paged_decode_tick(
    model: TelemetrySequenceModel, params, state: PagedKVState, feats_t,
    group: GroupSpec | None = None,
):
    """One continuous-batching decode step for ALL slots.

    ``feats_t`` is (slots, FEATURES); inactive slots run too (their
    writes are dropped, their outputs ignored) — that is what keeps the
    tick a single compiled program. Returns ((slots,) predictions,
    updated state). ``group`` (inside a ``shard_map`` member — the
    group engine) runs the forward member-local over this member's
    KV-head pool slice; the allocator arithmetic here is head-free, so
    it runs identically (in lockstep) on every member."""
    state = _alloc_for_tick(state)
    num_pages, page = _pool_geometry(state)
    slots = state.page_table.shape[0]

    rows = jnp.arange(slots)
    pidx = jnp.clip(state.seq_lens // page, 0, state.page_table.shape[1] - 1)
    write_pages = jnp.where(
        state.active, state.page_table[rows, pidx], num_pages  # OOB -> drop
    )
    info = PagedInfo(
        state.page_table,
        # inactive slots pass the -1 length sentinel: the kernel's live
        # page range [p_lo, n_hi) is then empty, so dead slots issue NO
        # page DMAs (round-4 advisor finding: a released slot's stale
        # page_table row used to cost one wasted page DMA per layer per
        # tick) and their rows are fully masked (output 0, ignored)
        jnp.where(state.active, state.seq_lens, -1),
        write_pages,
        state.seq_lens % page,
    )

    preds, new_kvs = model.apply(
        params,
        feats_t[:, None, :],
        cache=(state.k_pools, state.v_pools, info),
        group=group,
    )
    state = state._replace(
        k_pools=tuple(k for k, _ in new_kvs),
        v_pools=tuple(v for _, v in new_kvs),
        seq_lens=state.seq_lens + state.active.astype(jnp.int32),
    )
    return preds[:, 0], state


def _quantize_tokens(x: jax.Array, values_dtype):
    """(..., Dh, T) -> 8-bit values + (..., T) per-(head, token) scales
    via the pool's scheme (int8 symmetric or fp8/E8M0 — ONE dispatch in
    :func:`beholder_tpu.ops.quant.pool_quantize`; the decode tick's
    column writes must match the admit path's chunk writes exactly)."""
    from beholder_tpu.ops.quant import pool_quantize

    return pool_quantize(x, axis=-2, values_dtype=values_dtype)


def _slice_chunk_heads(chunk, group: GroupSpec):
    """This group member's KV-head slice of a FULL-HEAD chunk array
    (group-parallel decode — :mod:`beholder_tpu.cluster.group`): page
    chunks travel the wire full-head (``(n, Hkv, ...)``, head axis 1
    for both values and their ``(n, Hkv, page)`` scales), and each
    member keeps only its ``Hkv/size`` slice on import/adopt. Only
    meaningful inside a ``shard_map`` over ``group.axis``. Quantized
    chunks arrive as ``(values, scales)`` pairs; both slice on axis 1.
    Head-slicing commutes with the pool's per-(head, token) quantize,
    so slicing BEFORE :func:`_write_chunks` leaves each member's pool
    bytes exactly the full pool's slice."""

    def cut(a):
        hloc = a.shape[1] // group.size
        m = jax.lax.axis_index(group.axis)
        return jax.lax.dynamic_slice_in_dim(a, m * hloc, hloc, axis=1)

    if isinstance(chunk, tuple):
        vals, scales = chunk
        return (cut(vals), cut(scales))
    return cut(chunk)


def _write_chunks(pool, drop_pages, chunks):
    """Scatter (n, Hkv, Dh, page) chunks into pool rows ``drop_pages``
    (OOB entries dropped), quantizing per token when the pool is
    quantized (int8 or fp8)."""
    if isinstance(pool, QuantizedPool):
        q, scale = _quantize_tokens(chunks, pool.values.dtype)
        return QuantizedPool(
            pool.values.at[drop_pages].set(q, mode="drop"),
            pool.scales.at[drop_pages].set(scale, mode="drop"),
        )
    return pool.at[drop_pages].set(chunks.astype(pool.dtype), mode="drop")


def paged_admit(
    model: TelemetrySequenceModel,
    params,
    state: PagedKVState,
    slot: jax.Array,
    feats_padded: jax.Array,
    prefix_len: jax.Array,
):
    """Admit one request into ``slot``: prefill its (1, T_max, F) padded
    prefix in one forward, allocate ceil(prefix_len/page) pages, and
    write the prefix kv into them. Returns ((,) last prediction, state).

    The page count is data-dependent but the WORK is not: the masked
    writes cover all T_max//page chunks and drop the dead ones.
    """
    preds, state = paged_admit_batch(
        model, params, state,
        jnp.asarray(slot, jnp.int32).reshape(1), feats_padded,
        jnp.asarray(prefix_len, jnp.int32).reshape(1),
    )
    return preds[0], state


def paged_admit_batch(
    model: TelemetrySequenceModel,
    params,
    state: PagedKVState,
    slot_ids: jax.Array,
    feats_padded: jax.Array,
    prefix_lens: jax.Array,
    fused: bool = False,
    group: GroupSpec | None = None,
):
    """Admit a WAVE of requests in one prefill: ``feats_padded`` is
    (n, T_max, F) (page-multiple T_max), ``slot_ids``/``prefix_lens``
    are (n,). A request with ``prefix_lens[i] == 0`` is skipped (slot id
    should then be out of range so its table write drops). Returns
    ((n,) last predictions, state).

    The default (``fused=False``, the reference oracle) runs the plain
    dense prefill (``return_kv=True``): each layer materializes a
    (n, Hkv, T_max, Dh) context buffer for the wave. With ``fused=True``
    (the fused-wave lane — ``instance.serving.fused_wave``) the SAME
    forward instead routes through the fused chunk kernel
    (:func:`~beholder_tpu.ops.paged_attention.paged_chunk_attention`)
    with an EMPTY paged context (lens 0): wave membership is just the
    chunk slot set, attention is causal within each chunk exactly like
    the dense program, and no dense per-wave context transient ever
    lands — the no-transient contract the spec-verify and prefix-suffix
    paths already have, extended to fixed-horizon fleets. Both branches
    return the chunk's own kv columns, so the page scatter below is
    shared; the lane is bitwise-pinned against the dense wave program
    (tests/test_serving.py)."""
    num_pages, page = _pool_geometry(state)
    slots, max_pages = state.page_table.shape
    n, t_max, _ = feats_padded.shape
    if t_max % page:
        raise ValueError(f"padded prefix {t_max} not a page multiple ({page})")
    p_max = t_max // page

    if fused:
        # empty context: one (ignored) page per row, all lens 0; ctx
        # width t_max — the dense branch's buffer width, so the math
        # (and its accumulation order) is the dense program's, column
        # for column
        from beholder_tpu.ops.paged_attention import ChunkPagedInfo

        info = ChunkPagedInfo(
            jnp.zeros((n, 1), jnp.int32),
            jnp.zeros((n,), jnp.int32),
            t_max,
        )
        preds, kvs = model.apply(
            params, feats_padded,
            cache=(state.k_pools, state.v_pools, info),
            group=group,
        )
    else:
        preds, kvs = model.apply(params, feats_padded, return_kv=True)
        if group is not None:
            # cold group admit: the prefill forward itself runs
            # replicated full-head on every member (no paged context
            # to attend, nothing to shard); only the pool SCATTER is
            # member-local, so slice the kv columns here. The fused
            # branch above already returns member-local columns.
            kvs = [
                (_slice_chunk_heads(k, group), _slice_chunk_heads(v, group))
                for k, v in kvs
            ]
    last_pred = preds[
        jnp.arange(n), jnp.clip(prefix_lens - 1, 0, t_max - 1)
    ]

    n_pages = -(-prefix_lens // page)                      # (n,) ceil
    chunk_alive = (
        jax.lax.broadcasted_iota(jnp.int32, (n, p_max), 1)
        < n_pages[:, None]
    )
    pages, new_top, ref, failed = _pop_pages(state, chunk_alive.reshape(-1))
    pages = pages.reshape(n, p_max)
    failed = failed | jnp.any(n_pages > max_pages)

    table_rows = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (n, max_pages), 1)
        < n_pages[:, None],
        jnp.pad(pages, ((0, 0), (0, max(0, max_pages - p_max))))[
            :, :max_pages
        ],
        0,
    )
    drop = jnp.where(chunk_alive, pages, num_pages).reshape(-1)

    k_pools, v_pools = [], []
    for layer, (k, v) in enumerate(kvs):
        def chunks(a):
            # (n, Hkv, T_max, Dh) -> (n*p_max, Hkv, Dh, page)
            hkv, dh = a.shape[1], a.shape[3]
            a = a.transpose(0, 1, 3, 2)                 # (n, Hkv, Dh, T)
            a = a.reshape(n, hkv, dh, p_max, page)
            return a.transpose(0, 3, 1, 2, 4).reshape(
                n * p_max, hkv, dh, page
            )
        k_pools.append(_write_chunks(state.k_pools[layer], drop, chunks(k)))
        v_pools.append(_write_chunks(state.v_pools[layer], drop, chunks(v)))

    admitted = prefix_lens > 0
    safe_slots = jnp.where(
        admitted, jnp.clip(slot_ids, 0, slots - 1), slots  # OOB -> drop
    )
    state = state._replace(
        k_pools=tuple(k_pools),
        v_pools=tuple(v_pools),
        page_table=state.page_table.at[safe_slots].set(
            table_rows, mode="drop"
        ),
        seq_lens=state.seq_lens.at[safe_slots].set(
            prefix_lens, mode="drop"
        ),
        active=state.active.at[safe_slots].set(admitted, mode="drop"),
        free_top=new_top,
        page_ref=ref,
        alloc_failed=failed,
    )
    return last_pred, state


def paged_admit_with_prefix(
    model: TelemetrySequenceModel,
    params,
    state: PagedKVState,
    slot: jax.Array,
    suffix_feats: jax.Array,
    suffix_len: jax.Array,
    cached_pages: jax.Array,
    fused: bool = False,
    group: GroupSpec | None = None,
):
    """Admit one request whose first ``len(cached_pages) * page`` tokens
    are already resident in the pool (an automatic-prefix-cache hit —
    :mod:`beholder_tpu.cache.prefix`): prefill ONLY the uncached suffix.

    ``suffix_feats`` is the (1, S_max, F) page-multiple-padded feature
    tail (the tokens after the cached prefix), ``suffix_len`` how many
    of those rows are real (>= 1: the lookup is capped so at least one
    token is always prefilled — the admit prediction needs a live
    forward). ``cached_pages`` is the (P_hit,) static-width chain of
    pool pages holding the prefix KV, root-first.

    The suffix forward needs attention over the cached context. The
    default (``fused=False``, the reference oracle) gathers the hit
    pages into a dense per-layer (1, Hkv, T_hit, Dh) context once
    (dequantized under int8 pools) and runs the suffix through the
    model's chunked dense-cache path (causal within the chunk, full
    visibility of the context); with ``fused=True`` the suffix instead
    attends the cached pages IN PLACE through the fused chunk kernel
    (:func:`~beholder_tpu.ops.paged_attention.paged_chunk_attention` —
    no dense context buffer, int8 dequantized inside the kernel,
    bitwise-identical admit prediction and pool bytes). Either way the
    fresh suffix KV is scattered into newly popped pages exactly like
    :func:`paged_admit_batch`'s chunk writes. Cost scales with S, not
    T_hit + S — prefill FLOPs follow NOVEL tokens. The slot takes one
    reference on every adopted page (release drops it; the cache's own
    reference keeps the page resident after retirement).

    Returns ((,) last prediction, state)."""
    num_pages, page = _pool_geometry(state)
    slots, max_pages = state.page_table.shape
    _, s_max, _ = suffix_feats.shape
    if s_max % page:
        raise ValueError(f"padded suffix {s_max} not a page multiple ({page})")
    p_hit = cached_pages.shape[0]
    t_hit = p_hit * page
    p_sfx = s_max // page

    if group is not None and not fused:
        # the dense oracle gathers the cached context out of the pool,
        # and a group member's pool holds only its head slice — there
        # is no replicated full-head gather to run. Warm group admits
        # therefore ALWAYS take the fused kernel (fused == dense is
        # already bitwise-pinned repo-wide, and head-sliced fused
        # attention is pinned by the group engine's own tests).
        raise ValueError(
            "group-parallel prefix-hit admission requires fused=True "
            "(the dense context gather cannot run on a head slice)"
        )
    if fused:
        # fused path: the suffix chunk attends the cached pages in
        # place (per-row offsets all t_hit; ctx width t_hit + s_max —
        # the dense oracle's buffer width, so the forward is bitwise
        # the dense path below); kvs come back as the suffix's own
        # (1, Hkv, s_max, Dh) columns
        from beholder_tpu.ops.paged_attention import ChunkPagedInfo

        info = ChunkPagedInfo(
            cached_pages[None, :],
            jnp.full((1,), t_hit, jnp.int32),
            t_hit + s_max,
        )
        preds, kvs = model.apply(
            params, suffix_feats,
            cache=(state.k_pools, state.v_pools, info),
            group=group,
        )
    else:
        def dense_context(pool):
            """(1, Hkv, t_hit, Dh) context from the cached pages (bf16)."""
            if isinstance(pool, QuantizedPool):
                from beholder_tpu.ops.quant import pool_scales_f32

                vals = (
                    pool.values.astype(jnp.float32)
                    * pool_scales_f32(pool.scales)[:, :, None, :]
                ).astype(jnp.bfloat16)
            else:
                vals = pool.astype(jnp.bfloat16)
            g = vals[cached_pages]                # (P, Hkv, Dh, page)
            g = g.transpose(1, 0, 3, 2).reshape(
                vals.shape[1], t_hit, vals.shape[2]
            )
            return g[None]

        def ctx_cache(pool):
            ctx = dense_context(pool)
            buf = jnp.zeros(
                (1, ctx.shape[1], t_hit + s_max, ctx.shape[3]),
                jnp.bfloat16,
            )
            return jax.lax.dynamic_update_slice(buf, ctx, (0, 0, 0, 0))

        ks = tuple(ctx_cache(p) for p in state.k_pools)
        vs = tuple(ctx_cache(p) for p in state.v_pools)
        # chunked dense-cache forward: suffix queries attend cached
        # context + themselves (causal within the chunk —
        # sequence.Block's scalar-index path); writes land at
        # positions t_hit..t_hit+s_max-1
        preds, kvs = model.apply(
            params, suffix_feats, cache=(ks, vs, t_hit)
        )
    last_pred = preds[0, jnp.clip(suffix_len - 1, 0, s_max - 1)]

    n_sfx_pages = -(-suffix_len // page)
    chunk_alive = jnp.arange(p_sfx) < n_sfx_pages
    pages, new_top, ref, failed = _pop_pages(state, chunk_alive)
    failed = failed | (p_hit + n_sfx_pages > max_pages)
    drop = jnp.where(chunk_alive, pages, num_pages)

    k_pools, v_pools = [], []
    for layer, (k_dense, v_dense) in enumerate(kvs):
        def chunks(a):
            # suffix kv -> (p_sfx, Hkv, Dh, page). The dense path's kv
            # output is the full (1, Hkv, t_hit + s_max, Dh) updated
            # buffer (slice the suffix region out); the fused path
            # already returns only the suffix's own (1, Hkv, s_max,
            # Dh) columns — same values either way.
            hkv, dh = a.shape[1], a.shape[3]
            a = (
                a[0]
                if fused
                else jax.lax.dynamic_slice_in_dim(a[0], t_hit, s_max, axis=1)
            )
            a = a.transpose(0, 2, 1)                 # (Hkv, Dh, s_max)
            a = a.reshape(hkv, dh, p_sfx, page)
            return a.transpose(2, 0, 1, 3)           # (p_sfx, Hkv, Dh, page)
        k_pools.append(_write_chunks(state.k_pools[layer], drop, chunks(k_dense)))
        v_pools.append(_write_chunks(state.v_pools[layer], drop, chunks(v_dense)))

    # adopted pages: +1 reference for this slot (on top of the cache's)
    ref = ref.at[cached_pages].add(1, mode="drop")

    row = jnp.concatenate(
        [
            cached_pages,
            jnp.where(chunk_alive, pages, 0),
            jnp.zeros((max(0, max_pages - p_hit - p_sfx),), jnp.int32),
        ]
    )[:max_pages]
    safe_slot = jnp.clip(jnp.asarray(slot, jnp.int32), 0, slots - 1)
    return last_pred, state._replace(
        k_pools=tuple(k_pools),
        v_pools=tuple(v_pools),
        page_table=state.page_table.at[safe_slot].set(row),
        seq_lens=state.seq_lens.at[safe_slot].set(t_hit + suffix_len),
        active=state.active.at[safe_slot].set(True),
        free_top=new_top,
        page_ref=ref,
        alloc_failed=failed,
    )


def kv_prefill_chunks(
    model: TelemetrySequenceModel,
    params,
    feats_padded: jax.Array,
    prefix_len: jax.Array,
    page_size: int,
):
    """Prefill ONE request OFF-POOL for a prefill->decode handoff
    (:mod:`beholder_tpu.cluster`): run the same batched-prefill forward
    :func:`paged_admit_batch` runs, but instead of scattering the KV
    into THIS worker's pool, return it as page-granular chunks —
    per-layer ``(p_max, Hkv, Dh, page)`` arrays in pool layout, the
    unit :func:`paged_adopt_chunks` writes into a DIFFERENT shard's
    pool after a device-to-device transfer.

    The chunk construction is byte-for-byte the transpose/reshape
    ``paged_admit_batch`` feeds :func:`_write_chunks`, and the chunks
    stay in the forward's dtype (the adopting shard's
    ``_write_chunks`` applies the same cast/quantize the colocated
    admit would), so a handoff admit leaves the destination pool
    bitwise-identical to a local prefill of the same request.

    ``feats_padded`` is (1, T_max, F) with page-multiple T_max.
    Returns ((,) last prediction, per-layer k chunks tuple, per-layer
    v chunks tuple)."""
    n, t_max, _ = feats_padded.shape
    if n != 1:
        raise ValueError(f"kv_prefill_chunks takes ONE request, got {n}")
    if t_max % page_size:
        raise ValueError(
            f"padded prefix {t_max} not a page multiple ({page_size})"
        )
    p_max = t_max // page_size

    preds, kvs = model.apply(params, feats_padded, return_kv=True)
    last_pred = preds[0, jnp.clip(prefix_len - 1, 0, t_max - 1)]

    def chunks(a):
        # (1, Hkv, T_max, Dh) -> (p_max, Hkv, Dh, page) — the exact
        # layout paged_admit_batch scatters (its n == 1 case)
        hkv, dh = a.shape[1], a.shape[3]
        a = a.transpose(0, 1, 3, 2)                 # (1, Hkv, Dh, T)
        a = a.reshape(1, hkv, dh, p_max, page_size)
        return a.transpose(0, 3, 1, 2, 4).reshape(
            p_max, hkv, dh, page_size
        )

    chunks_k = tuple(chunks(k) for k, _ in kvs)
    chunks_v = tuple(chunks(v) for _, v in kvs)
    return last_pred, chunks_k, chunks_v


def paged_adopt_chunks(
    state: PagedKVState,
    slot: jax.Array,
    chunks_k: tuple,
    chunks_v: tuple,
    n_pages: jax.Array,
    seq_len: jax.Array,
    group: GroupSpec | None = None,
) -> PagedKVState:
    """Shard-aware pool op: admit one request whose prefill KV arrives
    as page chunks from ANOTHER worker (:func:`kv_prefill_chunks` +
    the cluster transfer engine) — pop ``n_pages`` pages off THIS
    shard's free stack, write the transferred chunks through the same
    :func:`_write_chunks` path a local prefill uses (cast/quantize
    included, so pool content is bitwise what a colocated admit would
    have written), and install the slot's page-table row, length, and
    active bit. The dead tail of the static-width chunks (rows past
    ``n_pages``) is masked off exactly like ``paged_admit_batch``'s
    chunk_alive handling.

    ``group``: transferred chunks arrive FULL-HEAD from a
    single-device prefill worker; each group member adopts only its
    KV-head slice (allocator arithmetic is head-free and runs in
    lockstep)."""
    num_pages, page = _pool_geometry(state)
    slots, max_pages = state.page_table.shape
    if group is not None:
        chunks_k = tuple(_slice_chunk_heads(c, group) for c in chunks_k)
        chunks_v = tuple(_slice_chunk_heads(c, group) for c in chunks_v)
    p_max = chunks_k[0].shape[0]
    chunk_alive = jnp.arange(p_max) < n_pages
    pages, new_top, ref, failed = _pop_pages(state, chunk_alive)
    failed = failed | (n_pages > max_pages)
    drop = jnp.where(chunk_alive, pages, num_pages)

    k_pools = tuple(
        _write_chunks(pool, drop, ck)
        for pool, ck in zip(state.k_pools, chunks_k)
    )
    v_pools = tuple(
        _write_chunks(pool, drop, cv)
        for pool, cv in zip(state.v_pools, chunks_v)
    )

    row = jnp.concatenate(
        [
            jnp.where(chunk_alive, pages, 0),
            jnp.zeros((max(0, max_pages - p_max),), jnp.int32),
        ]
    )[:max_pages]
    safe_slot = jnp.clip(jnp.asarray(slot, jnp.int32), 0, slots - 1)
    return state._replace(
        k_pools=k_pools,
        v_pools=v_pools,
        page_table=state.page_table.at[safe_slot].set(row),
        seq_lens=state.seq_lens.at[safe_slot].set(
            jnp.asarray(seq_len, jnp.int32)
        ),
        active=state.active.at[safe_slot].set(True),
        free_top=new_top,
        page_ref=ref,
        alloc_failed=failed,
    )


def paged_export_pages(state: PagedKVState, page_ids: jax.Array):
    """Gather pages ``page_ids`` (n,) in POOL REPRESENTATION for a
    live migration (:mod:`beholder_tpu.cluster.failover`): raw int8
    values + f32 scales under quantized pools, raw bf16 rows
    otherwise — NO dequantize/requantize round trip, so the importing
    pool ends up byte-identical to the source. Returns per-layer
    (k_chunks, v_chunks) tuples; each quantized layer's chunk is a
    ``(values, scales)`` pair, a plain pool's the (n, Hkv, Dh, page)
    rows themselves. The handoff path (:func:`kv_prefill_chunks` /
    :func:`paged_adopt_chunks`) moves FRESH KV through the cast path
    instead; this op moves RESIDENT pages verbatim."""

    def take(pool):
        if isinstance(pool, QuantizedPool):
            return (pool.values[page_ids], pool.scales[page_ids])
        return pool[page_ids]

    return (
        tuple(take(p) for p in state.k_pools),
        tuple(take(p) for p in state.v_pools),
    )


def paged_import_pages(
    state: PagedKVState,
    chunks_k: tuple,
    chunks_v: tuple,
    n_pages: jax.Array,
    refs: jax.Array,
    group: GroupSpec | None = None,
):
    """Adopt migrated pages into THIS pool: pop ``n_pages`` pages off
    the free stack, write the exported chunks VERBATIM (raw values and
    scales — the byte-identical twin of :func:`paged_export_pages`),
    and install the SOURCE refcounts ``refs`` (n,) so prefix sharing,
    cache references and fork structure survive the move. Rows past
    ``n_pages`` are masked off like every other static-width chunk op.
    Returns (state, dest_ids) — ``dest_ids[i]`` is the pool page now
    holding chunk row ``i`` (garbage past ``n_pages``); the host reads
    it back once to rewrite page tables and cache indexes (migration
    is an admin operation — the one place a readback is fine).

    ``group``: migrated chunks travel the wire FULL-HEAD (the export
    side merges member slices back to full — the wire format is the
    single-device one, byte for byte); each member imports only its
    KV-head slice, values and scales alike."""
    num_pages, _ = _pool_geometry(state)
    if group is not None:
        chunks_k = tuple(_slice_chunk_heads(c, group) for c in chunks_k)
        chunks_v = tuple(_slice_chunk_heads(c, group) for c in chunks_v)
    p_max = (
        chunks_k[0][0] if isinstance(chunks_k[0], tuple) else chunks_k[0]
    ).shape[0]
    chunk_alive = jnp.arange(p_max) < n_pages
    pages, new_top, ref, failed = _pop_pages(state, chunk_alive)
    drop = jnp.where(chunk_alive, pages, num_pages)

    def put(pool, chunk):
        if isinstance(pool, QuantizedPool):
            vals, scales = chunk
            return QuantizedPool(
                pool.values.at[drop].set(vals, mode="drop"),
                pool.scales.at[drop].set(scales, mode="drop"),
            )
        return pool.at[drop].set(chunk, mode="drop")

    k_pools = tuple(
        put(pool, ck) for pool, ck in zip(state.k_pools, chunks_k)
    )
    v_pools = tuple(
        put(pool, cv) for pool, cv in zip(state.v_pools, chunks_v)
    )
    # _pop_pages seeded the popped pages at refcount 1; the migrated
    # pages carry their SOURCE counts instead (shared pages stay shared)
    ref = ref.at[drop].set(
        jnp.where(chunk_alive, refs, 1), mode="drop"
    )
    return (
        state._replace(
            k_pools=k_pools,
            v_pools=v_pools,
            free_top=new_top,
            page_ref=ref,
            alloc_failed=failed,
        ),
        pages,
    )


def cache_ref_pages(
    state: PagedKVState, page_ids: jax.Array, alive: jax.Array
) -> PagedKVState:
    """Take the prefix cache's ONE reference on each freshly indexed
    page (``page_ids`` where ``alive``; padding rows pass alive=False).
    With the cache holding a reference, slot release leaves the page
    resident at refcount >= 1 — a cold cached page — instead of
    returning it to the free stack."""
    num_pages, _ = _pool_geometry(state)
    ids = jnp.where(alive, page_ids, num_pages)
    return state._replace(
        page_ref=state.page_ref.at[ids].add(1, mode="drop")
    )


def cache_unref_pages(
    state: PagedKVState, page_ids: jax.Array, alive: jax.Array
) -> PagedKVState:
    """Drop the cache's reference on evicted pages (pool-pressure
    reclaim). Reuses the allocator's vectorized unref, so a page still
    shared with a live or forked slot (refcount > 1 before the drop)
    is NOT pushed to the free stack — the refcount invariant the
    eviction stress test pins."""
    return _unref_pages(state, page_ids, alive)


def paged_release(state: PagedKVState, slot: jax.Array) -> PagedKVState:
    """Retire ``slot``: drop one reference from each of its pages;
    pages nobody else shares go back on the free stack."""
    return paged_release_many(
        state, jnp.asarray(slot, jnp.int32).reshape(1)
    )


def paged_release_many(
    state: PagedKVState, slot_ids: jax.Array
) -> PagedKVState:
    """Retire several (distinct) slots in one vectorized unref — the
    in-jit tail of :func:`serve_wave`. Inactive slots in ``slot_ids``
    contribute zero pages (their ``seq_lens`` is 0); pages shared
    between released forks are freed exactly once (the compaction in
    :func:`_unref_pages` works per pool page, not per table entry)."""
    _, page = _pool_geometry(state)
    max_pages = state.page_table.shape[1]
    n = slot_ids.shape[0]
    counts = -(-state.seq_lens[slot_ids] // page)              # (n,)
    alive = (
        jax.lax.broadcasted_iota(jnp.int32, (n, max_pages), 1)
        < counts[:, None]
    ).reshape(-1)
    state = _unref_pages(
        state, state.page_table[slot_ids].reshape(-1), alive
    )
    return state._replace(
        active=state.active.at[slot_ids].set(False, mode="drop"),
        seq_lens=state.seq_lens.at[slot_ids].set(0, mode="drop"),
    )


def paged_fork(
    state: PagedKVState, src: jax.Array, dst_slots: jax.Array
) -> PagedKVState:
    """Fork slot ``src``'s sequence into each slot of ``dst_slots``
    (distinct, not containing ``src``): vLLM-style prefix sharing.

    Every FULL page of the source is shared by reference — a slot only
    ever writes at its own length, which lies past all full prefix
    pages, so shared pages are naturally copy-on-write-free read-only.
    A partial tail page (``seq_lens[src] % page != 0``) WILL receive the
    fork's future writes, so each destination gets its own copy (one
    page DMA per fork, the entire fork cost). The pool then holds the
    prefix ONCE plus one tail page per fork, instead of once per
    branch — the memory and prefill lever behind
    :meth:`ContinuousBatcher.run_what_if`.

    Destinations become active at the source's length; the source keeps
    running (its tail page stays exclusively its own). All work is
    masked/vectorized — safe inside jit at static ``dst_slots`` width.
    """
    num_pages, page = _pool_geometry(state)
    slots, max_pages = state.page_table.shape
    k = dst_slots.shape[0]
    length = state.seq_lens[src]
    n_full = length // page                  # fully-shared pages
    has_tail = (length % page) != 0
    src_row = state.page_table[src]

    # share the full prefix pages: +1 reference per fork
    share_alive = jnp.arange(max_pages) < n_full
    ref = state.page_ref.at[
        jnp.where(share_alive, src_row, num_pages)
    ].add(k, mode="drop")
    state = state._replace(page_ref=ref)

    # one fresh page per fork for the tail copy (masked off if none)
    need = jnp.broadcast_to(has_tail, (k,))
    pages, new_top, ref, failed = _pop_pages(state, need)
    tail_col = jnp.clip(n_full, 0, max_pages - 1)
    src_tail = src_row[tail_col]
    dest = jnp.where(need, pages, num_pages)  # OOB -> dropped copy

    def copy_tail(pool):
        if isinstance(pool, QuantizedPool):
            return QuantizedPool(
                pool.values.at[dest].set(pool.values[src_tail], mode="drop"),
                pool.scales.at[dest].set(pool.scales[src_tail], mode="drop"),
            )
        return pool.at[dest].set(pool[src_tail], mode="drop")

    # destination table rows: shared prefix + own tail page
    row = jnp.where(share_alive, src_row, 0)
    rows = jnp.broadcast_to(row, (k, max_pages))
    rows = jnp.where(
        (jnp.arange(max_pages)[None, :] == tail_col) & need[:, None],
        pages[:, None],
        rows,
    )
    return state._replace(
        k_pools=tuple(copy_tail(p) for p in state.k_pools),
        v_pools=tuple(copy_tail(p) for p in state.v_pools),
        page_table=state.page_table.at[dst_slots].set(rows, mode="drop"),
        seq_lens=state.seq_lens.at[dst_slots].set(length, mode="drop"),
        active=state.active.at[dst_slots].set(True, mode="drop"),
        free_top=new_top,
        page_ref=ref,
        alloc_failed=failed,
    )


def paged_wave(
    model: TelemetrySequenceModel,
    params,
    state: PagedKVState,
    last_pred: jax.Array,
    status_oh: jax.Array,
    n_ticks: int,
):
    """Roll every active slot ``n_ticks`` decode steps ON DEVICE: the
    prediction feedback loop runs inside one ``lax.scan`` (one compiled
    program, zero per-token host traffic). Returns ((slots, n_ticks + 1)
    deltas — the admit prediction plus each tick's, i.e. a horizon of
    ``n_ticks + 1``) and the rolled state."""

    def step(carry, _):
        state, pred = carry
        feats_t = jnp.concatenate([pred[:, None], status_oh], axis=-1)
        new_pred, state = paged_decode_tick(
            model, params, state, feats_t.astype(jnp.float32)
        )
        return (state, new_pred), pred

    (state, last), deltas = jax.lax.scan(
        step, (state, last_pred), None, length=n_ticks
    )
    deltas = jnp.concatenate([deltas.T, last[:, None]], axis=-1)
    return deltas, state


def serve_wave(
    model: TelemetrySequenceModel,
    params,
    state: PagedKVState,
    feats_padded: jax.Array,
    prefix_lens: jax.Array,
    last_statuses: jax.Array,
    n_ticks: int,
    horizons: tuple[int, ...] | None = None,
    fused: bool = False,
):
    """One whole serving wave as ONE compiled program: admit ``n``
    requests into slots ``0..n-1`` (batched prefill), roll every slot
    ``n_ticks`` feedback steps in one ``lax.scan``, then release the
    wave's pages — a single dispatch with zero host round-trips (each
    device->host read costs ~65 ms on a tunneled accelerator; see the
    module docstring). ``feats_padded`` is (n, T_max, F),
    ``prefix_lens``/``last_statuses`` are (n,). ``fused=True`` routes
    the wave prefill through the fused chunk kernel instead of the
    dense per-wave context (see :func:`paged_admit_batch` — bitwise
    the same program). Returns ((n, n_ticks + 1) forecast deltas,
    state) — or, with a static ``horizons`` tuple, a tuple of
    per-request ``(horizons[i],)`` forecast arrays trimmed
    in-program."""
    n = feats_padded.shape[0]
    preds, state = paged_admit_batch(
        model, params, state, jnp.arange(n, dtype=jnp.int32),
        feats_padded, prefix_lens, fused=fused,
    )
    deltas, state = _roll_and_release(
        model, params, state, preds, last_statuses, n, n_ticks
    )
    if horizons is not None:
        # per-request trims INSIDE the program: an eager row slice after
        # the fact costs an extra dispatch per request (~1 ms each over
        # a tunnel), a traced slice is free
        return tuple(deltas[i, : horizons[i]] for i in range(n)), state
    return deltas[:n], state


def _roll_and_release(
    model, params, state: PagedKVState, preds, status_ids, n: int,
    n_ticks: int,
):
    """Shared tail of :func:`serve_wave` / :func:`fork_wave`: scatter
    the admit predictions and frozen per-slot status one-hots into
    slot-wide carriers, roll ``n_ticks`` feedback steps on device
    (:func:`paged_wave`), release slots ``0..n-1``. Returns the full
    (slots, n_ticks + 1) delta matrix and the state."""
    slots = state.page_table.shape[0]
    status_oh = (
        jnp.zeros((slots, NUM_STATUSES), jnp.float32)
        .at[:n]
        .set(jax.nn.one_hot(status_ids, NUM_STATUSES))
    )
    pred0 = jnp.zeros((slots,), jnp.float32).at[:n].set(
        preds.astype(jnp.float32)
    )
    deltas, state = paged_wave(
        model, params, state, pred0, status_oh, n_ticks
    )
    state = paged_release_many(state, jnp.arange(n, dtype=jnp.int32))
    return deltas, state


def fork_wave(
    model: TelemetrySequenceModel,
    params,
    state: PagedKVState,
    feats_padded: jax.Array,
    prefix_len: jax.Array,
    branch_statuses: jax.Array,
    n_ticks: int,
):
    """What-if forecasting as ONE compiled program: prefill a single
    telemetry prefix ONCE (slot 0), :func:`paged_fork` it into ``k - 1``
    more slots, pin each slot's frozen status one-hot to its own
    hypothetical branch (``branch_statuses`` (k,) — e.g. "what does the
    forecast look like if the job were DEPLOYED vs ERRORED from here"),
    roll all branches ``n_ticks`` feedback steps in one scan, release.

    Against admitting ``k`` copies (:func:`serve_wave`), the prefill
    runs once instead of ``k`` times and the pool holds the prefix once
    plus one tail page per branch — both prefill FLOPs and cache bytes
    stop scaling with the branch count. Branch 0 reads the source pages
    themselves; its forecast is bit-identical to an unforked rollout.

    Returns ((k, n_ticks + 1) forecast deltas, state)."""
    k = branch_statuses.shape[0]
    if feats_padded.shape[0] != 1:
        raise ValueError(
            f"fork_wave takes ONE prefix, got {feats_padded.shape[0]}"
        )
    preds, state = paged_admit_batch(
        model, params, state, jnp.zeros((1,), jnp.int32), feats_padded,
        jnp.asarray(prefix_len, jnp.int32).reshape(1),
    )
    state = paged_fork(
        state, jnp.int32(0), jnp.arange(1, k, dtype=jnp.int32)
    )
    deltas, state = _roll_and_release(
        model, params, state, jnp.broadcast_to(preds[0], (k,)),
        branch_statuses, k, n_ticks,
    )
    return deltas[:k], state


class _RunCarry(NamedTuple):
    """Device-resident feedback state for :meth:`ContinuousBatcher.run`:
    the per-tick scheduler never reads predictions back to the host, so
    the loop inputs (last prediction, frozen status one-hot) and the
    per-slot forecast accumulator live here."""

    last_pred: jax.Array  # (slots,) f32
    status_oh: jax.Array  # (slots, NUM_STATUSES) f32
    delta_buf: jax.Array  # (slots, cap) f32; tick t writes column t


def _admit_many_carry(
    model, params, state, carry: _RunCarry, slot_ids, feats_padded,
    prefix_lens, last_statuses, group: GroupSpec | None = None,
):
    """Admit a batch of requests in ONE program (one batched prefill —
    :func:`paged_admit_batch`) and record their prefill predictions +
    status one-hots in the device carry (no values cross to the host).
    Per-request admits used to cost one dispatch EACH; over a tunnel
    where dispatch+transfer latency dominates sub-ms programs, batching
    the admission round is what keeps :meth:`ContinuousBatcher.run`'s
    host traffic per scheduling EVENT, not per request."""
    preds, state = paged_admit_batch(
        model, params, state, slot_ids, feats_padded, prefix_lens,
        group=group,
    )
    return state, carry._replace(
        last_pred=carry.last_pred.at[slot_ids].set(
            preds.astype(jnp.float32)
        ),
        status_oh=carry.status_oh.at[slot_ids].set(
            jax.nn.one_hot(last_statuses, NUM_STATUSES)
        ),
    )


def _admit_cached_carry(
    model, params, state, carry: _RunCarry, slot, suffix_feats,
    suffix_len, cached_pages, last_status, fused=False,
    group: GroupSpec | None = None,
):
    """Admit one prefix-cache HIT (:func:`paged_admit_with_prefix`) and
    record its prediction + status one-hot in the device carry — the
    warm-path twin of :func:`_admit_many_carry`. One dispatch per hit:
    hit shapes (pages matched, suffix width) vary per request, so warm
    admits don't batch; the work saved (prefill FLOPs scale with the
    suffix) dwarfs the extra dispatch. ``fused`` routes the suffix
    forward through the fused chunk kernel (the batcher's
    ``fused_verify`` knob)."""
    pred, state = paged_admit_with_prefix(
        model, params, state, slot, suffix_feats, suffix_len,
        cached_pages, fused=fused, group=group,
    )
    slot = jnp.asarray(slot, jnp.int32)
    return state, carry._replace(
        last_pred=carry.last_pred.at[slot].set(pred.astype(jnp.float32)),
        status_oh=carry.status_oh.at[slot].set(
            jax.nn.one_hot(last_status, NUM_STATUSES)
        ),
    )


def _adopt_chunks_carry(
    state, carry: _RunCarry, slot, chunks_k, chunks_v, n_pages, seq_len,
    pred, last_status, group: GroupSpec | None = None,
):
    """Admit one TRANSFERRED request (:func:`paged_adopt_chunks`) and
    record its prefill prediction + status one-hot in the device
    carry — the handoff twin of :func:`_admit_many_carry`. The
    prediction was computed by the prefill worker's forward and rides
    the transfer with the chunks; the same ``astype(float32)`` the
    colocated admit applies keeps the carry seed bitwise identical."""
    state = paged_adopt_chunks(
        state, slot, chunks_k, chunks_v, n_pages, seq_len, group=group
    )
    slot = jnp.asarray(slot, jnp.int32)
    return state, carry._replace(
        last_pred=carry.last_pred.at[slot].set(pred.astype(jnp.float32)),
        status_oh=carry.status_oh.at[slot].set(
            jax.nn.one_hot(last_status, NUM_STATUSES)
        ),
    )


def _tick_with_carry(
    model, params, state, carry: _RunCarry, write_idx,
    group: GroupSpec | None = None,
):
    """One decode tick for all slots, feedback on device: append each
    active slot's pending prediction to its forecast row (inactive
    slots pass ``write_idx == cap`` so the write drops), build the tick
    features from the carry, run the tick, store the new predictions."""
    slots = carry.delta_buf.shape[0]
    buf = carry.delta_buf.at[jnp.arange(slots), write_idx].set(
        carry.last_pred, mode="drop"
    )
    feats_t = jnp.concatenate(
        [carry.last_pred[:, None], carry.status_oh], axis=-1
    )
    preds, state = paged_decode_tick(model, params, state, feats_t, group)
    return state, carry._replace(
        last_pred=preds.astype(jnp.float32), delta_buf=buf
    )


def _tick_chunk(
    model, params, state, carry: _RunCarry, write_idx, n,
    group: GroupSpec | None = None,
):
    """``n`` decode ticks in ONE program. Between two scheduling events
    (admission, retirement) the per-tick scheduler has no decisions to
    make, so it runs the whole event-free stretch on device —
    ``lax.while_loop`` because ``n`` is traced (one compile serves every
    chunk length; a scan's length would be a static recompile key).
    ``write_idx`` gives each active slot's forecast column for the FIRST
    tick; tick i writes column ``write_idx + i`` (the cap sentinel stays
    OOB for the whole chunk since the buffer is only ``cap`` wide and
    drops handle the rest)."""
    cap = carry.delta_buf.shape[1]

    def cond(c):
        i, _, _ = c
        return i < n

    def body(c):
        i, state, carry = c
        cur = jnp.where(write_idx >= cap, cap, write_idx + i)
        state, carry = _tick_with_carry(
            model, params, state, carry, cur, group
        )
        return i + 1, state, carry

    _, state, carry = jax.lax.while_loop(
        cond, body, (jnp.int32(0), state, carry)
    )
    return state, carry


class Request(NamedTuple):
    progress: np.ndarray   # (T+1,) observed progress
    statuses: np.ndarray   # (T+1,) observed statuses
    horizon: int
    #: optional :class:`beholder_tpu.reliability.policy.Deadline` — the
    #: request's absolute time budget. None (the default) changes
    #: nothing; set, the scheduler retires the request with an explicit
    #: :class:`DeadlineExceededResult` once the budget runs out (checked
    #: at every host scheduling event: claim and tick-chunk boundaries)
    #: instead of letting it wedge a slot through a recovery storm.
    deadline: object = None
    #: optional tenant id (control subsystem): the workload this request
    #: belongs to. None (the default) changes nothing; set, the
    #: tenant-fair intake (:class:`beholder_tpu.control.admission.
    #: TenantFairQueue`) schedules it under weighted deficit-round-robin
    #: + per-tenant quotas, the recorder-only ``req.claim`` instant
    #: carries it so the SLO layer folds PER-TENANT digests and burn,
    #: and the ``beholder_control_*`` catalog attributes admissions and
    #: sheds to it.
    tenant: str | None = None
    #: optional W3C trace context (flight-plane subsystem): the
    #: ``traceparent`` of the span that caused this request. None (the
    #: default) changes nothing; set, the serving layer's recorder-only
    #: request-lifecycle instants inherit the trace id, so a request's
    #: claim/retire legs join the cross-process trace the ingest wire
    #: carried in (:mod:`beholder_tpu.obs.flightplane`).
    traceparent: str | None = None


class DeadlineExceededResult:
    """Explicit terminal outcome for a request whose
    :class:`~beholder_tpu.reliability.policy.Deadline` expired before
    its horizon completed. ``tokens`` carries whatever forecast prefix
    WAS decoded (empty when the deadline expired before the claim) —
    the caller gets the partial stream plus an unambiguous outcome
    instead of a silently short array."""

    __slots__ = ("tokens",)
    outcome = "deadline_exceeded"

    def __init__(self, tokens: np.ndarray | None = None):
        self.tokens = (
            tokens if tokens is not None else np.zeros(0, np.float32)
        )

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"DeadlineExceededResult(tokens={len(self.tokens)})"


class _ServingMetrics:
    """Prometheus instrumentation for one batcher (extension surface:
    the reference's registry carries only its two counters — these
    series appear ONLY when a registry is handed to
    :class:`ContinuousBatcher`, so the default exposition stays
    byte-identical to the reference). Every value comes from the
    scheduler's host-side bookkeeping: instrumentation adds ZERO device
    reads (the whole round-5 serving story)."""

    #: scheduling rounds span sub-ms tick dispatches to the ~65 ms
    #: tunnel readback constant; default prom buckets start too high
    ROUND_BUCKETS = (
        0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
        0.5, 1.0, 2.5,
    )
    RUN_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
    TOKEN_BUCKETS = (
        1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
        1e-2, 0.1,
    )

    def __init__(self, registry, num_pages: int):
        # get_or_create: a REPLACEMENT batcher (the documented recovery
        # from a pool-exhaustion error) re-attaches to the service's
        # existing series instead of tripping the duplicate guard; a
        # name held by a DIFFERENT metric kind raises ValueError here
        # rather than AttributeError mid-run
        from beholder_tpu.metrics import get_or_create

        self.pool_pages_free = get_or_create(
            registry, "gauge",
            "beholder_serving_pool_pages_free",
            "KV pages not reserved by any in-flight request",
        )
        self.slots_active = get_or_create(
            registry, "gauge",
            "beholder_serving_slots_active",
            "Serving slots holding an in-flight request",
        )
        self.requests_total = get_or_create(
            registry, "counter",
            "beholder_serving_requests_total",
            "Requests fully served by the paged serving layer",
        )
        self.tokens_total = get_or_create(
            registry, "counter",
            "beholder_serving_tokens_total",
            "Forecast tokens decoded by the paged serving layer",
        )
        # device_results mode returns UNCHECKED device arrays (the
        # caller owns the alloc_failed check), so its work counts as
        # dispatched, never served — a tripped allocator can no longer
        # permanently overcount the served series
        self.requests_dispatched_total = get_or_create(
            registry, "counter",
            "beholder_serving_requests_dispatched_total",
            "Requests dispatched in device_results mode (unverified by "
            "the end-of-run allocator check)",
        )
        self.tokens_dispatched_total = get_or_create(
            registry, "counter",
            "beholder_serving_tokens_dispatched_total",
            "Forecast tokens dispatched in device_results mode "
            "(unverified by the end-of-run allocator check)",
        )
        self.round_seconds = get_or_create(
            registry, "histogram",
            "beholder_serving_round_duration_seconds",
            "Wall time of one scheduling round by phase "
            "(admit/tick/retire/wave/readback)",
            labelnames=["phase"],
            buckets=self.ROUND_BUCKETS,
        )
        self.run_seconds = get_or_create(
            registry, "histogram",
            "beholder_serving_run_duration_seconds",
            "End-to-end scheduler call wall time by mode",
            labelnames=["mode"],
            buckets=self.RUN_BUCKETS,
        )
        self.token_seconds = get_or_create(
            registry, "histogram",
            "beholder_serving_token_latency_seconds",
            "Per-token wall time of one scheduler call (run wall time / "
            "forecast tokens produced)",
            labelnames=["mode"],
            buckets=self.TOKEN_BUCKETS,
        )
        self.pool_pages_free.set(num_pages)
        # pool-pressure gauges (PR 16) register LAZILY on first feed:
        # beholder_serving_pool_fragmentation and the per-tenant
        # committed-pages series appear only once a scheduler actually
        # reports pressure, keeping every pre-existing exposition pin
        # (which renders this registry before a run) byte-identical
        self._registry = registry
        self._pool_frag = None
        self._tenant_pages = None
        self._tenants_seen: set = set()

    def pool_pressure(
        self,
        free: int,
        claimable: int,
        committed: dict | None = None,
    ) -> None:
        """Feed the lazily-registered pool observability gauges at a
        ``pool_pages_free`` update site. ``free`` is the pool's free
        page count, ``claimable`` the largest run of those pages ONE
        request could claim right now (bounded by the per-seq page cap
        and slot availability — under paging indirection that cap, not
        physical adjacency, is what strands free pages), ``committed``
        maps tenant id -> pages reserved by that tenant's in-flight
        requests. Fragmentation renders as ``claimable / free`` (1.0 =
        any free page is claimable; 0.0 = pages exist but no request
        can take one; between = pages stranded behind the per-seq cap
        for a single claimant)."""
        from beholder_tpu.metrics import get_or_create

        if self._pool_frag is None:
            self._pool_frag = get_or_create(
                self._registry, "gauge",
                "beholder_serving_pool_fragmentation",
                "Largest single-request-claimable free page run over "
                "free pages (1 = unfragmented; < 1 = free pages "
                "stranded behind the per-seq cap or slot exhaustion)",
            )
        self._pool_frag.set(
            round(min(claimable, free) / free, 6) if free > 0 else 1.0
        )
        if committed is None or (
            not committed and self._tenant_pages is None
        ):
            # the tenant family first registers when a TENANTED request
            # actually commits pages — an all-untenanted run adds no
            # empty metric family to the exposition
            return
        if self._tenant_pages is None:
            self._tenant_pages = get_or_create(
                self._registry, "gauge",
                "beholder_serving_tenant_committed_pages",
                "KV pages committed to a tenant's in-flight requests",
                labelnames=["tenant"],
            )
        for tenant, pages in committed.items():
            label = str(tenant)
            self._tenants_seen.add(label)
            self._tenant_pages.set(float(pages), tenant=label)
        # a tenant whose last request retired must read 0, not its
        # final in-flight value frozen forever
        for label in self._tenants_seen - {str(t) for t in committed}:
            self._tenant_pages.set(0.0, tenant=label)

    def pool_pressure_from(
        self, free, req_of, requests, total_need, page_cap
    ) -> None:
        """Site-shaped :meth:`pool_pressure` feed from the scheduler
        loops' shared bookkeeping: ``req_of`` (slot -> rid or None),
        ``requests`` (rid-indexable, each optionally carrying
        ``tenant``), ``total_need`` (per-slot pages at horizon end) and
        the per-seq page cap. Shared by run(), the spec scheduler and
        the cluster router — all three keep identical host mirrors."""
        slot_open = any(r is None for r in req_of)
        claimable = min(free, page_cap) if slot_open and free > 0 else 0
        committed: dict[str, int] = {}
        for slot, rid in enumerate(req_of):
            if rid is None:
                continue
            tenant = getattr(requests[rid], "tenant", None)
            if tenant is None:
                continue
            label = str(tenant)
            committed[label] = committed.get(label, 0) + int(
                total_need[slot]
            )
        self.pool_pressure(free, claimable, committed)

    def served(self, n_requests: int, n_tokens: int) -> None:
        self.requests_total.inc(n_requests)
        self.tokens_total.inc(n_tokens)

    def dispatched(self, n_requests: int, n_tokens: int) -> None:
        self.requests_dispatched_total.inc(n_requests)
        self.tokens_dispatched_total.inc(n_tokens)

    def observe_round(
        self, phase: str, seconds: float, trace_id: str | None = None
    ) -> None:
        # trace_id: exemplar cross-link — the round span closes before
        # this observation lands, so the batcher passes the id it
        # captured inside the span (a slow bucket is then one lookup
        # from its flight-recorder timeline)
        self.round_seconds.observe(
            seconds, exemplar_trace_id=trace_id, phase=phase
        )

    def observe_run(
        self,
        mode: str,
        seconds: float,
        n_tokens: int,
        trace_id: str | None = None,
    ) -> None:
        self.run_seconds.observe(
            seconds, exemplar_trace_id=trace_id, mode=mode
        )
        if n_tokens > 0:
            self.token_seconds.observe(
                seconds / n_tokens, exemplar_trace_id=trace_id, mode=mode
            )

    def idle(self, num_pages: int) -> None:
        self.slots_active.set(0)
        self.pool_pages_free.set(num_pages)
        # the fragmentation gauge keeps its last computed value (the
        # final retire site already reported the drained pool); a
        # drained pool owes no tenant anything
        if self._tenant_pages is not None:
            for label in self._tenants_seen:
                self._tenant_pages.set(0.0, tenant=label)


class ContinuousBatcher:
    """Host-side vLLM-style scheduler over the paged state.

    Submit any number of :class:`Request`\\ s, then :meth:`run` (admit
    into free slots as they open; one fused tick dispatch per step,
    zero mid-flight readbacks — the latency/flexibility path) or
    :meth:`run_waves` (one compiled admit+scan+release program per wave
    of up to ``slots`` requests — the throughput path; measured in
    ``bench.py``). Results are per-request forecast delta arrays, equal
    to the dense per-request rollout, read back from the device in ONE
    transfer at the end of either scheduler.

    Host-side admission math mirrors the device allocator exactly
    (worst-case pages per request are a function of request lengths
    only), so scheduling decisions never wait on the device; the sticky
    ``alloc_failed`` flag is still checked once at the end as a safety
    net. After an exhaustion error the batcher's pool state is
    undefined — construct a fresh one.

    ``metrics`` (a :class:`beholder_tpu.metrics.Registry`, or a
    :class:`~beholder_tpu.metrics.Metrics` whose registry is used)
    exports the scheduler's pool/slot occupancy as prometheus gauges,
    served/dispatched request+token counters, and latency histograms
    (per-round by phase, per-run by mode, per-token) alongside the
    service's own series — the serving layer's telemetry rides the same
    /metrics endpoint the reference exposes. Purely host-side (zero
    device reads); omitted, nothing is registered and the reference
    exposition stays byte-identical.

    ``tracer`` (a :class:`beholder_tpu.tracing.Tracer`) opens one span
    per scheduler call (``serving.run`` / ``serving.run_waves`` /
    ``serving.what_if``) with one child span per scheduling round
    (admit/tick/retire/wave/readback); histogram observations made
    inside those spans carry the trace id in the metrics observation
    log, so a latency outlier cross-links to its serving timeline.

    ``max_pending``/``max_pending_pages`` (or an explicit ``intake``
    :class:`~beholder_tpu.reliability.shed.IntakeQueue`) put admission
    control in front of the schedulers: :meth:`submit` offers a request
    to a BOUNDED queue and returns an explicit accept/shed outcome
    (``beholder_serving_shed_total{reason}`` when a registry is wired),
    :meth:`run_pending` drains and serves. Without them the batcher
    keeps its original call-with-a-list contract.

    ``prefix_cache`` (a :class:`beholder_tpu.cache.PrefixCache` built
    with this batcher's ``page_size``) turns on AUTOMATIC PREFIX
    CACHING for the per-event scheduler (:meth:`run` /
    ``run_pending(waves=False)``): each admit looks up the longest
    cached page-aligned prefix by content, adopts the matching pages by
    refcount, and prefills only the uncached suffix
    (:func:`paged_admit_with_prefix`); each retirement leaves the
    request's full prefix pages resident on a cold LRU list the
    allocator reclaims only under pool pressure. Two host-contract
    changes in cache mode, both bounded per scheduling EVENT: one
    page-table-row readback per admission round (the host must learn
    where prefill landed to index it), and the host's free-page
    arithmetic reserves the cache's cold pages (conservative — eviction
    of a page still shared with a live slot frees nothing on device; the
    refcount makes that safe, the arithmetic just stays pessimistic).
    :meth:`run_waves` is unaffected: its fused admit+scan+release
    program releases everything in-program, so it trades cache reuse
    for fusion. Off (None, the default) every path is byte-identical
    to the uncached batcher.

    ``spec`` (a :class:`beholder_tpu.spec.SpecConfig`) arms
    :meth:`run_spec`: draft-then-verify decoding where one chunked
    model step scores k draft tokens per slot through the dense-cache
    forward, accepted KV lands in the paged pool and rejected suffixes
    roll back refcount-aware — N tokens per scheduled step instead of
    one. Composes with ``prefix_cache`` (warm admits adopt cached
    pages; rollback never reclaims a shared page). Off (None, the
    default) nothing changes.

    ``flight_recorder`` (a :class:`beholder_tpu.obs.FlightRecorder`)
    arms the per-step engine timeline: every scheduling phase all three
    schedulers run (claim, admit, draft, tick/wave, verify, readback,
    rollback, retire) lands in the recorder's bounded ring with the
    active trace id, plus instant markers for prefix-cache lookups,
    pressure-deferral stalls, and spec accept/rollback outcomes; with
    an attributor wired, dispatch phases are tagged with estimated
    FLOPs and achieved-fraction-of-ceiling (``beholder_tpu.obs.
    roofline``). Host clocks only — zero device reads, like the
    metrics. Off (None, the default) serving output and the /metrics
    exposition are byte-identical (pinned by
    ``tests/test_flight_recorder.py``).
    """

    def __init__(
        self,
        model: TelemetrySequenceModel,
        params,
        *,
        num_pages: int = 64,
        page_size: int = 16,
        slots: int = 4,
        max_prefix: int = 64,
        max_pages_per_seq: int = 32,
        cache_dtype=jnp.bfloat16,
        metrics=None,
        tracer=None,
        intake=None,
        max_pending: int | None = None,
        max_pending_pages: int | None = None,
        prefix_cache=None,
        spec=None,
        flight_recorder=None,
        fused_verify: bool = False,
        fused_wave: bool = False,
        autotune_table: str | None = None,
    ):
        self.model = model
        self.params = params
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_pages_per_seq = max_pages_per_seq
        self.max_prefix = -(-max_prefix // page_size) * page_size
        self.state = init_paged(
            model, num_pages, page_size, slots, max_pages_per_seq,
            cache_dtype=cache_dtype,
        )
        self.slots = slots
        self._registry = (
            getattr(metrics, "registry", metrics)
            if metrics is not None
            else None
        )
        self._metrics = (
            _ServingMetrics(self._registry, num_pages)
            if metrics is not None
            else None
        )
        self._tracer = tracer
        #: optional admission control (reliability subsystem): a bounded
        #: intake in front of the schedulers — submit() yields an
        #: explicit accept/shed outcome instead of unbounded queueing
        if intake is None and (
            max_pending is not None or max_pending_pages is not None
        ):
            from beholder_tpu.reliability.shed import IntakeQueue

            intake = IntakeQueue(
                max_pending if max_pending is not None else 2 * slots,
                max_cost=max_pending_pages,
                cost_fn=self._need_pages,
                metrics=(
                    getattr(metrics, "registry", metrics)
                    if metrics is not None
                    else None
                ),
            )
        self.intake = intake
        #: optional automatic prefix caching (cache subsystem): the
        #: radix index over admitted prefixes; page_size must match so
        #: content hashes and pool pages describe the same chunks
        if prefix_cache is not None and prefix_cache.page_size != page_size:
            raise ValueError(
                f"prefix_cache page_size {prefix_cache.page_size} != "
                f"batcher page_size {page_size}"
            )
        self.prefix_cache = prefix_cache
        #: optional speculative decoding (spec subsystem): a
        #: :class:`beholder_tpu.spec.SpecConfig` turns :meth:`run_spec`
        #: on — draft-then-verify decoding over this batcher's paged
        #: pool. None (the default) leaves every path byte-identical.
        if spec is not None:
            from beholder_tpu.spec import SpecConfig

            if not isinstance(spec, SpecConfig):
                raise TypeError(
                    f"spec must be a beholder_tpu.spec.SpecConfig, got "
                    f"{type(spec).__name__}"
                )
        self.spec = spec
        #: optional flight recorder (obs subsystem): the bounded per-
        #: step engine timeline. None (the default) records nothing and
        #: leaves every path byte-identical.
        self.flight_recorder = flight_recorder
        if flight_recorder is not None:
            # arm the autotuner's malformed-table reporting (process-
            # global like the table itself; see autotune.set_recorder)
            from beholder_tpu.ops import autotune as _autotune

            _autotune.set_recorder(flight_recorder)
        #: fused paged verify/prefix attention
        #: (``instance.serving.fused_verify``): spec verify rounds and
        #: prefix-hit admissions attend the paged pools IN PLACE
        #: through :func:`~beholder_tpu.ops.paged_attention.
        #: paged_chunk_attention` instead of gathering a dense
        #: per-layer ``(slots, Hkv, max_pages*page, Dh)`` context.
        #: Served tokens are BITWISE-identical either way (the kernel
        #: reproduces the dense oracle's arithmetic; pinned by
        #: tests/test_paged_chunk_kernel.py); what changes is the
        #: transient (gone), int8 HBM traffic (pages dequantize inside
        #: the kernel), and the verify page budget (_need_pages stops
        #: reserving the max_draft tentative-write transient, so more
        #: requests fit a pool). Off (False, the default) every path
        #: is byte-identical to the dense-gather batcher.
        self.fused_verify = bool(fused_verify)
        #: fused wave prefill (``instance.serving.fused_wave``):
        #: :meth:`run_waves` admits each wave through the fused chunk
        #: kernel with an empty paged context instead of the dense
        #: per-wave (n, Hkv, T_max, Dh) context buffers — wave
        #: membership IS the chunk slot set (see
        #: :func:`paged_admit_batch`). Bitwise-identical deltas either
        #: way (pinned by tests/test_serving.py); the knob joins the
        #: serve-program jit key. Off (False, the default) the wave
        #: path is byte-identical to before the lane existed.
        self.fused_wave = bool(fused_wave)
        if autotune_table is not None:
            # point the kernel's block-size table at the configured
            # location (``instance.serving.autotune.table``) before the
            # first fused build resolves a config. Deliberately
            # PROCESS-GLOBAL (autotune.configure — last writer wins;
            # None leaves the current resolution untouched): the table
            # is a property of the HOST the kernels were tuned on, not
            # of one batcher, and jit caches keyed per-instance could
            # not undo a build made under a different table anyway. A
            # process serving two batchers tuned against different
            # tables is a config error, not a supported mode.
            from beholder_tpu.ops import autotune

            autotune.configure(autotune_table)
        #: lazily built by the spec scheduler (a drafter may hold its
        #: own paged state across calls; the controller's EMA carries)
        self._spec_drafter = None
        self._spec_controller = None
        self._spec_metrics = None
        #: hash chain (full prefix pages) each live slot holds in the
        #: prefix cache; released at retirement
        self._slot_chain: list[list[bytes]] = [[] for _ in range(slots)]
        #: optional cluster-fabric admission hook
        #: (``fabric(hashes, max_pages, free_pages)``): invoked right
        #: before the prefix-cache lookup so a chain warm on another
        #: shard can be pulled into THIS pool and the ordinary local
        #: lookup below hits it. None (the default) leaves admission
        #: byte-identical to a fabric-less batcher.
        self.prefix_fetcher = None
        #: request geometries :meth:`run` has served — ``(T+1, horizon)
        #: -> max concurrent count`` (capped at ``slots``). Programs jit
        #: per shape, so this map IS the executable working set; the
        #: cluster fabric replays it through a dark standby at spawn so
        #: promotion re-admits onto already-compiled programs.
        self.seen_request_shapes: dict[tuple[int, int], int] = {}
        if prefix_cache is not None:
            self._cache_ref = jax.jit(cache_ref_pages)
            self._cache_unref = jax.jit(cache_unref_pages)
        self._release_many = jax.jit(paged_release_many)
        self._tick_carry = jax.jit(
            lambda p, s, c, w: _tick_with_carry(model, p, s, c, w)
        )
        self._tick_chunk = jax.jit(
            lambda p, s, c, w, n: _tick_chunk(model, p, s, c, w, n)
        )
        # serve_wave programs jit per (n, n_ticks, horizons) — the scan
        # length and in-program trims are static
        self._serve_cache: dict[tuple, object] = {}
        # set when an exception escaped mid-flight: device state may
        # hold admitted-but-unreleased pages, so the host's free-page
        # arithmetic no longer mirrors the allocator
        self._poisoned = False
        #: lazily registered on the FIRST deadline expiry (the failover
        #: catalog's counter — registering it eagerly would widen the
        #: pinned default exposition for every batcher with metrics)
        self._deadline_counter = None
        #: per-request timeline annotations for the NEXT scheduler call
        #: (:meth:`annotate_requests` — rid -> {gid, queue_wait_s});
        #: consumed by ``_start_run`` into ``_run_notes``, which the
        #: recorder-only ``req.claim``/``req.retire`` instants merge so
        #: the SLO timeline layer can key requests across cluster
        #: routing and failover recovery legs. Empty dicts cost nothing
        #: and, recorder-off, neither is ever read.
        self._timeline_notes: dict[int, dict] = {}
        self._run_notes: dict[int, dict] = {}

    # -- shared helpers -------------------------------------------------

    def _need_pages(self, req: Request) -> int:
        """Worst-case pages a request consumes: prefix + the horizon-1
        fed-back tokens (the horizon-th prediction needs no tick — see
        run()'s early release). With spec configured on the
        DENSE-GATHER verify path, a verify step tentatively writes up
        to ``max_draft`` tokens past the final accepted end before
        rollback reclaims them, so admission (and the intake's shed
        cost) must budget that transient too. The FUSED verify path
        (``fused_verify``) never writes a rejected token — the chunk
        attends its own kv from the kernel overlay and only the
        accepted prefix commits — so its worst case follows accepted
        tokens (bounded by the horizon: drafts are clamped to the
        remaining horizon) and the transient budget disappears. That
        is the capacity gain: the same pool admits more concurrent
        requests before shedding (pinned by
        tests/test_paged_chunk_kernel.py)."""
        feats_len = len(req.progress) - 1
        tokens = feats_len + max(req.horizon - 1, 0)
        if self.spec is not None and not self.fused_verify:
            tokens += self.spec.max_draft
        return -(-tokens // self.page_size)

    def _prep_np(self, req: Request):
        """Pure-NumPy :func:`~.sequence.stream_features` for one request
        — feature prep must not issue eager device ops (each would pay
        tunnel latency). Returns ((t, F) feats, t); callers pad to the
        page-aligned width they need (the WAVE's max, not the global
        ``max_prefix`` — prefill cost then scales with the tokens
        actually admitted, another place paging beats fixed-width
        batches). Padding width is inert for correctness: prefill is
        causal and only ceil(t/page) pages are written."""
        deltas = np.diff(np.asarray(req.progress, np.float32))
        oh = np.eye(NUM_STATUSES, dtype=np.float32)[
            np.asarray(req.statuses[1:], np.int64)
        ]
        feats = np.concatenate([deltas[:, None], oh], axis=1)
        t = feats.shape[0]
        if t > self.max_prefix:
            raise ValueError(
                f"prefix {t} exceeds max_prefix {self.max_prefix}"
            )
        return feats, t

    def _pad_to(self, feats: np.ndarray, width: int) -> np.ndarray:
        return np.pad(feats, ((0, width - feats.shape[0]), (0, 0)))

    def _page_id_batch(self, pages: list[int]) -> tuple[jax.Array, jax.Array]:
        """(ids, alive) padded to the pool width, so the cache ref/unref
        dispatches compile ONCE regardless of how many pages move."""
        ids = np.zeros(self.num_pages, np.int32)
        alive = np.zeros(self.num_pages, bool)
        ids[: len(pages)] = pages
        alive[: len(pages)] = True
        return jnp.asarray(ids), jnp.asarray(alive)

    @property
    def transfer_device(self):
        """The device wire transfers to/from this batcher land on. A
        single-device batcher is trivially its pool's device; a group
        batcher (:mod:`beholder_tpu.cluster.group`) overrides this with
        member 0 — the group's wire endpoint. The migration and fabric
        paths address the batcher through this property instead of
        peeking at ``state.seq_lens.devices()``. None degrades to the
        no-hop local path (uncommitted single-device state)."""
        try:
            return next(iter(self.state.seq_lens.devices()))
        except Exception:  # noqa: BLE001 - uncommitted state
            return None

    def export_pages(self, page_ids: jax.Array):
        """Pages ``page_ids`` in WIRE representation — full-head pool
        chunks exactly as :func:`paged_export_pages` returns them. The
        group engine overrides this to merge member head-slices back to
        the full-head wire format, so migration and fabric moves speak
        one byte-identical dialect regardless of the source's layout."""
        return paged_export_pages(self.state, page_ids)

    def import_pages(self, chunks_k, chunks_v, n_pages, refs):
        """Adopt full-head wire chunks into this pool — the
        :func:`paged_import_pages` half of a move; the group engine
        overrides this to slice each member's heads on the way in.
        Returns (new_state, dest_ids); the CALLER assigns
        ``self.state`` (both sides of a move update state and page
        tables together)."""
        return paged_import_pages(
            self.state, chunks_k, chunks_v, n_pages, refs
        )

    def _evict_cached(self, n_pages: int) -> int:
        """Reclaim up to ``n_pages`` cold cached pages (LRU leaf-first)
        under pool pressure: the index forgets them, then ONE vectorized
        unref drops the cache's device reference — a page still shared
        with a live slot survives at refcount >= 1 (the allocator's
        push-on-zero makes over-eviction safe, just wasted)."""
        pages = self.prefix_cache.evict(n_pages)
        if not pages:
            return 0
        ids, alive = self._page_id_batch(pages)
        self.state = self._cache_unref(self.state, ids, alive)
        return len(pages)

    def _index_admitted(self, admitted: list[tuple[int, list[bytes], int]]):
        """Index one admission round's freshly prefilled full pages:
        ONE page-table readback (the host must learn where prefill
        landed), then insert + pin each slot's chain and take the
        cache's single device reference on every newly indexed page."""
        idx = jnp.asarray([slot for slot, _, _ in admitted], jnp.int32)
        rows = np.asarray(jax.device_get(self.state.page_table[idx]))
        fresh_pages: list[int] = []
        for (slot, hashes, n_full), row in zip(admitted, rows):
            chain = hashes[:n_full]
            pinned = len(self._slot_chain[slot])  # hit pages, pinned at claim
            new_ids, _ = self.prefix_cache.insert(
                chain, [int(p) for p in row[:n_full]]
            )
            fresh_pages.extend(new_ids)
            self.prefix_cache.acquire(chain[pinned:])
            self._slot_chain[slot] = chain
        if fresh_pages:
            ids, alive = self._page_id_batch(fresh_pages)
            self.state = self._cache_ref(self.state, ids, alive)

    def _check_not_poisoned(self):
        if self._poisoned:
            raise RuntimeError(
                "batcher state undefined after an earlier mid-run error "
                "— construct a fresh ContinuousBatcher"
            )

    _ALLOCATOR_TRIPPED = (
        "page pool exhausted mid-run (device allocator tripped despite "
        "host headroom checks) — raise num_pages"
    )

    def _count_deadline_exceeded(self, n: int = 1) -> None:
        """Count deadline expiries on the failover catalog's counter
        (``beholder_failover_deadline_exceeded_total``) — registered on
        first use only, so a batcher that never sees a deadline leaves
        the exposition untouched."""
        if self._registry is None:
            return
        if self._deadline_counter is None:
            from beholder_tpu.metrics import get_or_create

            self._deadline_counter = get_or_create(
                self._registry, "counter",
                "beholder_failover_deadline_exceeded_total",
                "Requests retired with an expired deadline (explicit "
                "deadline_exceeded outcome instead of a wedged slot)",
            )
        self._deadline_counter.inc(n)

    @staticmethod
    def _deadline_expired(req) -> bool:
        deadline = getattr(req, "deadline", None)
        return deadline is not None and deadline.expired

    def _claim_admissions(
        self, queue, results, req_of, free_pages, commit
    ) -> list[tuple[int, int, np.ndarray, int, list, list]]:
        """One admission round's CLAIM loop, shared by the per-event
        scheduler (:meth:`run`) and the speculative scheduler
        (``spec.scheduler``): claim every (slot, request) pair that
        fits under the page-headroom arithmetic, so both paths carry
        the same hardening invariants — prefix-cache hit chains are
        looked up and PINNED before any pressure eviction this round
        (eviction must never reclaim pages a claim is about to adopt;
        pinned pages leave the cold set so ``free_pages`` stops
        reserving them — the claim's full ``need`` covers them
        instead), pins are released on deferral, stats count once per
        ADMISSION (``record=False`` probes — a deferred request
        re-probes every round), and zero-horizon requests resolve
        without a prefill round-trip.

        ``free_pages`` is the caller's headroom closure (it must see
        ``commit``'s bookkeeping within the same round);
        ``commit(slot, rid, req, need)`` records the caller's per-slot
        state for each claim. Returns the claimed batch as
        (slot, rid, feats, t, hit_pages, hashes) tuples; raises when
        nothing is active and the head request can never fit."""
        batch: list[tuple[int, int, np.ndarray, int, list, list]] = []
        # flight-recorder-only instrumentation: claim must NOT appear as
        # a new round-histogram phase label (the recorder-off exposition
        # is pinned byte-identical), so it records straight to the ring
        fr = self.flight_recorder
        claim_ts = time.time() if fr is not None else 0.0
        claim_t0 = time.perf_counter()
        claim_tid = current_trace_id() if fr is not None else None
        try:
            for slot in range(self.slots):
                if not queue or req_of[slot] is not None:
                    continue
                rid, req = queue[0]
                if req.horizon <= 0:
                    # forecast_deltas(horizon=0) returns an empty array;
                    # skip the prefill/alloc round-trip entirely
                    queue.pop(0)
                    results[rid] = np.zeros(0, np.float32)
                    continue
                if self._deadline_expired(req):
                    # the budget ran out while queued (e.g. a recovery
                    # storm re-admitting work): explicit outcome, no
                    # prefill, the slot goes to a request that can
                    # still make its deadline
                    queue.pop(0)
                    results[rid] = DeadlineExceededResult()
                    self._count_deadline_exceeded()
                    if fr is not None:
                        fr.instant(
                            "deadline_exceeded", trace_id=claim_tid,
                            stage="claim", rid=rid,
                            **self._run_notes.get(rid, {}),
                        )
                    continue
                self._check_servable(req)
                feats_np, t = self._prep_np(req)
                hit_pages: list[int] = []
                hashes: list[bytes] = []
                pinned: list[bytes] = []
                if self.prefix_cache is not None:
                    hashes = self.prefix_cache.hashes(feats_np)
                    if self.prefix_fetcher is not None and hashes:
                        # cluster fabric: pull a remotely warm chain
                        # into this pool so the local lookup below hits
                        self.prefix_fetcher(
                            hashes, (t - 1) // self.page_size, free_pages
                        )
                    hit_pages = self.prefix_cache.lookup(
                        hashes, (t - 1) // self.page_size, record=False
                    )
                    pinned = hashes[: len(hit_pages)]
                    self.prefix_cache.acquire(pinned)
                    if fr is not None:
                        fr.instant(
                            "prefix_lookup", trace_id=claim_tid, slot=slot,
                            hit_pages=len(hit_pages),
                        )
                need = self._need_pages(req)
                free = free_pages()
                if need > free and self.prefix_cache is not None:
                    # pool pressure: surrender cold cached pages before
                    # deferring (the cache is a best-effort tenant; pinned
                    # chains are protected by live_users)
                    free += self._evict_cached(need - free)
                if need > free:
                    if self.prefix_cache is not None:
                        self.prefix_cache.release(pinned)  # not admitted
                    if not any(r is not None for r in req_of):
                        raise RuntimeError(
                            "page pool exhausted: request needs "
                            f"{need} pages but only {free} exist free — "
                            "raise num_pages or lower concurrency"
                        )
                    if fr is not None:
                        fr.instant(
                            "stall", trace_id=claim_tid,
                            reason="pressure_deferral", slot=slot,
                            need=int(need), free=int(free),
                        )
                    break  # defer until an active request retires
                queue.pop(0)
                if self.prefix_cache is not None:
                    self._slot_chain[slot] = pinned
                    self.prefix_cache.record_admit(hit_pages)
                batch.append((slot, rid, feats_np, t, hit_pages, hashes))
                req_of[slot] = rid
                commit(slot, rid, req, need)
                if fr is not None:
                    # the request-level lifecycle marker the SLO/
                    # timeline layer folds (obs/timeline.py): claim
                    # time anchors queue-wait and TTFT. A tenant id
                    # rides along ONLY when set — an untenanted fleet's
                    # event shape is unchanged
                    tenant_note = (
                        {"tenant": req.tenant}
                        if getattr(req, "tenant", None) is not None
                        else {}
                    )
                    # a request carrying W3C trace context (the flight
                    # plane's wire propagation) hands its trace id to
                    # the lifecycle instant, joining this claim to the
                    # cross-process trace; without one the shared claim
                    # trace id applies as before
                    req_tid = claim_tid
                    tp = getattr(req, "traceparent", None)
                    if tp is not None:
                        pctx = from_traceparent(str(tp))
                        if pctx is not None:
                            req_tid = f"{pctx.trace_id:032x}"
                    fr.instant(
                        "req.claim", trace_id=req_tid, rid=rid,
                        slot=slot, prefix_tokens=int(t),
                        hit_pages=len(hit_pages),
                        horizon=int(req.horizon),
                        **tenant_note,
                        **self._run_notes.get(rid, {}),
                    )
        finally:
            if fr is not None:
                fr.record(
                    "claim", claim_ts, time.perf_counter() - claim_t0,
                    trace_id=claim_tid, claimed=len(batch),
                    queued=len(queue),
                )
        return batch

    def _check_servable(self, req: Request):
        need = self._need_pages(req)
        if need > self.num_pages or need > self.max_pages_per_seq:
            raise RuntimeError(
                f"page pool exhausted: request needs {need} pages "
                f"(pool {self.num_pages}, per-seq cap "
                f"{self.max_pages_per_seq}) — raise num_pages or shorten "
                f"the horizon"
            )

    def _run_span(self, operation: str, **tags):
        """Root span for one scheduler call (``with``-able; nullcontext
        when no tracer is wired, so the bare path costs nothing)."""
        if self._tracer is None:
            return nullcontext()
        return self._tracer.start_span(operation, tags=tags)

    @staticmethod
    def _span_trace_id(span) -> str | None:
        """The 32-hex trace id of a run span (None for nullcontext) —
        the exemplar link for observations made after the span closes."""
        ctx = getattr(span, "context", None)
        return f"{ctx.trace_id:032x}" if ctx is not None else None

    def _kernel_tags(self, family: str, flops: float) -> dict:
        """Roofline-attribution tags for one dispatch round — empty
        unless the flight recorder is armed, so the bare path builds no
        extra dict entries."""
        if self.flight_recorder is None:
            return {}
        return self.flight_recorder.kernel_tags(family, flops)

    @property
    def pool_family(self) -> str:
        """The KV pool's dtype family (``"bf16"``/``"int8"``/``"fp8"``)
        — the same label the autotune table keys by, used to qualify
        the fused verify round's roofline family so each encoding's
        achieved ceiling fraction gates as its own series."""
        pool = self.state.k_pools[0]
        quantized = isinstance(pool, QuantizedPool)
        return pool_dtype_family(
            pool.values if quantized else pool, quantized=quantized
        )

    def _flops_per_token(self, ctx: float) -> float:
        from beholder_tpu.obs.roofline import model_flops_per_token

        return model_flops_per_token(self.model, ctx)

    @contextmanager
    def _round(self, parent, phase: str, **tags):
        """One scheduling round: a child span under the run span plus a
        ``round_duration_seconds{phase=...}`` observation, and — with a
        flight recorder wired — one timeline event carrying the round's
        tags (kernel-attribution tags included). The trace id is
        captured INSIDE the child span so both the recorder event and
        the histogram exemplar link to this round's span. Host-side
        clocks only — instrumentation adds zero device reads."""
        fr = self.flight_recorder
        ts = time.time() if fr is not None else 0.0
        t0 = time.perf_counter()
        cm = (
            self._tracer.start_span(
                f"serving.{phase}", child_of=parent, tags=tags
            )
            if self._tracer is not None and parent is not None
            else nullcontext()
        )
        trace_id = None
        try:
            with cm:
                trace_id = current_trace_id()
                yield
        finally:
            dur = time.perf_counter() - t0
            if self._metrics is not None:
                self._metrics.observe_round(phase, dur, trace_id=trace_id)
            if fr is not None:
                fr.record(phase, ts, dur, trace_id=trace_id, **tags)

    def _start_run(self, requests: list[Request]):
        """Fail fast BEFORE anything is admitted: every per-request
        precondition (prefix cap, pool/table fit) is checked up front so
        an unservable request cannot raise mid-flight with earlier
        requests' pages still held. An exception that nevertheless
        escapes mid-run (allocator safety net, device error) POISONS the
        batcher — the host's free-page arithmetic would no longer mirror
        the device allocator — and every later call refuses to run."""
        self._check_not_poisoned()
        # timeline annotations apply to exactly one scheduler call: the
        # one whose requests they index (set by run_pending / the
        # cluster router immediately before the call)
        self._run_notes, self._timeline_notes = self._timeline_notes, {}
        for req in requests:
            if req.horizon <= 0:
                continue
            t = len(req.progress) - 1
            if t > self.max_prefix:
                raise ValueError(
                    f"prefix {t} exceeds max_prefix {self.max_prefix}"
                )
            self._check_servable(req)

    def _emit_req_retire(
        self, rid: int, slot: int, tokens: int, outcome: str = "ok",
        **extra,
    ) -> None:
        """ONE copy of the ``req.retire`` lifecycle instant all four
        serving loops emit (run's retire_many, the fused wave release,
        the spec loop's retire, the disagg loop's retire_many) — the
        SLO/timeline fold keys on this exact event shape, so its
        contract must not be able to drift between loops. ``extra``
        seeds defaults (e.g. the disagg lane's ``worker=``); the
        caller-set timeline notes win on collision."""
        fr = self.flight_recorder
        if fr is None:
            return
        note = {**extra, **self._run_notes.get(rid, {})}
        fr.instant(
            "req.retire", rid=rid, slot=slot, tokens=int(tokens),
            outcome=outcome, **note,
        )

    def annotate_requests(self, notes: dict[int, dict]) -> None:
        """Attach per-request timeline annotations to the NEXT scheduler
        call: ``notes[rid]`` merges into that request's recorder-only
        ``req.claim``/``req.retire`` instants (keys: ``gid`` — a
        caller-global request id, stable across failover recovery
        legs — and ``queue_wait_s``, the intake residency the SLO layer
        folds into queue-wait). Purely observational: with no flight
        recorder armed the notes are never read."""
        self._timeline_notes = dict(notes)

    # -- admission control: bounded intake + shed -----------------------

    def submit(self, request: Request):
        """Offer one request to the bounded intake queue; returns an
        :class:`~beholder_tpu.reliability.shed.Admission` — accepted, or
        shed with an explicit reason (``queue_full`` / ``cost_backlog``
        / ``oversized``). Saying no costs O(1) and nothing on device;
        unbounded queueing under overload would convert load into
        latency + memory instead. Requires the batcher to be built with
        ``intake=``/``max_pending=``."""
        if self.intake is None:
            raise RuntimeError(
                "no intake queue configured — construct the batcher with "
                "max_pending= (or an explicit IntakeQueue) to use submit()"
            )
        from beholder_tpu.reliability.shed import SHED_OVERSIZED

        need = self._need_pages(request)
        if need > self.num_pages or need > self.max_pages_per_seq:
            # unservable at ANY load: shed rather than poison a run
            return self.intake.shed(SHED_OVERSIZED)
        return self.intake.offer(request, cost=need)

    def run_pending(self, waves: bool | None = None) -> list[np.ndarray]:
        """Drain the intake queue and serve everything admitted since
        the last drain (``run_waves`` by default, ``run`` with
        ``waves=False``). Results are in admission order. With a prefix
        cache wired, the default flips to the per-event scheduler —
        ``run_waves``' fused admit+scan+release program releases every
        page in-program, so only ``run`` can reuse and repopulate the
        cache; with a ``spec`` config it flips further to the
        speculative scheduler (:meth:`run_spec`, which composes with
        the prefix cache). Pass ``waves`` explicitly to override either
        way (``waves=False`` still picks spec when configured)."""
        if self.intake is None:
            raise RuntimeError("no intake queue configured")
        pending, waits, _ = self.intake.drain_all()
        # tenant-fair intakes (control subsystem) may have preempted
        # previously-accepted items under pressure: resolve each to an
        # explicit Preempted outcome APPENDED to this drain's results —
        # an accepted request is never silently lost. A plain
        # IntakeQueue has no take_preempted, and the import only
        # happens when something was actually preempted.
        take_preempted = getattr(self.intake, "take_preempted", None)
        preempted = take_preempted() if take_preempted is not None else []
        tail: list = []
        if preempted:
            from beholder_tpu.control.admission import Preempted

            tail = [Preempted(tenant) for _, tenant in preempted]
        if not pending:
            return tail
        if self.flight_recorder is not None:
            # intake residency (measured at the drain, read atomically
            # with the items) rides the timeline: the SLO layer's
            # queue-wait is measured, not inferred
            self.annotate_requests({
                rid: {"queue_wait_s": round(wait, 6)}
                for rid, wait in enumerate(waits)
            })
        if waves is None:
            waves = self.prefix_cache is None and self.spec is None
        if waves:
            return self.run_waves(pending) + tail
        if self.spec is not None:
            return self.run_spec(pending) + tail
        return self.run(pending) + tail

    # -- speculative path: draft-then-verify ----------------------------

    def run_spec(self, requests: list[Request]) -> list[np.ndarray]:
        """Speculative decoding over the paged pool: a drafter proposes
        up to k tokens per slot, ONE chunked verify step scores them
        all through the dense-cache forward, accepted tokens' KV stays
        chunk-scattered in the pool and the rejected suffix's pages
        roll back. Requires the batcher to be built with ``spec=``
        (:class:`beholder_tpu.spec.SpecConfig`); see
        :mod:`beholder_tpu.spec` for the exactness and distribution
        guarantees. Results match :meth:`run`'s contract; under greedy
        exact acceptance the stream is bitwise-independent of the
        drafter and tracks the dense reference rollout to
        reassociation ULPs.
        """
        if self.spec is None:
            raise RuntimeError(
                "no spec config — construct the batcher with "
                "spec=SpecConfig(...) to use run_spec()"
            )
        from beholder_tpu.spec.scheduler import run_spec

        return run_spec(self, requests)

    # -- flexible path: per-tick scheduling -----------------------------

    def run(self, requests: list[Request]) -> list[np.ndarray]:
        """Per-EVENT scheduling with on-device feedback: the scheduler
        only touches the host at scheduling events (admissions and
        retirements); the event-free stretches between them — every
        tick until the earliest retirement — run as one device program
        (:func:`_tick_chunk`), each admission round is ONE batched
        prefill (:func:`_admit_many_carry`), and each retirement round
        is three dispatches total. Retirement snapshots forecast rows as
        device arrays (async gathers, no sync); everything comes back in
        one single-buffer ``jax.device_get`` at the end.

        This is the flexibility path — requests admit the moment a slot
        frees up, so mixed-horizon fleets keep all slots busy.
        :meth:`run_waves` still wins on throughput by fusing admission,
        scan, and release into one program per wave AND deferring its
        readback (``device_results=True``), which run() cannot: its
        contract returns host arrays, so one d2h crossing (~65 ms on
        this tunnel) is part of every call. Both paths are measured side
        by side in ``bench.py`` (``serving.run_value`` vs
        ``serving.value``)."""
        self._start_run(requests)
        counts: dict[tuple[int, int], int] = {}
        for r in requests:
            key = (len(r.progress), r.horizon)
            counts[key] = counts.get(key, 0) + 1
        for key, n in counts.items():
            self.seen_request_shapes[key] = max(
                self.seen_request_shapes.get(key, 0), min(n, self.slots)
            )
        t0 = time.perf_counter()
        try:
            with self._run_span(
                "serving.run", requests=len(requests)
            ) as span:
                results = self._run(requests, span)
        except BaseException:
            self._poisoned = True
            raise
        if self._metrics:
            self._metrics.observe_run(
                "run",
                time.perf_counter() - t0,
                sum(max(r.horizon, 0) for r in requests),
                trace_id=self._span_trace_id(span),
            )
        return results

    def _run(self, requests: list[Request], span=None) -> list[np.ndarray]:
        queue = list(enumerate(requests))
        results: list = [None] * len(requests)
        cap = max(
            1, max((r.horizon for r in requests), default=1) - 1
        )
        carry = _RunCarry(
            jnp.zeros((self.slots,), jnp.float32),
            jnp.zeros((self.slots, NUM_STATUSES), jnp.float32),
            jnp.zeros((self.slots, cap), jnp.float32),
        )
        # per-slot host bookkeeping (host mirrors the allocator: pages a
        # request can ever hold depend only on its lengths)
        req_of = [None] * self.slots
        remaining = np.zeros(self.slots, np.int64)
        total_need = np.zeros(self.slots, np.int64)  # pages at horizon end
        written = np.zeros(self.slots, np.int64)     # forecast entries
        # each scheduling event appends ONE batch: (rids, (R, cap) rows,
        # (R,) tails, per-rid live widths) — rows/tails device-resident
        snap_batches: list[tuple[list, jax.Array, jax.Array, list]] = []
        served = [0, 0]  # requests, tokens — counted into metrics only
        # AFTER the allocator check (a failed run served nothing)

        def free_pages() -> int:
            """Free pages after honoring every active slot's worst-case
            future growth (deferring admission beats the sticky
            alloc_failed abort): num_pages minus the active worst
            cases — held pages cancel between free_top and committed
            growth, so no device read is needed. With a prefix cache
            the cold cached pages are reserved too (conservative: a
            page both adopted by a live slot and cached counts in the
            slot's need, never in the cold set, so the estimate only
            ever understates free — the safe direction)."""
            cold = (
                self.prefix_cache.cold_page_count
                if self.prefix_cache is not None
                else 0
            )
            return self.num_pages - int(total_need.sum()) - cold

        #: rids retired by deadline expiry — their post-readback results
        #: wrap in DeadlineExceededResult (partial tokens attached)
        deadline_rids: list[int] = []
        has_deadlines = any(
            getattr(r, "deadline", None) is not None for r in requests
        )

        def retire_many(done: list[int], expired: bool = False):
            """Snapshot + release a retirement round in THREE dispatches
            (two batched gathers + one vectorized release) regardless of
            how many slots finish together. No extra tick runs (the
            horizon-th prediction is last_pred itself; a tick for it
            could allocate a page for a token nobody reads), and nothing
            crosses to the host — full (cap,) rows are gathered so every
            event's snapshot has a packable shape, with the live widths
            riding along host-side for the post-fetch trim. ``expired``
            retires slots whose DEADLINE ran out: same snapshot/release
            machinery (the partial forecast row is still delivered),
            but the rid is marked for the deadline_exceeded outcome and
            served tokens count what was actually decoded."""
            with self._round(span, "retire", slots=len(done)):
                idx = jnp.asarray(done, jnp.int32)
                rids = [req_of[s] for s in done]
                widths = [int(written[s]) for s in done]
                snap_batches.append((
                    rids,
                    carry.delta_buf[idx],
                    carry.last_pred[idx],
                    widths,
                ))
                self.state = self._release_many(self.state, idx)
                for s in done:
                    req_of[s] = None
                    total_need[s] = 0
                    written[s] = 0
                    if self.prefix_cache is not None and self._slot_chain[s]:
                        # the slot's device refs just dropped; the
                        # cache's own ref keeps its prefix pages
                        # resident as COLD entries (evictable under
                        # pool pressure, reusable until then)
                        self.prefix_cache.release(self._slot_chain[s])
                        self._slot_chain[s] = []
                served[0] += len(done)
                if expired:
                    served[1] += sum(w + 1 for w in widths)
                    deadline_rids.extend(rids)
                    self._count_deadline_exceeded(len(done))
                    if self.flight_recorder is not None:
                        self.flight_recorder.instant(
                            "deadline_exceeded", stage="tick",
                            slots=len(done),
                        )
                else:
                    served[1] += sum(requests[r].horizon for r in rids)
                outcome = "deadline_exceeded" if expired else "ok"
                for s, rid, w in zip(done, rids, widths):
                    self._emit_req_retire(rid, s, w + 1, outcome)

        while queue or any(r is not None for r in req_of):
            if has_deadlines:
                # deadline sweep at the scheduling-event boundary: an
                # expired slot retires NOW with its partial forecast —
                # it must not hold pages through another tick chunk
                # (the recovery-storm wedge this check exists for)
                lapsed = [
                    s for s in range(self.slots)
                    if req_of[s] is not None
                    and self._deadline_expired(requests[req_of[s]])
                ]
                if lapsed:
                    retire_many(lapsed, expired=True)
            # admission round: claim every (slot, request) pair that fits
            # under the page-headroom arithmetic (the claim loop — pin-
            # before-evict, deferral, once-per-admission stats — is
            # shared with the spec scheduler), then admit them all in
            # ONE batched-prefill dispatch (host traffic per scheduling
            # EVENT, not per request)
            def commit(slot, rid, req, need):
                remaining[slot] = req.horizon
                total_need[slot] = need
                written[slot] = 0

            batch = self._claim_admissions(
                queue, results, req_of, free_pages, commit
            )
            if batch:
                admit_tags = {"requests": len(batch)}
                if self.flight_recorder is not None:
                    # prefill FLOPs follow the uncached suffix tokens;
                    # ctx ~ t/2 is the mean causal visibility
                    admit_tags.update(self._kernel_tags("flash", sum(
                        (t - len(hp) * self.page_size)
                        * self._flops_per_token(t / 2.0)
                        for _, _, _, t, hp, _ in batch
                    )))
                with self._round(span, "admit", **admit_tags):
                    cold = [b for b in batch if not b[4]]
                    warm = [b for b in batch if b[4]]
                    if cold:
                        t_pad = -(
                            -max(t for _, _, _, t, _, _ in cold)
                            // self.page_size
                        ) * self.page_size
                        admit = self._cached_jit(
                            ("admit", len(cold), t_pad),
                            lambda: lambda p, s, c, ids, f, ln, st: (
                                _admit_many_carry(self.model, p, s, c, ids, f, ln, st)
                            ),
                        )
                        self.state, carry = admit(
                            self.params, self.state, carry,
                            jnp.asarray(
                                [s for s, _, _, _, _, _ in cold], jnp.int32
                            ),
                            jnp.asarray(np.stack(
                                [self._pad_to(f, t_pad)
                                 for _, _, f, _, _, _ in cold]
                            )),
                            jnp.asarray(
                                [t for _, _, _, t, _, _ in cold], jnp.int32
                            ),
                            jnp.asarray(
                                [int(requests[r].statuses[-1])
                                 for _, r, _, _, _, _ in cold],
                                jnp.int32,
                            ),
                        )
                    for slot, rid, feats_np, t, hit_pages, _ in warm:
                        # warm path: adopt the cached pages, prefill the
                        # suffix only (one dispatch per hit — hit shapes
                        # vary; the prefill FLOPs saved dwarf it)
                        t_hit = len(hit_pages) * self.page_size
                        s_len = t - t_hit
                        s_pad = -(-s_len // self.page_size) * self.page_size
                        admit_c = self._cached_jit(
                            (
                                "admit_cached", len(hit_pages), s_pad,
                                self.fused_verify,
                            ),
                            lambda: lambda p, s, c, sl, f, ln, pg, st: (
                                _admit_cached_carry(
                                    self.model, p, s, c, sl, f, ln, pg,
                                    st, fused=self.fused_verify,
                                )
                            ),
                        )
                        self.state, carry = admit_c(
                            self.params, self.state, carry,
                            jnp.int32(slot),
                            jnp.asarray(
                                self._pad_to(feats_np[t_hit:], s_pad)
                            )[None],
                            jnp.int32(s_len),
                            jnp.asarray(hit_pages, jnp.int32),
                            jnp.int32(int(requests[rid].statuses[-1])),
                        )
                    if self.prefix_cache is not None:
                        self.prefix_cache.prefilled(sum(
                            t - len(hp) * self.page_size
                            for _, _, _, t, hp, _ in batch
                        ))
                        self._index_admitted([
                            (slot, hs, t // self.page_size)
                            for slot, _, _, t, _, hs in batch
                        ])
                done = [b[0] for b in batch if remaining[b[0]] == 1]
                if done:
                    retire_many(done)  # admit predictions WERE the forecasts
            if self._metrics:
                self._metrics.slots_active.set(
                    sum(r is not None for r in req_of)
                )
                free_now = free_pages()
                self._metrics.pool_pages_free.set(free_now)
                self._metrics.pool_pressure_from(
                    free_now, req_of, requests, total_need,
                    self.max_pages_per_seq,
                )

            if not any(r is not None for r in req_of):
                continue

            # run every tick until the NEXT scheduling event (the
            # earliest retirement) as ONE device program: between events
            # the scheduler has no decisions to make, so per-tick
            # dispatch would be pure overhead (inactive slots ride
            # along; their forecast writes drop at the cap sentinel)
            active = [r is not None for r in req_of]
            n_chunk = max(
                1, int(min(remaining[s] for s in range(self.slots)
                           if active[s])) - 1
            )
            write_idx = np.where(active, written, cap).astype(np.int32)
            tick_tags = {"ticks": n_chunk}
            if self.flight_recorder is not None:
                lens = [
                    len(requests[req_of[s]].progress) - 1 + int(written[s])
                    for s in range(self.slots)
                    if active[s]
                ]
                tick_tags.update(self._kernel_tags(
                    "paged",
                    n_chunk * len(lens)
                    * self._flops_per_token(float(np.mean(lens))),
                ))
            with self._round(span, "tick", **tick_tags):
                self.state, carry = self._tick_chunk(
                    self.params, self.state, carry, jnp.asarray(write_idx),
                    jnp.int32(n_chunk),
                )
            done = []
            for slot in range(self.slots):
                if req_of[slot] is None:
                    continue
                written[slot] += n_chunk
                remaining[slot] -= n_chunk
                if remaining[slot] <= 1:
                    done.append(slot)
            if done:
                retire_many(done)
                if self._metrics:
                    self._metrics.slots_active.set(
                        sum(r is not None for r in req_of)
                    )
                    free_now = free_pages()
                    self._metrics.pool_pages_free.set(free_now)
                    self._metrics.pool_pressure_from(
                        free_now, req_of, requests, total_need,
                        self.max_pages_per_seq,
                    )

        # ONE host readback of ONE buffer: this tunnel charges its
        # ~65 ms d2h constant PER BUFFER, not per call — a device_get
        # over the 2R+1 separate snapshot arrays cost ~R readbacks and
        # capped run() at ~2k tok/s (measured round 5) — so the flag,
        # tails, and rows are packed into a single flat device array
        # first (a few ~20 us dispatches) and fetched in one crossing
        if snap_batches:
            with self._round(span, "readback", batches=len(snap_batches)):
                rows = jnp.concatenate([b[1] for b in snap_batches])
                tails = jnp.concatenate([b[2] for b in snap_batches])
                packed = jnp.concatenate(
                    [
                        self.state.alloc_failed.astype(jnp.float32)[None],
                        tails.astype(jnp.float32),
                        rows.reshape(-1),
                    ]
                )
                got = np.asarray(jax.device_get(packed), np.float32)
            if got[0]:
                raise RuntimeError(self._ALLOCATOR_TRIPPED)
            rids = [rid for b in snap_batches for rid in b[0]]
            widths = [w for b in snap_batches for w in b[3]]
            r = len(rids)
            tails_v = got[1 : 1 + r]
            rows_v = got[1 + r :].reshape(r, cap)
            for i, (rid, w) in enumerate(zip(rids, widths)):
                results[rid] = np.append(rows_v[i, :w], tails_v[i])
            for rid in deadline_rids:
                results[rid] = DeadlineExceededResult(results[rid])
        elif bool(jax.device_get(self.state.alloc_failed)):
            raise RuntimeError(self._ALLOCATOR_TRIPPED)
        if self._metrics:
            self._metrics.served(*served)
        return results

    # -- throughput path: on-device waves -------------------------------

    def _cached_jit(self, key: tuple, build):
        """One compiled program per static shape key (wave width, scan
        length, trims): jit on first use, reuse after."""
        fn = self._serve_cache.get(key)
        if fn is None:
            fn = jax.jit(build())
            self._serve_cache[key] = fn
        return fn

    def _serve_fn(
        self, n: int, n_ticks: int, horizons: tuple[int, ...] | None = None
    ):
        # the fused_wave knob joins the static key: flipping it mid-
        # process recompiles rather than serving a stale program
        return self._cached_jit(
            (n, n_ticks, horizons, self.fused_wave),
            lambda: lambda p, s, f, ln, st: serve_wave(
                self.model, p, s, f, ln, st, n_ticks, horizons=horizons,
                fused=self.fused_wave,
            ),
        )

    def run_waves(
        self, requests: list[Request], device_results: bool = False
    ) -> list:
        """Fixed-horizon throughput mode: greedy waves of up to ``slots``
        requests, each wave ONE compiled admit+scan+release program
        (:func:`serve_wave`) over its max horizon (shorter-horizon
        members ride along; their surplus deltas are dropped when
        results are read back). Page headroom is checked per wave with
        host arithmetic (no device reads), with ride-along growth
        counted at the wave horizon.

        With ``device_results=True`` the per-request forecasts come back
        as device arrays with NO host readback at all — the pipelining /
        benchmarking mode; the caller owns checking
        ``state.alloc_failed`` before trusting them."""
        self._start_run(requests)
        t0 = time.perf_counter()
        try:
            with self._run_span(
                "serving.run_waves",
                requests=len(requests),
                device_results=device_results,
            ) as span:
                results = self._run_waves(requests, device_results, span)
        except BaseException:
            self._poisoned = True
            raise
        if self._metrics:
            self._metrics.observe_run(
                "run_waves",
                time.perf_counter() - t0,
                sum(max(r.horizon, 0) for r in requests),
                trace_id=self._span_trace_id(span),
            )
        return results

    def _run_waves(
        self, requests: list[Request], device_results: bool, span=None
    ) -> list:
        results: list = [None] * len(requests)
        queue = list(enumerate(requests))
        batches: list = []  # (wave members, (n, h) deltas device array)
        while queue:
            wave: list = []
            # serve_wave releases everything it admits, so every wave
            # starts from a full pool
            free = self.num_pages
            horizon = 0
            while queue and len(wave) < self.slots:
                rid, req = queue[0]
                if req.horizon <= 0:
                    queue.pop(0)
                    results[rid] = np.zeros(0, np.float32)
                    continue
                self._check_servable(req)
                h = max(horizon, req.horizon)
                # wave members decode h-1 ticks regardless of their own
                # horizon, so BOTH headroom checks run at the wave's
                # grown horizon: total pool pages AND each member's
                # page-table cap (a short request riding a long one can
                # overflow its own table — deferred to the next wave)
                def pages_at(r, hh):
                    return -(-(len(r.progress) - 1 + hh - 1)
                             // self.page_size)

                need = pages_at(req, h)
                others = sum(pages_at(r, h) for _, r in wave)
                over_cap = any(
                    pages_at(r, h) > self.max_pages_per_seq
                    for r in [req] + [r for _, r in wave]
                )
                if need + others > free or over_cap:
                    if not wave:
                        raise RuntimeError(
                            f"page pool exhausted: request needs {need} "
                            f"pages but only {free} exist free (per-seq "
                            f"cap {self.max_pages_per_seq})"
                        )
                    break
                queue.pop(0)
                wave.append((rid, req))
                horizon = h
                if self.flight_recorder is not None:
                    # the fused path's lifecycle marker: claim = wave
                    # membership (the wave slice that follows is the
                    # request's admission AND its first token)
                    tenant_note = (
                        {"tenant": req.tenant}
                        if getattr(req, "tenant", None) is not None
                        else {}
                    )
                    self.flight_recorder.instant(
                        "req.claim", rid=rid, slot=len(wave) - 1,
                        prefix_tokens=len(req.progress) - 1,
                        horizon=int(req.horizon),
                        **tenant_note,
                        **self._run_notes.get(rid, {}),
                    )
            if not wave:
                continue

            wave_tags = {"requests": len(wave), "horizon": horizon}
            if self.flight_recorder is not None:
                # one fused prefill + scan program: prefill FLOPs per
                # member plus horizon-1 decode ticks at end-of-wave ctx
                wave_tags.update(self._kernel_tags("paged", sum(
                    (len(req.progress) - 1)
                    * self._flops_per_token((len(req.progress) - 1) / 2.0)
                    + (horizon - 1) * self._flops_per_token(
                        len(req.progress) - 1 + horizon / 2.0
                    )
                    for _, req in wave
                )))
            with self._round(span, "wave", **wave_tags):
                prepped = [self._prep_np(req) for _, req in wave]
                t_pad = -(
                    -max(t for _, t in prepped) // self.page_size
                ) * self.page_size
                feats = np.stack([self._pad_to(p, t_pad) for p, _ in prepped])
                lens = np.asarray([t for _, t in prepped], np.int32)
                stats = np.asarray(
                    [int(req.statuses[-1]) for _, req in wave], np.int32
                )
                horizons = (
                    tuple(req.horizon for _, req in wave)
                    if device_results
                    else None
                )
                deltas, self.state = self._serve_fn(
                    len(wave), horizon - 1, horizons
                )(
                    self.params, self.state, jnp.asarray(feats),
                    jnp.asarray(lens), jnp.asarray(stats),
                )
                batches.append((wave, deltas))
            # retire = the fused program released the wave's slots
            # (run()'s retire semantics — pre-readback; the end-of-run
            # readback wall is charged to these requests by the
            # timeline fold's delivery rule)
            for slot_i, (rid, req) in enumerate(wave):
                self._emit_req_retire(rid, slot_i, req.horizon)
            if self._metrics:
                # the most recently DISPATCHED wave's occupancy (dispatch
                # is async; the device drains waves behind the loop).
                # served counters wait for the end-of-run allocator check
                self._metrics.slots_active.set(len(wave))
                free_now = self.num_pages - sum(
                    pages_at(r, horizon) for _, r in wave
                )
                self._metrics.pool_pages_free.set(free_now)
                self._metrics.pool_pressure_from(
                    free_now,
                    [rid for rid, _ in wave],
                    {rid: r for rid, r in wave},
                    [pages_at(r, horizon) for _, r in wave],
                    self.max_pages_per_seq,
                )

        if self._metrics:
            self._metrics.idle(self.num_pages)
        n_served = sum(len(w) for w, _ in batches)
        t_served = sum(req.horizon for w, _ in batches for _, req in w)
        if device_results:
            # each wave's deltas is already a tuple of per-request
            # in-program-trimmed arrays — no eager slicing here. The
            # caller owns the alloc_failed check in this mode, so this
            # work counts on the DISPATCHED counters only; the served
            # series stays reserved for allocator-checked results
            if self._metrics:
                self._metrics.dispatched(n_served, t_served)
            for wave, rows in batches:
                for (rid, _), row in zip(wave, rows):
                    results[rid] = row
            return results

        # ONE host readback for all waves' results + the allocator flag
        with self._round(span, "readback", batches=len(batches)):
            fetched = jax.device_get(
                [d for _, d in batches] + [self.state.alloc_failed]
            )
        if fetched[-1]:
            raise RuntimeError(self._ALLOCATOR_TRIPPED)
        if self._metrics:
            self._metrics.served(n_served, t_served)
        for (wave, _), arr in zip(batches, fetched):
            for i, (rid, req) in enumerate(wave):
                results[rid] = np.asarray(
                    arr[i, : req.horizon], np.float32
                )
        return results

    # -- what-if path: one prefix, many hypothetical futures ------------

    def run_what_if(
        self,
        progress: np.ndarray,
        statuses: np.ndarray,
        branch_statuses: list[int],
        horizon: int,
    ) -> np.ndarray:
        """Forecast ONE observed telemetry stream under ``k`` hypothetical
        status branches ("how does the remaining time change if the job
        goes to DEPLOYED vs ERRORED from here"): the prefix is prefilled
        ONCE, its full pages shared across branches (:func:`paged_fork`),
        and all branches roll together in one compiled program
        (:func:`fork_wave`). Cost vs ``k`` independent requests: 1/k of
        the prefill FLOPs, and the pool holds the prefix once plus one
        tail page per branch. Returns (k, horizon) forecast deltas."""
        k = len(branch_statuses)
        if not 1 <= k <= self.slots:
            raise ValueError(
                f"branches {k} must be in [1, slots={self.slots}]"
            )
        if horizon < 1:
            raise ValueError(f"horizon must be >= 1, got {horizon}")
        bad = [
            s for s in branch_statuses if not 0 <= int(s) < NUM_STATUSES
        ]
        if bad:
            # an out-of-range status would one-hot to an all-zeros row —
            # a silently status-blind branch, not an error
            raise ValueError(
                f"branch statuses {bad} out of range [0, {NUM_STATUSES})"
            )
        self._check_not_poisoned()
        req = Request(
            np.asarray(progress), np.asarray(statuses), horizon
        )
        feats_np, t = self._prep_np(req)
        if t == 0:
            # must fail HERE with the other pre-checks: a (1, 0, F)
            # prefill inside the traced program would raise mid-flight
            # and needlessly poison a batcher that admitted nothing
            raise ValueError(
                "prefix must contain at least one observed delta "
                "(progress needs >= 2 samples)"
            )
        n_ticks = horizon - 1
        end_pages = -(-(t + n_ticks) // self.page_size)
        shared = t // self.page_size
        need = shared + k * (end_pages - shared)
        if end_pages > self.max_pages_per_seq or need > self.num_pages:
            raise RuntimeError(
                f"page pool exhausted: {k} branches of a {t}-token "
                f"prefix at horizon {horizon} need {need} pages "
                f"(pool {self.num_pages}, per-seq cap "
                f"{self.max_pages_per_seq})"
            )
        t_pad = -(-t // self.page_size) * self.page_size
        fn = self._cached_jit(
            ("what_if", k, n_ticks, t_pad),
            lambda: lambda p, s, f, ln, br: fork_wave(
                self.model, p, s, f, ln, br, n_ticks
            ),
        )
        t0 = time.perf_counter()
        try:
            with self._run_span(
                "serving.what_if", branches=k, horizon=horizon
            ) as span:
                with self._round(span, "wave", requests=1, horizon=horizon):
                    deltas, self.state = fn(
                        self.params, self.state,
                        jnp.asarray(self._pad_to(feats_np, t_pad))[None],
                        jnp.int32(t),
                        jnp.asarray(branch_statuses, jnp.int32),
                    )
                # flag + deltas packed into ONE buffer before the fetch —
                # the tunnel charges its ~65 ms d2h constant per BUFFER
                # (same packing as run()'s final readback)
                with self._round(span, "readback", batches=1):
                    packed = jnp.concatenate(
                        [
                            self.state.alloc_failed.astype(jnp.float32)[None],
                            deltas.astype(jnp.float32).reshape(-1),
                        ]
                    )
                    got = np.asarray(jax.device_get(packed), np.float32)
        except BaseException:
            self._poisoned = True
            raise
        if got[0]:
            self._poisoned = True
            raise RuntimeError(self._ALLOCATOR_TRIPPED)
        out = got[1:].reshape(k, n_ticks + 1)
        if self._metrics:
            # one request, k branch rollouts' worth of decode work
            # (counted here, after the allocator check above)
            self._metrics.served(1, k * horizon)
            self._metrics.idle(self.num_pages)
            self._metrics.observe_run(
                "what_if", time.perf_counter() - t0, k * horizon,
                trace_id=self._span_trace_id(span),
            )
        return np.asarray(out[:, :horizon], np.float32)
