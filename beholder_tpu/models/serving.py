"""Serving v2: paged KV cache + continuous batching.

EXTENSION BEYOND THE REFERENCE (which has no inference of any kind —
SURVEY.md §0). :mod:`beholder_tpu.models.decode` serves a FIXED batch
with one dense (B, Hkv, max_len, Dh) cache per layer; this module serves
a CHANGING population of requests the way modern LLM servers do
(vLLM-style), re-thought for XLA's static-shape compilation model:

- **Paged pool.** Each layer's cache is a (num_pages, Hkv, page_size,
  Dh) pool; a sequence owns a list of pages (``page_table`` row). Memory
  scales with TOKENS IN FLIGHT, not slots x max_len: short and long
  requests share the pool, and a retiring request returns its pages to a
  free stack for the next admit.
- **Static shapes everywhere.** The decode tick is ONE compiled program
  for all slots: gather each slot's pages into a transient view
  (XLA gather), run the model's cached decode with PER-SLOT positions
  (each slot sits at its own length — the vector-index cache path in
  :class:`~beholder_tpu.models.sequence.Block`), scatter the new kv
  column back into the pool. Admission and retirement are also fixed
  shape: page allocation is a masked vectorized stack pop, freeing a
  masked push — no data-dependent Python in jit.
- **Continuous batching.** The host-side :class:`ContinuousBatcher`
  admits queued requests into free slots mid-flight, ticks all active
  slots together, and retires finished ones — the accelerator never
  waits for the longest request in a "static batch" to finish. The only
  host<->device traffic per tick is the (slots,) predictions readback
  that the batcher feeds back as the next inputs.

The paged decode is numerically equivalent to the dense per-request
rollout (pinned by ``tests/test_serving.py``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from beholder_tpu.ops import NUM_STATUSES

from .sequence import TelemetrySequenceModel


class PagedKVState(NamedTuple):
    """Paged serving state (a pytree; every leaf has a static shape).

    - ``k_pools``/``v_pools``: per-layer (num_pages, Hkv, page, Dh)
    - ``page_table``: (slots, max_pages) pool indices per slot
    - ``seq_lens``: (slots,) tokens written per slot
    - ``active``: (slots,) bool
    - ``free_stack``: (num_pages,) pool indices; ``free_stack[:free_top]``
      are free
    - ``alloc_failed``: sticky error flag (pool exhausted / table
      overflow) — checked host-side by the batcher
    """

    k_pools: tuple
    v_pools: tuple
    page_table: jax.Array
    seq_lens: jax.Array
    active: jax.Array
    free_stack: jax.Array
    free_top: jax.Array
    alloc_failed: jax.Array


def init_paged(
    model: TelemetrySequenceModel,
    num_pages: int,
    page_size: int,
    slots: int,
    max_pages_per_seq: int,
) -> PagedKVState:
    dh = model.dim // model.heads
    hkv = model.kv_heads or model.heads
    shape = (num_pages, hkv, page_size, dh)
    k_pools = tuple(jnp.zeros(shape, jnp.bfloat16) for _ in range(model.layers))
    v_pools = tuple(jnp.zeros(shape, jnp.bfloat16) for _ in range(model.layers))
    return PagedKVState(
        k_pools,
        v_pools,
        jnp.zeros((slots, max_pages_per_seq), jnp.int32),
        jnp.zeros((slots,), jnp.int32),
        jnp.zeros((slots,), bool),
        jnp.arange(num_pages, dtype=jnp.int32),
        jnp.int32(num_pages),
        jnp.zeros((), bool),
    )


def _pop_pages(state: PagedKVState, need: jax.Array):
    """Vectorized masked stack pop: slot i with ``need[i]`` gets page
    ``free_stack[free_top - 1 - rank_i]`` where rank_i numbers the
    needers. Returns (pages (slots,), new_top, failed)."""
    rank = jnp.cumsum(need.astype(jnp.int32)) - 1
    n = need.sum().astype(jnp.int32)
    idx = state.free_top - 1 - rank
    failed = state.alloc_failed | (n > state.free_top)
    pages = state.free_stack[jnp.clip(idx, 0, state.free_stack.shape[0] - 1)]
    return pages, state.free_top - n, failed


def _alloc_for_tick(state: PagedKVState) -> PagedKVState:
    """Give every active slot whose next write position opens a fresh
    page (len % page == 0) a page off the free stack."""
    page = state.k_pools[0].shape[2]
    slots, max_pages = state.page_table.shape
    need = state.active & (state.seq_lens % page == 0)
    pages, new_top, failed = _pop_pages(state, need)
    pidx = state.seq_lens // page
    failed = failed | jnp.any(need & (pidx >= max_pages))
    rows = jnp.where(need, jnp.arange(slots), slots)  # OOB row -> dropped
    table = state.page_table.at[
        rows, jnp.clip(pidx, 0, max_pages - 1)
    ].set(pages, mode="drop")
    return state._replace(
        page_table=table, free_top=new_top, alloc_failed=failed
    )


def _views(state: PagedKVState):
    """Transient dense (slots, Hkv, max_pages*page, Dh) gather of each
    slot's pages, per layer. The POOL is the persistent storage; these
    views live only inside one decode tick."""
    table = state.page_table  # (S, P)
    s, p = table.shape

    def one(pool):
        g = pool[table]                      # (S, P, Hkv, page, Dh)
        g = g.transpose(0, 2, 1, 3, 4)       # (S, Hkv, P, page, Dh)
        return g.reshape(s, g.shape[1], p * g.shape[3], g.shape[4])

    return tuple(one(k) for k in state.k_pools), tuple(
        one(v) for v in state.v_pools
    )


def _scatter_column(pool, pages, offsets, cols):
    """pool[(pages[i], :, offsets[i], :)] = cols[i] with OOB pages
    dropped (inactive slots)."""
    return pool.at[pages, :, offsets, :].set(
        cols.astype(pool.dtype), mode="drop"
    )


def paged_decode_tick(
    model: TelemetrySequenceModel, params, state: PagedKVState, feats_t
):
    """One continuous-batching decode step for ALL slots.

    ``feats_t`` is (slots, FEATURES); inactive slots run too (their
    writes are dropped, their outputs ignored) — that is what keeps the
    tick a single compiled program. Returns ((slots,) predictions,
    updated state)."""
    state = _alloc_for_tick(state)
    page = state.k_pools[0].shape[2]
    slots = state.page_table.shape[0]
    k_views, v_views = _views(state)

    preds, new_kvs = model.apply(
        params,
        feats_t[:, None, :],
        cache=(k_views, v_views, state.seq_lens),
    )

    rows = jnp.arange(slots)
    pidx = jnp.clip(state.seq_lens // page, 0, state.page_table.shape[1] - 1)
    pages = jnp.where(
        state.active,
        state.page_table[rows, pidx],
        state.k_pools[0].shape[0],  # OOB -> dropped
    )
    offsets = state.seq_lens % page
    k_pools, v_pools = [], []
    for layer, (k_view, v_view) in enumerate(new_kvs):
        # the model wrote each slot's new kv column into its view at the
        # slot's own position; persist that column into the pool
        k_col = k_view[rows, :, state.seq_lens, :]  # (S, Hkv, Dh)
        v_col = v_view[rows, :, state.seq_lens, :]
        k_pools.append(
            _scatter_column(state.k_pools[layer], pages, offsets, k_col)
        )
        v_pools.append(
            _scatter_column(state.v_pools[layer], pages, offsets, v_col)
        )

    state = state._replace(
        k_pools=tuple(k_pools),
        v_pools=tuple(v_pools),
        seq_lens=state.seq_lens + state.active.astype(jnp.int32),
    )
    return preds[:, 0], state


def paged_admit(
    model: TelemetrySequenceModel,
    params,
    state: PagedKVState,
    slot: jax.Array,
    feats_padded: jax.Array,
    prefix_len: jax.Array,
):
    """Admit one request into ``slot``: prefill its (1, T_max, F) padded
    prefix in one forward, allocate ceil(prefix_len/page) pages, and
    write the prefix kv into them. Returns ((,) last prediction, state).

    The page count is data-dependent but the WORK is not: the masked
    writes cover all T_max//page chunks and drop the dead ones.
    """
    page = state.k_pools[0].shape[2]
    num_pages = state.k_pools[0].shape[0]
    slots, max_pages = state.page_table.shape
    t_max = feats_padded.shape[1]
    if t_max % page:
        raise ValueError(f"padded prefix {t_max} not a page multiple ({page})")
    p_max = t_max // page

    preds, kvs = model.apply(params, feats_padded, return_kv=True)
    last_pred = preds[0, jnp.clip(prefix_len - 1, 0, t_max - 1)]

    n_pages = -(-prefix_len // page)  # ceil
    chunk_alive = jnp.arange(p_max) < n_pages
    pages, new_top, failed = _pop_pages(state, chunk_alive)  # (p_max,)
    failed = failed | (n_pages > max_pages)
    table_row = jnp.where(
        jnp.arange(max_pages) < n_pages,
        jnp.pad(pages, (0, max(0, max_pages - p_max)))[:max_pages],
        0,
    )

    k_pools, v_pools = [], []
    drop = jnp.where(chunk_alive, pages, num_pages)     # OOB -> dropped
    for layer, (k, v) in enumerate(kvs):
        # (1, Hkv, T_max, Dh) -> (p_max, Hkv, page, Dh) page chunks
        def chunks(a):
            a = a[0].transpose(1, 0, 2)                 # (T_max, Hkv, Dh)
            a = a.reshape(p_max, page, a.shape[1], a.shape[2])
            return a.transpose(0, 2, 1, 3)
        k_pools.append(
            state.k_pools[layer].at[drop].set(
                chunks(k).astype(state.k_pools[layer].dtype), mode="drop"
            )
        )
        v_pools.append(
            state.v_pools[layer].at[drop].set(
                chunks(v).astype(state.v_pools[layer].dtype), mode="drop"
            )
        )

    state = state._replace(
        k_pools=tuple(k_pools),
        v_pools=tuple(v_pools),
        page_table=state.page_table.at[slot].set(table_row),
        seq_lens=state.seq_lens.at[slot].set(prefix_len),
        active=state.active.at[slot].set(True),
        free_top=new_top,
        alloc_failed=failed,
    )
    return last_pred, state


def paged_release(state: PagedKVState, slot: jax.Array) -> PagedKVState:
    """Retire ``slot``: push its pages back onto the free stack."""
    page = state.k_pools[0].shape[2]
    num_pages = state.k_pools[0].shape[0]
    max_pages = state.page_table.shape[1]
    n = -(-state.seq_lens[slot] // page)
    alive = jnp.arange(max_pages) < n
    dest = jnp.where(
        alive, state.free_top + jnp.arange(max_pages), num_pages
    )
    stack = state.free_stack.at[dest].set(
        state.page_table[slot], mode="drop"
    )
    return state._replace(
        free_stack=stack,
        free_top=state.free_top + n,
        active=state.active.at[slot].set(False),
        seq_lens=state.seq_lens.at[slot].set(0),
    )


class Request(NamedTuple):
    progress: np.ndarray   # (T+1,) observed progress
    statuses: np.ndarray   # (T+1,) observed statuses
    horizon: int


class ContinuousBatcher:
    """Host-side vLLM-style scheduler over the paged state.

    Submit any number of :class:`Request`\\ s, then :meth:`run`. The
    batcher admits requests into free slots as they open (prefill is one
    jit per admission; padded to ``max_prefix``), ticks every active
    slot in one compiled step, feeds each slot's prediction back as its
    next input, and retires slots whose horizon is exhausted — freeing
    their pages for queued requests. Results are per-request forecast
    delta arrays, equal to the dense per-request rollout.
    """

    def __init__(
        self,
        model: TelemetrySequenceModel,
        params,
        *,
        num_pages: int = 64,
        page_size: int = 16,
        slots: int = 4,
        max_prefix: int = 64,
        max_pages_per_seq: int = 32,
    ):
        self.model = model
        self.params = params
        self.page_size = page_size
        self.max_prefix = -(-max_prefix // page_size) * page_size
        self.state = init_paged(
            model, num_pages, page_size, slots, max_pages_per_seq
        )
        self.slots = slots
        self._tick = jax.jit(
            lambda p, s, f: paged_decode_tick(model, p, s, f)
        )
        self._admit = jax.jit(
            lambda p, s, slot, feats, n: paged_admit(
                model, p, s, slot, feats, n
            )
        )
        self._release = jax.jit(paged_release)

    def run(self, requests: list[Request]) -> list[np.ndarray]:
        from .sequence import stream_features

        queue = list(enumerate(requests))
        results: list = [None] * len(requests)
        # per-slot host bookkeeping
        req_of = [None] * self.slots
        deltas: list = [None] * self.slots
        remaining = np.zeros(self.slots, np.int64)
        last_pred = np.zeros(self.slots, np.float32)
        status_oh = np.zeros((self.slots, NUM_STATUSES), np.float32)

        while queue or any(r is not None for r in req_of):
            # admit while there is a free slot and a queued request
            for slot in range(self.slots):
                if not queue or req_of[slot] is not None:
                    continue
                rid, req = queue.pop(0)
                if req.horizon <= 0:
                    # forecast_deltas(horizon=0) returns an empty array;
                    # skip the prefill/alloc round-trip entirely
                    results[rid] = np.zeros(0, np.float32)
                    continue
                feats, _ = stream_features(
                    jnp.asarray(req.progress)[None], jnp.asarray(req.statuses)[None]
                )
                t = feats.shape[1]
                if t > self.max_prefix:
                    raise ValueError(
                        f"prefix {t} exceeds max_prefix {self.max_prefix}"
                    )
                padded = jnp.pad(
                    feats, ((0, 0), (0, self.max_prefix - t), (0, 0))
                )
                pred, self.state = self._admit(
                    self.params, self.state, jnp.int32(slot), padded,
                    jnp.int32(t),
                )
                if bool(self.state.alloc_failed):
                    raise RuntimeError(
                        "page pool exhausted — raise num_pages or lower "
                        "concurrency"
                    )
                req_of[slot] = rid
                deltas[slot] = []
                remaining[slot] = req.horizon
                last_pred[slot] = float(pred)
                status_oh[slot] = np.asarray(
                    jax.nn.one_hot(int(req.statuses[-1]), NUM_STATUSES)
                )

            # one compiled tick for every slot (inactive slots ride along)
            feats_t = jnp.asarray(
                np.concatenate([last_pred[:, None], status_oh], axis=1),
                jnp.float32,
            )
            preds, self.state = self._tick(self.params, self.state, feats_t)
            if bool(self.state.alloc_failed):
                raise RuntimeError("page pool exhausted mid-decode")
            preds = np.asarray(preds)

            for slot in range(self.slots):
                if req_of[slot] is None:
                    continue
                deltas[slot].append(last_pred[slot])
                last_pred[slot] = preds[slot]
                remaining[slot] -= 1
                if remaining[slot] <= 0:
                    results[req_of[slot]] = np.asarray(
                        deltas[slot], np.float32
                    )
                    self.state = self._release(self.state, jnp.int32(slot))
                    req_of[slot] = None
        return results
