"""Serving v2: paged KV cache + continuous batching.

EXTENSION BEYOND THE REFERENCE (which has no inference of any kind —
SURVEY.md §0). :mod:`beholder_tpu.models.decode` serves a FIXED batch
with one dense (B, Hkv, max_len, Dh) cache per layer; this module serves
a CHANGING population of requests the way modern LLM servers do
(vLLM-style), re-thought for XLA's static-shape compilation model:

- **Paged pool.** Each layer's cache is a (num_pages, Hkv, Dh, page)
  pool — tokens on the minor (lane) dim, the TPU-native page layout (see
  :mod:`beholder_tpu.ops.paged_attention`); a sequence owns a list of
  pages (``page_table`` row). Memory scales with TOKENS IN FLIGHT, not
  slots x max_len; a retiring request returns its pages to a free stack.
- **Paged at COMPUTE time too.** The decode tick scatters each slot's
  new kv column into its page and then attends the pages IN PLACE via
  the scalar-prefetched page table inside a Pallas kernel
  (:func:`~beholder_tpu.ops.paged_attention.paged_decode_attention`) —
  no dense (slots, max_pages*page) view of the cache ever materializes
  (round 3 gathered one per layer per tick; pinned gone by
  ``tests/test_serving.py::test_tick_never_materializes_dense_views``).
- **Static shapes everywhere.** The tick is ONE compiled program for all
  slots; admission and retirement are fixed shape too: page allocation
  is a masked vectorized stack pop, freeing a masked push — no
  data-dependent Python in jit.
- **Int8 KV cache** (``cache_dtype="int8"``): pages are stored int8 with
  per-(token, head) scales, dequantized inside the decode kernel — the
  cache's HBM footprint AND the tick's page traffic halve vs bf16,
  composing with GQA's kv-head shrink (same lever stack as vLLM + the
  weight-only quant in :mod:`beholder_tpu.ops.quant`).
- **Continuous batching, two ways.** :meth:`ContinuousBatcher.run` is
  the flexible scheduler: admit queued requests into free slots
  mid-flight, tick all active slots together, retire finished ones. For
  fixed-horizon fleets :meth:`ContinuousBatcher.run_waves` fuses
  admit -> scan(ticks) -> retire into compiled code — the prediction
  feedback loop stays ON DEVICE inside one ``lax.scan`` (no per-token
  host round-trip, the round-3 latency wall).

The paged decode is numerically equivalent to the dense per-request
rollout (pinned by ``tests/test_serving.py``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from beholder_tpu.ops import NUM_STATUSES
from beholder_tpu.ops.paged_attention import PagedInfo, QuantizedPool

from .sequence import TelemetrySequenceModel


class PagedKVState(NamedTuple):
    """Paged serving state (a pytree; every leaf has a static shape).

    - ``k_pools``/``v_pools``: per-layer (num_pages, Hkv, Dh, page)
      arrays, or :class:`~beholder_tpu.ops.paged_attention.QuantizedPool`
      (int8 values + (num_pages, Hkv, page) f32 scales) under int8
      caching
    - ``page_table``: (slots, max_pages) pool indices per slot
    - ``seq_lens``: (slots,) tokens written per slot
    - ``active``: (slots,) bool
    - ``free_stack``: (num_pages,) pool indices; ``free_stack[:free_top]``
      are free
    - ``alloc_failed``: sticky error flag (pool exhausted / table
      overflow) — checked host-side by the batcher
    """

    k_pools: tuple
    v_pools: tuple
    page_table: jax.Array
    seq_lens: jax.Array
    active: jax.Array
    free_stack: jax.Array
    free_top: jax.Array
    alloc_failed: jax.Array


def init_paged(
    model: TelemetrySequenceModel,
    num_pages: int,
    page_size: int,
    slots: int,
    max_pages_per_seq: int,
    cache_dtype=jnp.bfloat16,
) -> PagedKVState:
    dh = model.dim // model.heads
    hkv = model.kv_heads or model.heads
    shape = (num_pages, hkv, dh, page_size)
    if cache_dtype in (jnp.int8, "int8"):
        def pool():
            return QuantizedPool(
                jnp.zeros(shape, jnp.int8),
                jnp.ones((num_pages, hkv, page_size), jnp.float32),
            )
    else:
        def pool():
            return jnp.zeros(shape, cache_dtype)
    return PagedKVState(
        tuple(pool() for _ in range(model.layers)),
        tuple(pool() for _ in range(model.layers)),
        jnp.zeros((slots, max_pages_per_seq), jnp.int32),
        jnp.zeros((slots,), jnp.int32),
        jnp.zeros((slots,), bool),
        jnp.arange(num_pages, dtype=jnp.int32),
        jnp.int32(num_pages),
        jnp.zeros((), bool),
    )


def _pool_geometry(state: PagedKVState) -> tuple[int, int]:
    """(num_pages, page_size) of the state's pools (quantized or not)."""
    p0 = state.k_pools[0]
    vals = p0.values if isinstance(p0, QuantizedPool) else p0
    return vals.shape[0], vals.shape[3]


def _pop_pages(state: PagedKVState, need: jax.Array):
    """Vectorized masked stack pop: needer i (with ``need[i]``) gets page
    ``free_stack[free_top - 1 - rank_i]`` where rank_i numbers the
    needers. Returns (pages (len(need),), new_top, failed)."""
    rank = jnp.cumsum(need.astype(jnp.int32)) - 1
    n = need.sum().astype(jnp.int32)
    idx = state.free_top - 1 - rank
    failed = state.alloc_failed | (n > state.free_top)
    pages = state.free_stack[jnp.clip(idx, 0, state.free_stack.shape[0] - 1)]
    return pages, state.free_top - n, failed


def _alloc_for_tick(state: PagedKVState) -> PagedKVState:
    """Give every active slot whose next write position opens a fresh
    page (len % page == 0) a page off the free stack."""
    _, page = _pool_geometry(state)
    slots, max_pages = state.page_table.shape
    need = state.active & (state.seq_lens % page == 0)
    pages, new_top, failed = _pop_pages(state, need)
    pidx = state.seq_lens // page
    failed = failed | jnp.any(need & (pidx >= max_pages))
    rows = jnp.where(need, jnp.arange(slots), slots)  # OOB row -> dropped
    table = state.page_table.at[
        rows, jnp.clip(pidx, 0, max_pages - 1)
    ].set(pages, mode="drop")
    return state._replace(
        page_table=table, free_top=new_top, alloc_failed=failed
    )


def slot_cache(state: PagedKVState, slot: int, layer: int):
    """DEBUG/TEST helper: gather ``slot``'s written cache for ``layer``
    as dense (Hkv, Dh, seq_len) arrays (dequantized). Never called by
    the serving path — the tick attends pages in place."""
    num_pages, page = _pool_geometry(state)

    def dense(pool):
        if isinstance(pool, QuantizedPool):
            vals = pool.values.astype(jnp.float32) * pool.scales[:, :, None, :]
        else:
            vals = pool.astype(jnp.float32)
        g = vals[state.page_table[slot]]          # (P, Hkv, Dh, page)
        g = g.transpose(1, 2, 0, 3).reshape(
            vals.shape[1], vals.shape[2], -1
        )
        return g[:, :, : int(state.seq_lens[slot])]

    return dense(state.k_pools[layer]), dense(state.v_pools[layer])


def paged_decode_tick(
    model: TelemetrySequenceModel, params, state: PagedKVState, feats_t
):
    """One continuous-batching decode step for ALL slots.

    ``feats_t`` is (slots, FEATURES); inactive slots run too (their
    writes are dropped, their outputs ignored) — that is what keeps the
    tick a single compiled program. Returns ((slots,) predictions,
    updated state)."""
    state = _alloc_for_tick(state)
    num_pages, page = _pool_geometry(state)
    slots = state.page_table.shape[0]

    rows = jnp.arange(slots)
    pidx = jnp.clip(state.seq_lens // page, 0, state.page_table.shape[1] - 1)
    write_pages = jnp.where(
        state.active, state.page_table[rows, pidx], num_pages  # OOB -> drop
    )
    info = PagedInfo(
        state.page_table, state.seq_lens, write_pages,
        state.seq_lens % page,
    )

    preds, new_kvs = model.apply(
        params,
        feats_t[:, None, :],
        cache=(state.k_pools, state.v_pools, info),
    )
    state = state._replace(
        k_pools=tuple(k for k, _ in new_kvs),
        v_pools=tuple(v for _, v in new_kvs),
        seq_lens=state.seq_lens + state.active.astype(jnp.int32),
    )
    return preds[:, 0], state


def _quantize_tokens(x: jax.Array):
    """(..., Dh, T) -> int8 values + (..., T) per-(head, token) scales —
    the shared symmetric scheme (one definition; the decode tick's
    column writes must match the admit path's chunk writes exactly)."""
    from beholder_tpu.ops.quant import quantize_symmetric

    return quantize_symmetric(x, axis=-2)


def _write_chunks(pool, drop_pages, chunks):
    """Scatter (n, Hkv, Dh, page) chunks into pool rows ``drop_pages``
    (OOB entries dropped), quantizing per token when the pool is int8."""
    if isinstance(pool, QuantizedPool):
        q, scale = _quantize_tokens(chunks)
        return QuantizedPool(
            pool.values.at[drop_pages].set(q, mode="drop"),
            pool.scales.at[drop_pages].set(scale, mode="drop"),
        )
    return pool.at[drop_pages].set(chunks.astype(pool.dtype), mode="drop")


def paged_admit(
    model: TelemetrySequenceModel,
    params,
    state: PagedKVState,
    slot: jax.Array,
    feats_padded: jax.Array,
    prefix_len: jax.Array,
):
    """Admit one request into ``slot``: prefill its (1, T_max, F) padded
    prefix in one forward, allocate ceil(prefix_len/page) pages, and
    write the prefix kv into them. Returns ((,) last prediction, state).

    The page count is data-dependent but the WORK is not: the masked
    writes cover all T_max//page chunks and drop the dead ones.
    """
    preds, state = paged_admit_batch(
        model, params, state,
        jnp.asarray(slot, jnp.int32).reshape(1), feats_padded,
        jnp.asarray(prefix_len, jnp.int32).reshape(1),
    )
    return preds[0], state


def paged_admit_batch(
    model: TelemetrySequenceModel,
    params,
    state: PagedKVState,
    slot_ids: jax.Array,
    feats_padded: jax.Array,
    prefix_lens: jax.Array,
):
    """Admit a WAVE of requests in one prefill: ``feats_padded`` is
    (n, T_max, F) (page-multiple T_max), ``slot_ids``/``prefix_lens``
    are (n,). A request with ``prefix_lens[i] == 0`` is skipped (slot id
    should then be out of range so its table write drops). Returns
    ((n,) last predictions, state)."""
    num_pages, page = _pool_geometry(state)
    slots, max_pages = state.page_table.shape
    n, t_max, _ = feats_padded.shape
    if t_max % page:
        raise ValueError(f"padded prefix {t_max} not a page multiple ({page})")
    p_max = t_max // page

    preds, kvs = model.apply(params, feats_padded, return_kv=True)
    last_pred = preds[
        jnp.arange(n), jnp.clip(prefix_lens - 1, 0, t_max - 1)
    ]

    n_pages = -(-prefix_lens // page)                      # (n,) ceil
    chunk_alive = (
        jax.lax.broadcasted_iota(jnp.int32, (n, p_max), 1)
        < n_pages[:, None]
    )
    pages, new_top, failed = _pop_pages(state, chunk_alive.reshape(-1))
    pages = pages.reshape(n, p_max)
    failed = failed | jnp.any(n_pages > max_pages)

    table_rows = jnp.where(
        jax.lax.broadcasted_iota(jnp.int32, (n, max_pages), 1)
        < n_pages[:, None],
        jnp.pad(pages, ((0, 0), (0, max(0, max_pages - p_max))))[
            :, :max_pages
        ],
        0,
    )
    drop = jnp.where(chunk_alive, pages, num_pages).reshape(-1)

    k_pools, v_pools = [], []
    for layer, (k, v) in enumerate(kvs):
        def chunks(a):
            # (n, Hkv, T_max, Dh) -> (n*p_max, Hkv, Dh, page)
            hkv, dh = a.shape[1], a.shape[3]
            a = a.transpose(0, 1, 3, 2)                 # (n, Hkv, Dh, T)
            a = a.reshape(n, hkv, dh, p_max, page)
            return a.transpose(0, 3, 1, 2, 4).reshape(
                n * p_max, hkv, dh, page
            )
        k_pools.append(_write_chunks(state.k_pools[layer], drop, chunks(k)))
        v_pools.append(_write_chunks(state.v_pools[layer], drop, chunks(v)))

    admitted = prefix_lens > 0
    safe_slots = jnp.where(
        admitted, jnp.clip(slot_ids, 0, slots - 1), slots  # OOB -> drop
    )
    state = state._replace(
        k_pools=tuple(k_pools),
        v_pools=tuple(v_pools),
        page_table=state.page_table.at[safe_slots].set(
            table_rows, mode="drop"
        ),
        seq_lens=state.seq_lens.at[safe_slots].set(
            prefix_lens, mode="drop"
        ),
        active=state.active.at[safe_slots].set(admitted, mode="drop"),
        free_top=new_top,
        alloc_failed=failed,
    )
    return last_pred, state


def paged_release(state: PagedKVState, slot: jax.Array) -> PagedKVState:
    """Retire ``slot``: push its pages back onto the free stack."""
    num_pages, page = _pool_geometry(state)
    max_pages = state.page_table.shape[1]
    n = -(-state.seq_lens[slot] // page)
    alive = jnp.arange(max_pages) < n
    dest = jnp.where(
        alive, state.free_top + jnp.arange(max_pages), num_pages
    )
    stack = state.free_stack.at[dest].set(
        state.page_table[slot], mode="drop"
    )
    return state._replace(
        free_stack=stack,
        free_top=state.free_top + n,
        active=state.active.at[slot].set(False),
        seq_lens=state.seq_lens.at[slot].set(0),
    )


def paged_wave(
    model: TelemetrySequenceModel,
    params,
    state: PagedKVState,
    last_pred: jax.Array,
    status_oh: jax.Array,
    n_ticks: int,
):
    """Roll every active slot ``n_ticks`` decode steps ON DEVICE: the
    prediction feedback loop runs inside one ``lax.scan`` (one compiled
    program, zero per-token host traffic). Returns ((slots, n_ticks + 1)
    deltas — the admit prediction plus each tick's, i.e. a horizon of
    ``n_ticks + 1``) and the rolled state."""

    def step(carry, _):
        state, pred = carry
        feats_t = jnp.concatenate([pred[:, None], status_oh], axis=-1)
        new_pred, state = paged_decode_tick(
            model, params, state, feats_t.astype(jnp.float32)
        )
        return (state, new_pred), pred

    (state, last), deltas = jax.lax.scan(
        step, (state, last_pred), None, length=n_ticks
    )
    deltas = jnp.concatenate([deltas.T, last[:, None]], axis=-1)
    return deltas, state


class Request(NamedTuple):
    progress: np.ndarray   # (T+1,) observed progress
    statuses: np.ndarray   # (T+1,) observed statuses
    horizon: int


class ContinuousBatcher:
    """Host-side vLLM-style scheduler over the paged state.

    Submit any number of :class:`Request`\\ s, then :meth:`run` (admit
    into free slots as they open; one host round-trip per tick) or
    :meth:`run_waves` (admit up to ``slots`` requests in ONE batched
    prefill, roll the whole wave's horizon on device in one compiled
    scan, retire, repeat — the throughput path). Results are per-request
    forecast delta arrays, equal to the dense per-request rollout.
    """

    def __init__(
        self,
        model: TelemetrySequenceModel,
        params,
        *,
        num_pages: int = 64,
        page_size: int = 16,
        slots: int = 4,
        max_prefix: int = 64,
        max_pages_per_seq: int = 32,
        cache_dtype=jnp.bfloat16,
    ):
        self.model = model
        self.params = params
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_pages_per_seq = max_pages_per_seq
        self.max_prefix = -(-max_prefix // page_size) * page_size
        self.state = init_paged(
            model, num_pages, page_size, slots, max_pages_per_seq,
            cache_dtype=cache_dtype,
        )
        self.slots = slots
        self._tick = jax.jit(
            lambda p, s, f: paged_decode_tick(model, p, s, f)
        )
        self._admit = jax.jit(
            lambda p, s, slot, feats, ns: paged_admit_batch(
                model, p, s, slot, feats, ns
            )
        )
        self._release = jax.jit(paged_release)
        # wave rollouts jit per horizon (the scan length is static)
        self._wave_cache: dict[int, object] = {}

    # -- shared helpers -------------------------------------------------

    def _need_pages(self, req: Request) -> int:
        """Worst-case pages a request consumes: prefix + the horizon-1
        fed-back tokens (the horizon-th prediction needs no tick — see
        run()'s early release)."""
        feats_len = len(req.progress) - 1
        tokens = feats_len + max(req.horizon - 1, 0)
        return -(-tokens // self.page_size)

    def _prep(self, req: Request):
        from .sequence import stream_features

        feats, _ = stream_features(
            jnp.asarray(req.progress)[None], jnp.asarray(req.statuses)[None]
        )
        t = feats.shape[1]
        if t > self.max_prefix:
            raise ValueError(
                f"prefix {t} exceeds max_prefix {self.max_prefix}"
            )
        padded = jnp.pad(feats, ((0, 0), (0, self.max_prefix - t), (0, 0)))
        return padded, t

    def _check_servable(self, req: Request):
        need = self._need_pages(req)
        if need > self.num_pages or need > self.max_pages_per_seq:
            raise RuntimeError(
                f"page pool exhausted: request needs {need} pages "
                f"(pool {self.num_pages}, per-seq cap "
                f"{self.max_pages_per_seq}) — raise num_pages or shorten "
                f"the horizon"
            )

    # -- flexible path: per-tick scheduling -----------------------------

    def run(self, requests: list[Request]) -> list[np.ndarray]:
        queue = list(enumerate(requests))
        results: list = [None] * len(requests)
        # per-slot host bookkeeping
        req_of = [None] * self.slots
        deltas: list = [None] * self.slots
        remaining = np.zeros(self.slots, np.int64)
        total_need = np.zeros(self.slots, np.int64)  # pages at horizon end
        cur_len = np.zeros(self.slots, np.int64)     # tokens written
        last_pred = np.zeros(self.slots, np.float32)
        status_oh = np.zeros((self.slots, NUM_STATUSES), np.float32)

        def committed() -> int:
            """Pages active slots will STILL allocate: worst-case total
            minus what they already hold (free_top already reflects held
            pages, so subtracting total_need alone would double-count
            growth that has materialized)."""
            held = -(-cur_len // self.page_size)
            return int(np.sum((total_need - held)[np.asarray(
                [r is not None for r in req_of]
            )]))

        def retire(slot):
            """Collect the slot's final delta WITHOUT running another
            tick (the horizon-th prediction is last_pred itself; a tick
            for it could allocate a page for a token nobody reads)."""
            deltas[slot].append(last_pred[slot])
            results[req_of[slot]] = np.asarray(deltas[slot], np.float32)
            self.state = self._release(self.state, jnp.int32(slot))
            req_of[slot] = None
            total_need[slot] = 0
            cur_len[slot] = 0

        while queue or any(r is not None for r in req_of):
            # admit while there is a free slot, a queued request, AND
            # enough free-page headroom after honoring every active
            # slot's worst-case future growth (deferring beats the
            # sticky alloc_failed abort)
            for slot in range(self.slots):
                if not queue or req_of[slot] is not None:
                    continue
                rid, req = queue[0]
                if req.horizon <= 0:
                    # forecast_deltas(horizon=0) returns an empty array;
                    # skip the prefill/alloc round-trip entirely
                    queue.pop(0)
                    results[rid] = np.zeros(0, np.float32)
                    continue
                self._check_servable(req)
                need = self._need_pages(req)
                free = int(self.state.free_top) - committed()
                if need > free:
                    if not any(r is not None for r in req_of):
                        raise RuntimeError(
                            "page pool exhausted: request needs "
                            f"{need} pages but only {free} exist free — "
                            "raise num_pages or lower concurrency"
                        )
                    break  # defer until an active request retires
                queue.pop(0)
                padded, t = self._prep(req)
                pred, self.state = self._admit(
                    self.params, self.state,
                    jnp.asarray([slot], jnp.int32), padded,
                    jnp.asarray([t], jnp.int32),
                )
                if bool(self.state.alloc_failed):
                    raise RuntimeError(
                        "page pool exhausted — raise num_pages or lower "
                        "concurrency"
                    )
                req_of[slot] = rid
                deltas[slot] = []
                remaining[slot] = req.horizon
                total_need[slot] = need
                cur_len[slot] = t
                last_pred[slot] = float(pred[0])
                status_oh[slot] = np.asarray(
                    jax.nn.one_hot(int(req.statuses[-1]), NUM_STATUSES)
                )
                if remaining[slot] == 1:
                    retire(slot)  # the admit prediction was the forecast

            if not any(r is not None for r in req_of):
                continue

            # one compiled tick for every slot (inactive slots ride along)
            feats_t = jnp.asarray(
                np.concatenate([last_pred[:, None], status_oh], axis=1),
                jnp.float32,
            )
            preds, self.state = self._tick(self.params, self.state, feats_t)
            if bool(self.state.alloc_failed):
                raise RuntimeError("page pool exhausted mid-decode")
            preds = np.asarray(preds)

            for slot in range(self.slots):
                if req_of[slot] is None:
                    continue
                deltas[slot].append(last_pred[slot])
                last_pred[slot] = preds[slot]
                remaining[slot] -= 1
                cur_len[slot] += 1  # the tick wrote this slot's token
                if remaining[slot] <= 1:
                    retire(slot)
        return results

    # -- throughput path: on-device waves -------------------------------

    def _wave_fn(self, n_ticks: int):
        fn = self._wave_cache.get(n_ticks)
        if fn is None:
            fn = jax.jit(
                lambda p, s, pred, oh: paged_wave(
                    self.model, p, s, pred, oh, n_ticks
                )
            )
            self._wave_cache[n_ticks] = fn
        return fn

    def run_waves(self, requests: list[Request]) -> list[np.ndarray]:
        """Fixed-horizon throughput mode: greedy waves of up to ``slots``
        requests, each wave = one batched prefill + ONE compiled scan
        over its max horizon (shorter-horizon members ride along; their
        surplus deltas are dropped host-side). Page headroom is checked
        per wave, with ride-along growth counted at the wave horizon."""
        results: list = [None] * len(requests)
        queue = list(enumerate(requests))
        while queue:
            wave: list = []
            free = int(self.state.free_top)
            horizon = 0
            while queue and len(wave) < self.slots:
                rid, req = queue[0]
                if req.horizon <= 0:
                    queue.pop(0)
                    results[rid] = np.zeros(0, np.float32)
                    continue
                self._check_servable(req)
                t = len(req.progress) - 1
                h = max(horizon, req.horizon)
                # wave members decode h-1 ticks regardless of their own
                # horizon, so BOTH headroom checks run at the wave's
                # grown horizon: total pool pages AND each member's
                # page-table cap (a short request riding a long one can
                # overflow its own table — deferred to the next wave)
                def pages_at(r, hh):
                    return -(-(len(r.progress) - 1 + hh - 1)
                             // self.page_size)

                need = pages_at(req, h)
                others = sum(pages_at(r, h) for _, r in wave)
                over_cap = any(
                    pages_at(r, h) > self.max_pages_per_seq
                    for r in [req] + [r for _, r in wave]
                )
                if need + others > free or over_cap:
                    if not wave:
                        raise RuntimeError(
                            f"page pool exhausted: request needs {need} "
                            f"pages but only {free} exist free (per-seq "
                            f"cap {self.max_pages_per_seq})"
                        )
                    break
                queue.pop(0)
                wave.append((rid, req))
                horizon = h
            if not wave:
                continue

            prepped = [self._prep(req) for _, req in wave]
            feats = jnp.concatenate([p for p, _ in prepped], axis=0)
            lens = jnp.asarray([t for _, t in prepped], jnp.int32)
            slot_ids = jnp.arange(len(wave), dtype=jnp.int32)
            preds, self.state = self._admit(
                self.params, self.state, slot_ids, feats, lens
            )
            if bool(self.state.alloc_failed):
                raise RuntimeError("page pool exhausted during admit")
            oh = np.zeros((self.slots, NUM_STATUSES), np.float32)
            pred0 = np.zeros(self.slots, np.float32)
            for i, (_, req) in enumerate(wave):
                oh[i] = np.asarray(
                    jax.nn.one_hot(int(req.statuses[-1]), NUM_STATUSES)
                )
                pred0[i] = float(preds[i])

            deltas, self.state = self._wave_fn(horizon - 1)(
                self.params, self.state, jnp.asarray(pred0),
                jnp.asarray(oh),
            )
            if bool(self.state.alloc_failed):
                raise RuntimeError("page pool exhausted mid-decode")
            deltas = np.asarray(deltas, np.float32)
            for i, (rid, req) in enumerate(wave):
                results[rid] = deltas[i, : req.horizon]
                self.state = self._release(self.state, jnp.int32(i))
        return results
