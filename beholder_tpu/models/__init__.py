"""Models for telemetry analytics.

EXTENSION BEYOND THE REFERENCE (which contains no models — SURVEY.md §0).
The flagship is :class:`~beholder_tpu.models.anomaly.ProgressAnomalyModel`,
a next-step progress predictor whose prediction error flags stalled or
misbehaving encode jobs from their progress streams.
"""

from .anomaly import (
    ProgressAnomalyModel,
    anomaly_scores,
    init_train_state,
    make_windows,
    train_step,
)
from .decode import (
    decode_step,
    forecast_deltas,
    forecast_eta,
    init_cache,
    prefill,
)
from .sequence import (
    TelemetrySequenceModel,
    init_seq_state,
    seq_train_step,
    stream_features,
)
from .serving import (
    ContinuousBatcher,
    PagedKVState,
    Request,
    fork_wave,
    init_paged,
    paged_admit,
    paged_admit_batch,
    paged_decode_tick,
    paged_fork,
    paged_release,
    paged_wave,
)

__all__ = [
    "ContinuousBatcher",
    "PagedKVState",
    "Request",
    "fork_wave",
    "init_paged",
    "paged_admit",
    "paged_admit_batch",
    "paged_decode_tick",
    "paged_fork",
    "paged_release",
    "paged_wave",
    "decode_step",
    "forecast_deltas",
    "forecast_eta",
    "init_cache",
    "prefill",
    "ProgressAnomalyModel",
    "make_windows",
    "init_train_state",
    "train_step",
    "anomaly_scores",
    "TelemetrySequenceModel",
    "init_seq_state",
    "seq_train_step",
    "stream_features",
]
