"""Progress-stream anomaly model.

A small MLP that predicts the next progress value of an encode job from a
window of recent (progress delta, status) observations; the absolute
prediction error is the anomaly score. Stalls (delta collapses to 0 while
status says CONVERTING) and jumps (progress regressions after retries)
surface as high error without hand-written thresholds.

TPU-first design choices:
- fixed window size -> static shapes; batch is the only leading dim
- bfloat16 matmuls with float32 params/accumulation (MXU-native mix)
- pure-functional train step (params in, params out) so it jits and
  shards with pjit/GSPMD (see beholder_tpu.parallel)
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from beholder_tpu.ops import NUM_STATUSES

from .train import TrainState, apply_gradients

WINDOW = 16  # observations per window
FEATURES = 1 + NUM_STATUSES  # progress delta + one-hot status
HIDDEN = 128


class ProgressAnomalyModel(nn.Module):
    """MLP over flattened windows: (B, WINDOW*FEATURES) -> (B,) next delta."""

    hidden: int = HIDDEN

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = x.astype(jnp.bfloat16)
        x = nn.Dense(self.hidden, name="in_proj")(x)
        x = nn.relu(x)
        x = nn.Dense(self.hidden, name="mid_proj")(x)
        x = nn.relu(x)
        x = nn.Dense(1, name="out_proj", dtype=jnp.float32)(x)
        return x[..., 0].astype(jnp.float32)


def make_windows(
    progress: jax.Array, statuses: jax.Array, window: int = WINDOW
) -> tuple[jax.Array, jax.Array]:
    """Slice a telemetry stream into model inputs.

    Args:
        progress: (T,) progress values of one media job, time-ordered.
        statuses: (T,) status ids aligned with ``progress``.

    Returns:
        features: (T-window-1, window*FEATURES) flattened windows of
            (progress delta, one-hot status).
        targets: (T-window-1,) the delta immediately after each window.
    """
    deltas = jnp.diff(progress.astype(jnp.float32))  # (T-1,)
    status_oh = jax.nn.one_hot(statuses[1:], NUM_STATUSES)  # aligned w/ deltas
    feats = jnp.concatenate([deltas[:, None], status_oh], axis=-1)  # (T-1, F)

    n = deltas.shape[0] - window
    idx = jnp.arange(n)[:, None] + jnp.arange(window)[None, :]  # (n, window)
    windows = feats[idx].reshape(n, window * FEATURES)
    targets = deltas[window:]
    return windows, targets


def init_train_state(
    rng: jax.Array, learning_rate: float = 1e-3, window: int = WINDOW
) -> tuple[TrainState, optax.GradientTransformation]:
    model = ProgressAnomalyModel()
    params = model.init(rng, jnp.zeros((1, window * FEATURES)))
    tx = optax.adam(learning_rate)
    return TrainState(params, tx.init(params), jnp.int32(0)), tx


def loss_fn(params: Any, windows: jax.Array, targets: jax.Array) -> jax.Array:
    pred = ProgressAnomalyModel().apply(params, windows)
    return jnp.mean((pred - targets) ** 2)


def train_step(
    state: TrainState,
    tx: optax.GradientTransformation,
    windows: jax.Array,
    targets: jax.Array,
) -> tuple[TrainState, jax.Array]:
    """One SGD step. Pure function — jit/pjit it at the call site so the
    same code serves single-chip and sharded execution."""
    return apply_gradients(state, tx, lambda p: loss_fn(p, windows, targets))


def anomaly_scores(params: Any, windows: jax.Array, targets: jax.Array) -> jax.Array:
    """|predicted next delta - actual| per window; higher = more anomalous."""
    pred = ProgressAnomalyModel().apply(params, windows)
    return jnp.abs(pred - targets)
