"""Caching subsystem: a shared keyed-cache core + the serving layer's
automatic prefix cache.

- :mod:`.core` — policy-pluggable (LRU/LFU/TTL) keyed cache with
  byte/entry capacity accounting, singleflight duplicate-load collapse,
  and explicit writer-side invalidation. Used by the storage query
  cache (:mod:`beholder_tpu.storage.cached`), the outbound HTTP lookup
  cache (:class:`beholder_tpu.clients.http.CachingTransport`), and the
  read-only endpoint response cache
  (:class:`beholder_tpu.httpd.CachedRoute`).
- :mod:`.prefix` — radix (chained page hash) index mapping admitted
  token prefixes to KV pool pages; the host half of vLLM-style
  automatic prefix caching for
  :class:`beholder_tpu.models.serving.ContinuousBatcher`.
- :mod:`.instruments` — the metric catalog, registered only on demand
  so the pinned default exposition stays byte-identical.

Everything here is opt-in: no service or batcher constructs a cache
unless configured to (``instance.cache.*`` / ``prefix_cache=``), and
with caching off behavior is byte-identical to the uncached paths.
"""

from .core import (
    EvictionPolicy,
    KeyedCache,
    LFUPolicy,
    LRUPolicy,
    SingleFlight,
    TTLPolicy,
)
from .instruments import CacheMetrics, PrefixCacheMetrics
from .prefix import PrefixCache, page_hashes

__all__ = [
    "KeyedCache",
    "SingleFlight",
    "EvictionPolicy",
    "LRUPolicy",
    "LFUPolicy",
    "TTLPolicy",
    "PrefixCache",
    "page_hashes",
    "CacheMetrics",
    "PrefixCacheMetrics",
]
